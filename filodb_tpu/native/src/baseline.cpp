// Honest CPU baseline for the north-star benchmark.
//
// A faithful, multithreaded C++ implementation of the reference's
// per-series / per-window query hot loop — ChunkedRateFunction over
// sorted timestamp vectors with counter correction and Prometheus
// extrapolation, reduced with sum by (group)  (reference:
// query/src/main/scala/filodb/query/exec/rangefn/RateFunctions.scala:140-207,
// exec/AggrOverRangeVectors.scala:161-277,
// jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:45-249).
//
// The JVM publishes no absolute numbers and no JVM exists in this
// environment (BASELINE.md), so this -O3 C++ loop is the stand-in for the
// JVM's iterator path: same algorithm (binary search per window, one pass
// per series), same data, scaled across hardware threads the way the
// reference's query scheduler spreads range vectors across its pool.
//
// Semantics intentionally match bench.py's _numpy_rate_sum oracle
// bit-for-bit (same correction and extrapolation formulas) so the
// TPU-vs-CPU comparison is apples-to-apples.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// One series: compact finite samples, apply counter correction, then emit
// the extrapolated rate for every window into a thread-private [G,T] sum.
void series_rate(const int64_t* ts, const double* vals, size_t nrows,
                 const int64_t* steps, size_t nsteps, int64_t window_ms,
                 int32_t group, size_t nsteps_stride, double* out,
                 double* cnt, int64_t* t_buf, double* v_buf) {
  size_t n = 0;
  for (size_t i = 0; i < nrows; ++i) {
    if (std::isfinite(vals[i])) {
      t_buf[n] = ts[i];
      v_buf[n] = vals[i];
      ++n;
    }
  }
  if (n < 2) return;
  // counter correction: running sum of drops, added back (prefix scan)
  double corr = 0.0;
  double prev = v_buf[0];
  for (size_t i = 1; i < n; ++i) {
    double cur = v_buf[i];
    if (cur < prev) corr += prev - cur;
    prev = cur;
    v_buf[i] = cur + corr;
  }
  double* orow = out + static_cast<size_t>(group) * nsteps_stride;
  double* crow = cnt + static_cast<size_t>(group) * nsteps_stride;
  for (size_t j = 0; j < nsteps; ++j) {
    const int64_t st = steps[j];
    const int64_t ws = st - window_ms;
    // (ws, st] window; timestamps sorted: binary search both bounds
    const int64_t* tb = t_buf;
    const int64_t* lo_p = std::upper_bound(tb, tb + n, ws);
    const int64_t* hi_p = std::upper_bound(lo_p, tb + n, st);
    const size_t lo = static_cast<size_t>(lo_p - tb);
    const size_t hi = static_cast<size_t>(hi_p - tb);
    if (hi - lo < 2) continue;
    const int64_t t1 = t_buf[lo], t2 = t_buf[hi - 1];
    if (t2 == t1) continue;
    const double delta = v_buf[hi - 1] - v_buf[lo];
    const double nw = static_cast<double>(hi - lo);
    const double avg_dur = static_cast<double>(t2 - t1) / (nw - 1.0);
    double ext_start, ext_end;
    if (static_cast<double>(t1 - ws) <= avg_dur * 1.1)
      ext_start = std::min(static_cast<double>(ws) + avg_dur / 2.0,
                           static_cast<double>(t1));
    else
      ext_start = static_cast<double>(t1) - avg_dur / 2.0;
    if (static_cast<double>(st - t2) <= avg_dur * 1.1)
      ext_end = std::max(static_cast<double>(st) - avg_dur / 2.0,
                         static_cast<double>(t2));
    else
      ext_end = static_cast<double>(t2) + avg_dur / 2.0;
    const double rate = delta * ((ext_end - ext_start) /
                                 static_cast<double>(t2 - t1)) /
                        (static_cast<double>(window_ms) / 1000.0);
    orow[j] += rate;
    crow[j] += 1.0;
  }
}

}  // namespace

extern "C" {

int baseline_hw_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n ? static_cast<int>(n) : 1;
}

// ts/vals: [S, R] row-major (one series per row; NaN-padded vals).
// ids: [S] group id in [0, G). steps: [T] window end timestamps (ms).
// out/cnt: [G, T] caller-zeroed. Returns 0, or -1 on bad args.
int baseline_rate_sum(const int64_t* ts, const double* vals, size_t S,
                      size_t R, const int32_t* ids, size_t G,
                      const int64_t* steps, size_t T, int64_t window_ms,
                      double* out, double* cnt, int nthreads) {
  if (!ts || !vals || !ids || !steps || !out || !cnt || G == 0) return -1;
  for (size_t s = 0; s < S; ++s)
    if (ids[s] < 0 || static_cast<size_t>(ids[s]) >= G) return -1;
  if (nthreads <= 0) nthreads = baseline_hw_threads();
  const size_t nt = std::min<size_t>(static_cast<size_t>(nthreads),
                                     std::max<size_t>(S, 1));

  std::vector<std::vector<double>> priv_out(nt), priv_cnt(nt);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  const size_t per = (S + nt - 1) / nt;
  for (size_t t = 0; t < nt; ++t) {
    priv_out[t].assign(G * T, 0.0);
    priv_cnt[t].assign(G * T, 0.0);
    const size_t s0 = t * per, s1 = std::min(S, s0 + per);
    threads.emplace_back([=, &priv_out, &priv_cnt]() {
      std::vector<int64_t> t_buf(R);
      std::vector<double> v_buf(R);
      double* po = priv_out[t].data();
      double* pc = priv_cnt[t].data();
      for (size_t s = s0; s < s1; ++s)
        series_rate(ts + s * R, vals + s * R, R, steps, T, window_ms,
                    ids[s], T, po, pc, t_buf.data(), v_buf.data());
    });
  }
  for (auto& th : threads) th.join();
  for (size_t t = 0; t < nt; ++t)
    for (size_t i = 0; i < G * T; ++i) {
      out[i] += priv_out[t][i];
      cnt[i] += priv_cnt[t][i];
    }
  return 0;
}

// sum_over_time variant (no correction/extrapolation): per window, sum of
// samples in (st-window, st]. Used by the bench suite for a second
// workload point (reference: AggrOverTimeFunctions.scala SumOverTime).
int baseline_sum_over_time(const int64_t* ts, const double* vals, size_t S,
                           size_t R, const int32_t* ids, size_t G,
                           const int64_t* steps, size_t T,
                           int64_t window_ms, double* out, double* cnt,
                           int nthreads) {
  if (!ts || !vals || !ids || !steps || !out || !cnt || G == 0) return -1;
  for (size_t s = 0; s < S; ++s)
    if (ids[s] < 0 || static_cast<size_t>(ids[s]) >= G) return -1;
  if (nthreads <= 0) nthreads = baseline_hw_threads();
  const size_t nt = std::min<size_t>(static_cast<size_t>(nthreads),
                                     std::max<size_t>(S, 1));
  std::vector<std::vector<double>> priv_out(nt), priv_cnt(nt);
  std::vector<std::thread> threads;
  const size_t per = (S + nt - 1) / nt;
  for (size_t t = 0; t < nt; ++t) {
    priv_out[t].assign(G * T, 0.0);
    priv_cnt[t].assign(G * T, 0.0);
    const size_t s0 = t * per, s1 = std::min(S, s0 + per);
    threads.emplace_back([=, &priv_out, &priv_cnt]() {
      std::vector<int64_t> t_buf(R);
      std::vector<double> v_buf(R);
      double* po = priv_out[t].data();
      double* pc = priv_cnt[t].data();
      for (size_t s = s0; s < s1; ++s) {
        const int64_t* trow = ts + s * R;
        const double* vrow = vals + s * R;
        size_t n = 0;
        for (size_t i = 0; i < R; ++i)
          if (std::isfinite(vrow[i])) {
            t_buf[n] = trow[i];
            v_buf[n] = vrow[i];
            ++n;
          }
        if (!n) continue;
        double* orow = po + static_cast<size_t>(ids[s]) * T;
        double* crow = pc + static_cast<size_t>(ids[s]) * T;
        const int64_t* tb = t_buf.data();
        for (size_t j = 0; j < T; ++j) {
          const int64_t st = steps[j];
          const int64_t* lo_p = std::upper_bound(tb, tb + n, st - window_ms);
          const int64_t* hi_p = std::upper_bound(lo_p, tb + n, st);
          if (lo_p == hi_p) continue;
          double acc = 0.0;
          for (const int64_t* p = lo_p; p != hi_p; ++p)
            acc += v_buf[p - tb];
          orow[j] += acc;
          crow[j] += 1.0;
        }
      }
    });
  }
  for (auto& th : threads) th.join();
  for (size_t t = 0; t < nt; ++t)
    for (size_t i = 0; i < G * T; ++i) {
      out[i] += priv_out[t][i];
      cnt[i] += priv_cnt[t][i];
    }
  return 0;
}

}  // extern "C"
