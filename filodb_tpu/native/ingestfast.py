"""Columnar container decode: the C++ ingest fast path binding.

Decodes a whole RecordContainer into numpy columns with per-series
partkey dedup in one native call (``cd_decode`` in src/codecs.cpp), so
the shard ingest loop touches one Python object per *series* instead of
per record — the ingest-side answer to the reference's zero-copy
off-heap record iteration (reference: binaryrecord2/RecordContainer.scala:27,
TimeSeriesShard.scala:488-522 IngestConsumer).

Falls back transparently: :func:`decode` returns ``None`` whenever the
container can't take the fast path (no compiler, string columns, mixed
schemas, malformed input) and callers use the Python
:func:`filodb_tpu.core.record.decode_container` iterator instead.
Histogram columns ARE fast-pathed: ``cd_decode`` records each blob's
offset and ``hist_col_decode`` expands all blobs of a column into one
dense cumulative-counts matrix natively (VERDICT r2 weak #3 — hist
ingest was 150x slower than scalars on the per-record Python path).
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Optional

import numpy as np

from filodb_tpu.core.histogram import (CustomBuckets, GeometricBuckets,
                                       HistogramBuckets)
from filodb_tpu.core.schemas import ColumnType, Schemas

_TYPE_CODES = {
    ColumnType.DOUBLE: 1,
    ColumnType.LONG: 2,
    ColumnType.TIMESTAMP: 2,
    ColumnType.INT: 3,
    ColumnType.HISTOGRAM: 4,
}

# min wire bytes per record: 18B header + 2B pklen (empty pk, no cols)
_MIN_RECORD = 20


@dataclasses.dataclass
class HistColumn:
    """One histogram data column, blob-expanded: dense cumulative counts
    plus per-record bucket count and deduplicated bucket schemes."""

    counts: np.ndarray        # int64 [N, hb_cap], edge-padded
    nbuckets: np.ndarray      # int32 [N]
    scheme_idx: np.ndarray    # int32 [N] — index into schemes
    schemes: list[HistogramBuckets]

    def __getitem__(self, sel) -> "HistColumn":
        """Row-subset (boolean mask / index array / slice), so the shard
        ingest path can filter and split hist columns exactly like
        scalar numpy columns."""
        return HistColumn(self.counts[sel], self.nbuckets[sel],
                          self.scheme_idx[sel], self.schemes)


@dataclasses.dataclass
class DecodedContainer:
    """Columnar view of one single-schema container."""

    schema_hash: int
    ts: np.ndarray            # int64 [N]
    cols: list                # per data column: np.ndarray or HistColumn
    shard_hashes: np.ndarray  # uint32 [N]
    part_hashes: np.ndarray   # uint32 [N]
    uniq_idx: np.ndarray      # int32 [N] — index into partkeys
    partkeys: list[bytes]     # unique, first-seen order
    uniq_first: np.ndarray    # int64 [U] — first record index per partkey

    @property
    def num_records(self) -> int:
        return len(self.ts)


class _SchemaTable:
    """Flattened schema registry passed to cd_decode, cached per Schemas."""

    __slots__ = ("hashes", "ncols", "types", "max_cols", "fastable")

    def __init__(self, schemas: Schemas):
        all_s = schemas.all
        self.max_cols = max((len(s.data.columns) - 1 for s in all_s),
                            default=0) or 1
        self.hashes = np.zeros(len(all_s), dtype=np.uint16)
        self.ncols = np.zeros(len(all_s), dtype=np.uint8)
        self.types = np.zeros((len(all_s), self.max_cols), dtype=np.uint8)
        self.fastable = set()
        for i, s in enumerate(all_s):
            self.hashes[i] = s.schema_hash
            dcols = s.data.columns[1:]
            self.ncols[i] = len(dcols)
            ok = True
            for c, col in enumerate(dcols):
                code = _TYPE_CODES.get(col.ctype, 0)
                self.types[i, c] = code
                ok = ok and code != 0
            if ok:
                self.fastable.add(s.schema_hash)


def _table_for(schemas: Schemas) -> _SchemaTable:
    # cached on the Schemas object itself — an id()-keyed dict would hand
    # a stale table to a new Schemas reusing the freed address
    t = getattr(schemas, "_ingestfast_table", None)
    if t is None:
        t = _SchemaTable(schemas)
        schemas._ingestfast_table = t
    return t


_cd = None
_hist = None
_cd_failed = False


def _lib():
    global _cd, _hist, _cd_failed
    if _cd is not None or _cd_failed:
        return _cd
    from filodb_tpu import native
    raw = native._load()
    if raw is None:
        _cd_failed = True
        return None
    fn = raw.cd_decode
    fn.restype = ctypes.c_longlong
    fn.argtypes = [ctypes.c_void_p, ctypes.c_size_t,      # buf
                   ctypes.c_void_p, ctypes.c_void_p,      # hashes, ncols
                   ctypes.c_void_p, ctypes.c_size_t,      # types, max_cols
                   ctypes.c_size_t, ctypes.c_size_t,      # n_schemas, cap
                   ctypes.c_void_p, ctypes.c_void_p,      # ts, vals
                   ctypes.c_void_p, ctypes.c_void_p,      # shard, part
                   ctypes.c_void_p,                        # uniq
                   ctypes.c_void_p, ctypes.c_void_p,      # pk_off, pk_len
                   ctypes.c_void_p,                        # uniq_first
                   ctypes.c_void_p, ctypes.c_void_p]      # n_uniq, schema
    hf = raw.hist_col_decode
    hf.restype = ctypes.c_longlong
    hf.argtypes = [ctypes.c_void_p, ctypes.c_size_t,      # buf
                   ctypes.c_void_p, ctypes.c_size_t,      # blob_off, n
                   ctypes.c_int, ctypes.c_int, ctypes.c_int,  # wire/schemes
                   ctypes.c_size_t,                        # hb_cap
                   ctypes.c_void_p, ctypes.c_void_p,      # counts, nb
                   ctypes.c_void_p,                        # scheme_idx
                   ctypes.c_void_p, ctypes.c_void_p,      # uscheme off/len
                   ctypes.c_size_t, ctypes.c_void_p]      # cap, n_schemes
    _hist = hf
    _cd = fn
    return _cd


def available() -> bool:
    return _lib() is not None


_SCHEME_CAP = 64   # distinct bucket schemes per (container, column)


def _decode_hist_col(buf: bytes, offs: np.ndarray) -> Optional[HistColumn]:
    """Expand one histogram column's blobs via hist_col_decode."""
    from filodb_tpu.codecs.wire import WireType
    n = len(offs)
    if n == 0:
        return HistColumn(np.empty((0, 0), np.int64),
                          np.empty(0, np.int32), np.empty(0, np.int32), [])
    arr8 = np.frombuffer(buf, np.uint8)
    # per-record bucket counts live at blob_off+1 (u16 LE); a malformed
    # sub-3-byte blob at the container tail would gather out of bounds
    if int(offs.max()) + 2 >= len(arr8):
        return None
    nv = arr8[offs + 1].astype(np.int64) | \
        (arr8[offs + 2].astype(np.int64) << 8)
    hb_cap = int(nv.max())
    if hb_cap == 0 or hb_cap > 1024:
        return None
    counts = np.empty((n, hb_cap), np.int64)
    nb = np.empty(n, np.int32)
    sidx = np.empty(n, np.int32)
    us_off = np.empty(_SCHEME_CAP, np.int64)
    us_len = np.empty(_SCHEME_CAP, np.int64)
    ns = ctypes.c_longlong(0)
    offs64 = np.ascontiguousarray(offs, np.int64)
    got = _hist(buf, len(buf), offs64.ctypes.data, n,
                int(WireType.HIST_BLOB), GeometricBuckets.scheme_id,
                CustomBuckets.scheme_id, hb_cap,
                counts.ctypes.data, nb.ctypes.data, sidx.ctypes.data,
                us_off.ctypes.data, us_len.ctypes.data, _SCHEME_CAP,
                ctypes.byref(ns))
    if got < 0:
        return None
    schemes = []
    for i in range(int(ns.value)):
        o = int(us_off[i])
        scheme, _ = HistogramBuckets.deserialize(buf, o)
        schemes.append(scheme)
    return HistColumn(counts, nb, sidx, schemes)


def decode(container: bytes, schemas: Schemas) -> Optional[DecodedContainer]:
    """Decode one container columnar-fast, or None to signal fallback."""
    fn = _lib()
    if fn is None or len(container) < 4:
        return None
    table = _table_for(schemas)
    if len(table.hashes) == 0:
        return None
    # cheap pre-check: first record's schema must be all-scalar
    if len(container) >= 6:
        first_hash = int.from_bytes(container[4:6], "little")
        if first_hash not in table.fastable:
            return None
    buf = container if isinstance(container, bytes) else bytes(container)
    cap = max(len(buf) // _MIN_RECORD + 1, 1)
    ts = np.empty(cap, dtype=np.int64)
    vals = np.empty((cap, table.max_cols), dtype=np.int64)
    shard_h = np.empty(cap, dtype=np.uint32)
    part_h = np.empty(cap, dtype=np.uint32)
    uniq = np.empty(cap, dtype=np.int32)
    pk_off = np.empty(cap, dtype=np.int64)
    pk_len = np.empty(cap, dtype=np.int64)
    uniq_first = np.empty(cap, dtype=np.int64)
    n_uniq = ctypes.c_longlong(0)
    schema_hash = ctypes.c_int32(0)
    n = fn(buf, len(buf),
           table.hashes.ctypes.data, table.ncols.ctypes.data,
           table.types.ctypes.data, table.max_cols,
           len(table.hashes), cap,
           ts.ctypes.data, vals.ctypes.data,
           shard_h.ctypes.data, part_h.ctypes.data,
           uniq.ctypes.data,
           pk_off.ctypes.data, pk_len.ctypes.data, uniq_first.ctypes.data,
           ctypes.byref(n_uniq), ctypes.byref(schema_hash))
    if n < 0:
        return None
    n = int(n)
    nu = int(n_uniq.value)
    schema = schemas.by_hash(int(schema_hash.value)) if n else None
    cols: list = []
    if schema is not None:
        for c, col in enumerate(schema.data.columns[1:]):
            if col.ctype == ColumnType.HISTOGRAM:
                hc = _decode_hist_col(buf, vals[:n, c])
                if hc is None:
                    return None     # malformed / oversized: Python path
                cols.append(hc)
                continue
            raw = vals[:n, c].copy()
            cols.append(raw.view(np.float64)
                        if col.ctype == ColumnType.DOUBLE else raw)
    partkeys = [buf[int(pk_off[i]):int(pk_off[i]) + int(pk_len[i])]
                for i in range(nu)]
    return DecodedContainer(
        schema_hash=int(schema_hash.value) if n else 0,
        ts=ts[:n].copy(), cols=cols,
        shard_hashes=shard_h[:n].copy(), part_hashes=part_h[:n].copy(),
        uniq_idx=uniq[:n].copy(), partkeys=partkeys,
        uniq_first=uniq_first[:nu].copy())
