"""ctypes binding for the C++ CPU baseline (src/baseline.cpp).

This is the measurement side of BASELINE.md's protocol: a multithreaded
-O3 C++ implementation of the reference's per-series/per-window query
iterator (the JVM proxy — no JVM exists in the bench environment), used
by bench.py and benches/ to compute ``vs_baseline`` honestly.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "baseline.cpp")
_SO = os.path.join(_HERE, "_baseline.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _build() -> str | None:
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return None
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
               "-o", tmp, _SRC]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            if os.path.exists(tmp):
                os.remove(tmp)
            return proc.stderr.strip() or "g++ failed"
        os.replace(tmp, _SO)
        return None
    except Exception as e:
        return str(e)


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()  # filolint: disable=blocking-under-lock — single-flight native build: the first caller compiles once per process; contenders must wait for the artifact, not race the compiler
        if err is not None:
            _build_error = err
            return None
        lib = ctypes.CDLL(_SO)
        lib.baseline_hw_threads.restype = ctypes.c_int
        sig = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
               ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
               ctypes.c_void_p, ctypes.c_size_t, ctypes.c_longlong,
               ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
        for name in ("baseline_rate_sum", "baseline_sum_over_time"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = sig
        _lib = lib
        return _lib


def build_error() -> str | None:
    _load()
    return _build_error


def available() -> bool:
    return _load() is not None


def hw_threads() -> int:
    lib = _load()
    return int(lib.baseline_hw_threads()) if lib is not None else 1


def _run(name: str, ts: np.ndarray, vals: np.ndarray, ids: np.ndarray,
         n_groups: int, steps: np.ndarray, window_ms: int,
         nthreads: int = 0) -> tuple[np.ndarray, np.ndarray]:
    lib = _load()
    if lib is None:
        raise RuntimeError(f"baseline lib unavailable: {_build_error}")
    ts = np.ascontiguousarray(ts, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    steps = np.ascontiguousarray(steps, dtype=np.int64)
    S, R = ts.shape
    assert vals.shape == (S, R) and ids.shape == (S,)
    T = len(steps)
    out = np.zeros((n_groups, T), dtype=np.float64)
    cnt = np.zeros((n_groups, T), dtype=np.float64)
    rc = getattr(lib, name)(
        ts.ctypes.data, vals.ctypes.data, S, R, ids.ctypes.data, n_groups,
        steps.ctypes.data, T, window_ms, out.ctypes.data, cnt.ctypes.data,
        nthreads)
    if rc != 0:
        raise ValueError(f"{name} failed (bad group ids?)")
    return out, cnt


def rate_sum(ts, vals, ids, n_groups, steps, window_ms, nthreads=0):
    """sum by (group)(rate(metric[window])) — NaN where a group had no
    contributing series in a window."""
    out, cnt = _run("baseline_rate_sum", ts, vals, ids, n_groups, steps,
                    window_ms, nthreads)
    return np.where(cnt > 0, out, np.nan)


def sum_over_time_sum(ts, vals, ids, n_groups, steps, window_ms, nthreads=0):
    out, cnt = _run("baseline_sum_over_time", ts, vals, ids, n_groups,
                    steps, window_ms, nthreads)
    return np.where(cnt > 0, out, np.nan)
