"""Batched aggregators: map (shard-local) / reduce (cross-shard) / present.

Replaces the reference's RowAggregator family + fastReduce
(reference: query/exec/aggregator/RowAggregator.scala:29,114-141,
exec/AggrOverRangeVectors.scala:151-277).  The map phase runs device
segment-reductions over [S, T] batches; partial state is a dict of [G, ...]
arrays mergeable across shards (the analog of the reference's transportable
aggregate rows); present converts final state to a PeriodicBatch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from filodb_tpu.ops import aggregate as segops
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query.logical import AggregationOperator as Op
from filodb_tpu.query.model import PeriodicBatch, QueryError


@dataclasses.dataclass
class AggPartialBatch:
    """Mergeable aggregation state: per-group arrays keyed by name."""

    op: Op
    params: tuple
    group_keys: list[dict]
    steps: StepRange
    state: dict[str, np.ndarray]
    # series keys for ops whose reduce needs original series (topk/quantile)
    series_keys: Optional[list[dict]] = None
    # bucket tops when the state carries histogram sums ("hist_sum")
    bucket_tops: Optional[np.ndarray] = None

    @property
    def num_series(self) -> int:
        return len(self.group_keys)


def grouping_key(tags: dict, by: tuple, without: tuple, metric_col: str = "_metric_"):
    """The output key of by/without grouping (reference: AggregateMapReduce
    grouping): plain aggregation collapses to one group; ``without`` keeps
    the complement (minus the metric name); ``by`` keeps exactly those."""
    if by:
        return {k: tags.get(k, "") for k in by if k in tags}
    if without:
        drop = set(without) | {metric_col}
        return {k: v for k, v in tags.items() if k not in drop}
    return {}


def _group(keys: Sequence[dict], by, without, limit: int):
    gk = [tuple(sorted(grouping_key(t, by, without).items())) for t in keys]
    ids, uniq = segops.group_ids(gk)
    if len(uniq) > limit:
        raise QueryError("", f"group-by cardinality {len(uniq)} exceeds limit {limit}")
    return ids, [dict(u) for u in uniq]


def _padded_ids(ids: np.ndarray, total_series: int, num_groups: int) -> jnp.ndarray:
    """Pad ids to the padded series axis; padding rows land in a garbage
    group that is sliced off after the segment reduction."""
    out = np.full(total_series, num_groups, dtype=np.int32)
    out[:len(ids)] = ids
    return jnp.asarray(out)


class Aggregator:
    op: Op

    def map(self, batch: PeriodicBatch, by, without, params, limit) -> AggPartialBatch:
        raise NotImplementedError

    def reduce(self, partials: list[AggPartialBatch]) -> AggPartialBatch:
        raise NotImplementedError

    def present(self, partial: AggPartialBatch) -> PeriodicBatch:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# moment-based aggregators share alignment machinery
# ---------------------------------------------------------------------------

def _align(partials: list[AggPartialBatch], fill: float):
    """Union group keys; each partial's arrays scatter into union rows."""
    index: dict[tuple, int] = {}
    for p in partials:
        for k in p.group_keys:
            index.setdefault(tuple(sorted(k.items())), len(index))
    G = len(index)
    names = partials[0].state.keys()
    aligned = {n: [] for n in names}
    for p in partials:
        rows = np.array([index[tuple(sorted(k.items()))] for k in p.group_keys],
                        dtype=np.int64)
        for n in names:
            arr = np.asarray(p.state[n])
            f = -1 if np.issubdtype(arr.dtype, np.integer) else fill
            out = np.full((G,) + arr.shape[1:], f, dtype=arr.dtype)
            if len(rows):
                out[rows] = arr
            aligned[n].append(out)
    keys = [dict(k) for k in index.keys()]
    return keys, aligned


def _nansum_stack(arrs: list[np.ndarray]) -> np.ndarray:
    stack = np.stack(arrs)
    allnan = np.all(np.isnan(stack), axis=0)
    s = np.nansum(stack, axis=0)
    return np.where(allnan, np.nan, s)


class MomentAggregator(Aggregator):
    """sum/count/min/max/avg/stddev/stdvar/group via (sum, sumsq, count,
    min, max) moments — one implementation, different presenters."""

    def __init__(self, op: Op):
        self.op = op

    _NEEDS = {
        Op.SUM: ("sum", "count"), Op.COUNT: ("count",),
        Op.MIN: ("min",), Op.MAX: ("max",),
        Op.AVG: ("sum", "count"), Op.GROUP: ("count",),
        Op.STDDEV: ("sum", "sumsq", "count"),
        Op.STDVAR: ("sum", "sumsq", "count"),
    }

    def map(self, batch, by, without, params, limit):
        if batch.hist is not None:
            return self._map_hist(batch, by, without, params, limit)
        ids, keys = _group(batch.keys, by, without, limit)
        G = len(keys)
        vals = jnp.asarray(batch.values)
        pids = _padded_ids(ids, vals.shape[0], G)
        state = {}
        needs = self._NEEDS[self.op]
        if "sum" in needs or "count" in needs:
            fin = jnp.isfinite(vals)
            s = jax.ops.segment_sum(jnp.where(fin, vals, 0.0), pids, G + 1)[:G]
            n = jax.ops.segment_sum(fin.astype(vals.dtype), pids, G + 1)[:G]
            if "sum" in needs:
                state["sum"] = np.asarray(s)
            if "count" in needs:
                state["count"] = np.asarray(n)
        if "sumsq" in needs:
            fin = jnp.isfinite(vals)
            sq = jax.ops.segment_sum(jnp.where(fin, vals * vals, 0.0), pids,
                                     G + 1)[:G]
            state["sumsq"] = np.asarray(sq)
        if "min" in needs:
            state["min"] = np.asarray(
                segops.seg_min(vals, pids, G + 1)[:G])
        if "max" in needs:
            state["max"] = np.asarray(
                segops.seg_max(vals, pids, G + 1)[:G])
        return AggPartialBatch(self.op, params, keys, batch.steps, state)

    def _map_hist(self, batch, by, without, params, limit):
        """Bucket-wise histogram sum (reference: exec/aggregator/
        RowAggregator.scala HistSumRowAggregator).  Only sum is defined
        over first-class histogram series."""
        if self.op != Op.SUM:
            raise QueryError(
                "", f"{self.op.name.lower()}() over histogram series is not "
                    "supported (only sum; use hist_to_prom_vectors for "
                    "per-bucket series)")
        ids, keys = _group(batch.keys, by, without, limit)
        G = len(keys)
        h = jnp.asarray(np.asarray(batch.hist)[:len(batch.keys)])
        idsj = jnp.asarray(ids.astype(np.int32))
        fin = jnp.isfinite(h[..., -1])                   # [S, T]
        hs = jax.ops.segment_sum(jnp.where(fin[..., None], h, 0.0), idsj, G)
        n = jax.ops.segment_sum(fin.astype(h.dtype), idsj, G)
        state = {"hist_sum": np.asarray(hs), "count": np.asarray(n)}
        return AggPartialBatch(self.op, params, keys, batch.steps, state,
                               bucket_tops=np.asarray(batch.bucket_tops))

    @staticmethod
    def _align_hist_widths(partials):
        """Edge-pad cumulative bucket matrices to the widest scheme (the
        same convention as scan_batch / merge_batches): a narrower
        histogram's top bucket already holds the total count."""
        hists = [p for p in partials if "hist_sum" in p.state]
        if not hists:
            return None
        if len(hists) != len(partials):
            raise QueryError("", "cannot reduce histogram and scalar "
                                 "aggregates together (mixed schemas)")
        widest = max(hists, key=lambda p: p.state["hist_sum"].shape[-1])
        bmax = widest.state["hist_sum"].shape[-1]
        for i, p in enumerate(partials):
            h = np.asarray(p.state["hist_sum"])
            if h.shape[-1] < bmax:
                padded = np.pad(
                    h, [(0, 0)] * (h.ndim - 1) + [(0, bmax - h.shape[-1])],
                    mode="edge")
                # copy-on-write: the input partial stays self-consistent
                # (its own hist_sum width must keep matching bucket_tops)
                partials[i] = dataclasses.replace(
                    p, state={**p.state, "hist_sum": padded})
        return widest.bucket_tops

    def reduce(self, partials):
        first = partials[0]
        tops = self._align_hist_widths(partials)
        keys, aligned = _align(partials, np.nan)
        state = {}
        for n, arrs in aligned.items():
            if n in ("sum", "sumsq", "hist_sum"):
                state[n] = _nansum_stack(arrs)
            elif n == "count":
                zeroed = [np.nan_to_num(a, nan=0.0) for a in arrs]
                state[n] = np.sum(np.stack(zeroed), axis=0)
            elif n == "min":
                state[n] = np.nanmin(np.stack(arrs), axis=0)
            elif n == "max":
                state[n] = np.nanmax(np.stack(arrs), axis=0)
        return AggPartialBatch(self.op, first.params, keys, first.steps, state,
                               bucket_tops=tops)

    def present(self, p):
        s = p.state
        if "hist_sum" in s:
            n = np.asarray(s["count"])
            hist = np.where(n[..., None] > 0, s["hist_sum"], np.nan)
            return PeriodicBatch(p.group_keys, p.steps,
                                 np.full(n.shape, np.nan), hist=hist,
                                 bucket_tops=p.bucket_tops)
        if self.op == Op.SUM:
            vals = np.where(s["count"] > 0, s["sum"], np.nan)
        elif self.op == Op.COUNT:
            vals = np.where(s["count"] > 0, s["count"], np.nan)
        elif self.op == Op.GROUP:
            vals = np.where(s["count"] > 0, 1.0, np.nan)
        elif self.op == Op.MIN:
            vals = s["min"]
        elif self.op == Op.MAX:
            vals = s["max"]
        elif self.op == Op.AVG:
            n = s["count"]
            vals = np.where(n > 0, s["sum"] / np.maximum(n, 1.0), np.nan)
        else:  # stddev / stdvar
            n = s["count"]
            nsafe = np.maximum(n, 1.0)
            mean = s["sum"] / nsafe
            var = np.maximum(s["sumsq"] / nsafe - mean * mean, 0.0)
            if self.op == Op.STDDEV:
                var = np.sqrt(var)
            vals = np.where(n > 0, var, np.nan)
        return PeriodicBatch(p.group_keys, p.steps, vals)


class TopBottomKAggregator(Aggregator):
    """topk/bottomk: map keeps k candidate (value, series) slots per group per
    step; reduce concatenates candidate slots and re-selects; present emits
    the original contributing series with NaN at unselected steps
    (reference: TopBottomKRowAggregator)."""

    def __init__(self, op: Op):
        self.op = op

    def map(self, batch, by, without, params, limit):
        k = int(params[0])
        ids, keys = _group(batch.keys, by, without, limit)
        G = len(keys)
        vals = jnp.asarray(batch.values)
        pids = _padded_ids(ids, vals.shape[0], G)
        values, sidx = segops.seg_topk(vals, pids, G + 1, k,
                                       bottom=self.op == Op.BOTTOMK)
        return AggPartialBatch(self.op, params, keys, batch.steps,
                               {"values": np.asarray(values[:G]),
                                "sidx": np.asarray(sidx[:G])},
                               series_keys=list(batch.keys))

    def reduce(self, partials):
        k = int(partials[0].params[0])
        # remap per-partial series indices into a combined series key list
        all_keys: list[dict] = []
        offsets = []
        for p in partials:
            offsets.append(len(all_keys))
            all_keys.extend(p.series_keys or [])
        keys, aligned = _align(partials, np.nan)
        cands_v, cands_i = [], []
        for p, off, av, ai in zip(partials, offsets, aligned["values"],
                                  aligned["sidx"]):
            sidx = ai.astype(np.int64)
            remapped = np.where(sidx >= 0, sidx + off, -1)
            cands_v.append(av)
            cands_i.append(remapped)
        V = np.concatenate(cands_v, axis=1)   # [G, sum_k, T]
        I = np.concatenate(cands_i, axis=1)
        sign = -1.0 if self.op == Op.BOTTOMK else 1.0
        work = np.where(np.isfinite(V), V * sign, -np.inf)
        order = np.argsort(-work, axis=1, kind="stable")[:, :k]   # [G,k,T]
        top_v = np.take_along_axis(V, order, axis=1)
        top_i = np.take_along_axis(I, order, axis=1)
        top_w = np.take_along_axis(work, order, axis=1)
        top_v = np.where(np.isfinite(top_w), top_v, np.nan)
        top_i = np.where(np.isfinite(top_w), top_i, -1)
        return AggPartialBatch(self.op, partials[0].params, keys,
                               partials[0].steps,
                               {"values": top_v, "sidx": top_i.astype(np.int32)},
                               series_keys=all_keys)

    def present(self, p):
        V, I = p.state["values"], p.state["sidx"].astype(np.int64)
        skeys = p.series_keys or []
        G, k, T = V.shape
        out_keys: list[dict] = []
        rows: list[np.ndarray] = []
        import warnings
        for g in range(G):
            used = np.unique(I[g])
            for s in used:
                if s < 0:
                    continue
                row = np.full(T, np.nan)
                mask = I[g] == s                     # [k, T]
                sel = np.where(mask, V[g], np.nan)
                if mask.any():
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore", RuntimeWarning)
                        row = np.nanmax(sel, axis=0)
                out_keys.append(skeys[int(s)])
                rows.append(row)
        vals = np.stack(rows) if rows else np.empty((0, T))
        return PeriodicBatch(out_keys, p.steps, vals)


def _dense_members_map(op, batch, by, without, params, limit,
                       grouped=None):
    """Per-group dense member matrix [G, M, T] (exact-path partial).
    ``grouped`` lets callers pass precomputed (ids, keys, vals, M)."""
    if grouped is None:
        ids, keys = _group(batch.keys, by, without, limit)
        vals = np.asarray(batch.values)[:len(batch.keys)]
        counts = np.bincount(ids, minlength=len(keys)) if len(ids) \
            else np.zeros(len(keys), int)
        M = int(counts.max()) if len(keys) else 0
    else:
        ids, keys, vals, M = grouped
    G = len(keys)
    T = vals.shape[1]
    dense = np.full((G, max(M, 1), T), np.nan)
    pos = np.zeros(G, dtype=np.int64)
    for s, g in enumerate(ids):
        dense[g, pos[g]] = vals[s]
        pos[g] += 1
    return AggPartialBatch(op, params, keys, batch.steps, {"members": dense})


class QuantileAggregator(Aggregator):
    """Quantile with bounded memory: small groups stay exact (dense member
    matrix + nanquantile); past ``exact_members`` members per group the
    partial switches to a mergeable t-digest sketch, O(G*T*C) no matter
    the cardinality (reference: QuantileRowAggregator's TDigest partials,
    exec/aggregator/RowAggregator.scala).  Reduce handles mixed partials
    by sketching the exact side."""

    op = Op.QUANTILE
    exact_members = 128       # per-group member budget before sketching
    compression = 128

    def map(self, batch, by, without, params, limit):
        from filodb_tpu.query import tdigest

        ids, keys = _group(batch.keys, by, without, limit)
        G = len(keys)
        vals = np.asarray(batch.values)[:len(batch.keys)]
        counts = np.bincount(ids, minlength=G) if len(ids) \
            else np.zeros(G, int)
        M = int(counts.max()) if G else 0
        if M <= self.exact_members:
            return _dense_members_map(self.op, batch, by, without, params,
                                      limit, grouped=(ids, keys, vals, M))
        d = tdigest.from_values(vals, np.asarray(ids), G, self.compression)
        return AggPartialBatch(self.op, params, keys, batch.steps,
                               {"td_means": d.means, "td_weights": d.weights})

    @staticmethod
    def _is_digest(p) -> bool:
        return "td_means" in p.state

    def _to_digest_state(self, p) -> dict:
        from filodb_tpu.query import tdigest

        if self._is_digest(p):
            return p.state
        d = tdigest.from_members(p.state["members"], self.compression)
        return {"td_means": d.means, "td_weights": d.weights}

    def reduce(self, partials):
        from filodb_tpu.query import tdigest

        if not any(self._is_digest(p) for p in partials):
            total = sum(p.state["members"].shape[1] for p in partials)
            if total <= self.exact_members:
                keys, aligned = _align(partials, np.nan)
                members = np.concatenate(aligned["members"], axis=1)
                return AggPartialBatch(self.op, partials[0].params, keys,
                                       partials[0].steps,
                                       {"members": members})
        # sketch path: convert any exact partials, then cell-wise merge
        norm = [AggPartialBatch(p.op, p.params, p.group_keys, p.steps,
                                self._to_digest_state(p))
                for p in partials]
        keys, aligned = _align(norm, np.nan)
        acc = tdigest.TDigest(aligned["td_means"][0],
                              np.nan_to_num(aligned["td_weights"][0]))
        for m, w in zip(aligned["td_means"][1:], aligned["td_weights"][1:]):
            acc = tdigest.merge(acc, tdigest.TDigest(m, np.nan_to_num(w)))
        return AggPartialBatch(self.op, partials[0].params, keys,
                               partials[0].steps,
                               {"td_means": acc.means,
                                "td_weights": acc.weights})

    def present(self, p):
        q = float(p.params[0])
        if self._is_digest(p):
            from filodb_tpu.query import tdigest
            vals = tdigest.quantile(
                tdigest.TDigest(p.state["td_means"], p.state["td_weights"]),
                q)
            return PeriodicBatch(p.group_keys, p.steps, vals)
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            vals = np.nanquantile(p.state["members"], q, axis=1)
        return PeriodicBatch(p.group_keys, p.steps, vals)


# count_values guards: the (group, value, step) count cube is bounded by
# the response itself (one output series per distinct (group, value)), so
# exceeding these is a cardinality error, not an OOM (the reference's
# CountValuesRowAggregator map would blow its RowKeyMap the same way)
CV_MAX_DISTINCT = 65_536
CV_MAX_STATE_BYTES = 1 << 31


def count_values_state(vals2d: np.ndarray, gids: np.ndarray,
                       num_groups: int) -> dict:
    """Vectorized count_values partial from windowed series values.

    ``vals2d`` [S, T] stepped values (NaN = no sample), ``gids`` [S]
    group per series.  One np.unique + one bincount over the whole
    matrix — no per-series Python loop and no dense [G, M, T] member
    cube (VERDICT r4 weak #5 / next #8); the state is the
    (value, group, step) count tensor the reference's
    CountValuesRowAggregator carries as mergeable (value -> count) rows.
    Returns {"cv_vals": [U] sorted distinct values,
    "cv_counts": [G, U, T] float64}."""
    G = max(int(num_groups), 1)
    vals2d = np.asarray(vals2d)
    T = vals2d.shape[1] if vals2d.ndim == 2 else 0
    fin = np.isfinite(vals2d)
    if not fin.any():
        return {"cv_vals": np.empty(0, np.float64),
                "cv_counts": np.zeros((G, 0, T), np.float64)}
    uniq, inv = np.unique(vals2d[fin], return_inverse=True)
    U = len(uniq)
    if U > CV_MAX_DISTINCT or G * U * T * 8 > CV_MAX_STATE_BYTES:
        raise QueryError("", f"count_values cardinality too large "
                             f"({U} distinct values x {G} groups)")
    s_idx, t_idx = np.nonzero(fin)
    g_idx = np.asarray(gids, dtype=np.int64)[s_idx]
    flat = (g_idx * U + inv.ravel()) * T + t_idx
    counts = np.bincount(flat, minlength=G * U * T).astype(np.float64)
    return {"cv_vals": uniq.astype(np.float64),
            "cv_counts": counts.reshape(G, U, T)}


class CountValuesAggregator(Aggregator):
    """count_values("label", v): per-step count of each distinct value
    (reference: CountValuesRowAggregator).  Two partial forms: the exact
    member pass-through ([G, M, T] "members", the single-batch map) and
    the counted form ({"cv_vals", "cv_counts"}, produced by the resident
    mesh path / :func:`count_values_state`); reduce normalizes to the
    counted form whenever any input carries it."""

    op = Op.COUNT_VALUES

    def map(self, batch, by, without, params, limit):
        # exact values pass through as the COUNTED form: one np.unique +
        # bincount over the [S, T] matrix, no per-series loop and no
        # dense [G, M, T] member cube at high cardinality
        ids, keys = _group(batch.keys, by, without, limit)
        vals = np.asarray(batch.values)[:len(batch.keys)]
        state = count_values_state(vals, ids, len(keys))
        return AggPartialBatch(self.op, params, keys, batch.steps, state)

    @staticmethod
    def _is_cv(p) -> bool:
        return "cv_vals" in p.state

    @staticmethod
    def _to_cv_state(p) -> dict:
        if "cv_vals" in p.state:
            return p.state
        members = np.asarray(p.state["members"])        # [G, M, T]
        G, _M, T = members.shape
        return count_values_state(members.reshape(-1, T),
                                  np.repeat(np.arange(G), _M), G)

    def reduce(self, partials):
        if not any(self._is_cv(p) for p in partials):
            keys, aligned = _align(partials, np.nan)
            members = np.concatenate(aligned["members"], axis=1)
            return AggPartialBatch(self.op, partials[0].params, keys,
                                   partials[0].steps, {"members": members})
        index: dict[tuple, int] = {}
        for p in partials:
            for k in p.group_keys:
                index.setdefault(tuple(sorted(k.items())), len(index))
        G = len(index)
        states = [self._to_cv_state(p) for p in partials]
        all_vals = np.unique(np.concatenate(
            [s["cv_vals"] for s in states]))
        U = len(all_vals)
        T = states[0]["cv_counts"].shape[-1]
        if U > CV_MAX_DISTINCT or G * U * T * 8 > CV_MAX_STATE_BYTES:
            raise QueryError("", f"count_values cardinality too large "
                                 f"({U} distinct values x {G} groups)")
        out = np.zeros((G, U, T), np.float64)
        for p, s in zip(partials, states):
            rows = [index[tuple(sorted(k.items()))] for k in p.group_keys]
            cols = np.searchsorted(all_vals, s["cv_vals"])
            if len(rows) and len(cols):
                out[np.ix_(rows, cols, np.arange(T))] += s["cv_counts"]
        return AggPartialBatch(self.op, partials[0].params,
                               [dict(k) for k in index], partials[0].steps,
                               {"cv_vals": all_vals, "cv_counts": out})

    def present(self, p):
        label = str(p.params[0])
        if self._is_cv(p):
            uniq = p.state["cv_vals"]
            counts = p.state["cv_counts"]       # [G, U, T]
            T = counts.shape[-1]
            out_keys, rows = [], []
            present_mask = counts.sum(axis=2) > 0          # [G, U]
            for g, u in zip(*np.nonzero(present_mask)):
                key = dict(p.group_keys[g])
                key[label] = _fmt_value(float(uniq[u]))
                out_keys.append(key)
                cnt = counts[g, u]
                rows.append(np.where(cnt > 0, cnt, np.nan))
            valsarr = np.stack(rows) if rows else np.empty((0, T))
            return PeriodicBatch(out_keys, p.steps, valsarr)
        members = p.state["members"]            # [G, M, T]
        G, M, T = members.shape
        out_keys, rows = [], []
        for g in range(G):
            vals = members[g]
            uniq = np.unique(vals[np.isfinite(vals)])
            for u in uniq:
                cnt = np.sum(vals == u, axis=0).astype(float)  # [T]
                key = dict(p.group_keys[g])
                key[label] = _fmt_value(float(u))
                out_keys.append(key)
                rows.append(np.where(cnt > 0, cnt, np.nan))
        valsarr = np.stack(rows) if rows else np.empty((0, T))
        return PeriodicBatch(out_keys, p.steps, valsarr)


def _fmt_value(v: float) -> str:
    return str(int(v)) if v == int(v) else repr(v)


_AGGREGATORS = {
    **{op: (lambda op=op: MomentAggregator(op)) for op in
       (Op.SUM, Op.COUNT, Op.MIN, Op.MAX, Op.AVG, Op.STDDEV, Op.STDVAR,
        Op.GROUP)},
    Op.TOPK: lambda: TopBottomKAggregator(Op.TOPK),
    Op.BOTTOMK: lambda: TopBottomKAggregator(Op.BOTTOMK),
    Op.QUANTILE: lambda: QuantileAggregator(),
    Op.COUNT_VALUES: lambda: CountValuesAggregator(),
}


def aggregator_for(op: Op) -> Aggregator:
    try:
        return _AGGREGATORS[op]()
    except KeyError:
        raise ValueError(f"unsupported aggregation operator {op}")
