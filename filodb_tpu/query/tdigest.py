"""Vectorized mergeable t-digest for bounded-memory quantile aggregation.

The reference bounds quantile-aggregation memory with a t-digest per
group/step (reference: query/exec/aggregator/RowAggregator.scala
QuantileRowAggregator, which serializes TDigest sketches into the
partial rows).  A literal port would be a per-cell object graph; here a
digest is three dense arrays over every (group, step) cell at once —

    means   [G, T, C]   centroid means  (NaN = empty slot)
    weights [G, T, C]   centroid weights (0 = empty slot)

— and every operation (build, merge, quantile) is a batched numpy pass
over all G*T cells, which is the shape the rest of the aggregation
layer already works in (AggPartialBatch state dict).

Compression uses the k1 scale function ``k(q) = C/(2pi) * asin(2q-1)``:
sorted centroids are binned by floor(k-index) and bin-merged, which
bounds the centroid count at C per cell while keeping tail resolution —
the same invariant the MergingDigest maintains, computed in one
vectorized scatter-add instead of a sequential greedy loop.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class TDigest:
    """Batched digests for a [G, T] grid of cells."""

    means: np.ndarray     # [G, T, C]
    weights: np.ndarray   # [G, T, C]

    @property
    def compression(self) -> int:
        return self.means.shape[-1]

    @property
    def nbytes(self) -> int:
        return self.means.nbytes + self.weights.nbytes


def _k_scale(q: np.ndarray, compression: int) -> np.ndarray:
    """k1 scale spanning the FULL [0, compression] range: asin(2q-1)
    covers [-pi/2, pi/2], i.e. a span of pi, so the factor is C/pi."""
    q = np.clip(q, 0.0, 1.0)
    return compression / np.pi * (np.arcsin(2.0 * q - 1.0) + np.pi / 2.0)


def _compress(means: np.ndarray, weights: np.ndarray,
              compression: int) -> TDigest:
    """Compress [G, T, N] centroid sets down to C = compression slots.

    Cells are independent; NaN means / zero weights are ignored."""
    G, T, N = means.shape
    order = np.argsort(means, axis=-1)          # NaNs sort to the end
    m = np.take_along_axis(means, order, axis=-1)
    w = np.take_along_axis(weights, order, axis=-1)
    w = np.where(np.isfinite(m), w, 0.0)
    total = w.sum(axis=-1, keepdims=True)       # [G, T, 1]
    cumw = np.cumsum(w, axis=-1)
    qmid = np.where(total > 0, (cumw - w / 2.0) / np.maximum(total, 1e-300),
                    0.0)
    kidx = np.minimum(_k_scale(qmid, compression).astype(np.int64),
                      compression - 1)
    kidx = np.maximum(kidx, 0)
    # scatter-add centroids into their k-bins, all cells at once
    cell = np.arange(G * T).reshape(G, T, 1)
    flat = (cell * compression + kidx).ravel()
    wm_out = np.bincount(flat, weights=(w * np.where(np.isfinite(m), m, 0.0)
                                        ).ravel(),
                         minlength=G * T * compression)
    w_out = np.bincount(flat, weights=w.ravel(),
                        minlength=G * T * compression)
    w_out = w_out.reshape(G, T, compression)
    wm_out = wm_out.reshape(G, T, compression)
    with np.errstate(invalid="ignore"):
        m_out = np.where(w_out > 0, wm_out / np.maximum(w_out, 1e-300),
                         np.nan)
    return TDigest(m_out, w_out)


def from_values(values: np.ndarray, ids: np.ndarray, num_groups: int,
                compression: int = 128) -> TDigest:
    """Build per-(group, step) digests from raw series values.

    ``values`` [S, T] (NaN = no sample), ``ids`` [S] group of each series.
    Memory: O(G * T * C) regardless of S."""
    S, T = values.shape if values.size else (0, values.shape[-1]
                                             if values.ndim == 2 else 0)
    out = TDigest(np.full((num_groups, T, compression), np.nan),
                  np.zeros((num_groups, T, compression)))
    if S == 0 or num_groups == 0:
        return out
    # process series in slabs of <= compression so the intermediate
    # [G, T, N] stays bounded even at very high cardinality
    slab = max(compression, 16)
    for s0 in range(0, S, slab):
        sl_vals = values[s0:s0 + slab]
        sl_ids = ids[s0:s0 + slab]
        n = sl_vals.shape[0]
        # place each series' value into its group's member slot (series j
        # of the slab owns slot j; advanced indexing on axes 0 and 2)
        mem_m = np.full((num_groups, T, n), np.nan)
        mem_w = np.zeros((num_groups, T, n))
        jj = np.arange(n)
        mem_m[sl_ids[:n], :, jj] = sl_vals
        mem_w[sl_ids[:n], :, jj] = np.isfinite(sl_vals).astype(float)
        merged_m = np.concatenate([out.means, mem_m], axis=-1)
        merged_w = np.concatenate([out.weights, mem_w], axis=-1)
        out = _compress(merged_m, merged_w, compression)
    return out


def merge(a: TDigest, b: TDigest) -> TDigest:
    """Merge two digest grids cell-wise (the distributive reduce step)."""
    if a.means.shape[:2] != b.means.shape[:2]:
        raise ValueError(f"digest grids differ: {a.means.shape} vs "
                         f"{b.means.shape}")
    compression = max(a.compression, b.compression)
    return _compress(np.concatenate([a.means, b.means], axis=-1),
                     np.concatenate([a.weights, b.weights], axis=-1),
                     compression)


def quantile(d: TDigest, q: float) -> np.ndarray:
    """Per-cell quantile estimate [G, T]; NaN for empty cells.

    Linear interpolation between centroid mid-weights, matching the
    classic t-digest estimator."""
    m, w = d.means, d.weights
    C = d.compression
    # pack occupied centroids to the left (k-bins are sparse); bin means
    # are already ascending among occupied slots, so a stable sort on
    # the emptiness flag preserves value order
    occupied = w > 0
    order = np.argsort(~occupied, axis=-1, kind="stable")
    m = np.take_along_axis(m, order, axis=-1)
    w = np.take_along_axis(w, order, axis=-1)
    n_occ = occupied.sum(axis=-1)                 # [G, T]
    total = w.sum(axis=-1)
    cumw = np.cumsum(w, axis=-1)
    mid = cumw - w / 2.0                          # centroid mid positions
    target = q * total                            # [G, T]
    idx = ((mid < target[..., None]) & (w > 0)).sum(axis=-1)  # [G, T]
    i1 = np.clip(idx, 0, np.maximum(n_occ - 1, 0))[..., None]
    i0 = np.clip(idx - 1, 0, np.maximum(n_occ - 1, 0))[..., None]
    y1 = np.take_along_axis(m, i1, axis=-1)[..., 0]
    y0 = np.take_along_axis(m, i0, axis=-1)[..., 0]
    x1 = np.take_along_axis(mid, i1, axis=-1)[..., 0]
    x0 = np.take_along_axis(mid, i0, axis=-1)[..., 0]
    denom = x1 - x0
    with np.errstate(invalid="ignore"):
        frac = np.where(denom > 0,
                        (target - x0) / np.maximum(denom, 1e-300), 0.0)
    frac = np.clip(frac, 0.0, 1.0)
    out = y0 + frac * (y1 - y0)
    # edges: clamp to the extreme centroid means
    first = m[..., 0]
    last = np.take_along_axis(
        m, np.maximum(n_occ - 1, 0)[..., None], axis=-1)[..., 0]
    lastmid = np.take_along_axis(
        mid, np.maximum(n_occ - 1, 0)[..., None], axis=-1)[..., 0]
    out = np.where(idx <= 0, first, out)
    out = np.where(target >= lastmid, last, out)
    return np.where(total > 0, out, np.nan)


def from_members(members: np.ndarray, compression: int = 128) -> TDigest:
    """Convert a dense member matrix [G, M, T] (the exact-path partial
    state) into digests — used when reducing mixed exact/digest partials."""
    G, M, T = members.shape
    vals = np.transpose(members, (0, 2, 1))       # [G, T, M]
    weights = np.isfinite(vals).astype(float)
    means = np.where(np.isfinite(vals), vals, np.nan)
    return _compress(means, weights, compression)
