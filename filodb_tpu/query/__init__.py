"""Query engine: logical plans, exec plans, transformers, aggregators
(reference: query/src/main/scala/filodb/query/ + filodb.query.exec)."""

from filodb_tpu.query.model import (PeriodicBatch, QueryContext, QueryError,
                                    QueryResult, RawBatch, ScalarResult)
from filodb_tpu.query.logical import *  # noqa: F401,F403 - plan ADT surface

__all__ = ["PeriodicBatch", "QueryContext", "QueryError", "QueryResult",
           "RawBatch", "ScalarResult"]
