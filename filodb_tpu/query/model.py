"""Query result model: batched range vectors.

The reference materializes per-series ``RangeVector`` cursors
(reference: core/src/main/scala/filodb.core/query/RangeVector.scala:271,305,
SerializedRangeVector).  TPU-native results stay *batched*: one
``PeriodicBatch`` holds S series x T steps as a dense array, so every
transformer is an array->array function and serialization is one buffer, not
S iterators.  ``to_series`` unpacks at the API edge only.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.core.chunk import ChunkBatch
from filodb_tpu.ops.windows import StepRange


@dataclasses.dataclass
class QueryContext:
    """Per-query knobs (reference: core/query/QueryContext.scala:22)."""

    query_id: str = ""
    submit_time_ms: int = 0
    sample_limit: int = 1_000_000
    group_by_cardinality_limit: int = 100_000
    timeout_ms: int = 30_000
    spread: Optional[int] = None
    origin: str = ""
    # end-to-end trace id, minted at the HTTP/planner entry point and
    # propagated across remote dispatch (header + execplan-wire field)
    # so scatter-gather fan-out stitches into one span tree
    trace_id: str = ""
    # workload management (ISSUE 5, filodb_tpu/workload):
    # - deadline_ms: ABSOLUTE epoch-ms deadline minted at the HTTP entry
    #   (submit_time + timeout); 0 = no deadline.  Travels the wire as a
    #   RELATIVE budget (wall clocks differ between nodes) and caps
    #   every downstream wait/dispatch timeout
    # - tenant/priority: admission-control identity + class
    # - allow_partial_results: a down shard degrades to a warned partial
    #   result instead of failing the whole scatter-gather
    deadline_ms: int = 0
    tenant: str = ""
    priority: str = "default"
    allow_partial_results: bool = False
    # tiered-resolution serving (filodb_tpu/rollup):
    # - resolution_pref: the ?resolution= request knob — "" /"auto"
    #   lets the router pick, "raw" pins the raw dataset, an explicit
    #   duration ("1m"/"15m"/"1h") pins that tier
    # - rollup_resolution_ms: stamped by the router at materialize time
    #   with the tier it chose (0 = raw); the HTTP layer folds it into
    #   QueryStats + the query.execute span
    resolution_pref: str = ""
    rollup_resolution_ms: int = 0
    # True when a RollupRouterPlanner actually made a resolution
    # decision for this query (including "raw"): the HTTP layer tags
    # the query.execute span with the decision only for routed
    # datasets, so an un-tiered dataset's spans stay clean (ISSUE 15)
    rollup_routed: bool = False
    # storage tiers the router stitched for this query, appended at
    # materialize time in time order (e.g. ["rolled-cold",
    # "rolled-local", "raw"]); the HTTP layer folds them into
    # QueryStats.tiers + the query.execute span (ISSUE 16)
    rollup_tiers: list = dataclasses.field(default_factory=list)
    # ?downsample=<pixels>: M4 visualization downsampling target —
    # <= ~4*pixels pixel-exact points per series come back instead of
    # every raw step (0 = off; ISSUE 16)
    downsample_pixels: int = 0
    # fleet batching tier (ISSUE 20, filodb_tpu/batching):
    # - admission_permit: the live _Permit while this query executes
    #   inside its admission window (stamped by AdmissionController's
    #   permit context manager, cleared on release) — the batch leader
    #   re-checks it at stack time, so no batched member ever executes
    #   outside its own admission window
    # - batch_key: the insights ledger's batch-compatibility key,
    #   stamped by _exec so realized group sizes land next to the
    #   co-arrival headroom estimate for the same key
    admission_permit: object = None
    batch_key: str = ""


@dataclasses.dataclass
class QueryStats:
    samples_scanned: int = 0
    series_scanned: int = 0
    shards_queried: int = 0
    dropped_series: int = 0
    # quarantined (corrupt) chunks overlapping the scanned series: the
    # result is PARTIAL and the API layers surface a warning
    # (filodb_tpu/integrity quarantine exclusion)
    corrupt_chunks_excluded: int = 0
    # per-query resource accounting (ISSUE 2): scan-volume counters and
    # per-stage wall-time buckets (seconds, keys: plan/queue/scan/
    # decode/device_compute/serialize).  Accumulated on the shared
    # ExecContext, folded up the exec tree like corrupt_chunks_excluded,
    # and returned under data.stats when stats=true
    chunks_scanned: int = 0
    bytes_scanned: int = 0
    pages_in: int = 0
    timings: dict = dataclasses.field(default_factory=dict)
    # device-grid HBM bytes read under the device_compute stage, split
    # by resident format (keys "dense"/"compressed") — makes the format
    # actually serving traffic observable (ISSUE 3; the compressed
    # resident reads ~2.5 B/sample vs 4 for decoded planes)
    hbm_read_bytes: dict = dataclasses.field(default_factory=dict)
    # net change in ledger-tracked HBM residency this query caused
    # (ISSUE 4: blocks committed minus blocks evicted/freed while the
    # query's ExecContext was active); 0 for a fully warm query
    hbm_resident_delta_bytes: int = 0
    # shards whose dispatch failed but were degraded to an empty result
    # because the query set allow_partial_results (ISSUE 5): the result
    # is PARTIAL and the API layers surface a warning + header
    shards_down: int = 0
    # tiered-resolution serving (filodb_tpu/rollup): the coarsest rolled
    # tier that served (part of) this query, 0 = raw only.  Stamped by
    # the HTTP layer from the router's materialize-time choice and
    # visible under data.stats with stats=true
    resolution_ms: int = 0
    # query-frontend result cache (query/resultcache.py): result
    # samples served from memoized immutable-chunk partials vs samples
    # re-scanned fresh this evaluation — the cached-vs-recomputed split
    # under data.stats.resultCache with stats=true
    resultcache_cached_samples: int = 0
    resultcache_recomputed_samples: int = 0
    # cold tier (ISSUE 16, filodb_tpu/coldstore): chunks/bytes this
    # query pulled from the object bucket — 0 on a bucket-miss-free
    # query, so dashboards can tell a slow cold panel from a warm one
    cold_chunks_paged: int = 0
    cold_bytes_read: int = 0
    # storage tiers that served (part of) this query, "+"-joined in
    # time order ("rolled-cold+rolled-local+raw"); "" = un-routed
    tiers: str = ""
    # ?downsample= (ISSUE 16): finite points entering the M4
    # downsampler vs pixel-exact points kept (0/0 = not requested)
    downsample_points_in: int = 0
    downsample_points_out: int = 0
    # kernel flight deck (ISSUE 15, utils/devicewatch.KernelTimer):
    # measured device seconds per wrapped program, from the launches
    # SAMPLED while this query's ExecContext was active — the
    # per-program split of the device_compute timing bucket, so a slow
    # query names its offending kernel (data.stats.devicePrograms +
    # the query.execute span tag + /admin/slowlog)
    device_programs: dict = dataclasses.field(default_factory=dict)

    def merge(self, other: "QueryStats") -> None:
        self.samples_scanned += other.samples_scanned
        self.series_scanned += other.series_scanned
        self.shards_queried += other.shards_queried
        self.dropped_series += other.dropped_series
        self.corrupt_chunks_excluded += other.corrupt_chunks_excluded
        self.chunks_scanned += other.chunks_scanned
        self.bytes_scanned += other.bytes_scanned
        self.pages_in += other.pages_in
        for k, v in other.timings.items():
            self.timings[k] = self.timings.get(k, 0.0) + v
        for k, v in other.hbm_read_bytes.items():
            self.hbm_read_bytes[k] = self.hbm_read_bytes.get(k, 0) + v
        self.hbm_resident_delta_bytes += other.hbm_resident_delta_bytes
        self.shards_down += other.shards_down
        # coarsest tier wins: a stitched raw+rolled answer reports the
        # rolled resolution it leaned on
        self.resolution_ms = max(self.resolution_ms, other.resolution_ms)
        self.resultcache_cached_samples += other.resultcache_cached_samples
        self.resultcache_recomputed_samples += \
            other.resultcache_recomputed_samples
        self.cold_chunks_paged += other.cold_chunks_paged
        self.cold_bytes_read += other.cold_bytes_read
        if other.tiers and other.tiers != self.tiers:
            mine = self.tiers.split("+") if self.tiers else []
            mine += [t for t in other.tiers.split("+") if t not in mine]
            self.tiers = "+".join(mine)
        self.downsample_points_in += other.downsample_points_in
        self.downsample_points_out += other.downsample_points_out
        for k, v in other.device_programs.items():
            self.device_programs[k] = self.device_programs.get(k, 0.0) + v

    def add_timing(self, stage: str, seconds: float) -> None:
        self.timings[stage] = self.timings.get(stage, 0.0) + seconds


class QueryError(Exception):
    """Query failed (reference: filodb.query.QueryError)."""

    def __init__(self, query_id: str, message: str):
        super().__init__(message)
        self.query_id = query_id


class ShardUnavailable(QueryError):
    """A shard's dispatch failed at the TRANSPORT level (connection
    refused/reset/timed out after retries, or no endpoint configured) —
    distinct from a semantic QueryError so scatter-gather can degrade
    to a warned partial result when ``allow_partial_results`` is set
    (ISSUE 5; reference: PartialResults support in QueryResult).

    ``reason`` (ISSUE 7) tags the failure class for failover telemetry
    — "refused" (the node answered 503: overload/budget refusal),
    "unreachable" (connection-level, the default), "no_endpoint" —
    set at the raise site; substring-matching the message would
    misread urllib's "[Errno 111] Connection refused" as a work
    refusal."""

    reason: str = "unreachable"


@dataclasses.dataclass
class RawBatch:
    """Leaf-scan output: irregular samples as a padded ChunkBatch + keys."""

    keys: list[dict]
    batch: Optional[ChunkBatch]

    @property
    def num_series(self) -> int:
        return len(self.keys)


@dataclasses.dataclass
class PeriodicBatch:
    """S series sampled on a regular step grid: values [S, T] (NaN = no
    sample at that step) or hist [S, T, B].

    ``values`` may carry MORE rows than ``keys`` — the series axis stays
    padded for stable jit shapes; padding rows are NaN.  Device kernels
    consume ``values`` as-is; host consumers use :meth:`np_values`, which
    slices to the real series."""

    keys: list[dict]
    steps: StepRange
    values: np.ndarray
    hist: Optional[np.ndarray] = None
    bucket_tops: Optional[np.ndarray] = None

    @property
    def num_series(self) -> int:
        return len(self.keys)

    def np_values(self) -> np.ndarray:
        return np.asarray(self.values)[:len(self.keys)]

    def to_series(self) -> list[tuple[dict, np.ndarray, np.ndarray]]:
        """Unpack to [(tags, step_timestamps, values)] at the API edge."""
        ts = np.asarray(self.steps.timestamps())
        vals = self.np_values()
        return [(self.keys[i], ts, vals[i]) for i in range(len(self.keys))]


@dataclasses.dataclass
class ScalarResult:
    """A scalar-per-step result (scalar(), time(), fixed scalars)."""

    steps: StepRange
    values: np.ndarray  # [T]

    @property
    def num_series(self) -> int:
        return 1


@dataclasses.dataclass
class QueryResult:
    """Result of one ExecPlan (reference: filodb.query.QueryResult)."""

    query_id: str
    batches: list  # RawBatch | PeriodicBatch | ScalarResult | AggPartialBatch
    stats: QueryStats = dataclasses.field(default_factory=QueryStats)

    @property
    def num_series(self) -> int:
        return sum(b.num_series for b in self.batches)


def concat_periodic(batches: Sequence[PeriodicBatch]) -> Optional[PeriodicBatch]:
    """Concatenate PeriodicBatches along the series axis (steps must match)."""
    batches = [b for b in batches if b is not None and b.num_series > 0]
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    first = batches[0]
    for b in batches[1:]:
        if b.steps != first.steps:
            raise ValueError(f"step mismatch: {b.steps} vs {first.steps}")
    keys = [k for b in batches for k in b.keys]
    values = np.concatenate([b.np_values()[:len(b.keys)] for b in batches])
    hist = None
    tops = first.bucket_tops
    if first.hist is not None:
        bmax = max(b.hist.shape[2] for b in batches)
        hs = []
        for b in batches:
            h = np.asarray(b.hist)[:len(b.keys)]
            if h.shape[2] < bmax:
                h = np.pad(h, ((0, 0), (0, 0), (0, bmax - h.shape[2])),
                           mode="edge")
            hs.append(h)
            if b.bucket_tops is not None and (tops is None or
                                              len(b.bucket_tops) > len(tops)):
                tops = b.bucket_tops
        hist = np.concatenate(hs)
    return PeriodicBatch(keys, first.steps, values, hist, tops)
