"""Schema-driven plan rewrites for downsampled / hist-max schemas.

The reference finalizes the leaf plan AFTER schema discovery: for the
downsample-gauge schema it selects the right aggregate columns and swaps
the range function (reference: query/src/main/scala/filodb/query/exec/
MultiSchemaPartitionsExec.scala:41-85, SelectRawPartitionsExec.scala:40-96,
rangefn/RangeFunction.scala:238-267 downsampleColsFromRangeFunction /
downsampleRangeFunction); for histogram schemas carrying a ``max`` double
column it pairs the hist kernel with a max kernel (histMaxRangeFunction,
RangeFunction.scala:359-365).

Without these rewrites, ``min_over_time``/``max_over_time``/``sum_over_time``/
``count_over_time``/``avg_over_time`` over a downsampled gauge would compute
over the per-period *averages* — wrong results, not just missing speed.
"""

from __future__ import annotations

from typing import Optional

from filodb_tpu.core.schemas import ColumnType, DataSchema
from filodb_tpu.query.logical import RangeFunctionId as F

# ds-gauge aggregate columns, all doubles (reference ds-gauge schema,
# filodb-defaults.conf: min/max/sum/count/avg with value-column avg)
_DS_GAUGE_COLS = frozenset(["min", "max", "sum", "count", "avg"])


def is_ds_gauge(data: DataSchema) -> bool:
    """Downsample-gauge detection by column signature (robust to custom
    schema names, unlike the reference's identity check vs Schemas.dsGauge):
    every aggregate column present as a double, value column = avg."""
    if data.value_column != "avg":
        return False
    doubles = {c.name for c in data.columns if c.ctype == ColumnType.DOUBLE}
    return _DS_GAUGE_COLS <= doubles


def hist_max_column(data: DataSchema) -> Optional[int]:
    """Column id of the ``max`` double column when the schema also has a
    histogram column (reference: SelectRawPartitionsExec.histMaxColumn)."""
    if not any(c.ctype == ColumnType.HISTOGRAM for c in data.columns):
        return None
    for c in data.columns:
        if c.name == "max" and c.ctype == ColumnType.DOUBLE:
            return c.id
    return None


# func -> (columns to read, function to run over them).  Functions absent
# from this table read the default value column (avg) unchanged — the
# reference maps changes/delta/deriv/stddev/quantile/... to Seq("avg")
# with the original function (RangeFunction.scala:238-258).
_DS_GAUGE_REWRITES = {
    F.MIN_OVER_TIME: (("min",), F.MIN_OVER_TIME),
    F.MAX_OVER_TIME: (("max",), F.MAX_OVER_TIME),
    F.SUM_OVER_TIME: (("sum",), F.SUM_OVER_TIME),
    # count over periods = sum of the per-period counts
    F.COUNT_OVER_TIME: (("count",), F.SUM_OVER_TIME),
    # avg = sum(period sums) / sum(period counts): the reference's
    # AvgWithSumAndCountOverTime (AggrOverTimeFunctions.scala:242)
    F.AVG_OVER_TIME: (("sum", "count"), None),
}


def ds_gauge_rewrite(func: Optional[F]):
    """Return (columns, new_func) for a ds-gauge read, or None when the
    default value column (avg) with the original function is already
    correct.  new_func None means the two-column AvgWithSumAndCount path.
    """
    if func is None:
        return None
    return _DS_GAUGE_REWRITES.get(func)
