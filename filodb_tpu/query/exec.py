"""ExecPlan tree: scatter-gather physical plans.

Mirrors the reference's ExecPlan machinery (reference: query/src/main/scala/
filodb/query/exec/ExecPlan.scala:40,278,337): ``execute`` = do_execute then
apply transformers then enforce limits; non-leaf plans dispatch children via
their PlanDispatcher and compose.  The in-process dispatcher is the local
path; the shard/mesh dispatchers live in filodb_tpu.parallel.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import threading
import time
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.ops import instant as instant_ops
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query.aggregators import AggPartialBatch, aggregator_for
from filodb_tpu.query import logical as lp
from filodb_tpu.query.logical import (AggregationOperator, BinaryOperator,
                                      Cardinality, ScalarFunctionId)
from filodb_tpu.query.model import (PeriodicBatch, QueryContext, QueryError,
                                    QueryResult, QueryStats, RawBatch,
                                    ScalarResult, ShardUnavailable,
                                    concat_periodic)
from filodb_tpu.query.transformers import RangeVectorTransformer, _drop_metric
from filodb_tpu.utils.observability import TRACER

# the ExecContext of the scan running on THIS thread: lower layers that
# have no ctx parameter (ODP page-in, predecode) attribute their stage
# timings / page-in counters to the active query through it
_ACTIVE = threading.local()


def active_exec_ctx() -> Optional["ExecContext"]:
    return getattr(_ACTIVE, "ctx", None)


@dataclasses.dataclass
class ExecContext:
    """What a plan needs to run locally: the data source + query knobs."""

    memstore: TimeSeriesMemStore
    query_context: QueryContext = dataclasses.field(default_factory=QueryContext)
    parallelism: int = 8
    # quarantined-chunk exclusions noted by leaf scans anywhere in the
    # plan tree (children run concurrently but share this ctx); the root
    # folds the total into QueryStats so the API layer can emit a
    # partial-data warning
    _corrupt_excluded: int = 0
    _corrupt_lock: object = dataclasses.field(
        default_factory=threading.Lock, repr=False)
    # per-stage wall-time + scan-volume accounting (ISSUE 2): leaves and
    # the ODP/device layers note into the shared ctx; remote dispatch
    # absorbs the data node's totals; the root folds the accumulated
    # numbers into its QueryResult's stats (same pattern as
    # corrupt_chunks_excluded — the outermost plan returns last)
    _timings: dict = dataclasses.field(default_factory=dict, repr=False)
    _counters: dict = dataclasses.field(default_factory=dict, repr=False)
    # per-program measured device seconds from launches the kernel
    # timer SAMPLED while this ctx was active (ISSUE 15): the split of
    # the device_compute bucket that names the offending kernel
    _device_programs: dict = dataclasses.field(default_factory=dict,
                                               repr=False)

    # shards degraded to empty results because their dispatch failed and
    # the query allows partial results (ISSUE 5); folds into
    # QueryStats.shards_down for the partial-data warning + header
    _shards_down: int = 0

    def note_corrupt_excluded(self, n: int) -> None:
        with self._corrupt_lock:
            self._corrupt_excluded += n

    def corrupt_excluded(self) -> int:
        return self._corrupt_excluded

    def note_shard_down(self, n: int = 1) -> None:
        with self._corrupt_lock:
            self._shards_down += n

    def note_timing(self, stage: str, seconds: float) -> None:
        with self._corrupt_lock:
            self._timings[stage] = self._timings.get(stage, 0.0) + seconds

    def note_counts(self, samples: int = 0, chunks: int = 0,
                    bytes_: int = 0, pages: int = 0,
                    hbm_dense: int = 0, hbm_compressed: int = 0,
                    hbm_delta: int = 0, hbm_hist: int = 0) -> None:
        with self._corrupt_lock:
            c = self._counters
            if samples:
                c["samples"] = c.get("samples", 0) + samples
            if chunks:
                c["chunks"] = c.get("chunks", 0) + chunks
            if bytes_:
                c["bytes"] = c.get("bytes", 0) + bytes_
            if pages:
                c["pages"] = c.get("pages", 0) + pages
            if hbm_dense:
                c["hbm_dense"] = c.get("hbm_dense", 0) + hbm_dense
            if hbm_compressed:
                c["hbm_compressed"] = c.get("hbm_compressed", 0) \
                    + hbm_compressed
            if hbm_hist:
                # histogram bucket planes served compressed (ISSUE 14)
                c["hbm_hist"] = c.get("hbm_hist", 0) + hbm_hist
            if hbm_delta:
                # signed: the devicewatch ledger credits commits and
                # debits frees caused while this query was active
                c["hbm_delta"] = c.get("hbm_delta", 0) + hbm_delta

    def note_device_program(self, program: str, seconds: float) -> None:
        """Kernel flight deck (utils/devicewatch.KernelTimer): fold a
        sampled launch's measured device seconds into this query's
        per-program split (data.stats.devicePrograms)."""
        with self._corrupt_lock:
            d = self._device_programs
            d[program] = d.get(program, 0.0) + seconds

    def note_resultcache(self, cached: int = 0, recomputed: int = 0) -> None:
        """Result-cache accounting (query/resultcache.py): result
        samples served from memoized partials vs samples re-scanned on
        the fresh/miss path — surfaced under data.stats.resultCache."""
        with self._corrupt_lock:
            c = self._counters
            if cached:
                c["rc_cached"] = c.get("rc_cached", 0) + cached
            if recomputed:
                c["rc_recomputed"] = c.get("rc_recomputed", 0) + recomputed

    def note_cold(self, chunks: int = 0, bytes_: int = 0) -> None:
        """Cold-tier accounting (filodb_tpu/coldstore): chunks/bytes
        this query pulled from the object bucket — surfaced under
        data.stats.coldTier so a slow cold panel is tellable from a
        warm one."""
        with self._corrupt_lock:
            c = self._counters
            if chunks:
                c["cold_chunks"] = c.get("cold_chunks", 0) + chunks
            if bytes_:
                c["cold_bytes"] = c.get("cold_bytes", 0) + bytes_

    def note_downsample(self, points_in: int = 0, points_out: int = 0) -> None:
        """?downsample= accounting (query/transformers.DownsampleMapper):
        finite points entering the M4 kernel vs pixel-exact points kept."""
        with self._corrupt_lock:
            c = self._counters
            c["ds_in"] = c.get("ds_in", 0) + points_in
            c["ds_out"] = c.get("ds_out", 0) + points_out

    def counter(self, name: str) -> int:
        with self._corrupt_lock:
            return self._counters.get(name, 0)

    def absorb_stats_from(self, other: "ExecContext") -> None:
        """Fold a nested sub-context's accumulated accounting into this
        one (the result cache runs fresh segments / delta fetches with
        their own ctx so per-segment volumes are exact)."""
        st = QueryStats()
        other.fold_into(st)
        st.corrupt_chunks_excluded = other.corrupt_excluded()
        self.absorb_stats(st)

    def absorb_stats(self, stats: QueryStats) -> None:
        """Fold a REMOTE child's stats into this query's accounting
        (local children share the ctx and need no absorb)."""
        self.note_counts(samples=stats.samples_scanned,
                         chunks=stats.chunks_scanned,
                         bytes_=stats.bytes_scanned, pages=stats.pages_in,
                         hbm_dense=stats.hbm_read_bytes.get("dense", 0),
                         hbm_compressed=stats.hbm_read_bytes.get(
                             "compressed", 0),
                         hbm_hist=stats.hbm_read_bytes.get(
                             "compressed-hist", 0),
                         hbm_delta=stats.hbm_resident_delta_bytes)
        self.note_resultcache(cached=stats.resultcache_cached_samples,
                              recomputed=stats.resultcache_recomputed_samples)
        if stats.cold_chunks_paged or stats.cold_bytes_read:
            self.note_cold(chunks=stats.cold_chunks_paged,
                           bytes_=stats.cold_bytes_read)
        if stats.downsample_points_in or stats.downsample_points_out:
            self.note_downsample(points_in=stats.downsample_points_in,
                                 points_out=stats.downsample_points_out)
        if stats.corrupt_chunks_excluded:
            self.note_corrupt_excluded(stats.corrupt_chunks_excluded)
        if stats.shards_down:
            self.note_shard_down(stats.shards_down)
        for k, v in stats.timings.items():
            self.note_timing(k, v)
        for k, v in stats.device_programs.items():
            self.note_device_program(k, v)

    def fold_into(self, stats: QueryStats) -> None:
        """Write the accumulated per-stage totals into an outgoing
        QueryResult's stats (overwrite: the ctx holds running totals)."""
        with self._corrupt_lock:
            stats.timings = dict(self._timings)
            c = self._counters
            stats.samples_scanned = c.get("samples", 0)
            stats.chunks_scanned = c.get("chunks", 0)
            stats.bytes_scanned = c.get("bytes", 0)
            stats.pages_in = c.get("pages", 0)
            stats.hbm_read_bytes = {
                k: c[ck] for k, ck in (("dense", "hbm_dense"),
                                       ("compressed", "hbm_compressed"),
                                       ("compressed-hist", "hbm_hist"))
                if c.get(ck)}
            stats.hbm_resident_delta_bytes = c.get("hbm_delta", 0)
            stats.resultcache_cached_samples = c.get("rc_cached", 0)
            stats.resultcache_recomputed_samples = c.get("rc_recomputed", 0)
            stats.cold_chunks_paged = c.get("cold_chunks", 0)
            stats.cold_bytes_read = c.get("cold_bytes", 0)
            stats.downsample_points_in = c.get("ds_in", 0)
            stats.downsample_points_out = c.get("ds_out", 0)
            stats.device_programs = dict(self._device_programs)
            stats.shards_down = self._shards_down


class PlanDispatcher:
    """Moves an ExecPlan to where its data lives (reference:
    PlanDispatcher.scala:20 — ActorPlanDispatcher / InProcessPlanDispatcher).
    """

    def dispatch(self, plan: "ExecPlan", ctx: ExecContext) -> QueryResult:
        raise NotImplementedError


class InProcessDispatcher(PlanDispatcher):
    def dispatch(self, plan, ctx):
        with TRACER.span("dispatch.inprocess",
                         plan=type(plan).__name__):
            return plan.execute(ctx)


IN_PROCESS = InProcessDispatcher()


class ExecPlan:
    def __init__(self, query_context: Optional[QueryContext] = None,
                 dispatcher: PlanDispatcher = IN_PROCESS):
        self.query_context = query_context or QueryContext()
        self.dispatcher = dispatcher
        self.transformers: list[RangeVectorTransformer] = []

    def add_transformer(self, t: RangeVectorTransformer) -> "ExecPlan":
        self.transformers.append(t)
        return self

    @property
    def children(self) -> Sequence["ExecPlan"]:
        return ()

    def do_execute(self, ctx: ExecContext) -> list:
        raise NotImplementedError

    def execute(self, ctx: ExecContext) -> QueryResult:
        # one span per plan node (reference: Kamon.spanBuilder in
        # ExecPlan.execute, ExecPlan.scala:99-126); tags carry the plan
        # type and, for data leaves, dataset/shard.  Span machinery
        # never raises into the query path — reporter failures are
        # swallowed by the tracer
        # deadline tripwire (ISSUE 5): one clock read per plan node so a
        # deep scatter-gather stops burning workers the moment its
        # end-to-end budget is gone (reference: queryTimeoutMillis
        # checked inside ExecPlan execution).  DeadlineExceeded is a
        # QueryError subclass the HTTP layer maps to 503, not 400 — a
        # timed-out query is an overload outcome, not a client bug.
        qctx = self.query_context
        if qctx.deadline_ms:
            from filodb_tpu.workload import deadline as dl
            dl.check(qctx, where=type(self).__name__)
        tags = {"plan": type(self).__name__}
        ds = getattr(self, "dataset", None)
        if ds is not None:
            tags["dataset"] = ds
            tags["shard"] = getattr(self, "shard", "")
        try:
            with TRACER.span("execplan.execute", **tags):
                batches = self.do_execute(ctx)
                for t in self.transformers:
                    batches = t.apply(batches, ctx)
                self._enforce_limits(batches, ctx)
                stats = self._collect_stats(batches)
                # quarantined-chunk exclusions accumulate on the shared
                # ctx; the outermost plan returns last, so its result
                # carries the whole tree's total for the partial-data
                # warning.  Stage timings/counters fold the same way.
                stats.corrupt_chunks_excluded = ctx.corrupt_excluded()
                ctx.fold_into(stats)
                return QueryResult(self.query_context.query_id, batches,
                                   stats)
        except QueryError:
            raise
        except Exception as e:  # noqa: BLE001 - plan failure surfaces as QueryError
            raise QueryError(self.query_context.query_id,
                             f"{type(self).__name__}: {e}") from e

    def _enforce_limits(self, batches, ctx):
        total = 0
        for b in batches:
            if isinstance(b, PeriodicBatch):
                total += len(b.keys) * b.steps.num_steps
        if total > ctx.query_context.sample_limit:
            raise QueryError(
                self.query_context.query_id,
                f"result samples {total} > limit {ctx.query_context.sample_limit}")

    @staticmethod
    def _collect_stats(batches) -> QueryStats:
        st = QueryStats()
        for b in batches:
            st.series_scanned += getattr(b, "num_series", 0)
        return st

    # -- debugging ----------------------------------------------------------

    def print_tree(self, level: int = 0) -> str:
        """Plan-shape dump used by planner tests (reference:
        ExecPlan.printTree)."""
        pad = "-" * level
        lines = [f"{pad}T~{type(t).__name__}" for t in reversed(self.transformers)]
        lines.append(f"{pad}E~{type(self).__name__}({self._args_str()})")
        for c in self.children:
            lines.append(c.print_tree(level + 1))
        return "\n".join(lines)

    def _args_str(self) -> str:
        return ""


class LeafExecPlan(ExecPlan):
    pass


class NonLeafExecPlan(ExecPlan):
    def __init__(self, children: Sequence[ExecPlan],
                 query_context: Optional[QueryContext] = None,
                 dispatcher: PlanDispatcher = IN_PROCESS,
                 parallel_children: bool = True):
        super().__init__(query_context, dispatcher)
        self._children = list(children)
        self.parallel_children = parallel_children

    @property
    def children(self) -> Sequence[ExecPlan]:
        return self._children

    def do_execute(self, ctx: ExecContext) -> list:
        results = self._dispatch_children(ctx)
        return self.compose(results, ctx)

    def _dispatch_children(self, ctx) -> list[QueryResult]:
        """Children run via their own dispatchers, concurrently (reference:
        NonLeafExecPlan.doExecute mapAsync, ExecPlan.scala:370-409).
        The trace context is captured here and re-attached on the pool
        threads so child spans parent onto this plan's span.

        A child whose dispatch fails at the TRANSPORT level
        (ShardUnavailable: shard's node down / unroutable) degrades to
        an empty result when the query set ``allow_partial_results`` —
        the root result then carries ``stats.shards_down`` and the API
        layer emits a Prometheus warning + the X-FiloDB-Partial-Data
        header (ISSUE 5; reference: PartialResults semantics)."""
        kids = self._children

        def one(c):
            try:
                return c.dispatcher.dispatch(c, ctx)
            except ShardUnavailable as e:
                if not ctx.query_context.allow_partial_results:
                    raise
                ctx.note_shard_down()
                TRACER.record("dispatch.shard_down", 0.0,
                              trace_id=ctx.query_context.trace_id or None,
                              shard=str(getattr(c, "shard", "")),
                              error=str(e)[:200])
                return QueryResult(c.query_context.query_id, [],
                                   QueryStats())

        if len(kids) <= 1 or not self.parallel_children:
            return [one(c) for c in kids]
        token = TRACER.capture()

        def run(c):
            with TRACER.attach(token):
                return one(c)

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(len(kids), ctx.parallelism)) as pool:
            futs = [pool.submit(run, c) for c in kids]
            return [f.result() for f in futs]

    def compose(self, results: list[QueryResult], ctx) -> list:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class MultiSchemaPartitionsExec(LeafExecPlan):
    """Leaf scan: index lookup + device batch materialization (reference:
    exec/MultiSchemaPartitionsExec.scala:27 + SelectRawPartitionsExec)."""

    def __init__(self, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], start_ms: int, end_ms: int,
                 column: Optional[str] = None,
                 query_context: Optional[QueryContext] = None,
                 dispatcher: PlanDispatcher = IN_PROCESS,
                 reshard_to: Optional[tuple] = None):
        super().__init__(query_context, dispatcher)
        self.dataset = dataset
        self.shard = shard
        self.filters = list(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms
        self.column = column
        # elastic resharding (ISSUE 13): (total_shards, ingest_spread)
        # stamped by the planner when this shard is a split PARENT whose
        # migrated half must be excluded from the scan (the child serves
        # it).  Plan-time stamping keeps one query on one topology view
        # even when the cutover commits mid-flight; travels the wire
        # with the leaf (query/wire.py).
        self.reshard_to = tuple(reshard_to) if reshard_to else None

    def do_execute(self, ctx: ExecContext) -> list:
        # the leaf owns the "scan" stage bucket; lower layers without a
        # ctx parameter (ODP page-in, predecode) attribute theirs
        # through the active-ctx thread-local installed here
        t0 = time.perf_counter()
        prev = getattr(_ACTIVE, "ctx", None)
        _ACTIVE.ctx = ctx
        try:
            shard = ctx.memstore.get_shard(self.dataset, self.shard)
            lookup = shard.lookup_partitions(self.filters, self.start_ms,
                                             self.end_ms)
            if self.reshard_to is not None:
                lookup = shard.filter_resharded(lookup, *self.reshard_to)
            try:
                batches = self._do_scan(ctx, shard, lookup)
                self._note_batch_counts(ctx, batches)
                return batches
            finally:
                # AFTER the scan, so corruption detected by this very
                # query already counts toward its own partial-data warning
                self._note_quarantined(ctx, shard, lookup.part_ids)
        finally:
            _ACTIVE.ctx = prev
            ctx.note_timing("scan", time.perf_counter() - t0)

    @staticmethod
    def _note_batch_counts(ctx: ExecContext, batches) -> None:
        """Scan-volume accounting from what the leaf actually returned."""
        samples = nbytes = 0
        for b in batches:
            if isinstance(b, PeriodicBatch):
                samples += len(b.keys) * b.steps.num_steps
                nbytes += getattr(b.values, "nbytes", 0)
            elif isinstance(b, RawBatch) and b.batch is not None:
                samples += int(np.asarray(b.batch.row_counts).sum())
                nbytes += getattr(b.batch.values, "nbytes", 0)
            elif isinstance(b, AggPartialBatch):
                for v in b.state.values():
                    nbytes += getattr(v, "nbytes", 0)
        if samples or nbytes:
            ctx.note_counts(samples=samples, bytes_=nbytes)

    @staticmethod
    def _grid_timed(fn, *args, **kw):
        """Run a device-grid serving call, attributing its wall time to
        the active query's device_compute stage bucket."""
        ctx = active_exec_ctx()
        if ctx is None:
            return fn(*args, **kw)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kw)
        finally:
            ctx.note_timing("device_compute", time.perf_counter() - t0)

    def _do_scan(self, ctx: ExecContext, shard, lookup) -> list:
        schema = None
        if lookup.first_schema_hash is not None:
            schema = shard.schemas.by_hash(lookup.first_schema_hash)
        column_id = None
        if self.column is not None and schema is not None:
            column_id = schema.data.column(self.column).id
        elif schema is not None:
            # schema-driven rewrites AFTER discovery, BEFORE scanning
            # (reference: MultiSchemaPartitionsExec.finalizePlan :41-85)
            served = self._try_schema_rewrite(shard, lookup.part_ids, schema)
            if served is not None:
                return served
        served = self._try_device_grid(shard, lookup.part_ids, column_id)
        if served is not None:
            return served
        tags, batch = shard.scan_batch(lookup.part_ids, self.start_ms,
                                       self.end_ms, column_id)
        return [RawBatch(tags, batch)]

    def _note_quarantined(self, ctx: ExecContext, shard, part_ids) -> None:
        """Partial-data tripwire: quarantined chunks among the scanned
        series AND overlapping this query's time range mean the result
        excludes data — now and on every re-query (quarantine persists
        until cleared).  A corrupt chunk outside the window excluded
        nothing from THIS result, so it must not flag it.  O(1) when
        the quarantine is empty, the overwhelmingly common case."""
        from filodb_tpu.integrity import QUARANTINE
        if not QUARANTINE:
            return
        pks = []
        for pid in part_ids:
            try:
                pks.append(shard.index.partkey(int(pid)))
            except KeyError:
                continue
        n = QUARANTINE.count_overlapping(pks, self.start_ms, self.end_ms)
        if n:
            ctx.note_corrupt_excluded(n)

    # -- downsample-gauge & hist-max schema rewrites ------------------------

    def _first_mapper(self):
        from filodb_tpu.query.transformers import PeriodicSamplesMapper
        if not self.transformers:
            return None
        mapper = self.transformers[0]
        if not isinstance(mapper, PeriodicSamplesMapper):
            return None
        if not mapper.well_formed:
            return None
        return mapper

    def _try_schema_rewrite(self, shard, part_ids, schema):
        """ds-gauge column selection + range-function swap, and hist+max
        column pairing (see filodb_tpu.query.dsrewrite).  Returns leaf
        batches (already stepped — the mapper passes them through) or
        None when no rewrite applies."""
        from filodb_tpu.query import dsrewrite
        mapper = self._first_mapper()
        if mapper is None or len(part_ids) == 0:
            return None
        if dsrewrite.is_ds_gauge(schema.data):
            return self._execute_ds_gauge(shard, part_ids, schema, mapper)
        if dsrewrite.hist_max_column(schema.data) is not None:
            return self._execute_hist_max(shard, part_ids, schema, mapper)
        return None

    def _scan_stepped(self, shard, part_ids, steps, window_ms, func, cid,
                      fargs=()):
        """One column read + windowed range function, grid-served when
        possible: returns (tags, values, bucket_tops) with values
        [len(tags), T] ([len(tags), T, hb] for hist columns)."""
        from filodb_tpu.query import rangefns
        got = self._grid_timed(shard.scan_grid, part_ids, func, steps.start,
                               steps.num_steps, steps.step, window_ms, cid,
                               fargs=fargs)
        if got is not None:
            return got
        tags, batch = shard.scan_batch(part_ids, self.start_ms, self.end_ms,
                                       cid)
        if batch is None or not tags:
            return None
        vals = np.asarray(rangefns.apply_range_function(
            batch, steps, window_ms, func, fargs))
        tops = np.asarray(batch.bucket_tops) if batch.hist is not None \
            else None
        # scan_batch pads the series axis; trim to the real tag rows so
        # paired two-column reads stay row-aligned
        return tags, vals[:len(tags)], tops

    @staticmethod
    def _align_pair(got_a, got_b):
        """Row-align two independently scanned planes by series tags.
        One plane can be grid-served ([n, T] exact) while the other
        fell back to scan_batch, and a partition evicted between the
        two scans can drop a row from one side only — intersect on the
        tag identity so series are never cross-paired."""
        tags_a, va, tops_a = got_a
        tags_b, vb, _ = got_b
        if tags_a == tags_b:
            return tags_a, va, vb, tops_a
        def key(t):
            return tuple(sorted(t.items()))
        idx_b = {key(t): i for i, t in enumerate(tags_b)}
        keep_a, keep_b, tags = [], [], []
        for i, t in enumerate(tags_a):
            j = idx_b.get(key(t))
            if j is not None:
                keep_a.append(i)
                keep_b.append(j)
                tags.append(t)
        if not tags:
            return None
        return tags, np.asarray(va)[keep_a], np.asarray(vb)[keep_b], tops_a

    def _execute_ds_gauge(self, shard, part_ids, schema, mapper):
        from filodb_tpu.query import dsrewrite
        from filodb_tpu.query.logical import RangeFunctionId as F
        rw = dsrewrite.ds_gauge_rewrite(mapper.function)
        if rw is None:
            return None        # default avg column is already correct
        cols, func = rw
        steps, report = mapper.step_ranges()
        window = mapper.effective_window_ms
        if func is not None:
            cid = schema.data.column(cols[0]).id
            got = self._scan_stepped(shard, part_ids, steps, window, func,
                                     cid, tuple(mapper.function_args))
            if got is None:
                return []
            tags, vals, _ = got
            return [PeriodicBatch(tags, report, vals)]
        # AvgWithSumAndCountOverTime: sum(period sums) / sum(period counts)
        sum_cid = schema.data.column("sum").id
        cnt_cid = schema.data.column("count").id
        got_s = self._scan_stepped(shard, part_ids, steps, window,
                                   F.SUM_OVER_TIME, sum_cid)
        got_c = self._scan_stepped(shard, part_ids, steps, window,
                                   F.SUM_OVER_TIME, cnt_cid)
        if got_s is None or got_c is None:
            return []
        pair = self._align_pair(got_s, got_c)
        if pair is None:
            return []
        tags, sums, counts, _ = pair
        with np.errstate(invalid="ignore", divide="ignore"):
            vals = np.where(counts > 0, sums / counts, np.nan)
        return [PeriodicBatch(tags, report, vals)]

    def _execute_hist_max(self, shard, part_ids, schema, mapper):
        """Histogram schema with a max column: pair the hist kernel with
        the max column so histogram_max_quantile sees both planes
        (reference: histMaxRangeFunction — None -> LastSampleHistMax,
        sum_over_time -> SumAndMaxOverTime)."""
        from filodb_tpu.query import dsrewrite
        from filodb_tpu.query.logical import RangeFunctionId as F
        if mapper.function not in (None, F.SUM_OVER_TIME):
            return None        # rate/increase etc: hist column only
        steps, report = mapper.step_ranges()
        window = mapper.effective_window_ms
        hist_cid = schema.data.value_column_id
        max_cid = dsrewrite.hist_max_column(schema.data)
        max_func = None if mapper.function is None else F.MAX_OVER_TIME
        got_h = self._scan_stepped(shard, part_ids, steps, window,
                                   mapper.function, hist_cid)
        if got_h is None:
            return []
        got_m = self._scan_stepped(shard, part_ids, steps, window,
                                   max_func, max_cid)
        if got_m is None:
            return []
        pair = self._align_pair(got_h, got_m)
        if pair is None:
            return []
        tags, hvals, mvals, tops = pair
        return [PeriodicBatch(tags, report, mvals, hist=hvals,
                              bucket_tops=tops)]

    # derived from the mesh table so the single-device fused path and the
    # grid x mesh path can never diverge on which ops are fused
    from filodb_tpu.parallel.meshgrid import GRID_MESH_OPS as _MESH_OPS
    _GRID_AGG_OPS = {op.name: v for op, v in _MESH_OPS.items()}
    del _MESH_OPS

    def _try_device_grid(self, shard, part_ids, column_id):
        """Serve leaf + PeriodicSamplesMapper straight from the shard's
        device-resident grid (memstore/devicestore.py) when the first
        transformer is an eligible windowed rate/increase.  Emits the
        already-stepped PeriodicBatch; the mapper passes it through.
        When an AggregateMapReduce follows the mapper, the aggregation
        is fused ON DEVICE too: only [G, T] partials cross the host
        link, which dominates served latency on tunnel-attached TPUs."""
        from filodb_tpu.query.transformers import (AggregateMapReduce,
                                                   PeriodicSamplesMapper)
        if not self.transformers or len(part_ids) == 0:
            return None
        mapper = self.transformers[0]
        if not isinstance(mapper, PeriodicSamplesMapper):
            return None
        if not mapper.well_formed:
            return None   # half-specified windowing: general path decides
        # bare instant selector: the staleness lookback is a
        # last-sample-in-window scan the grid serves directly
        window_ms = mapper.effective_window_ms
        steps, report = mapper.step_ranges()
        mapred = self.transformers[1] if len(self.transformers) > 1 else None
        if isinstance(mapred, AggregateMapReduce) and not mapred.params \
                and mapred.operator.name in self._GRID_AGG_OPS:
            served = self._try_grid_aggregated(shard, part_ids, column_id,
                                               mapper, mapred, steps, report,
                                               window_ms)
            if served is not None:
                return served
        got = self._grid_timed(shard.scan_grid, part_ids, mapper.function,
                               steps.start, steps.num_steps, steps.step,
                               window_ms, column_id,
                               fargs=tuple(mapper.function_args))
        if got is None:
            return None
        tags, vals, tops = got
        if vals.ndim == 3:      # histogram column: per-bucket [S, T, hb]
            return [PeriodicBatch(tags, report,
                                  np.full(vals.shape[:2], np.nan),
                                  hist=vals, bucket_tops=tops)]
        return [PeriodicBatch(tags, report, vals)]

    def _try_grid_aggregated(self, shard, part_ids, column_id, mapper,
                             mapred, steps, report, window_ms):
        from filodb_tpu.query.aggregators import (AggPartialBatch,
                                                  grouping_key)
        union: dict[tuple, int] = {}
        if not mapred.by and not mapred.without:
            # global aggregate: one group, skip the per-series key walk
            # (missing partitions are detected by the cache's plan walk)
            union[()] = 0
            gids = [0] * len(part_ids)
        else:
            gids = []
            for pid in part_ids:
                part = shard.grid_partition(int(pid))
                if part is None:
                    return None
                key = tuple(sorted(grouping_key(part.tags, mapred.by,
                                                mapred.without).items()))
                gids.append(union.setdefault(key, len(union)))
        state = self._grid_timed(
            shard.scan_grid_grouped, part_ids, mapper.function, steps.start,
            steps.num_steps, steps.step, window_ms, gids,
            max(len(union), 1), self._GRID_AGG_OPS[mapred.operator.name],
            column_id, fargs=tuple(mapper.function_args))
        if state is None:
            return None
        # the fused path never materializes per-series batches, so the
        # scanned volume is accounted here: S series x T steps of
        # windowed input went through the device program
        ctx = active_exec_ctx()
        if ctx is not None:
            ctx.note_counts(samples=len(part_ids) * steps.num_steps)
        tops = state.pop("bucket_tops", None)
        return [AggPartialBatch(mapred.operator, (),
                                [dict(k) for k in union], report, state,
                                bucket_tops=tops)]

    def _args_str(self) -> str:
        return f"dataset={self.dataset}, shard={self.shard}, " \
               f"filters={self.filters}, start={self.start_ms}, end={self.end_ms}"


class EmptyResultExec(LeafExecPlan):
    def do_execute(self, ctx):
        return []


class PartKeysExec(LeafExecPlan):
    """Metadata: series keys matching filters (reference:
    exec/MetadataExecPlan.scala PartKeysExec)."""

    def __init__(self, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], start_ms: int, end_ms: int,
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS,
                 reshard_to: Optional[tuple] = None):
        super().__init__(query_context, dispatcher)
        self.dataset = dataset
        self.shard = shard
        self.filters = list(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms
        # split-parent exclusion, as on MultiSchemaPartitionsExec — a
        # migrated series must be listed by its child only
        self.reshard_to = tuple(reshard_to) if reshard_to else None

    def do_execute(self, ctx):
        shard = ctx.memstore.get_shard(self.dataset, self.shard)
        keys = shard.part_keys(self.filters, self.start_ms, self.end_ms)
        if self.reshard_to is not None:
            from filodb_tpu.parallel.shardmap import shard_of_tags
            total, spread = self.reshard_to
            keys = [t for t in keys
                    if shard_of_tags(t, total, spread) == self.shard]
        return [keys]


class SelectChunkInfosExec(LeafExecPlan):
    """Chunk-level metadata for matching partitions (reference:
    exec/SelectChunkInfosExec.scala): per series, the frozen chunks'
    id/rows/time-range/encoded-bytes plus the write-buffer row count —
    the observability surface for retention and compression debugging."""

    def __init__(self, dataset: str, shard: int,
                 filters: Sequence[ColumnFilter], start_ms: int, end_ms: int,
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(query_context, dispatcher)
        self.dataset = dataset
        self.shard = shard
        self.filters = list(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms

    def do_execute(self, ctx):
        shard = ctx.memstore.get_shard(self.dataset, self.shard)
        lookup = shard.lookup_partitions(self.filters, self.start_ms,
                                         self.end_ms)
        out = []
        for pid in lookup.part_ids:
            part = shard.partitions.get(int(pid))
            if part is None:
                continue
            chunks = []
            for cs in part.chunks:
                info = cs.info
                if info.end_time < self.start_ms or \
                        info.start_time > self.end_ms:
                    continue
                chunks.append({
                    "chunk_id": int(info.chunk_id),
                    "num_rows": int(info.num_rows),
                    "start_time": int(info.start_time),
                    "end_time": int(info.end_time),
                    "bytes": int(cs.nbytes)})
            out.append({"tags": part.tags, "shard": self.shard,
                        "buffer_rows": int(part._buf_n),
                        "chunks": chunks})
        return [out]

    def _args_str(self) -> str:
        return f"dataset={self.dataset}, shard={self.shard}, " \
               f"filters={self.filters}"


class LabelValuesExec(LeafExecPlan):
    def __init__(self, dataset: str, shard: int, label_names: Sequence[str],
                 filters: Sequence[ColumnFilter], start_ms: int, end_ms: int,
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(query_context, dispatcher)
        self.dataset = dataset
        self.shard = shard
        self.label_names = list(label_names)
        self.filters = list(filters)
        self.start_ms = start_ms
        self.end_ms = end_ms

    def do_execute(self, ctx):
        shard = ctx.memstore.get_shard(self.dataset, self.shard)
        return [{label: shard.label_values(label, self.filters, self.start_ms,
                                           self.end_ms)
                 for label in self.label_names}]


# ---------------------------------------------------------------------------
# Scalar leaves
# ---------------------------------------------------------------------------

class ScalarFixedDoubleExec(LeafExecPlan):
    def __init__(self, scalar: float, start_ms: int, step_ms: int, end_ms: int,
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(query_context, dispatcher)
        self.scalar = scalar
        self.steps = StepRange(start_ms, end_ms, step_ms)

    def do_execute(self, ctx):
        return [ScalarResult(self.steps,
                             np.full(self.steps.num_steps, self.scalar))]


class TimeScalarGeneratorExec(LeafExecPlan):
    """time(), hour(), minute()... as per-step scalars (reference:
    exec/TimeScalarGeneratorExec.scala:91)."""

    def __init__(self, function: ScalarFunctionId, start_ms: int, step_ms: int,
                 end_ms: int, query_context=None,
                 dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(query_context, dispatcher)
        self.function = function
        self.steps = StepRange(start_ms, end_ms, step_ms)

    def do_execute(self, ctx):
        secs = np.asarray(self.steps.timestamps(), dtype=np.float64) / 1000.0
        if self.function == ScalarFunctionId.TIME:
            vals = secs
        else:
            fn = instant_ops.INSTANT_FUNCTIONS[self.function.value]
            import jax.numpy as jnp
            vals = np.asarray(fn(jnp.asarray(secs[None, :] * 1000.0)))[0]
        return [ScalarResult(self.steps, vals)]


# ---------------------------------------------------------------------------
# Non-leaves
# ---------------------------------------------------------------------------

class ReduceAggregateExec(NonLeafExecPlan):
    """Cross-shard (or cross-slice) aggregation reduce (reference:
    ReduceAggregateExec, AggrOverRangeVectors.scala:19-66)."""

    def __init__(self, children, operator: AggregationOperator,
                 params: tuple = (), query_context=None,
                 dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(children, query_context, dispatcher)
        self.operator = operator
        self.params = params

    def compose(self, results, ctx):
        partials = [b for r in results for b in r.batches
                    if isinstance(b, AggPartialBatch)]
        # already-presented batches (a fused MeshReduceExec child does
        # its reduce+present on device) pass through untouched instead
        # of being silently dropped by the partial filter
        presented = [b for r in results for b in r.batches
                     if not isinstance(b, AggPartialBatch)]
        if not partials:
            return presented
        agg = aggregator_for(self.operator)
        return [agg.reduce(partials)] + presented

    def _args_str(self):
        return f"operator={self.operator.name}"


class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (reference: DistConcatExec.scala:12)."""

    def compose(self, results, ctx):
        return [b for r in results for b in r.batches]


class StitchRvsExec(NonLeafExecPlan):
    """Concat + stitch split series (reference: StitchRvsExec.scala:61)."""

    def compose(self, results, ctx):
        from filodb_tpu.query.transformers import StitchRvsMapper
        batches = [b for r in results for b in r.batches]
        return StitchRvsMapper().apply(batches, ctx)


class BinaryJoinExec(NonLeafExecPlan):
    """Hash join on `on`/`ignoring` labels (reference:
    BinaryJoinExec.scala:37).  lhs children come first in the children list;
    ``lhs_count`` splits them."""

    def __init__(self, children, lhs_count: int, operator: BinaryOperator,
                 cardinality: Cardinality = Cardinality.ONE_TO_ONE,
                 on: tuple = (), ignoring: tuple = (), include: tuple = (),
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS,
                 bool_mode: bool = False):
        super().__init__(children, query_context, dispatcher)
        self.lhs_count = lhs_count
        self.operator = operator
        self.cardinality = cardinality
        self.on = tuple(on)
        self.ignoring = tuple(ignoring)
        self.include = tuple(include)
        self.bool_mode = bool_mode

    def _join_key(self, tags: dict) -> tuple:
        if self.on:
            return tuple((k, tags.get(k, "")) for k in sorted(self.on))
        drop = set(self.ignoring) | {"_metric_", "__name__"}
        return tuple(sorted((k, v) for k, v in tags.items() if k not in drop))

    def compose(self, results, ctx):
        lhs_b = concat_periodic([b for r in results[:self.lhs_count]
                                 for b in r.batches
                                 if isinstance(b, PeriodicBatch)])
        rhs_b = concat_periodic([b for r in results[self.lhs_count:]
                                 for b in r.batches
                                 if isinstance(b, PeriodicBatch)])
        if lhs_b is None or rhs_b is None:
            return []
        lv, rv = lhs_b.np_values(), rhs_b.np_values()
        # hash side = the "one" side (reference puts smaller on build side)
        rkeys: dict[tuple, int] = {}
        for i, t in enumerate(rhs_b.keys):
            k = self._join_key(t)
            if k in rkeys and self.cardinality == Cardinality.ONE_TO_ONE:
                raise QueryError(self.query_context.query_id,
                                 "duplicate series on right side of join")
            rkeys.setdefault(k, i)
        out_keys, rows = [], []
        seen: set[tuple] = set()
        many_on_left = self.cardinality != Cardinality.ONE_TO_MANY
        for i, t in enumerate(lhs_b.keys):
            k = self._join_key(t)
            j = rkeys.get(k)
            if j is None:
                continue
            if self.cardinality == Cardinality.ONE_TO_ONE:
                if k in seen:
                    raise QueryError(self.query_context.query_id,
                                     "duplicate series on left side of join")
                seen.add(k)
            res = np.asarray(instant_ops.apply_binary(
                self.operator.name, lv[i], rv[j], self.bool_mode))
            key = self._result_key(t, rhs_b.keys[j])
            out_keys.append(key)
            rows.append(res)
        T = lhs_b.steps.num_steps
        vals = np.stack(rows) if rows else np.empty((0, T))
        return [PeriodicBatch(out_keys, lhs_b.steps, vals)]

    def _result_key(self, lt: dict, rt: dict) -> dict:
        if self.operator.is_comparison:
            if self.bool_mode:  # bool comparisons drop the metric name
                return {k: v for k, v in lt.items()
                        if k not in ("_metric_", "__name__")}
            return dict(lt)
        if self.on:
            key = {k: lt.get(k, "") for k in self.on if k in lt}
        else:
            drop = set(self.ignoring) | {"_metric_", "__name__"}
            key = {k: v for k, v in lt.items() if k not in drop}
        for k in self.include:
            if k in rt:
                key[k] = rt[k]
        return key

    def _args_str(self):
        return f"operator={self.operator.name}, on={self.on}, " \
               f"ignoring={self.ignoring}"


class SetOperatorExec(NonLeafExecPlan):
    """and/or/unless set operators (reference: SetOperatorExec.scala:31)."""

    def __init__(self, children, lhs_count: int, operator: BinaryOperator,
                 on: tuple = (), ignoring: tuple = (),
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(children, query_context, dispatcher)
        self.lhs_count = lhs_count
        self.operator = operator
        self.on = tuple(on)
        self.ignoring = tuple(ignoring)

    def _join_key(self, tags: dict) -> tuple:
        if self.on:
            return tuple((k, tags.get(k, "")) for k in sorted(self.on))
        drop = set(self.ignoring) | {"_metric_", "__name__"}
        return tuple(sorted((k, v) for k, v in tags.items() if k not in drop))

    def compose(self, results, ctx):
        lhs_b = concat_periodic([b for r in results[:self.lhs_count]
                                 for b in r.batches
                                 if isinstance(b, PeriodicBatch)])
        rhs_b = concat_periodic([b for r in results[self.lhs_count:]
                                 for b in r.batches
                                 if isinstance(b, PeriodicBatch)])
        op = self.operator
        if lhs_b is None:
            if op == BinaryOperator.LOR and rhs_b is not None:
                return [rhs_b]
            return []
        if rhs_b is None:
            return [] if op == BinaryOperator.LAND else [lhs_b]
        rset = {self._join_key(t) for t in rhs_b.keys}
        lv = lhs_b.np_values()
        if op == BinaryOperator.LAND:
            idx = [i for i, t in enumerate(lhs_b.keys)
                   if self._join_key(t) in rset]
            return [PeriodicBatch([lhs_b.keys[i] for i in idx], lhs_b.steps,
                                  lv[idx] if idx else np.empty((0, lv.shape[1])))]
        if op == BinaryOperator.LUNLESS:
            idx = [i for i, t in enumerate(lhs_b.keys)
                   if self._join_key(t) not in rset]
            return [PeriodicBatch([lhs_b.keys[i] for i in idx], lhs_b.steps,
                                  lv[idx] if idx else np.empty((0, lv.shape[1])))]
        # or: all of lhs + rhs series whose join key not present on lhs
        lset = {self._join_key(t) for t in lhs_b.keys}
        rv = rhs_b.np_values()
        ridx = [i for i, t in enumerate(rhs_b.keys)
                if self._join_key(t) not in lset]
        keys = list(lhs_b.keys) + [rhs_b.keys[i] for i in ridx]
        vals = np.concatenate([lv[:len(lhs_b.keys)],
                               rv[ridx] if ridx else np.empty((0, rv.shape[1]))])
        return [PeriodicBatch(keys, lhs_b.steps, vals)]


class ScalarBinaryOperationExec(LeafExecPlan):
    """Pure scalar arithmetic tree (reference:
    ScalarBinaryOperationExec.scala)."""

    def __init__(self, operator: BinaryOperator, lhs, rhs,
                 start_ms: int, step_ms: int, end_ms: int,
                 query_context=None, dispatcher: PlanDispatcher = IN_PROCESS):
        super().__init__(query_context, dispatcher)
        self.operator = operator
        self.lhs = lhs
        self.rhs = rhs
        self.steps = StepRange(start_ms, end_ms, step_ms)

    def _eval(self, side, ctx) -> np.ndarray:
        if isinstance(side, (int, float)):
            return np.full(self.steps.num_steps, float(side))
        if isinstance(side, lp.ScalarBinaryOperation):
            # nested scalar expression: evaluate inline (reference:
            # ScalarBinaryOperationExec evaluates nested operands itself)
            nested = ScalarBinaryOperationExec(
                side.operator, side.lhs, side.rhs, self.steps.start,
                self.steps.step, self.steps.end, self.query_context)
            lv = nested._eval(side.lhs, ctx)
            rv = nested._eval(side.rhs, ctx)
            return np.asarray(instant_ops.apply_binary(
                side.operator.name, lv, rv, False))
        if isinstance(side, lp.ScalarFixedDoublePlan):
            return np.full(self.steps.num_steps, float(side.scalar))
        res = side.execute(ctx) if isinstance(side, ExecPlan) else None
        if res is not None:
            b = res.batches[0]
            return np.asarray(b.values)
        raise QueryError("", f"bad scalar operand {side}")

    def do_execute(self, ctx):
        lv = self._eval(self.lhs, ctx)
        rv = self._eval(self.rhs, ctx)
        vals = np.asarray(instant_ops.apply_binary(self.operator.name, lv, rv,
                                                   False))
        return [ScalarResult(self.steps, vals)]


class LabelValuesDistConcatExec(NonLeafExecPlan):
    """Merge per-shard label-value maps."""

    def compose(self, results, ctx):
        merged: dict[str, set] = {}
        for r in results:
            for b in r.batches:
                if isinstance(b, dict):
                    for label, vals in b.items():
                        merged.setdefault(label, set()).update(vals)
        return [{label: sorted(v) for label, v in merged.items()}]


class PartKeysDistConcatExec(NonLeafExecPlan):
    def compose(self, results, ctx):
        seen = set()
        out = []
        for r in results:
            for b in r.batches:
                for tags in b:
                    k = tuple(sorted(tags.items()))
                    if k not in seen:
                        seen.add(k)
                        out.append(tags)
        return [out]
