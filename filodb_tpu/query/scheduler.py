"""Query admission and scheduling: submit-time priority + bounded pool.

Capability match for the reference's QueryActor machinery (reference:
coordinator/src/main/scala/filodb.coordinator/QueryActor.scala:28-40 —
a priority mailbox ordering queries by ``submitTime`` so the oldest
query runs first; :112-131 — queries execute on a dedicated,
instrumented query scheduler, never on the ingest or network threads).

Here that is a :class:`QueryScheduler` per dataset: a bounded priority
queue (admission control — a full queue rejects instead of buffering
unboundedly) feeding a fixed pool of query worker threads.  Queries
whose queue wait already exceeded their timeout are failed without
executing (the reference relinquishes them the same way), so a backlog
drains fast instead of doing dead work.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Optional

from filodb_tpu.query.model import QueryError
from filodb_tpu.utils.observability import TRACER


class QueryRejected(QueryError):
    """Admission control rejection (queue full / scheduler down)."""


class QueryScheduler:
    """Bounded priority-queue executor for one dataset's queries."""

    def __init__(self, num_workers: int = 4, max_queued: int = 256,
                 name: str = "query", registry=None):
        if num_workers <= 0 or max_queued <= 0:
            raise ValueError("num_workers and max_queued must be positive")
        self.name = name
        self.max_queued = max_queued
        self._heap: list = []
        self._counter = itertools.count()  # FIFO tiebreak for equal times
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._shutdown = False
        self._workers = [threading.Thread(target=self._run,
                                          name=f"{name}-worker-{i}",
                                          daemon=True)
                         for i in range(num_workers)]
        for w in self._workers:
            w.start()
        reg = registry
        if reg is None:
            from filodb_tpu.utils.observability import REGISTRY as reg
        # saturation visibility (ISSUE 2 satellite): queue depth is a
        # live gauge backed by queue_depth(), rejections (full/shutdown)
        # count per reason — both visible on /metrics before overload
        # becomes timeouts
        self._m_depth = reg.gauge("filodb_query_queue_depth")
        self._m_done = reg.counter("filodb_queries_executed_total")
        self._m_rejected = reg.counter("filodb_queries_rejected_total")
        self._m_timed_out = reg.counter("filodb_queries_queue_timeout_total")
        self._m_wait = reg.histogram("filodb_query_queue_wait_seconds")
        self._m_run = reg.histogram("filodb_query_run_seconds")
        # deadline-expired-in-queue drops (ISSUE 5 satellite): dead work
        # is discarded at dequeue, never executed
        self._m_expired = reg.counter("filodb_query_sched_expired_total")
        self._m_depth.set_fn(self.queue_depth, scheduler=name)

    # ------------------------------------------------------------- submit

    def submit(self, fn: Callable, submit_time_ms: Optional[int] = None,
               timeout_ms: int = 30_000,
               deadline_ms: Optional[int] = None) -> Future:
        """Enqueue a query; earliest ``submit_time_ms`` runs first
        (reference: priority mailbox by submitTime).  Raises
        :class:`QueryRejected` when the queue is full.

        ``deadline_ms`` is the query's ABSOLUTE wall-clock deadline
        (epoch ms, workload/deadline.py): a query that sat in the queue
        past it is dropped at dequeue instead of executed.  It is NOT
        derived from ``submit_time_ms`` — callers use submit time as a
        pure priority key (cross-node it is the ORIGIN's clock), so only
        an explicit deadline is trusted against this node's clock."""
        st = submit_time_ms if submit_time_ms else int(time.time() * 1000)
        fut: Future = Future()
        # trace context captured HERE travels to the worker thread so
        # the queue-wait/run-time split stitches into the query's tree
        token = TRACER.capture()
        entry = (st, next(self._counter), time.monotonic(), timeout_ms,
                 deadline_ms, token, fn, fut)
        with self._lock:
            if self._shutdown:
                self._m_rejected.inc(scheduler=self.name, reason="shutdown")
                raise QueryRejected("", "query scheduler is shut down")
            if len(self._heap) >= self.max_queued:
                self._m_rejected.inc(scheduler=self.name, reason="full")
                raise QueryRejected(
                    "", f"query queue full ({self.max_queued})")
            heapq.heappush(self._heap, entry)
            self._work.notify()
        return fut

    def execute(self, fn: Callable, submit_time_ms: Optional[int] = None,
                timeout_ms: int = 30_000,
                deadline_ms: Optional[int] = None):
        """Submit and wait — the synchronous API the HTTP layer uses.
        The timeout covers queue wait + execution."""
        fut = self.submit(fn, submit_time_ms, timeout_ms, deadline_ms)
        try:
            return fut.result(timeout=timeout_ms / 1000.0)
        except _FutureTimeout:
            # pre-3.11 concurrent.futures.TimeoutError is NOT the
            # builtin TimeoutError; catching the builtin missed it and
            # leaked the raw future timeout to the HTTP layer
            fut.cancel()
            raise QueryError("", f"query timed out after {timeout_ms}ms")

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._heap)

    # ------------------------------------------------------------- workers

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._heap and not self._shutdown:
                    self._work.wait()
                if self._shutdown and not self._heap:
                    return
                (_, _, enq_mono, timeout_ms, deadline_ms, token, fn,
                 fut) = heapq.heappop(self._heap)
            waited = time.monotonic() - enq_mono
            self._m_wait.observe(waited)
            if token[0] is not None:
                # synthetic span: the wait happened in the queue, not on
                # any thread — report it parented on the submitter's span
                TRACER.record("scheduler.queue_wait", waited,
                              trace_id=token[0], parent_id=token[1],
                              scheduler=self.name)
            if deadline_ms and time.time() * 1000.0 > deadline_ms:
                # ISSUE 5 satellite: the submit-time deadline expired
                # while queued — the caller (local client or upstream
                # coordinator hop) stopped waiting; executing would be
                # pure dead work.  Dropped with QueryRejected, counted.
                self._m_expired.inc(scheduler=self.name)
                if not fut.cancelled():
                    try:
                        fut.set_exception(QueryRejected(
                            "", f"query deadline expired after "
                                f"{int(waited * 1000)}ms in queue; "
                                f"dropped without executing"))
                    except Exception:  # lost the race to a cancel
                        pass
                continue
            if waited * 1000.0 > timeout_ms:
                # dead work: the client already timed out (reference
                # QueryActor discards overdue queries).  The future may
                # already be CANCELLED (execute()'s timeout cancels it) —
                # set_exception would raise InvalidStateError and kill
                # this worker thread permanently.
                self._m_timed_out.inc(scheduler=self.name)
                if not fut.cancelled():
                    try:
                        fut.set_exception(QueryError(
                            "", f"query spent {int(waited * 1000)}ms in "
                                f"queue, exceeding its {timeout_ms}ms "
                                f"timeout"))
                    except Exception:  # lost the race to a cancel
                        pass
                continue
            if not fut.set_running_or_notify_cancel():
                continue  # cancelled while queued
            t_run = time.monotonic()
            try:
                with TRACER.attach(token), \
                        TRACER.span("scheduler.run", scheduler=self.name):
                    out = fn()
                fut.set_result(out)
            except BaseException as e:  # noqa: BLE001 — surface via future
                fut.set_exception(e)
            finally:
                self._m_run.observe(time.monotonic() - t_run)
                self._m_done.inc(scheduler=self.name)

    def shutdown(self, wait: bool = True) -> None:
        # deregister the depth callback: the global gauge must not keep
        # this scheduler (heap, queued closures) alive or keep exporting
        # a row for a dead instance
        self._m_depth.remove(scheduler=self.name)
        with self._lock:
            self._shutdown = True
            # fail whatever is still queued
            pending = self._heap
            self._heap = []
            self._work.notify_all()
        for *_, fut in pending:
            if not fut.cancelled():
                try:
                    fut.set_exception(
                        QueryRejected("", "scheduler shut down"))
                except Exception:  # cancelled concurrently
                    pass
        if wait:
            for w in self._workers:
                w.join(timeout=5)
