"""RangeFunctionId -> batched kernel dispatch.

The reference picks a ChunkedRangeFunction per (function, column type)
(reference: query/exec/rangefn/RangeFunction.scala:233-405 factory).  Here
each function maps to one batched kernel from :mod:`filodb_tpu.ops.windows`
/ :mod:`filodb_tpu.ops.histogram_ops`, jit-compiled per shape bucket.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from filodb_tpu.core.chunk import ChunkBatch
from filodb_tpu.ops import histogram_ops, windows
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query.logical import RangeFunctionId as F

# prefix-path kernels: fn(ts, vals, steps, window) -> [S,T]
def _last_sample_value(ts, vals, steps, window):
    return windows.last_sample(ts, vals, steps, window)[0]


_PREFIX = {
    F.SUM_OVER_TIME: windows.sum_over_time,
    F.COUNT_OVER_TIME: windows.count_over_time,
    F.AVG_OVER_TIME: windows.avg_over_time,
    F.STDDEV_OVER_TIME: windows.stddev_over_time,
    F.STDVAR_OVER_TIME: windows.stdvar_over_time,
    F.CHANGES: windows.changes_over_time,
    F.RESETS: windows.resets_over_time,
    F.RATE: windows.rate,
    F.INCREASE: windows.increase,
    F.DELTA: windows.delta_fn,
    F.IRATE: windows.irate,
    F.IDELTA: windows.idelta,
    F.TIMESTAMP: windows.timestamp_fn,
    F.Z_SCORE: windows.z_score,
    # last_over_time == the instant selector's last-sample scan with an
    # explicit window (reference: LastSampleChunkedFunctionD)
    F.LAST_OVER_TIME: _last_sample_value,
}

# gather-path kernels: fn(ts, vals, steps, window, wmax, *args) -> [S,T]
_GATHER = {
    F.MIN_OVER_TIME: windows.min_over_time,
    F.MAX_OVER_TIME: windows.max_over_time,
    F.QUANTILE_OVER_TIME: windows.quantile_over_time,
    F.MAD_OVER_TIME: windows.mad_over_time,
    F.DERIV: windows.deriv,
    F.PREDICT_LINEAR: windows.predict_linear,
    F.HOLT_WINTERS: windows.holt_winters,
}

_HIST = {
    F.RATE: histogram_ops.hist_rate,
    F.INCREASE: histogram_ops.hist_increase,
    F.SUM_OVER_TIME: histogram_ops.hist_sum_over_time,
    None: histogram_ops.hist_last_sample,
}


@functools.lru_cache(maxsize=256)
def _jit(fn, static_argnums=()):
    return jax.jit(fn, static_argnums=static_argnums)


def supported(func: Optional[F], hist: bool) -> bool:
    if hist:
        return func in _HIST
    return func is None or func in _PREFIX or func in _GATHER


def apply_range_function(batch: ChunkBatch, steps: StepRange,
                         window_ms: int, func: Optional[F],
                         args: tuple = ()) -> np.ndarray:
    """Run one windowed range function over a whole ChunkBatch.

    ``func=None`` is the plain instant-vector selector: last sample within
    the lookback window (reference: PeriodicSamplesMapper with no range
    function uses LastSampleChunkedFunction).  Returns values [S, T], or a
    hist result [S, T, B] when the batch holds histograms.
    """
    step_arr = jnp.asarray(steps.timestamps())
    ts = jnp.asarray(batch.timestamps)
    window = jnp.asarray(window_ms, dtype=ts.dtype)
    if batch.hist is not None:
        kern = _HIST.get(func)
        if kern is None:
            raise ValueError(f"range function {func} not supported on histograms")
        return _jit(kern)(ts, jnp.asarray(batch.hist), step_arr, window)
    vals = jnp.asarray(batch.values)
    if func is None:
        return _jit(_last_sample_value)(ts, vals, step_arr, window)
    if func in _PREFIX:
        return _jit(_PREFIX[func])(ts, vals, step_arr, window)
    if func in _GATHER:
        wmax = windows.max_window_rows(ts, step_arr, window)
        wmax = max(int(np.ceil(wmax / 16)) * 16, 16)  # bucket wmax: bounded recompiles
        kern = _GATHER[func]
        extra = tuple(float(a) for a in args)
        return _jit(kern, static_argnums=tuple(range(4, 5 + len(extra))))(
            ts, vals, step_arr, window, wmax, *extra)
    raise ValueError(f"unsupported range function {func}")


# --------------------------------------------------------------------------
# Kernel introspection for the mesh engine (parallel/mesh.py), which builds
# its own SPMD program around the raw kernels rather than calling
# apply_range_function per shard.
# --------------------------------------------------------------------------

def kernel_kind(func: Optional[F]) -> str:
    """'last' | 'prefix' | 'gather' — how the kernel is invoked."""
    if func is None:
        return "last"
    if func in _PREFIX:
        return "prefix"
    if func in _GATHER:
        return "gather"
    raise ValueError(f"unsupported range function {func}")


def raw_kernel(func: Optional[F]):
    if func is None:
        return _last_sample_value
    return _PREFIX.get(func) or _GATHER[func]


def hist_kernel(func: Optional[F]):
    """Per-bucket window kernel for first-class histogram columns
    ([S, R] ts + [S, R, B] buckets -> [S, T, B])."""
    return _HIST[func]


def bucket_wmax(ts, steps, window) -> int:
    """Max rows in any window, rounded to a 16-multiple shape bucket."""
    wmax = windows.max_window_rows(jnp.asarray(ts), jnp.asarray(steps),
                                   jnp.asarray(window))
    return max(int(np.ceil(wmax / 16)) * 16, 16)
