"""LogicalPlan ADT + plan enums.

Mirrors the reference's LogicalPlan hierarchy and PlanEnums
(reference: query/src/main/scala/filodb/query/LogicalPlan.scala:83-410,
PlanEnums.scala:1-209).  Logical plans are built by the PromQL parser and
materialized into ExecPlans by the planners.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence, Union

from filodb_tpu.core.filters import ColumnFilter


class AggregationOperator(enum.Enum):
    AVG = "avg"
    COUNT = "count"
    GROUP = "group"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    STDDEV = "stddev"
    STDVAR = "stdvar"
    TOPK = "topk"
    BOTTOMK = "bottomk"
    QUANTILE = "quantile"
    COUNT_VALUES = "count_values"


class RangeFunctionId(enum.Enum):
    AVG_OVER_TIME = "avg_over_time"
    CHANGES = "changes"
    COUNT_OVER_TIME = "count_over_time"
    DELTA = "delta"
    DERIV = "deriv"
    HOLT_WINTERS = "holt_winters"
    IDELTA = "idelta"
    INCREASE = "increase"
    IRATE = "irate"
    LAST_OVER_TIME = "last_over_time"
    MAX_OVER_TIME = "max_over_time"
    MIN_OVER_TIME = "min_over_time"
    PREDICT_LINEAR = "predict_linear"
    QUANTILE_OVER_TIME = "quantile_over_time"
    MAD_OVER_TIME = "mad_over_time"
    RATE = "rate"
    RESETS = "resets"
    STDDEV_OVER_TIME = "stddev_over_time"
    STDVAR_OVER_TIME = "stdvar_over_time"
    SUM_OVER_TIME = "sum_over_time"
    TIMESTAMP = "timestamp"
    Z_SCORE = "z_score"


class InstantFunctionId(enum.Enum):
    ABS = "abs"
    CEIL = "ceil"
    CLAMP_MAX = "clamp_max"
    CLAMP_MIN = "clamp_min"
    EXP = "exp"
    FLOOR = "floor"
    HISTOGRAM_QUANTILE = "histogram_quantile"
    HISTOGRAM_MAX_QUANTILE = "histogram_max_quantile"
    HISTOGRAM_BUCKET = "histogram_bucket"
    LN = "ln"
    LOG10 = "log10"
    LOG2 = "log2"
    ROUND = "round"
    SGN = "sgn"
    SQRT = "sqrt"
    DAYS_IN_MONTH = "days_in_month"
    DAY_OF_MONTH = "day_of_month"
    DAY_OF_WEEK = "day_of_week"
    HOUR = "hour"
    MINUTE = "minute"
    MONTH = "month"
    YEAR = "year"


class BinaryOperator(enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"
    POW = "^"
    EQL = "=="
    NEQ = "!="
    GTR = ">"
    LSS = "<"
    GTE = ">="
    LTE = "<="
    LAND = "and"
    LOR = "or"
    LUNLESS = "unless"

    @property
    def is_comparison(self) -> bool:
        return self in (BinaryOperator.EQL, BinaryOperator.NEQ,
                        BinaryOperator.GTR, BinaryOperator.LSS,
                        BinaryOperator.GTE, BinaryOperator.LTE)

    @property
    def is_set_op(self) -> bool:
        return self in (BinaryOperator.LAND, BinaryOperator.LOR,
                        BinaryOperator.LUNLESS)


class Cardinality(enum.Enum):
    ONE_TO_ONE = "OneToOne"
    ONE_TO_MANY = "OneToMany"
    MANY_TO_ONE = "ManyToOne"
    MANY_TO_MANY = "ManyToMany"


class MiscellaneousFunctionId(enum.Enum):
    LABEL_REPLACE = "label_replace"
    LABEL_JOIN = "label_join"
    HIST_TO_PROM_VECTORS = "hist_to_prom_vectors"


class SortFunctionId(enum.Enum):
    SORT = "sort"
    SORT_DESC = "sort_desc"


class ScalarFunctionId(enum.Enum):
    SCALAR = "scalar"
    TIME = "time"
    HOUR = "hour"
    MINUTE = "minute"
    MONTH = "month"
    YEAR = "year"
    DAY_OF_MONTH = "day_of_month"
    DAY_OF_WEEK = "day_of_week"
    DAYS_IN_MONTH = "days_in_month"


class VectorFunctionId(enum.Enum):
    VECTOR = "vector"


# ---------------------------------------------------------------------------
# Plan nodes
# ---------------------------------------------------------------------------

class LogicalPlan:
    """Base; RawSeriesLikePlan/PeriodicSeriesPlan split as in the reference."""


class RawSeriesLikePlan(LogicalPlan):
    pass


class PeriodicSeriesPlan(LogicalPlan):
    pass


class MetadataQueryPlan(LogicalPlan):
    pass


@dataclasses.dataclass(frozen=True)
class IntervalSelector:
    """[from, to] epoch ms range of raw data to read (reference:
    RangeSelector/IntervalSelector)."""

    from_ms: int
    to_ms: int


@dataclasses.dataclass(frozen=True)
class RawSeries(RawSeriesLikePlan):
    range_selector: IntervalSelector
    filters: tuple[ColumnFilter, ...]
    columns: tuple[str, ...] = ()
    lookback_ms: Optional[int] = None
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class RawChunkMeta(RawSeriesLikePlan):
    range_selector: IntervalSelector
    filters: tuple[ColumnFilter, ...]
    column: str = ""


@dataclasses.dataclass(frozen=True)
class PeriodicSeries(PeriodicSeriesPlan):
    """Raw series resampled at regular steps, no range function — the
    instant-vector selector (reference LogicalPlan.scala PeriodicSeries)."""

    raw_series: RawSeries
    start_ms: int
    step_ms: int
    end_ms: int
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class PeriodicSeriesWithWindowing(PeriodicSeriesPlan):
    series: RawSeries
    start_ms: int
    step_ms: int
    end_ms: int
    window_ms: int
    function: RangeFunctionId
    function_args: tuple = ()
    offset_ms: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Aggregate(PeriodicSeriesPlan):
    operator: AggregationOperator
    vectors: PeriodicSeriesPlan
    params: tuple = ()
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class BinaryJoin(PeriodicSeriesPlan):
    lhs: PeriodicSeriesPlan
    operator: BinaryOperator
    cardinality: Cardinality
    rhs: PeriodicSeriesPlan
    on: tuple[str, ...] = ()
    ignoring: tuple[str, ...] = ()
    include: tuple[str, ...] = ()
    bool_mode: bool = False  # comparison returns 0/1 instead of filtering


@dataclasses.dataclass(frozen=True)
class ScalarVectorBinaryOperation(PeriodicSeriesPlan):
    operator: BinaryOperator
    scalar_arg: "LogicalPlan"  # ScalarPlan subtype
    vector: PeriodicSeriesPlan
    scalar_is_lhs: bool = False
    bool_mode: bool = False


@dataclasses.dataclass(frozen=True)
class ApplyInstantFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    function: InstantFunctionId
    function_args: tuple = ()


@dataclasses.dataclass(frozen=True)
class ApplyMiscellaneousFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    function: MiscellaneousFunctionId
    string_args: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ApplySortFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    function: SortFunctionId


@dataclasses.dataclass(frozen=True)
class ApplyAbsentFunction(PeriodicSeriesPlan):
    vectors: PeriodicSeriesPlan
    filters: tuple[ColumnFilter, ...]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0


# -- scalar plans -----------------------------------------------------------

class ScalarPlan(PeriodicSeriesPlan):
    pass


@dataclasses.dataclass(frozen=True)
class ScalarTimeBasedPlan(ScalarPlan):
    function: ScalarFunctionId
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class ScalarFixedDoublePlan(ScalarPlan):
    scalar: float
    start_ms: int
    step_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class ScalarVaryingDoublePlan(ScalarPlan):
    """scalar(vector-expr): per-step scalar from a one-series vector."""

    vectors: PeriodicSeriesPlan
    function: ScalarFunctionId = ScalarFunctionId.SCALAR


@dataclasses.dataclass(frozen=True)
class ScalarBinaryOperation(ScalarPlan):
    operator: BinaryOperator
    lhs: Union[float, "ScalarBinaryOperation", ScalarPlan]
    rhs: Union[float, "ScalarBinaryOperation", ScalarPlan]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0


@dataclasses.dataclass(frozen=True)
class VectorPlan(PeriodicSeriesPlan):
    """vector(scalar-expr)."""

    scalars: ScalarPlan


# -- metadata plans ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LabelValues(MetadataQueryPlan):
    label_names: tuple[str, ...]
    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class SeriesKeysByFilters(MetadataQueryPlan):
    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclasses.dataclass(frozen=True)
class RawChunkMeta(MetadataQueryPlan):
    """Chunk-level metadata for matching series — the debugging /
    capacity-planning query (reference: LogicalPlan.scala RawChunkMeta +
    exec/SelectChunkInfosExec).  Chunks here store all columns together
    (one ChunkSet), so unlike the reference there is no per-column
    variant."""

    filters: tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


# ---------------------------------------------------------------------------
# Tree utilities (reference: LogicalPlanUtils / LogicalPlan object helpers)
# ---------------------------------------------------------------------------

def leaf_raw_series(plan: LogicalPlan) -> list[RawSeries]:
    out: list[RawSeries] = []

    def walk(p):
        if isinstance(p, RawSeries):
            out.append(p)
        elif dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, LogicalPlan):
                    walk(v)
    walk(plan)
    return out


def raw_series_filters(plan: LogicalPlan) -> list[tuple[ColumnFilter, ...]]:
    return [rs.filters for rs in leaf_raw_series(plan)]


def time_range(plan: LogicalPlan) -> tuple[int, int, int]:
    """(start, step, end) of a periodic plan."""
    for attr in ("start_ms",):
        if hasattr(plan, attr):
            return plan.start_ms, plan.step_ms, plan.end_ms
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, PeriodicSeriesPlan):
            return time_range(v)
    raise ValueError(f"no time range on {type(plan).__name__}")
