"""Wire format for cross-node query dispatch.

Capability match for the reference's Kryo-serialized plan/result
transport (reference: coordinator/.../client/Serializer.scala:165,
FiloKryoSerializers.scala — ExecPlan subtrees travel to the node owning
the shard, QueryResult(SerializedRangeVector) travels back;
PlanDispatcher.scala:29-46).  JSON envelopes with base64-npy arrays
replace Kryo: leaf scan plans and their transformer stacks are rebuilt
from a class registry on the receiving node; batches round-trip
losslessly (ndarray bit-exact via .npy bytes).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import io
from typing import Optional

import numpy as np

from filodb_tpu.core import filters as flt
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import transformers as tf
from filodb_tpu.query.aggregators import AggPartialBatch
from filodb_tpu.query.exec import (LabelValuesExec,
                                   MultiSchemaPartitionsExec, PartKeysExec,
                                   SelectChunkInfosExec)
from filodb_tpu.query.logical import (AggregationOperator, InstantFunctionId,
                                      MiscellaneousFunctionId,
                                      RangeFunctionId, SortFunctionId,
                                      VectorFunctionId, BinaryOperator)
from filodb_tpu.query.model import (PeriodicBatch, QueryContext, QueryResult,
                                    QueryStats, RawBatch, ScalarResult)


class WireError(ValueError):
    """Plan/result not expressible on the wire (e.g. exec-plan scalar
    args inside a transformer — the reference serializes those too; here
    they must be resolved before dispatch)."""


# ---------------------------------------------------------------------------
# ndarray <-> base64 .npy
# ---------------------------------------------------------------------------


def _enc_array(a: Optional[np.ndarray]):
    if a is None:
        return None
    buf = io.BytesIO()
    np.save(buf, np.asarray(a), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode()


def _dec_array(s) -> Optional[np.ndarray]:
    if s is None:
        return None
    return np.load(io.BytesIO(base64.b64decode(s)), allow_pickle=False)


# ---------------------------------------------------------------------------
# Filters
# ---------------------------------------------------------------------------

_FILTERS = {c.__name__: c for c in
            (flt.Equals, flt.NotEquals, flt.EqualsRegex, flt.NotEqualsRegex,
             getattr(flt, "In", None)) if c is not None}


def _enc_filter(f: flt.ColumnFilter) -> dict:
    inner = f.filter
    d = {"column": f.column, "kind": type(inner).__name__}
    for field in dataclasses.fields(inner):
        v = getattr(inner, field.name)
        d[field.name] = sorted(v) if isinstance(v, (set, frozenset)) else v
    return d


def _dec_filter(d: dict) -> flt.ColumnFilter:
    cls = _FILTERS.get(d["kind"])
    if cls is None:
        raise WireError(f"unknown filter kind {d['kind']}")
    kwargs = {f.name: d[f.name] for f in dataclasses.fields(cls)}
    if "values" in kwargs and isinstance(kwargs["values"], list):
        kwargs["values"] = frozenset(kwargs["values"])
    return flt.ColumnFilter(d["column"], cls(**kwargs))


# ---------------------------------------------------------------------------
# Transformers (generic dataclass serde over a registry)
# ---------------------------------------------------------------------------

_TRANSFORMERS = {c.__name__: c for c in (
    tf.PeriodicSamplesMapper, tf.InstantVectorFunctionMapper,
    tf.ScalarOperationMapper, tf.AggregateMapReduce, tf.AggregatePresenter,
    tf.MiscellaneousFunctionMapper, tf.SortFunctionMapper,
    tf.AbsentFunctionMapper, tf.HistogramQuantileMapper, tf.StitchRvsMapper,
    tf.VectorFunctionMapper)}

_ENUMS = {c.__name__: c for c in (
    AggregationOperator, RangeFunctionId, InstantFunctionId,
    MiscellaneousFunctionId, SortFunctionId, VectorFunctionId,
    BinaryOperator)}


def _enc_value(v):
    if isinstance(v, enum.Enum):
        return {"__enum__": type(v).__name__, "name": v.name}
    if isinstance(v, (tuple, list)):
        return {"__seq__": [_enc_value(x) for x in v]}
    if isinstance(v, flt.ColumnFilter):
        return {"__filter__": _enc_filter(v)}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise WireError(f"cannot serialize transformer field value {v!r}")


def _dec_value(v):
    if isinstance(v, dict):
        if "__enum__" in v:
            return _ENUMS[v["__enum__"]][v["name"]]
        if "__seq__" in v:
            return tuple(_dec_value(x) for x in v["__seq__"])
        if "__filter__" in v:
            return _dec_filter(v["__filter__"])
    return v


def _enc_transformer(t) -> dict:
    name = type(t).__name__
    if name not in _TRANSFORMERS:
        raise WireError(f"transformer {name} is not wire-serializable")
    d = {"type": name}
    for field in dataclasses.fields(t):
        d[field.name] = _enc_value(getattr(t, field.name))
    return d


def _dec_transformer(d: dict):
    cls = _TRANSFORMERS[d["type"]]
    kwargs = {f.name: _dec_value(d[f.name])
              for f in dataclasses.fields(cls) if f.name in d}
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# Leaf plans
# ---------------------------------------------------------------------------


def _enc_qctx(qctx: QueryContext) -> dict:
    """Full QueryContext travels: limits set by the caller must be
    enforced on the data node where the work actually runs.

    The deadline crosses as a RELATIVE ``budget_ms`` (remaining at
    serialization time), never the absolute ``deadline_ms`` — wall
    clocks differ between nodes, and re-anchoring the remaining budget
    against the receiver's clock is what makes the budget measurably
    SHRINK at every hop (ISSUE 5 deadline propagation)."""
    d = {f.name: getattr(qctx, f.name)
         for f in dataclasses.fields(QueryContext)}
    # the live admission permit is node-local: the remote owner admits
    # the leaf under ITS OWN controller, so a permit handle never
    # crosses the wire (and a _Permit is not JSON-serializable anyway)
    d.pop("admission_permit", None)
    if qctx.deadline_ms:
        import time as _time
        d["budget_ms"] = max(
            qctx.deadline_ms - int(_time.time() * 1000), 0)
    d.pop("deadline_ms", None)
    return d


def _dec_qctx(d: dict) -> QueryContext:
    known = {f.name for f in dataclasses.fields(QueryContext)}
    qctx = QueryContext(**{k: v for k, v in d.items()
                           if k in known and k != "deadline_ms"})
    budget = d.get("budget_ms")
    if budget is not None:
        import time as _time
        qctx.deadline_ms = int(_time.time() * 1000) + int(budget)
    return qctx


def serialize_plan(plan) -> dict:
    """Leaf plan + transformer stack -> wire dict.  Only leaves travel:
    the scatter-gather tree's non-leaf composition always runs on the
    query entry node, exactly like the reference (SURVEY.md §3.1)."""
    if not isinstance(plan, (MultiSchemaPartitionsExec, PartKeysExec,
                             LabelValuesExec, SelectChunkInfosExec)):
        raise WireError(f"only leaf plans dispatch remotely, "
                        f"got {type(plan).__name__}")
    base = {
        "dataset": plan.dataset,
        "shard": plan.shard,
        "filters": [_enc_filter(f) for f in plan.filters],
        "start_ms": plan.start_ms,
        "end_ms": plan.end_ms,
        "transformers": [_enc_transformer(t) for t in plan.transformers],
        "qctx": _enc_qctx(plan.query_context),
    }
    # split-parent scan exclusion (ISSUE 13) travels with the leaf so
    # the remote owner slices the migrated half exactly as the planner
    # that stamped it would have locally
    if getattr(plan, "reshard_to", None):
        base["reshard_to"] = list(plan.reshard_to)
    if isinstance(plan, MultiSchemaPartitionsExec):
        return {**base, "type": "MultiSchemaPartitionsExec",
                "column": plan.column}
    if isinstance(plan, PartKeysExec):
        return {**base, "type": "PartKeysExec"}
    if isinstance(plan, SelectChunkInfosExec):
        return {**base, "type": "SelectChunkInfosExec"}
    return {**base, "type": "LabelValuesExec",
            "label_names": list(plan.label_names)}


def deserialize_plan(d: dict):
    kind = d.get("type")
    qctx = _dec_qctx(d.get("qctx", {})) if "qctx" in d else QueryContext(
        query_id=d.get("query_id", ""),
        sample_limit=d.get("sample_limit", 1_000_000))
    filters = [_dec_filter(f) for f in d["filters"]]
    reshard = tuple(d["reshard_to"]) if d.get("reshard_to") else None
    if kind == "MultiSchemaPartitionsExec":
        plan = MultiSchemaPartitionsExec(
            d["dataset"], d["shard"], filters, d["start_ms"], d["end_ms"],
            d.get("column"), qctx, reshard_to=reshard)
    elif kind == "PartKeysExec":
        plan = PartKeysExec(d["dataset"], d["shard"], filters,
                            d["start_ms"], d["end_ms"], qctx,
                            reshard_to=reshard)
    elif kind == "SelectChunkInfosExec":
        plan = SelectChunkInfosExec(d["dataset"], d["shard"], filters,
                                    d["start_ms"], d["end_ms"], qctx)
    elif kind == "LabelValuesExec":
        plan = LabelValuesExec(d["dataset"], d["shard"],
                               d.get("label_names", []), filters,
                               d["start_ms"], d["end_ms"], qctx)
    else:
        raise WireError(f"unknown plan type {kind}")
    for t in d.get("transformers", ()):
        plan.add_transformer(_dec_transformer(t))
    return plan


# ---------------------------------------------------------------------------
# Results
# ---------------------------------------------------------------------------


def _enc_steps(s: StepRange) -> list:
    return [s.start, s.end, s.step]


def _dec_steps(v) -> StepRange:
    return StepRange(*v)


def serialize_result(result: QueryResult) -> dict:
    batches = []
    for b in result.batches:
        if isinstance(b, PeriodicBatch):
            batches.append({
                "type": "PeriodicBatch", "keys": b.keys,
                "steps": _enc_steps(b.steps),
                "values": _enc_array(b.values),
                "hist": _enc_array(b.hist),
                "bucket_tops": _enc_array(b.bucket_tops)})
        elif isinstance(b, AggPartialBatch):
            batches.append({
                "type": "AggPartialBatch", "op": b.op.name,
                "params": list(b.params), "group_keys": b.group_keys,
                "steps": _enc_steps(b.steps),
                "state": {k: _enc_array(v) for k, v in b.state.items()},
                "series_keys": b.series_keys,
                "bucket_tops": _enc_array(b.bucket_tops)})
        elif isinstance(b, ScalarResult):
            batches.append({"type": "ScalarResult",
                            "steps": _enc_steps(b.steps),
                            "values": _enc_array(b.values)})
        elif isinstance(b, RawBatch):
            cb = b.batch
            batches.append({
                "type": "RawBatch", "keys": b.keys,
                "timestamps": _enc_array(cb.timestamps if cb else None),
                "values": _enc_array(cb.values if cb else None),
                "row_counts": _enc_array(cb.row_counts if cb else None),
                "hist": _enc_array(cb.hist if cb else None),
                "bucket_tops": _enc_array(cb.bucket_tops if cb else None)})
        elif isinstance(b, (list, dict)):
            # metadata leaves (PartKeysExec/LabelValuesExec) emit plain
            # JSON-able structures
            batches.append({"type": "Json", "data": b})
        else:
            raise WireError(f"cannot serialize batch {type(b).__name__}")
    st = result.stats
    # FULL stats travel (ISSUE 2): per-stage timings and scan-volume
    # counters merge up the coordinator's exec tree like local ones
    stats = {f.name: getattr(st, f.name)
             for f in dataclasses.fields(QueryStats) if f.name != "timings"}
    stats["timings"] = {k: float(v) for k, v in st.timings.items()}
    return {"query_id": result.query_id, "batches": batches, "stats": stats}


def deserialize_result(d: dict) -> QueryResult:
    batches = []
    for b in d.get("batches", ()):
        kind = b["type"]
        if kind == "PeriodicBatch":
            batches.append(PeriodicBatch(
                b["keys"], _dec_steps(b["steps"]), _dec_array(b["values"]),
                hist=_dec_array(b.get("hist")),
                bucket_tops=_dec_array(b.get("bucket_tops"))))
        elif kind == "AggPartialBatch":
            batches.append(AggPartialBatch(
                AggregationOperator[b["op"]], tuple(b["params"]),
                b["group_keys"], _dec_steps(b["steps"]),
                {k: _dec_array(v) for k, v in b["state"].items()},
                series_keys=b.get("series_keys"),
                bucket_tops=_dec_array(b.get("bucket_tops"))))
        elif kind == "ScalarResult":
            batches.append(ScalarResult(_dec_steps(b["steps"]),
                                        _dec_array(b["values"])))
        elif kind == "Json":
            batches.append(b["data"])
        elif kind == "RawBatch":
            from filodb_tpu.core.chunk import ChunkBatch
            ts = _dec_array(b.get("timestamps"))
            cb = None
            if ts is not None:
                cb = ChunkBatch(ts, _dec_array(b["values"]),
                                _dec_array(b["row_counts"]),
                                hist=_dec_array(b.get("hist")),
                                bucket_tops=_dec_array(b.get("bucket_tops")))
            batches.append(RawBatch(b["keys"], cb))
        else:
            raise WireError(f"unknown batch type {kind}")
    known = {f.name for f in dataclasses.fields(QueryStats)}
    stats = QueryStats(**{k: v for k, v in d.get("stats", {}).items()
                          if k in known})
    return QueryResult(d.get("query_id", ""), batches, stats)
