"""Shared incremental window-state core: resident windows, ticked by deltas.

Factored out of ``rules/incremental.py`` (PR 14) so BOTH consumers of
the constant-state streaming formulation (arXiv:2603.09555) share one
implementation:

- the rule engine (``filodb_tpu/rules``): recording rules keep their
  window resident and consume only newly-arrived samples per tick;
- the query-frontend result cache (``filodb_tpu/query/resultcache``):
  a repeatedly-refreshed instant dashboard panel keeps its window
  resident the same way, so each refresh re-scans only the open head
  chunk's sliver instead of the whole window.

Two state shapes:

- :class:`WindowState` — the PR 14 shape, ``fn(selector[w])``: one
  window value per input series.
- :class:`AggWindowState` — NEW: ``agg by/without (fn(selector[w]))``
  for the moment aggregations (sum/count/min/max/avg/group/stddev/
  stdvar).  Per-series window values are computed with the very same
  :func:`~filodb_tpu.query.rangefns.apply_range_function` kernel the
  query path dispatches, then aggregated through the NORMAL aggregator
  machinery — per-shard-bucket ``Aggregator.map`` partials merged with
  the same ``AggPartialBatch`` reduce ``ReduceAggregateExec`` runs —
  so the float association matches the query path's scatter-gather
  exactly.  Two ordering disciplines make that hold:

  * buckets mirror the fetch's per-shard batches in shard order (the
    reduce order of the query path's child list);
  * within a bucket, series keep their FIRST-APPEARANCE slot forever
    (emptied series leave a tombstone rather than being deleted):
    part-ids are assigned in creation order and index lookups return
    them ascending, so first-appearance order IS the leaf-scan batch
    order, and a series that empties and later resumes must not move
    to the back of the association.

The load-bearing invariant is inherited from PR 14 and asserted
generatively in tests/test_rules.py + tests/test_resultcache.py: warm
incremental output is **bit-equal** to a cold full evaluation, which is
bit-equal to the normal query path.  Late-arriving samples (a NEW
series materializing with timestamps at or below an already-consumed
slice boundary) are invisible to warm state until :meth:`reset`; the
rule engine documents that semantics (doc/rules.md), while the result
cache detects the case with a part-id signature and resets (a cache
may never diverge from a cold evaluation).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from filodb_tpu.core.chunk import build_batch
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import logical as lp
from filodb_tpu.query.rangefns import apply_range_function, supported

# row padding for the buffered batches: the same default the shard
# store config uses, so incremental and cold batches land in the same
# jit shape buckets (values are padding-independent either way)
_ROW_PAD = 64

# tombstone/residency backstop: a state holding more series than this
# (live + tombstoned) resets cold instead of growing without bound
_MAX_SERIES = 200_000


class WindowUnsupported(Exception):
    """The data didn't match the recognized shape at tick time (hist
    planes, bucket-count drift past reset, residency blow-up) — the
    caller falls back to full evaluation."""


@dataclasses.dataclass
class WindowSpec:
    """The recognized incremental shape: ``fn(selector[w])``."""

    filters: tuple
    window_ms: int
    function: object                # RangeFunctionId
    args: tuple = ()


# the aggregations whose map partials are zero-insensitive moments:
# adding an absent/NaN series contributes an exact 0.0 (or -inf/inf for
# min/max), so the incremental association matches the query path's
# bit-for-bit.  topk/quantile/count_values reduce through value
# ordering and are excluded on purpose.
_AGG_OPS = frozenset({"SUM", "COUNT", "MIN", "MAX", "AVG", "GROUP",
                      "STDDEV", "STDVAR"})


@dataclasses.dataclass
class AggWindowSpec:
    """The extended incremental shape: ``agg by (..)(fn(selector[w]))``."""

    window: WindowSpec
    operator: object                # AggregationOperator
    by: tuple = ()
    without: tuple = ()


def window_spec(plan) -> Optional[WindowSpec]:
    """Return the :class:`WindowSpec` when ``plan`` is a bare windowed
    range function the incremental path supports; ``None`` falls back
    to full evaluation (aggregations, joins, offsets, histograms...).

    ``offset`` is excluded on purpose: an offset window reads the past,
    where "newly-arrived samples" no longer describes the delta.
    """
    if not isinstance(plan, lp.PeriodicSeriesWithWindowing):
        return None
    if plan.offset_ms:
        return None
    if not isinstance(plan.series, lp.RawSeries) or plan.series.columns:
        return None
    if not supported(plan.function, hist=False):
        return None
    return WindowSpec(tuple(plan.series.filters), int(plan.window_ms),
                      plan.function, tuple(plan.function_args))


def agg_window_spec(plan) -> Optional[AggWindowSpec]:
    """Recognize ``agg [by|without (...)] (fn(selector[w]))`` — the
    shape recorded dashboards use most (``sum(rate(...))``,
    ``sum by (le)(rate(..._bucket[5m]))``); ``None`` falls back."""
    if not isinstance(plan, lp.Aggregate):
        return None
    if plan.params:
        return None
    if getattr(plan.operator, "name", None) not in _AGG_OPS:
        return None
    inner = window_spec(plan.vectors)
    if inner is None:
        return None
    return AggWindowSpec(inner, plan.operator, tuple(plan.by),
                         tuple(plan.without))


def batches_to_buckets(batches) -> list:
    """Unpack a RawSeries plan's result batches into the per-shard
    bucket shape the window states consume: one inner
    ``[(tags, ts, vals)]`` list per per-shard ``RawBatch``, in the
    scatter-gather child order (the order the query path's reduce
    associates in).  Histogram planes raise :class:`WindowUnsupported`
    — the buffers hold scalar floats.  Shared by the rule engine's
    delta fetch and the result cache's instant path, so the unpack
    semantics (row-count clamp, hist policy) can never drift between
    the two consumers of the bit-equality invariant."""
    from filodb_tpu.query.model import RawBatch
    buckets: list = []
    for b in batches:
        if not isinstance(b, RawBatch):
            continue
        rows: list = []
        if b.batch is not None:
            if b.batch.hist is not None:
                raise WindowUnsupported("histogram-schema selector")
            for i, tags in enumerate(b.keys):
                n = int(b.batch.row_counts[i])
                rows.append((tags, np.asarray(b.batch.timestamps[i][:n]),
                             np.asarray(b.batch.values[i][:n])))
        buckets.append(rows)
    return buckets


class _SeriesBuffer:
    """One input series' resident window: samples grouped into blocks
    keyed on chunk-aligned boundaries (``ts // block_ms``), so eviction
    drops whole immutable blocks instead of scanning sample-by-sample."""

    __slots__ = ("tags", "blocks", "last_ts")

    def __init__(self, tags: dict):
        self.tags = tags
        self.blocks: dict[int, list] = {}   # block idx -> [(ts, val)...]
        self.last_ts = -(1 << 62)           # newest buffered timestamp

    def append(self, ts: np.ndarray, vals: np.ndarray,
               block_ms: int) -> None:
        for t, v in zip(ts.tolist(), vals.tolist()):
            self.blocks.setdefault(int(t) // block_ms, []).append(
                (int(t), float(v)))
        if len(ts):
            self.last_ts = max(self.last_ts, int(ts[-1]))

    def evict_before(self, cutoff_ms: int, block_ms: int) -> None:
        """Drop blocks wholly below ``cutoff_ms`` (a block containing
        the cutoff stays; compute-time clamping handles its head)."""
        dead = [b for b in self.blocks if (b + 1) * block_ms <= cutoff_ms]
        for b in dead:
            del self.blocks[b]

    def window_rows(self, start_ms: int,
                    end_ms: int) -> tuple[np.ndarray, np.ndarray]:
        """Samples with ``start <= ts <= end`` in timestamp order — the
        same inclusive clamp a leaf scan's ``read_range`` applies."""
        ts_out: list[int] = []
        val_out: list[float] = []
        for b in sorted(self.blocks):
            for t, v in self.blocks[b]:
                if start_ms <= t <= end_ms:
                    ts_out.append(t)
                    val_out.append(v)
        return (np.asarray(ts_out, dtype=np.int64),
                np.asarray(val_out, dtype=np.float64))

    @property
    def sample_count(self) -> int:
        return sum(len(rows) for rows in self.blocks.values())


class WindowState:
    """Incremental evaluator for one ``fn(selector[w])`` shape.

    ``fetch`` is the consumer's raw-series reader — it issues a
    ``RawSeries`` plan through the normal planner -> admission ->
    scheduler path and returns ``[(tags, ts, vals)]`` clamped to the
    requested interval.
    """

    def __init__(self, spec: WindowSpec, block_ms: Optional[int] = None):
        self.spec = spec
        # chunk-aligned block boundary: the window itself (>= 1s), so a
        # live window spans at most 2 resident blocks + the open one
        self.block_ms = int(block_ms or max(spec.window_ms, 1000))
        self.fetched_through_ms: Optional[int] = None
        self.series: dict[tuple, _SeriesBuffer] = {}
        self.samples_consumed = 0      # lifetime, for telemetry

    # --------------------------------------------------------------- state

    def reset(self) -> None:
        """Forget everything: the next tick re-reads the full window
        (cold).  Called by consumers after any failed evaluation so a
        missed slice cannot leave a silent hole in the window."""
        self.fetched_through_ms = None
        self.series.clear()

    @property
    def resident_series(self) -> int:
        return len(self.series)

    @property
    def resident_samples(self) -> int:
        return sum(b.sample_count for b in self.series.values())

    # ---------------------------------------------------------------- tick

    def tick(self, eval_ms: int,
             fetch: Callable[[tuple, int, int], list]
             ) -> list[tuple[dict, float]]:
        """Consume newly-arrived samples and produce ``[(tags, value)]``
        for every series with a non-NaN window value at ``eval_ms``."""
        window_start = eval_ms - self.spec.window_ms
        warm = self.fetched_through_ms is not None \
            and self.fetched_through_ms <= eval_ms
        fetch_from = self.fetched_through_ms if warm else window_start
        new = 0
        for tags, ts, vals in fetch(self.spec.filters, fetch_from, eval_ms):
            key = tuple(sorted(tags.items()))
            buf = self.series.get(key)
            if buf is not None:
                # dedupe against THIS series' newest buffered row, not
                # the global fetch boundary: a sample stamped exactly at
                # the boundary but ingested after the boundary fetch ran
                # would otherwise vanish from warm state (and break the
                # bit-equality invariant vs a cold pass)
                keep = ts > buf.last_ts
            else:
                keep = ts >= (fetch_from if warm else window_start)
            ts, vals = ts[keep], vals[keep]
            if not len(ts):
                continue
            if buf is None:
                buf = self.series[key] = _SeriesBuffer(dict(tags))
            buf.append(ts, vals, self.block_ms)
            new += len(ts)
        self.samples_consumed += new
        self.fetched_through_ms = eval_ms
        # evict aged blocks; a series whose whole window emptied is
        # dropped outright — the stale-series discipline (doc/rules.md):
        # state for a vanished series must not survive it
        for key in list(self.series):
            buf = self.series[key]
            buf.evict_before(window_start, self.block_ms)
            if not buf.blocks:
                del self.series[key]
        if not self.series:
            return []
        keys, ts_list, val_list = [], [], []
        for buf in self.series.values():
            ts, vals = buf.window_rows(window_start, eval_ms)
            if not len(ts):
                continue
            keys.append(buf.tags)
            ts_list.append(ts)
            val_list.append(vals)
        if not keys:
            return []
        batch = build_batch(ts_list, val_list, pad_to=_ROW_PAD)
        values = np.asarray(apply_range_function(
            batch, StepRange(eval_ms, eval_ms, 1000),
            self.spec.window_ms, self.spec.function, self.spec.args))
        out = []
        for i, tags in enumerate(keys):
            v = float(values[i, 0])
            if not np.isnan(v):
                out.append((tags, v))
        return out


class _Bucket:
    """One shard's resident series, in first-appearance order with
    tombstones (see the module docstring's ordering discipline)."""

    __slots__ = ("series",)

    def __init__(self):
        self.series: dict[tuple, _SeriesBuffer] = {}


class AggWindowState:
    """Incremental evaluator for ``agg by (..)(fn(selector[w]))``.

    ``fetch`` returns the delta grouped per shard bucket, in the same
    order the query path's scatter-gather children reduce in:
    ``[[(tags, ts, vals), ...], ...]`` — one inner list per per-shard
    ``RawBatch`` of the fetch plan, ascending shard order.
    """

    def __init__(self, spec: AggWindowSpec, block_ms: Optional[int] = None,
                 max_buckets: int = 16):
        self.spec = spec
        self.block_ms = int(block_ms or max(spec.window.window_ms, 1000))
        # >= hierarchical_reduce_at shards reduce in sqrt groups on the
        # query path — a different association this flat reduce cannot
        # reproduce, so such fan-outs fall back to full evaluation
        self.max_buckets = max_buckets
        self.fetched_through_ms: Optional[int] = None
        self.buckets: list[_Bucket] = []
        self.samples_consumed = 0

    # --------------------------------------------------------------- state

    def reset(self) -> None:
        self.fetched_through_ms = None
        self.buckets = []

    @property
    def resident_series(self) -> int:
        return sum(1 for b in self.buckets
                   for buf in b.series.values() if buf.blocks)

    @property
    def resident_samples(self) -> int:
        return sum(buf.sample_count for b in self.buckets
                   for buf in b.series.values())

    # ---------------------------------------------------------------- tick

    def _consume(self, eval_ms: int, fetch) -> None:
        window_start = eval_ms - self.spec.window.window_ms
        warm = self.fetched_through_ms is not None \
            and self.fetched_through_ms <= eval_ms
        fetch_from = self.fetched_through_ms if warm else window_start
        fetched = fetch(self.spec.window.filters, fetch_from, eval_ms)
        if len(fetched) > self.max_buckets:
            raise WindowUnsupported(
                f"{len(fetched)} shard buckets >= hierarchical-reduce "
                f"fan-in — query path associates differently")
        if warm and len(fetched) != len(self.buckets):
            # the fan-out changed shape (shard set grew/shrank): the
            # per-bucket association no longer lines up — go cold
            self.reset()
            warm = False
            fetch_from = window_start
            fetched = fetch(self.spec.window.filters, fetch_from, eval_ms)
            if len(fetched) > self.max_buckets:
                raise WindowUnsupported("bucket blow-up on cold refetch")
        if not self.buckets:
            self.buckets = [_Bucket() for _ in fetched]
        new = 0
        for bucket, rows in zip(self.buckets, fetched):
            for tags, ts, vals in rows:
                key = tuple(sorted(tags.items()))
                buf = bucket.series.get(key)
                if buf is not None and buf.blocks:
                    keep = ts > buf.last_ts
                else:
                    keep = ts >= (fetch_from if warm else window_start)
                ts, vals = ts[keep], vals[keep]
                if not len(ts):
                    continue
                if buf is None:
                    buf = bucket.series[key] = _SeriesBuffer(dict(tags))
                buf.append(ts, vals, self.block_ms)
                new += len(ts)
        self.samples_consumed += new
        self.fetched_through_ms = eval_ms
        total = 0
        for bucket in self.buckets:
            for buf in bucket.series.values():
                buf.evict_before(window_start, self.block_ms)
                if not buf.blocks:
                    # tombstone: keep the slot (and its association
                    # order), drop the payload
                    buf.last_ts = -(1 << 62)
            total += len(bucket.series)
        if total > _MAX_SERIES:
            raise WindowUnsupported(
                f"{total} resident series exceeds the state backstop")

    def tick(self, eval_ms: int, fetch,
             group_limit: int = 100_000):
        """Consume the delta and produce the aggregated
        :class:`~filodb_tpu.query.model.PeriodicBatch` at ``eval_ms``
        (None when no series holds data), via the normal aggregator
        map -> AggPartialBatch reduce -> present chain."""
        from filodb_tpu.query.aggregators import aggregator_for
        from filodb_tpu.query.model import PeriodicBatch
        self._consume(eval_ms, fetch)
        window_start = eval_ms - self.spec.window.window_ms
        steps = StepRange(eval_ms, eval_ms, 1000)
        agg = aggregator_for(self.spec.operator)
        partials = []
        for bucket in self.buckets:
            keys, ts_list, val_list = [], [], []
            for buf in bucket.series.values():
                if not buf.blocks:
                    continue
                ts, vals = buf.window_rows(window_start, eval_ms)
                if not len(ts):
                    continue
                keys.append(buf.tags)
                ts_list.append(ts)
                val_list.append(vals)
            if not keys:
                continue
            batch = build_batch(ts_list, val_list, pad_to=_ROW_PAD)
            values = np.asarray(apply_range_function(
                batch, steps, self.spec.window.window_ms,
                self.spec.window.function, self.spec.window.args))
            pb = PeriodicBatch(keys, steps, values[:len(keys)])
            partials.append(agg.map(pb, self.spec.by, self.spec.without,
                                    (), group_limit))
        if not partials:
            return None
        return agg.present(agg.reduce(partials))
