"""RangeVectorTransformers: batch -> batch functions applied on top of an
ExecPlan's own result (reference: query/exec/RangeVectorTransformer.scala:56-430,
PeriodicSamplesMapper.scala:27, AggrOverRangeVectors.scala:74-122).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.ops import histogram_ops, instant as instant_ops
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import rangefns
from filodb_tpu.query.aggregators import (AggPartialBatch, aggregator_for,
                                          grouping_key)
from filodb_tpu.query.logical import (AggregationOperator, InstantFunctionId,
                                      MiscellaneousFunctionId, RangeFunctionId,
                                      SortFunctionId)
from filodb_tpu.query.model import (PeriodicBatch, QueryError, RawBatch,
                                    ScalarResult)


class RangeVectorTransformer:
    def apply(self, batches: list, ctx) -> list:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


def effective_window_ms(window_ms, stale_ms: int = 300_000) -> int:
    """The lookback actually scanned: the explicit range-function window,
    or the staleness lookback for bare instant selectors.  The single
    home of this substitution — the general path, the grid fast path,
    and the mesh path must all agree on it."""
    return window_ms if window_ms else stale_ms


@dataclasses.dataclass
class PeriodicSamplesMapper(RangeVectorTransformer):
    """Raw irregular samples -> regular-step samples, optionally through a
    windowed range function (reference: PeriodicSamplesMapper.scala:27).
    ``offset_ms`` shifts the window into the past while reporting at the
    query grid."""

    start_ms: int
    step_ms: int
    end_ms: int
    window_ms: Optional[int] = None
    function: Optional[RangeFunctionId] = None
    function_args: tuple = ()
    offset_ms: int = 0
    stale_ms: int = 300_000  # staleness lookback for instant selectors

    @property
    def effective_window_ms(self) -> int:
        return effective_window_ms(self.window_ms, self.stale_ms)

    @property
    def well_formed(self) -> bool:
        """False for half-specified windowing (window without function or
        vice versa) — fast paths must decline and let apply() decide."""
        return (self.window_ms is None) == (self.function is None)

    def step_ranges(self) -> tuple[StepRange, StepRange]:
        """(compute steps, report steps): ``offset`` shifts the scanned
        windows into the past while results are reported at the query
        grid.  The single home of this math — apply(), the device-grid
        fast path, and the schema-rewrite path all call it."""
        steps = StepRange(self.start_ms - self.offset_ms,
                          self.end_ms - self.offset_ms, self.step_ms)
        report = StepRange(self.start_ms, self.end_ms, self.step_ms)
        return steps, report

    def apply(self, batches, ctx):
        out = []
        steps, report = self.step_ranges()
        window = self.effective_window_ms
        for b in batches:
            if isinstance(b, (PeriodicBatch, AggPartialBatch)):
                # the leaf already stepped (or even aggregated) this batch
                # from the device grid
                # (exec.MultiSchemaPartitionsExec._try_device_grid)
                out.append(b)
                continue
            if not isinstance(b, RawBatch):
                raise QueryError("", f"PeriodicSamplesMapper over {type(b).__name__}")
            if b.batch is None or not b.keys:
                continue
            vals = rangefns.apply_range_function(b.batch, steps, window,
                                                 self.function,
                                                 self.function_args)
            vals = np.asarray(vals)
            if vals.ndim == 3:  # histogram result [S,T,B]
                out.append(PeriodicBatch(b.keys, report,
                                         np.full(vals.shape[:2], np.nan),
                                         hist=vals,
                                         bucket_tops=np.asarray(
                                             b.batch.bucket_tops)))
            else:
                out.append(PeriodicBatch(b.keys, report, vals))
        return out


@dataclasses.dataclass
class InstantVectorFunctionMapper(RangeVectorTransformer):
    function: InstantFunctionId
    args: tuple = ()

    def apply(self, batches, ctx):
        fid = self.function
        # resolve ExecPlan-valued args ONCE, not once per batch (they may be
        # whole scalar subqueries, reference: ExecPlanFuncArgs)
        resolved = [_resolve(a, ctx) for a in self.args]
        out = []
        for b in batches:
            if fid == InstantFunctionId.HISTOGRAM_QUANTILE:
                q = float(_scalar_arg(resolved, 0))
                vals = np.asarray(histogram_ops.hist_quantile(
                    jnp.asarray(b.bucket_tops), jnp.asarray(b.hist), q))
                out.append(PeriodicBatch(b.keys, b.steps, vals))
            elif fid == InstantFunctionId.HISTOGRAM_MAX_QUANTILE:
                q = float(_scalar_arg(resolved, 0))
                vals = np.asarray(histogram_ops.hist_max_quantile(
                    jnp.asarray(b.bucket_tops), jnp.asarray(b.hist),
                    jnp.asarray(b.values), q))
                out.append(PeriodicBatch(b.keys, b.steps, vals))
            elif fid == InstantFunctionId.HISTOGRAM_BUCKET:
                le = float(_scalar_arg(resolved, 0))
                vals = np.asarray(histogram_ops.hist_bucket(
                    jnp.asarray(b.bucket_tops), jnp.asarray(b.hist), le))
                out.append(PeriodicBatch(b.keys, b.steps, vals))
            else:
                fn = instant_ops.INSTANT_FUNCTIONS[fid.value]
                args = [np.asarray(_eval_arg(a, b.steps)) for a in resolved]
                vals = np.asarray(fn(jnp.asarray(b.values), *args))
                out.append(PeriodicBatch(b.keys, b.steps, vals))
        return out


def _resolve(a, ctx):
    """Scalar argument: float | ScalarResult | ExecPlan producing a scalar
    (the reference's ExecPlanFuncArgs evaluated at run time)."""
    if hasattr(a, "execute") and ctx is not None:  # ExecPlan
        res = a.execute(ctx)
        return res.batches[0] if res.batches else ScalarResult(None, np.nan)
    return a


def _scalar_arg(args, i, ctx=None):
    a = _resolve(args[i], ctx)
    if isinstance(a, ScalarResult):
        return float(np.asarray(a.values).ravel()[0])
    return float(a)


def _eval_arg(a, steps, ctx=None):
    a = _resolve(a, ctx)
    if isinstance(a, ScalarResult):
        return np.asarray(a.values)
    return np.asarray(float(a))


_MIRROR = {"GTR": "LSS", "LSS": "GTR", "GTE": "LTE", "LTE": "GTE",
           "EQL": "EQL", "NEQ": "NEQ"}


@dataclasses.dataclass
class ScalarOperationMapper(RangeVectorTransformer):
    """vector <op> scalar / scalar <op> vector (reference:
    ScalarOperationMapper, RangeVectorTransformer.scala:193).  ``operator``
    is a BinaryOperator enum *name* ("ADD", "GTR", ...)."""

    operator: str
    scalar: object  # float | ScalarResult
    scalar_on_lhs: bool = False
    bool_mode: bool = False

    def apply(self, batches, ctx):
        scalar = _resolve(self.scalar, ctx)
        sval = (np.asarray(scalar.values)
                if isinstance(scalar, ScalarResult)
                else np.asarray(float(scalar)))
        is_cmp = self.operator in _MIRROR
        out = []
        for b in batches:
            v = b.np_values()
            if is_cmp and self.scalar_on_lhs and not self.bool_mode:
                # `s < vec` filters on the VECTOR value: mirror to `vec > s`
                res = instant_ops.apply_binary(_MIRROR[self.operator],
                                               jnp.asarray(v), sval, False)
            elif self.scalar_on_lhs:
                res = instant_ops.apply_binary(self.operator, sval,
                                               jnp.asarray(v), self.bool_mode)
            else:
                res = instant_ops.apply_binary(self.operator, jnp.asarray(v),
                                               sval, self.bool_mode)
            # arithmetic and bool-mode comparisons drop the metric name;
            # filtering comparisons keep the input series identity
            keys = b.keys if is_cmp and not self.bool_mode \
                else _drop_metric(b.keys)
            out.append(PeriodicBatch(keys, b.steps, np.asarray(res),
                                     b.hist, b.bucket_tops))
        return out


def _drop_metric(keys: list[dict]) -> list[dict]:
    return [{k: v for k, v in t.items() if k != "_metric_"} for t in keys]


@dataclasses.dataclass
class AggregateMapReduce(RangeVectorTransformer):
    """Shard-local map+partial-reduce (reference: AggregateMapReduce,
    AggrOverRangeVectors.scala:74-120).  Emits AggPartialBatch for the
    ReduceAggregateExec above."""

    operator: AggregationOperator
    params: tuple = ()
    by: tuple = ()
    without: tuple = ()

    def apply(self, batches, ctx):
        agg = aggregator_for(self.operator)
        limit = ctx.query_context.group_by_cardinality_limit
        parts = [agg.map(b, self.by, self.without, self.params, limit)
                 for b in batches if isinstance(b, PeriodicBatch) and b.keys]
        # device-grid leaves may emit already-aggregated partials
        # (exec._try_grid_aggregated); merge them rather than re-mapping
        pre = [b for b in batches if isinstance(b, AggPartialBatch)]
        for p in pre:
            if len(p.group_keys) > limit:
                raise QueryError(
                    "", f"group-by cardinality {len(p.group_keys)} "
                        f"exceeds limit {limit}")
        parts = pre + parts
        if not parts:
            return []
        if len(parts) == 1:
            return parts
        return [agg.reduce(parts)]


@dataclasses.dataclass
class AggregatePresenter(RangeVectorTransformer):
    operator: AggregationOperator
    params: tuple = ()

    def apply(self, batches, ctx):
        agg = aggregator_for(self.operator)
        out = []
        for b in batches:
            if isinstance(b, AggPartialBatch):
                out.append(agg.present(b))
            else:
                out.append(b)
        return out


@dataclasses.dataclass
class MiscellaneousFunctionMapper(RangeVectorTransformer):
    function: MiscellaneousFunctionId
    args: tuple = ()

    def apply(self, batches, ctx):
        fid = self.function
        out = []
        for b in batches:
            if fid == MiscellaneousFunctionId.LABEL_REPLACE:
                dst, repl, src, regex = self.args[:4]
                rx = re.compile(regex)
                keys = []
                for t in b.keys:
                    t2 = dict(t)
                    m = rx.fullmatch(t.get(src, ""))
                    if m:
                        val = m.expand(_prom_template(repl))
                        if val:
                            t2[dst] = val
                        else:
                            t2.pop(dst, None)
                    keys.append(t2)
                out.append(dataclasses.replace(b, keys=keys))
            elif fid == MiscellaneousFunctionId.LABEL_JOIN:
                dst, sep, *srcs = self.args
                keys = []
                for t in b.keys:
                    t2 = dict(t)
                    val = sep.join(t.get(s, "") for s in srcs)
                    if val:
                        t2[dst] = val
                    else:
                        t2.pop(dst, None)
                    keys.append(t2)
                out.append(dataclasses.replace(b, keys=keys))
            elif fid == MiscellaneousFunctionId.HIST_TO_PROM_VECTORS:
                out.append(_hist_to_prom_series(b))
            else:
                raise QueryError("", f"unsupported misc function {fid}")
        return out


def _prom_template(repl: str) -> str:
    """PromQL $1 -> python regex \\1 template."""
    return re.sub(r"\$(\d+)", r"\\\1", repl)


def _hist_to_prom_series(b: PeriodicBatch) -> PeriodicBatch:
    """Explode histogram series into per-bucket le-labelled series
    (reference: HistToPromSeriesMapper, RangeVectorTransformer.scala:409)."""
    if b.hist is None:
        return b
    _, T, B = b.hist.shape
    S = len(b.keys)  # hist rows beyond the keys are series padding
    keys, rows = [], []
    tops = np.asarray(b.bucket_tops)
    for s in range(S):
        for j in range(B):
            t2 = dict(b.keys[s])
            top = tops[j]
            t2["le"] = "+Inf" if np.isinf(top) else _fmt(top)
            keys.append(t2)
            rows.append(np.asarray(b.hist)[s, :, j])
    return PeriodicBatch(keys, b.steps, np.stack(rows) if rows
                         else np.empty((0, T)))


def _fmt(v: float) -> str:
    return str(int(v)) if float(v) == int(v) else repr(float(v))


@dataclasses.dataclass
class SortFunctionMapper(RangeVectorTransformer):
    function: SortFunctionId

    def apply(self, batches, ctx):
        out = []
        desc = self.function == SortFunctionId.SORT_DESC
        for b in batches:
            if not isinstance(b, PeriodicBatch) or not b.keys:
                out.append(b)
                continue
            v = b.np_values()[:len(b.keys)]
            # sort by mean of last-step finite values (reference sorts by
            # average value like Prometheus's instant sort)
            with np.errstate(invalid="ignore"):
                key = np.nanmean(v, axis=1)
            key = np.where(np.isnan(key), -np.inf if not desc else np.inf, key)
            order = np.argsort(-key if desc else key, kind="stable")
            out.append(PeriodicBatch([b.keys[i] for i in order], b.steps,
                                     v[order],
                                     None if b.hist is None
                                     else np.asarray(b.hist)[:len(b.keys)][order],
                                     b.bucket_tops))
        return out


@dataclasses.dataclass
class AbsentFunctionMapper(RangeVectorTransformer):
    """absent(expr): 1 when no series present (reference:
    AbsentFunctionMapper, RangeVectorTransformer.scala:344)."""

    filters: tuple = ()
    start_ms: int = 0
    step_ms: int = 1
    end_ms: int = 0

    def apply(self, batches, ctx):
        steps = None
        present: Optional[np.ndarray] = None
        for b in batches:
            if isinstance(b, PeriodicBatch):
                steps = b.steps
                fin = np.isfinite(b.np_values()[:len(b.keys)])
                p = fin.any(axis=0)
                present = p if present is None else (present | p)
        if steps is None:
            steps = StepRange(self.start_ms, self.end_ms, max(self.step_ms, 1))
            present = np.zeros(steps.num_steps, dtype=bool)
        vals = np.where(present, np.nan, 1.0)[None, :]
        key = {f.column: f.filter.value for f in self.filters
               if type(f.filter).__name__ == "Equals" and f.column != "_metric_"}
        return [PeriodicBatch([key], steps, vals)]


@dataclasses.dataclass
class HistogramQuantileMapper(RangeVectorTransformer):
    """quantile over le-labelled bucket-per-series vectors (reference:
    HistogramQuantileMapper.scala:22).  Groups series by tags-minus-le,
    sorts buckets by le, interpolates."""

    q: float

    def apply(self, batches, ctx):
        out = []
        for b in batches:
            if not isinstance(b, PeriodicBatch) or not b.keys:
                continue
            groups: dict[tuple, list[int]] = {}
            les: list[float] = []
            for i, t in enumerate(b.keys):
                le = t.get("le")
                if le is None:
                    continue
                k = tuple(sorted((kk, vv) for kk, vv in t.items() if kk != "le"))
                groups.setdefault(k, []).append(i)
                les.append(float("inf") if le in ("+Inf", "Inf") else float(le))
            v = b.np_values()
            keys, rows = [], []
            for k, idxs in groups.items():
                idxs = sorted(idxs, key=lambda i: les[i])
                tops = np.array([les[i] for i in idxs])
                hist = np.stack([v[i] for i in idxs], axis=-1)[None]  # [1,T,B]
                res = np.asarray(histogram_ops.hist_quantile(
                    jnp.asarray(tops), jnp.asarray(hist), self.q))[0]
                keys.append(dict(k))
                rows.append(res)
            if keys:
                out.append(PeriodicBatch(keys, b.steps, np.stack(rows)))
        return out


@dataclasses.dataclass
class StitchRvsMapper(RangeVectorTransformer):
    """Merge same-key series split across time (reference:
    StitchRvsExec.scala:13,61): NaN slots fill from the other split."""

    def apply(self, batches, ctx):
        """Children may cover different sub-ranges of one step grid (time
        splits, raw-vs-downsample routing, HA failover segments): merge
        onto the UNION grid, each child's values placed by step offset."""
        pbs = [b for b in batches if isinstance(b, PeriodicBatch)]
        if not pbs:
            return []
        step = pbs[0].steps.step
        for b in pbs:
            if b.steps.step != step:
                raise ValueError(
                    f"cannot stitch mismatched steps {b.steps.step} != {step}")
        start = min(b.steps.start for b in pbs)
        end = max(b.steps.end for b in pbs)
        union = StepRange(start, end, step)
        n = union.num_steps
        merged: dict[tuple, np.ndarray] = {}
        order: list[tuple] = []
        for b in pbs:
            v = b.np_values()
            off = (b.steps.start - start) // step
            m = b.steps.num_steps
            for i, t in enumerate(b.keys):
                k = tuple(sorted(t.items()))
                cur = merged.get(k)
                if cur is None:
                    cur = np.full(n, np.nan)
                    merged[k] = cur
                    order.append(k)
                seg = cur[off:off + m]
                cur[off:off + m] = np.where(np.isnan(seg), v[i], seg)
        keys = [dict(k) for k in order]
        vals = np.stack([merged[k] for k in order]) if order \
            else np.empty((0, n))
        return [PeriodicBatch(keys, union, vals)]


@dataclasses.dataclass
class ScalarFunctionMapper(RangeVectorTransformer):
    """scalar(vector): single-series vector -> per-step scalar (NaN when 0
    or >1 series) (reference: ScalarFunctionMapper)."""

    def apply(self, batches, ctx):
        series = [b for b in batches
                  if isinstance(b, PeriodicBatch) and b.keys]
        total = sum(b.num_series for b in series)
        if total == 1:
            b = series[0]
            return [ScalarResult(b.steps, b.np_values()[0])]
        steps = series[0].steps if series else None
        if steps is None:
            for b in batches:
                if hasattr(b, "steps"):
                    steps = b.steps
        n = steps.num_steps if steps else 0
        return [ScalarResult(steps, np.full(n, np.nan))]


@dataclasses.dataclass
class VectorFunctionMapper(RangeVectorTransformer):
    """vector(scalar): scalar -> one labelless series."""

    def apply(self, batches, ctx):
        out = []
        for b in batches:
            if isinstance(b, ScalarResult):
                out.append(PeriodicBatch([{}], b.steps,
                                         np.asarray(b.values)[None, :]))
            else:
                out.append(b)
        return out


@dataclasses.dataclass
class DownsampleMapper(RangeVectorTransformer):
    """?downsample=<pixels> (ISSUE 16): M4 visualization downsampling
    as the OUTERMOST transformer — per series, per pixel bin, keep only
    the min/max/first/last samples (<= 4 x pixels points), which is
    everything a panel that wide can render (arXiv:2307.05389).

    The kept points stay on the original step grid: non-selected steps
    become NaN, the dense batch shape is unchanged, and the HTTP
    matrix serializer (which already drops NaN steps) emits only the
    selected points — the egress reduction costs zero serialization
    changes.  Selection runs in ops/grid.m4_grid (banded device
    kernel; portable/interpret path off-TPU), so a year-long panel
    never round-trips millions of samples through Python."""

    pixels: int

    def apply(self, batches, ctx):
        from filodb_tpu.ops.grid import m4_grid_auto
        from filodb_tpu.utils.observability import downsample_metrics
        out = []
        for b in batches:
            if not isinstance(b, PeriodicBatch) or b.hist is not None \
                    or b.num_series == 0 \
                    or b.steps.num_steps <= self.pixels:
                out.append(b)   # already at panel resolution (or not
                continue        # a plain matrix): nothing to thin
            vals = np.asarray(b.np_values(), np.float32)  # [S, T]
            ns, nsteps = vals.shape
            planes = np.asarray(m4_grid_auto(vals.T, self.pixels))
            w = -(-nsteps // self.pixels)
            # local bin indices -> global step indices; -1 marks empty
            idx = planes[:, 4:8, :].astype(np.int64)      # [P, 4, S]
            keep = idx >= 0
            idx = idx + (np.arange(self.pixels) * w)[:, None, None]
            sel = np.zeros((ns, nsteps), bool)
            s_ix = np.broadcast_to(np.arange(ns)[None, None, :], idx.shape)
            sel[s_ix[keep], np.minimum(idx[keep], nsteps - 1)] = True
            points_in = int(np.isfinite(vals).sum())
            points_out = int(sel.sum())
            thinned = np.where(sel, vals, np.nan)
            if ctx is not None:
                ctx.note_downsample(points_in=points_in,
                                    points_out=points_out)
            m = downsample_metrics()
            m["points_in"].inc(points_in)
            m["points_out"].inc(points_out)
            out.append(PeriodicBatch(b.keys, b.steps, thinned))
        return out
