"""Query-frontend result cache: chunk-aligned partial memoization.

At millions of users, thousands of browsers refresh the SAME dashboard
panels every few seconds — and every refresh used to re-run the full
scan -> window -> aggregate pipeline from scratch.  Chunks are
immutable once encoded, so partials computed over them never change
(the insight PR 14's incremental rule evaluation already proved
bit-equal to cold evaluation); this module lifts that machinery into
the serving path itself.

Two memoization shapes, both behind :class:`ResultCachingPlanner`:

**Range queries** split on chunk-aligned segment boundaries
(``segment_ms``, defaulting to the dataset's flush interval).  A
segment whose input interval is fully covered by encoded (immutable)
chunks is evaluated once through the normal planner and its final
batches memoized, keyed by ``(plan fingerprint, segment)`` where the
fingerprint is the canonical PromQL rendering (the representation the
generative round-trip sweep protects) + step/phase/lookback.  A hit is
honored only when the segment's chunk-id digest, the integrity
quarantine epoch, and the replica routing token all still match — so a
cache hit can never serve data a cache miss would refuse.  On a
refresh, only the open head sliver (and any invalidated segment) is
recomputed, and the stitch merge is the same ``StitchRvsMapper`` the
time-split and rollup-boundary paths already use.  The rollup tier
boundary needs no token here by construction: the cache wraps each
tier's planner BELOW the resolution router, so when the boundary moves
a step from raw to rolled, the ROUTER changes which cache is asked —
stale raw segments simply stop being requested.

**Instant queries** of the incremental shapes (``fn(sel[w])`` and
``agg by (..)(fn(sel[w]))``) keep a resident
:mod:`~filodb_tpu.query.windowstate` window per fingerprint: each
refresh fetches only ``(fetched_through, now]`` through the normal
planner path and merges with the resident window via the normal
aggregator map / ``AggPartialBatch`` reduce — the open head chunk's
sliver is all that is re-scanned.  A part-id signature over the window
interval resets the state whenever a series appears or vanishes (a new
series materializing with OLD timestamps is exactly the case warm
state cannot see), and the quarantine epoch / routing token reset it
like any other entry.

Byte accounting is HbmLedger-style: every entry's bytes are tracked on
insert/resize/evict and ``reconcile()`` proves the total equals a walk
of the live entries (asserted in tests).  Bounded LRU; metrics
``filodb_resultcache_*``; ``/admin/resultcache`` snapshot.
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from filodb_tpu.coordinator.planner import QueryPlanner
from filodb_tpu.coordinator.planners import (logical_plan_to_promql,
                                             copy_with_time_range,
                                             plan_lookback_ms)
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import ExecContext, ExecPlan, LeafExecPlan
from filodb_tpu.query.model import PeriodicBatch, QueryContext
from filodb_tpu.query.windowstate import (AggWindowState, WindowState,
                                          WindowUnsupported,
                                          agg_window_spec, window_spec)

_METRICS = None


def _m() -> dict:
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import resultcache_metrics
        _METRICS = resultcache_metrics()
    return _METRICS


# aggregation operators whose segment-split evaluation is bit-equal to
# the unsplit evaluation: their map partials are zero-insensitive
# moments, so a series absent from one segment (vs present with NaN
# steps) contributes an exact 0.0 either way.  Rank-based reduces
# (topk/quantile/count_values) are excluded.
_CACHE_AGG_OPS = frozenset({"SUM", "COUNT", "MIN", "MAX", "AVG", "GROUP",
                            "STDDEV", "STDVAR"})

# hard ceiling on segments per query: a multi-year range at a 1h
# segment would otherwise balloon the plan walk
_MAX_SEGMENTS = 512


def _cacheable(plan) -> bool:
    """Allowlist walk: only shapes whose split evaluation provably
    matches unsplit evaluation (see _CACHE_AGG_OPS) and whose canonical
    PromQL rendering captures every semantic knob."""
    if isinstance(plan, lp.PeriodicSeries):
        rs = plan.raw_series
        return not plan.offset_ms and not rs.columns and not rs.offset_ms
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        rs = plan.series
        return (not plan.offset_ms and isinstance(rs, lp.RawSeries)
                and not rs.columns and not rs.offset_ms)
    if isinstance(plan, lp.Aggregate):
        return (getattr(plan.operator, "name", "") in _CACHE_AGG_OPS
                and not plan.params and _cacheable(plan.vectors))
    if isinstance(plan, lp.ApplyInstantFunction):
        return (all(not isinstance(a, lp.LogicalPlan)
                    for a in plan.function_args)
                and _cacheable(plan.vectors))
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        return (isinstance(plan.scalar_arg,
                           (int, float, lp.ScalarFixedDoublePlan,
                            lp.ScalarTimeBasedPlan))
                and _cacheable(plan.vector))
    return False


def plan_fingerprint(plan, step_ms: int, start_ms: int) -> Optional[str]:
    """Cache key half 1: the canonical PromQL rendering (time range is
    not part of the rendering) + step + step-grid phase + lookback.
    ``None`` = not a cacheable shape."""
    if not _cacheable(plan):
        return None
    if len(lp.leaf_raw_series(plan)) != 1:
        return None
    try:
        rendered = logical_plan_to_promql(plan)
    except ValueError:
        return None
    phase = (start_ms % step_ms) if step_ms > 0 else 0
    return (f"{rendered}|step={step_ms}|phase={phase}"
            f"|look={plan_lookback_ms(plan)}")


def _quarantine_epoch() -> int:
    from filodb_tpu.integrity import QUARANTINE
    return QUARANTINE.epoch()


def _input_pad_ms(plan) -> int:
    """How far BELOW a step the plan's leaf scans can reach: lookback
    PLUS window (``copy_with_time_range`` widens the selector by their
    sum).  Deliberately >= plan_lookback_ms (which takes the max) — an
    over-wide immutability probe only costs extra invalidations, never
    staleness."""
    import dataclasses as _dc
    look = max((rs.lookback_ms or 0 for rs in lp.leaf_raw_series(plan)),
               default=0)
    window = 0

    def walk(p):
        nonlocal window
        if _dc.is_dataclass(p):
            window = max(window, getattr(p, "window_ms", 0) or 0)
            for f in _dc.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
    walk(plan)
    return look + window


def _segment_states(memstore, dataset: str, filters, segs,
                    look: int) -> dict:
    """Per-segment ``(chunk-id digest, closed)`` across the dataset's
    local shards, in ONE pass per partition (a per-segment walk would
    multiply the lock traffic by the segment count — measured 25%
    query overhead at 6 segments, vs <1% for this shape).

    ``closed`` = no partition has mutable (write-buffer /
    pending-encode) rows at or below the segment's input end, i.e. a
    result computed over it can never change without the digest
    changing too: encoded chunks are immutable, per-partition ingest is
    monotone, and a new series materializing with old timestamps
    appears as a new part id with chunks/buffers that change the
    digest or the closed bit."""
    lo = min(s.lo for s in segs) - look
    hi = max(s.hi for s in segs)
    hashers = {s.k: hashlib.blake2b(digest_size=16) for s in segs}
    closed = {s.k: True for s in segs}
    for sh in memstore.shards(dataset):
        lookup = sh.lookup_partitions(list(filters), lo, hi)
        # the shard's epoch-cached span table (rebuilt only on chunk
        # freeze/removal) restricted to the matched partitions; each
        # segment then digests with one vectorized overlap mask
        pid_a, cid_a, cs_a, ce_a = sh.chunk_span_table()
        if len(pid_a) and len(lookup.part_ids):
            sel = np.isin(pid_a, np.asarray(lookup.part_ids, np.int64))
            pid_a, cid_a = pid_a[sel], cid_a[sel]
            cs_a, ce_a = cs_a[sel], ce_a[sel]
        elif len(pid_a):
            pid_a = pid_a[:0]
        # the shard-wide mutable floor (cached per ingest epoch):
        # filter-independent and so conservative — an unmatched
        # partition's buffer marking a segment open costs a cache
        # miss, never staleness
        mut_min = sh.mutable_floor()
        for s in segs:
            a, b = s.lo - look, s.hi
            h = hashers[s.k]
            h.update(struct.pack("<i", sh.shard_num))
            if len(pid_a):
                m = (ce_a >= a) & (cs_a <= b)
                if m.any():
                    h.update(pid_a[m].tobytes())
                    h.update(cid_a[m].tobytes())
            if mut_min is not None and mut_min <= b:
                closed[s.k] = False
            for pk in lookup.missing_partkeys:
                # paged/evicted series: persisted = immutable;
                # membership changes invalidate every segment
                h.update(pk)
    return {s.k: (hashers[s.k].hexdigest(), closed[s.k]) for s in segs}


def _pid_signature(memstore, dataset: str, filters,
                   t0: int, t1: int) -> bytes:
    """Cheap series-set signature over ``[t0, t1]`` — the instant
    window states reset when it changes (series born or evicted)."""
    h = hashlib.blake2b(digest_size=16)
    for sh in memstore.shards(dataset):
        lookup = sh.lookup_partitions(list(filters), t0, t1)
        h.update(struct.pack("<iq", sh.shard_num, len(lookup.part_ids)))
        h.update(np.asarray(lookup.part_ids, np.int64).tobytes())
        for pk in lookup.missing_partkeys:
            h.update(pk)
    return h.digest()


# ---------------------------------------------------------------------------
# entries
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SegmentEntry:
    """One memoized closed segment: the final (post-transformer)
    batches of the sub-plan, read-only."""

    batches: list
    nbytes: int
    digest: str
    quarantine_epoch: int
    routing_token: int
    result_samples: int


@dataclasses.dataclass
class HeadEntry:
    """The memoized STABLE PREFIX of a range query's open head segment
    (ISSUE 20, PR 17 follow-up): steps whose inputs all end below the
    dataset's mutable floor never change without the prefix digest
    changing too, so a warm dashboard's refresh replays them and
    recomputes only the true sliver ``(stable_hi, head.hi]`` — the
    mutable tail — instead of the whole head segment."""

    batches: list
    nbytes: int
    digest: str          # over the prefix input range [lo - look, stable_hi]
    stable_hi: int       # last step covered by the memoized prefix
    lo: int              # first step of the head segment's grid
    step: int
    quarantine_epoch: int
    routing_token: int
    result_samples: int


class InstantEntry:
    """One fingerprint's resident instant window state."""

    __slots__ = ("state", "lock", "pid_sig", "quarantine_epoch",
                 "routing_token", "dead", "nbytes")

    def __init__(self, state):
        self.state = state
        self.lock = threading.Lock()
        self.pid_sig: Optional[bytes] = None
        self.quarantine_epoch = -1
        self.routing_token = 0
        self.dead = False          # WindowUnsupported: permanent bypass
        self.nbytes = 512


def _entry_bytes(batches) -> tuple[int, int]:
    """(nbytes, result samples) for a list of stored batches."""
    nbytes, samples = 256, 0
    for b in batches:
        nbytes += int(getattr(b.values, "nbytes", 0)) + 64 * len(b.keys)
        if b.hist is not None:
            nbytes += int(b.hist.nbytes)
        samples += len(b.keys) * b.steps.num_steps
    return nbytes, samples


class ResultCache:
    """Bounded byte-LRU over segment entries + instant window states,
    with exact byte reconciliation (the HbmLedger discipline: totals
    always equal a walk of the live entries)."""

    def __init__(self, dataset: str, max_bytes: int = 64 * 1024 * 1024,
                 enabled: bool = False, doorkeeper: bool = True):
        self.dataset = dataset
        self.enabled = bool(enabled)
        self.max_bytes = int(max_bytes)
        # doorkeeper admission (the TinyLFU idea): only a fingerprint
        # seen BEFORE gets the split/probe/store treatment, so a stream
        # of never-repeating queries pays one set probe instead of
        # digesting and storing segments nothing will ever hit
        self.doorkeeper = bool(doorkeeper)
        self._seen: OrderedDict = OrderedDict()     # guarded-by: _lock
        self._entries: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._bytes = 0                             # guarded-by: _lock
        self._lock = threading.Lock()
        # local counters mirrored into the metric families (admin view)
        self.hits = 0
        self.misses = 0
        self.skips = 0
        self.evictions = 0
        self.invalidations = 0

    # ------------------------------------------------------------- config

    def configure(self, enabled: Optional[bool] = None,
                  max_bytes: Optional[int] = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if max_bytes is not None:
            self.max_bytes = int(max_bytes)
            with self._lock:
                evicted = self._evict_to_budget_locked()
            self._note_evictions(evicted)

    # ------------------------------------------------------------ entries

    def admit(self, fp: str) -> bool:
        """Doorkeeper probe: True when this fingerprint has been seen
        before (worth caching).  A first sighting registers it and
        returns False — the caller serves the uncached path untouched.
        Survives :meth:`clear` on purpose: the operator flushes
        ENTRIES, not the evidence of which panels repeat."""
        if not self.doorkeeper:
            return True
        with self._lock:
            if fp in self._seen:
                self._seen.move_to_end(fp)
                return True
            self._seen[fp] = None
            while len(self._seen) > 4096:
                self._seen.popitem(last=False)
            return False

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
            return entry

    def put(self, key, entry) -> None:
        if entry.nbytes > self.max_bytes // 4:
            return               # one giant panel must not flush the rest
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += entry.nbytes
            evicted = self._evict_to_budget_locked()
            total = self._bytes
        self._note_evictions(evicted)
        _m()["bytes"].set(total, dataset=self.dataset)

    def resize(self, key, nbytes: int) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            self._bytes += int(nbytes) - entry.nbytes
            entry.nbytes = int(nbytes)
            evicted = self._evict_to_budget_locked()
            total = self._bytes
        self._note_evictions(evicted)
        _m()["bytes"].set(total, dataset=self.dataset)

    def _evict_to_budget_locked(self) -> int:
        n = 0
        while self._bytes > self.max_bytes and self._entries:
            _k, old = self._entries.popitem(last=False)
            self._bytes -= old.nbytes
            n += 1
        return n

    def _note_evictions(self, n: int) -> None:
        if n:
            self.evictions += n
            _m()["evictions"].inc(n, dataset=self.dataset, reason="budget")

    def discard(self, key, reason: str) -> None:
        """Invalidate one entry (stale digest / epoch / routing)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            total = self._bytes
        if old is not None:
            self.invalidations += 1
            _m()["invalidations"].inc(dataset=self.dataset, reason=reason)
            _m()["bytes"].set(total, dataset=self.dataset)

    def note_invalidation(self, reason: str) -> None:
        """An in-place state reset (instant windows go cold rather than
        being dropped) still counts as an invalidation."""
        self.invalidations += 1
        _m()["invalidations"].inc(dataset=self.dataset, reason=reason)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
        _m()["bytes"].set(0, dataset=self.dataset)

    # ----------------------------------------------------------- counters

    def note_hit(self, kind: str) -> None:
        self.hits += 1
        _m()["hits"].inc(dataset=self.dataset, kind=kind)

    def note_miss(self, kind: str) -> None:
        self.misses += 1
        _m()["misses"].inc(dataset=self.dataset, kind=kind)

    def note_skip(self, reason: str) -> None:
        self.skips += 1
        _m()["skipped"].inc(dataset=self.dataset, reason=reason)

    # -------------------------------------------------------------- views

    def reconcile(self) -> tuple[int, int]:
        """(accounted total, walked total) — equal by construction;
        asserted in tests, dumped by /admin/resultcache."""
        with self._lock:
            return self._bytes, sum(e.nbytes
                                    for e in self._entries.values())

    def snapshot(self) -> dict:
        with self._lock:
            entries = len(self._entries)
            nbytes = self._bytes
            heads = [
                {"fingerprint": k[0][:160], "segment": k[1],
                 "stable_hi": e.stable_hi,
                 "samples": e.result_samples}
                for k, e in self._entries.items()
                if isinstance(e, HeadEntry)]
            instants = [
                {"fingerprint": k[0][:160],
                 "series": e.state.resident_series,
                 "samples": e.state.resident_samples,
                 "fetched_through_ms": e.state.fetched_through_ms,
                 "dead": e.dead}
                for k, e in self._entries.items()
                if isinstance(e, InstantEntry)]
        return {"enabled": self.enabled, "max_bytes": self.max_bytes,
                "bytes": nbytes, "entries": entries,
                "hits": self.hits, "misses": self.misses,
                "skips": self.skips, "evictions": self.evictions,
                "invalidations": self.invalidations,
                "head_windows": heads,
                "instant_windows": instants}


# ---------------------------------------------------------------------------
# planner integration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Seg:
    """One segment of a range query's step grid."""

    k: int           # absolute segment index (t // segment_ms)
    lo: int          # first step in this segment
    hi: int          # last step in this segment
    full: bool = False   # covers the segment's complete step set
    key: tuple = ()
    digest: str = ""
    storable: bool = False


class ResultCachingPlanner(QueryPlanner):
    """Wraps one dataset's planner with the result cache.  Sits BELOW
    the rollup resolution router (each tier's planner gets its own
    wrapper), so tier selection and boundary stitching stay upstream
    and the cache only ever sees ranges the router already assigned."""

    def __init__(self, dataset: str, inner: QueryPlanner, memstore,
                 cache: ResultCache, segment_ms: int = 3_600_000,
                 routing_token_fn=None, instant: bool = True):
        self.dataset = dataset
        self.inner = inner
        self.memstore = memstore
        self.cache = cache
        self.segment_ms = max(int(segment_ms), 1000)
        self.routing_token_fn = routing_token_fn
        self.instant = instant

    # ------------------------------------------------------------- helpers

    def _routing_token(self) -> int:
        """Replica-routing validity key (ShardMapper.routing_token).
        Folds the topology GENERATION (ISSUE 13), so a live shard
        split's cutover invalidates every entry sliced on the retired
        shard layout — without it, a warm dashboard would keep serving
        hits computed against the pre-split fan-out.  This is the
        topology-generation lint's sanctioned validation path."""
        if self.routing_token_fn is None:
            return 0
        return int(self.routing_token_fn())

    def _plan_local(self, plan, qctx) -> bool:
        fn = getattr(self.inner, "plan_is_local", None)
        return True if fn is None else fn(plan, qctx)

    # --------------------------------------------------------- materialize

    def materialize(self, plan: lp.LogicalPlan,
                    qctx: Optional[QueryContext] = None) -> ExecPlan:
        qctx = qctx or QueryContext()
        cache = self.cache
        if not cache.enabled or not isinstance(plan, lp.PeriodicSeriesPlan):
            # bypass observability (ISSUE 19): only cache-SHAPED plans
            # count as "disabled" bypasses — metadata/raw plans never
            # were cache traffic and would drown the signal
            if not cache.enabled \
                    and isinstance(plan, lp.PeriodicSeriesPlan):
                _m()["bypass"].inc(dataset=self.dataset,
                                   reason="disabled")
            return self.inner.materialize(plan, qctx)
        try:
            start, step, end = lp.time_range(plan)
        except ValueError:
            return self.inner.materialize(plan, qctx)
        fp = plan_fingerprint(plan, step, start)
        if fp is None:
            cache.note_skip("shape")
            _m()["bypass"].inc(dataset=self.dataset,
                               reason="unfingerprintable")
            return self.inner.materialize(plan, qctx)
        if not self._plan_local(plan, qctx):
            # remote-shard plans bypass the cache silently (the known
            # federation coherence gap) — now measurable (ISSUE 19)
            cache.note_skip("remote")
            _m()["bypass"].inc(dataset=self.dataset, reason="remote")
            return self.inner.materialize(plan, qctx)
        if not cache.admit(fp):
            cache.note_skip("first-sight")
            return self.inner.materialize(plan, qctx)
        if start == end:
            return self._materialize_instant(plan, qctx, fp, start)
        return self._materialize_range(plan, qctx, fp, start, step, end)

    # -------------------------------------------------------------- range

    def _materialize_range(self, plan, qctx, fp, start, step, end):
        cache = self.cache
        seg_ms = self.segment_ms
        first_k, last_k = start // seg_ms, end // seg_ms
        if last_k - first_k < 1 or last_k - first_k + 1 > _MAX_SEGMENTS:
            cache.note_skip("range")
            return self.inner.materialize(plan, qctx)
        look = _input_pad_ms(plan)
        filters = tuple(lp.leaf_raw_series(plan)[0].filters)
        qepoch = _quarantine_epoch()
        rtok = self._routing_token()
        segs: list[_Seg] = []
        phase = start % step
        for k in range(first_k, last_k + 1):
            lo = start + -(-(max(k * seg_ms, start) - start) // step) * step
            hi = start + ((min((k + 1) * seg_ms - 1, end) - start)
                          // step) * step
            if lo > hi:
                continue         # the step grid skips this segment
            # FULL segments carry the segment's complete absolute-grid
            # step set — only those are cache-eligible.  A partial
            # first/last segment's step subset depends on THIS query's
            # start/end, so a memoized copy would replay steps outside
            # (or short of) the next refresh's range.
            full_lo = k * seg_ms + (phase - k * seg_ms) % step
            full_hi = full_lo + ((k + 1) * seg_ms - 1 - full_lo) \
                // step * step
            segs.append(_Seg(k, lo, hi,
                             full=(lo == full_lo and hi == full_hi)))
        if not segs:
            return self.inner.materialize(plan, qctx)
        # head-segment prefix (ISSUE 20, PR 17 follow-up): the open
        # head segment's steps below the mutable floor are stable —
        # probe/extend a memoized prefix so a warm refresh recomputes
        # only the true sliver.  Both the probe of the resident entry
        # and the digest for the new prefix ride the SAME
        # _segment_states pass as synthetic rows (one partition walk).
        head = segs[-1]
        head_key = (fp, head.k, seg_ms, "head")
        head_entry = self.cache.get(head_key)
        if not isinstance(head_entry, HeadEntry):
            head_entry = None
        mut_min = None
        for sh in self.memstore.shards(self.dataset):
            f = sh.mutable_floor()
            if f is not None:
                mut_min = f if mut_min is None else min(mut_min, f)
        if mut_min is None:
            stable_hi_now: Optional[int] = head.hi
        elif mut_min > head.lo:
            stable_hi_now = head.lo + ((min(head.hi, mut_min - 1)
                                        - head.lo) // step) * step
        else:
            stable_hi_now = None
        probe: list[_Seg] = []
        _PROBE_K, _STORE_K = ("head", "probe"), ("head", "store")
        if head_entry is not None and head.lo <= head_entry.stable_hi \
                <= head.hi:
            probe.append(_Seg(_PROBE_K, head.lo, head_entry.stable_hi))
        if stable_hi_now is not None:
            probe.append(_Seg(_STORE_K, head.lo, stable_hi_now))
        states = _segment_states(self.memstore, self.dataset, filters,
                                 segs + probe, look)
        hits: dict[int, SegmentEntry] = {}
        for seg in segs:
            seg.key = (fp, seg.k, seg_ms)
            digest, closed = states[seg.k]
            seg.digest, seg.storable = digest, closed and seg.full
            if not seg.storable:
                continue
            entry = cache.get(seg.key)
            if entry is None or isinstance(entry, InstantEntry):
                continue
            if entry.digest != digest:
                cache.discard(seg.key, "chunks")
            elif entry.quarantine_epoch != qepoch:
                cache.discard(seg.key, "quarantine")
            elif entry.routing_token != rtok:
                cache.discard(seg.key, "routing")
            else:
                hits[seg.k] = entry
        head_hit: Optional[HeadEntry] = None
        head_store: Optional[tuple] = None
        if not head.storable:
            if head_entry is not None and states.get(_PROBE_K):
                digest, closed = states[_PROBE_K]
                if head_entry.lo != head.lo or head_entry.step != step \
                        or not head.lo <= head_entry.stable_hi <= head.hi:
                    pass             # different grid: plain miss
                elif head_entry.digest != digest or not closed:
                    cache.discard(head_key, "chunks")
                elif head_entry.quarantine_epoch != qepoch:
                    cache.discard(head_key, "quarantine")
                elif head_entry.routing_token != rtok:
                    cache.discard(head_key, "routing")
                else:
                    head_hit = head_entry
            if head_hit is None and stable_hi_now is not None:
                digest, closed = states[_STORE_K]
                if closed:           # the floor did not move mid-pass
                    head_store = (head_key, head, stable_hi_now, digest,
                                  step)
        if not hits and head_hit is None and head_store is None \
                and not any(s.storable for s in segs):
            # nothing cached and nothing cacheable (all-open range):
            # serve the unsplit plan — zero overhead on the miss path
            cache.note_skip("open")
            return self.inner.materialize(plan, qctx)
        # group consecutive non-hit segments into runs: one sub-plan per
        # run (a cold first refresh is exactly ONE child == the unsplit
        # plan), sliced per segment for storage afterwards
        items: list[tuple] = []
        run: list[_Seg] = []

        def flush_run():
            if not run:
                return
            sub = copy_with_time_range(plan, run[0].lo, run[-1].hi)
            items.append(("run", self.inner.materialize(sub, qctx),
                          list(run)))
            run.clear()

        for seg in segs:
            if seg.k in hits:
                flush_run()
                items.append(("hit", hits[seg.k], seg))
            elif seg is head and head_hit is not None:
                # replay the stable prefix; recompute only the sliver
                flush_run()
                items.append(("head", head_hit, seg))
                sliver_lo = head_hit.stable_hi + step
                if sliver_lo <= seg.hi:
                    sub = copy_with_time_range(plan, sliver_lo, seg.hi)
                    items.append(("run", self.inner.materialize(sub,
                                                                qctx),
                                  []))
            else:
                run.append(seg)
        flush_run()
        return CachedRangeExec(self, items, qepoch, rtok, qctx,
                               head_store=head_store)

    # ------------------------------------------------------------ instant

    def _materialize_instant(self, plan, qctx, fp, eval_ms):
        cache = self.cache
        if not self.instant:
            cache.note_skip("instant-off")
            return self.inner.materialize(plan, qctx)
        spec = window_spec(plan)
        aspec = None if spec is not None else agg_window_spec(plan)
        if spec is None and aspec is None:
            cache.note_skip("instant-shape")
            return self.inner.materialize(plan, qctx)
        key = (fp, "instant")
        entry = cache.get(key)
        if entry is not None and not isinstance(entry, InstantEntry):
            entry = None
        if entry is not None and entry.dead:
            cache.note_skip("instant-unsupported")
            return self.inner.materialize(plan, qctx)
        if entry is None:
            state = WindowState(spec) if spec is not None \
                else AggWindowState(aspec)
            entry = InstantEntry(state)
            cache.put(key, entry)
        return InstantWindowExec(self, plan, qctx, key, entry, eval_ms)


# ---------------------------------------------------------------------------
# exec plans
# ---------------------------------------------------------------------------


class CachedRangeExec(ExecPlan):
    """Root of a cached range query: replays hit segments, executes
    miss runs through the normal path, stores the newly-closed
    segments, and stitches — the same merge the time-split path uses."""

    def __init__(self, planner: ResultCachingPlanner, items: list,
                 quarantine_epoch: int, routing_token: int,
                 query_context: Optional[QueryContext] = None,
                 head_store: Optional[tuple] = None):
        super().__init__(query_context)
        self._planner = planner
        self._items = items
        self._qepoch = quarantine_epoch
        self._rtok = routing_token
        # (key, head _Seg, stable_hi, digest) when the head segment's
        # stable prefix should be memoized off this execution
        self._head_store = head_store

    @property
    def children(self):
        return [it[1] for it in self._items if it[0] == "run"]

    def do_execute(self, ctx: ExecContext) -> list:
        from filodb_tpu.query.transformers import StitchRvsMapper
        cache = self._planner.cache
        if len(self._items) == 1 and self._items[0][0] == "run":
            # all-miss (cold) query: one child covers the whole range —
            # execute it exactly like the uncached path (no stitch) and
            # only slice the closed segments into the cache afterwards
            _kind, child, seg_metas = self._items[0]
            sub_ctx = ExecContext(ctx.memstore, ctx.query_context,
                                  ctx.parallelism)
            res = child.execute(sub_ctx)
            ctx.absorb_stats_from(sub_ctx)
            for seg in seg_metas:
                if seg.storable:
                    cache.note_miss("range")
            self._store(res, seg_metas)
            ctx.note_resultcache(recomputed=res.stats.samples_scanned)
            return res.batches
        batches: list = []
        cached_samples = recomputed = 0
        for item in self._items:
            if item[0] in ("hit", "head"):
                _kind, entry, _seg = item
                batches.extend(entry.batches)
                cached_samples += entry.result_samples
                cache.note_hit("range" if item[0] == "hit" else "head")
                continue
            _kind, child, seg_metas = item
            sub_ctx = ExecContext(ctx.memstore, ctx.query_context,
                                  ctx.parallelism)
            res = child.execute(sub_ctx)
            ctx.absorb_stats(res.stats)
            recomputed += res.stats.samples_scanned
            for seg in seg_metas:
                if seg.storable:
                    cache.note_miss("range")
            self._store(res, seg_metas)
            batches.extend(res.batches)
        ctx.note_resultcache(cached=cached_samples, recomputed=recomputed)
        return StitchRvsMapper().apply(batches, ctx)

    def _store(self, res, seg_metas) -> None:
        """Memoize each closed segment of a fresh run by slicing the
        run's step axis.  Partial or corrupt-overlapping results are
        never stored — a hit must be indistinguishable from a miss."""
        if res.stats.shards_down or res.stats.corrupt_chunks_excluded:
            return
        cache = self._planner.cache
        for b in res.batches:
            if not isinstance(b, PeriodicBatch):
                return           # unexpected shape: don't memoize any of it
            if b.hist is not None:
                # histogram planes don't survive the warm-path stitch
                # (StitchRvsMapper rebuilds value-only batches), so a
                # hit would drop buckets a miss serves — never store
                cache.note_skip("hist")
                return
        for seg in seg_metas:
            if not seg.storable:
                continue
            stored: list = []
            ok = True
            for b in res.batches:
                st = b.steps
                if (seg.lo - st.start) % st.step or seg.lo < st.start \
                        or seg.hi > st.end:
                    ok = False
                    break
                i0 = (seg.lo - st.start) // st.step
                i1 = (seg.hi - st.start) // st.step + 1
                vals = np.ascontiguousarray(b.np_values()[:, i0:i1])
                vals.setflags(write=False)
                stored.append(PeriodicBatch(
                    list(b.keys), StepRange(seg.lo, seg.hi, st.step),
                    vals))
            if not ok:
                continue
            nbytes, samples = _entry_bytes(stored)
            cache.put(seg.key, SegmentEntry(
                stored, nbytes, seg.digest, self._qepoch, self._rtok,
                samples))
        if self._head_store is not None \
                and any(s is self._head_store[1] for s in seg_metas):
            self._store_head(res)

    def _store_head(self, res) -> None:
        """Memoize the head segment's stable prefix off a fresh run
        (same slicing discipline as the closed segments — the guards in
        :meth:`_store` already vetoed partial/hist results)."""
        key, seg, stable_hi, digest, step = self._head_store
        cache = self._planner.cache
        stored: list = []
        for b in res.batches:
            st = b.steps
            if st.step != step or (seg.lo - st.start) % st.step \
                    or seg.lo < st.start or stable_hi > st.end:
                return
            i0 = (seg.lo - st.start) // st.step
            i1 = (stable_hi - st.start) // st.step + 1
            vals = np.ascontiguousarray(b.np_values()[:, i0:i1])
            vals.setflags(write=False)
            stored.append(PeriodicBatch(
                list(b.keys), StepRange(seg.lo, stable_hi, st.step),
                vals))
        # an empty ``stored`` (no series matched) is still worth
        # memoizing: the refresh skips the stable steps, and the digest
        # guards late series births
        nbytes, samples = _entry_bytes(stored)
        cache.put(key, HeadEntry(stored, nbytes, digest, stable_hi,
                                 seg.lo, step, self._qepoch, self._rtok,
                                 samples))
        cache.note_miss("head")


class InstantWindowExec(LeafExecPlan):
    """A repeatedly-refreshed instant panel served from a resident
    window: the delta fetch runs through the normal planner path (so
    admission, quarantine exclusion, and stats all apply) and only the
    head sliver is re-scanned."""

    def __init__(self, planner: ResultCachingPlanner, plan, qctx,
                 key, entry: InstantEntry, eval_ms: int):
        super().__init__(qctx)
        self._planner = planner
        self._plan = plan
        self._key = key
        self._entry = entry
        self.eval_ms = int(eval_ms)

    def _fallback(self, ctx) -> list:
        child = self._planner.inner.materialize(self._plan,
                                                self.query_context)
        sub_ctx = ExecContext(ctx.memstore, ctx.query_context,
                              ctx.parallelism)
        res = child.execute(sub_ctx)
        ctx.absorb_stats(res.stats)
        return res.batches

    def _fetch_sharded(self, sub_ctx, filters, start_ms, end_ms) -> list:
        from filodb_tpu.query.windowstate import batches_to_buckets
        plan = lp.RawSeries(lp.IntervalSelector(int(start_ms), int(end_ms)),
                            tuple(filters))
        ep = self._planner.inner.materialize(plan, self.query_context)
        res = ep.execute(sub_ctx)
        return batches_to_buckets(res.batches)

    def do_execute(self, ctx: ExecContext) -> list:
        planner, cache, entry = self._planner, self._planner.cache, \
            self._entry
        state = entry.state
        spec = state.spec.window if isinstance(state, AggWindowState) \
            else state.spec
        eval_ms = self.eval_ms
        with entry.lock:
            sig = _pid_signature(ctx.memstore, planner.dataset,
                                 spec.filters, eval_ms - spec.window_ms,
                                 eval_ms)
            qepoch = _quarantine_epoch()
            rtok = planner._routing_token()
            warm = state.fetched_through_ms is not None
            if warm:
                reason = None
                if entry.pid_sig != sig:
                    reason = "series"
                elif entry.quarantine_epoch != qepoch:
                    reason = "quarantine"
                elif entry.routing_token != rtok:
                    reason = "routing"
                elif eval_ms < state.fetched_through_ms:
                    reason = "regressed"
                if reason is not None:
                    state.reset()
                    cache.note_invalidation(reason)
                    warm = False
            entry.pid_sig = sig
            entry.quarantine_epoch = qepoch
            entry.routing_token = rtok
            sub_ctx = ExecContext(ctx.memstore, ctx.query_context,
                                  ctx.parallelism)
            fetch = lambda f, s, e: self._fetch_sharded(sub_ctx, f, s, e)  # noqa: E731
            try:
                if isinstance(state, AggWindowState):
                    limit = ctx.query_context.group_by_cardinality_limit
                    batch = state.tick(eval_ms, fetch, group_limit=limit)
                else:
                    flat = lambda f, s, e: [r for b in fetch(f, s, e)  # noqa: E731
                                            for r in b]
                    pairs = state.tick(eval_ms, flat)
                    batch = self._pairs_batch(pairs, eval_ms)
            except WindowUnsupported:
                entry.dead = True
                cache.note_skip("instant-unsupported")
                ctx.absorb_stats_from(sub_ctx)
                return self._fallback(ctx)
            ctx.absorb_stats_from(sub_ctx)
            fetched = sub_ctx.counter("samples")
            resident = state.resident_samples
            cache.note_hit("instant") if warm else cache.note_miss("instant")
            ctx.note_resultcache(cached=max(0, resident - fetched),
                                 recomputed=fetched)
            # resize() computes its delta from entry.nbytes and updates
            # it — pre-mutating the entry here would zero the delta and
            # leave the byte ledger stuck at the insert-time size
            cache.resize(self._key, 512 + 24 * resident)
        return [] if batch is None else [batch]

    @staticmethod
    def _pairs_batch(pairs, eval_ms) -> Optional[PeriodicBatch]:
        if not pairs:
            return None
        keys = [t for t, _v in pairs]
        vals = np.asarray([[v] for _t, v in pairs], dtype=np.float64)
        return PeriodicBatch(keys, StepRange(eval_ms, eval_ms, 1000), vals)
