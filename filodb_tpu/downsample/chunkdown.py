"""Chunk downsamplers + period markers.

Capability match for the reference's streaming downsample primitives
(reference: core/src/main/scala/filodb.core/downsample/
ChunkDownsampler.scala:1-371 — dMin/dMax/dSum/dCount/dAvg/dAvgAc/dAvgSc/
tTime/dLast/hSum/hLast; DownsamplePeriodMarker.scala:163 — time- and
counter-aware period splitting).  Instead of per-row iterators, each
downsampler is a vectorized reduction over row ranges of a decoded chunk:
periods are computed once per chunk as ``np.searchsorted`` row boundaries
and every downsampler reduces with numpy ufuncs over those slices — the
same whole-chunk-at-a-time shape the TPU kernels use.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

import numpy as np

_SPEC_RE = re.compile(r"^([a-zA-Z]+)\((\d+)\)$")


def _ranges_reduce(vals: np.ndarray, bounds: np.ndarray, fn, empty):
    """Reduce ``vals`` over [bounds[i], bounds[i+1]) slices, NaN-aware."""
    out = np.full(len(bounds) - 1, empty, dtype=np.float64)
    for i in range(len(bounds) - 1):
        seg = vals[bounds[i]:bounds[i + 1]]
        seg = seg[~np.isnan(seg)] if seg.dtype.kind == "f" else seg
        if len(seg):
            out[i] = fn(seg)
    return out


@dataclasses.dataclass(frozen=True)
class ChunkDownsampler:
    """One output column of a downsample record."""

    name: str
    col_id: int  # input column index in the raw schema (0 = timestamp)

    def downsample(self, ts: np.ndarray, cols: Sequence, bounds: np.ndarray,
                   period_ends: np.ndarray):
        raise NotImplementedError

    @property
    def is_time(self) -> bool:
        return False


class TTime(ChunkDownsampler):
    """Timestamp column: the period end time (reference: TimeDownsampler)."""

    @property
    def is_time(self) -> bool:
        return True

    def downsample(self, ts, cols, bounds, period_ends):
        return period_ends.astype(np.int64)


class DMin(ChunkDownsampler):
    def downsample(self, ts, cols, bounds, period_ends):
        return _ranges_reduce(cols[self.col_id - 1], bounds, np.min, np.nan)


class DMax(ChunkDownsampler):
    def downsample(self, ts, cols, bounds, period_ends):
        return _ranges_reduce(cols[self.col_id - 1], bounds, np.max, np.nan)


class DSum(ChunkDownsampler):
    def downsample(self, ts, cols, bounds, period_ends):
        return _ranges_reduce(cols[self.col_id - 1], bounds, np.sum, np.nan)


class DCount(ChunkDownsampler):
    def downsample(self, ts, cols, bounds, period_ends):
        return _ranges_reduce(cols[self.col_id - 1], bounds, len, 0.0)


class DAvg(ChunkDownsampler):
    def downsample(self, ts, cols, bounds, period_ends):
        return _ranges_reduce(cols[self.col_id - 1], bounds, np.mean, np.nan)


class DAvgSc(ChunkDownsampler):
    """Average from separate sum and count columns — re-downsampling a
    ds-gauge dataset (reference: AvgScDownsampler)."""

    def __init__(self, name: str, sum_col: int, count_col: int):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "col_id", sum_col)
        object.__setattr__(self, "count_col", count_col)

    def downsample(self, ts, cols, bounds, period_ends):
        s = _ranges_reduce(cols[self.col_id - 1], bounds, np.sum, np.nan)
        c = _ranges_reduce(cols[self.count_col - 1], bounds, np.sum, 0.0)
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(c > 0, s / c, np.nan)


class DAvgAc(ChunkDownsampler):
    """Average from an avg column weighted by a count column (reference:
    AvgAcDownsampler)."""

    def __init__(self, name: str, avg_col: int, count_col: int):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "col_id", avg_col)
        object.__setattr__(self, "count_col", count_col)

    def downsample(self, ts, cols, bounds, period_ends):
        avg = cols[self.col_id - 1]
        cnt = cols[self.count_col - 1]
        out = np.full(len(bounds) - 1, np.nan)
        for i in range(len(bounds) - 1):
            a = avg[bounds[i]:bounds[i + 1]]
            c = cnt[bounds[i]:bounds[i + 1]]
            ok = ~np.isnan(a)
            if ok.any() and c[ok].sum() > 0:
                out[i] = float((a[ok] * c[ok]).sum() / c[ok].sum())
        return out


class DLast(ChunkDownsampler):
    """Last value in the period — correct for counters since within-period
    increase is recoverable from consecutive lasts (reference:
    LastValueDDownsampler)."""

    def downsample(self, ts, cols, bounds, period_ends):
        vals = cols[self.col_id - 1]
        out = np.full(len(bounds) - 1, np.nan)
        for i in range(len(bounds) - 1):
            seg = vals[bounds[i]:bounds[i + 1]]
            fin = np.flatnonzero(~np.isnan(seg))
            if len(fin):
                out[i] = seg[fin[-1]]
        return out


class HLast(ChunkDownsampler):
    """Last histogram row per period (reference: LastValueHDownsampler).
    Input column decodes to (HistogramBuckets, int64[rows, buckets])."""

    def downsample(self, ts, cols, bounds, period_ends):
        buckets, rows = cols[self.col_id - 1]
        out = np.zeros((len(bounds) - 1, rows.shape[1] if rows.ndim == 2 else 0),
                       dtype=np.float64)
        for i in range(len(bounds) - 1):
            if bounds[i + 1] > bounds[i]:
                out[i] = rows[bounds[i + 1] - 1]
        return buckets, out


class HSum(ChunkDownsampler):
    """Bucket-wise histogram sum per period (reference: SumHDownsampler)."""

    def downsample(self, ts, cols, bounds, period_ends):
        buckets, rows = cols[self.col_id - 1]
        out = np.zeros((len(bounds) - 1, rows.shape[1] if rows.ndim == 2 else 0),
                       dtype=np.float64)
        for i in range(len(bounds) - 1):
            if bounds[i + 1] > bounds[i]:
                out[i] = rows[bounds[i]:bounds[i + 1]].sum(axis=0)
        return buckets, out


_REGISTRY = {
    "tTime": TTime, "dMin": DMin, "dMax": DMax, "dSum": DSum,
    "dCount": DCount, "dAvg": DAvg, "dLast": DLast, "hLast": HLast,
    "hSum": HSum,
}


def parse_downsampler(spec: str) -> ChunkDownsampler:
    """Parse "dMin(1)" / "tTime(0)" specs (reference: DownsamplerName +
    ChunkDownsampler.downsamplers factory).  dAvgSc/dAvgAc take two column
    ids: "dAvgSc(3,4)"."""
    m = re.match(r"^([a-zA-Z]+)\((\d+)(?:,(\d+))?\)$", spec)
    if not m:
        raise ValueError(f"bad downsampler spec: {spec}")
    name, c1, c2 = m.group(1), int(m.group(2)), m.group(3)
    if name == "dAvgSc":
        return DAvgSc(spec, c1, int(c2))
    if name == "dAvgAc":
        return DAvgAc(spec, c1, int(c2))
    cls = _REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown downsampler {name!r} in {spec}")
    return cls(spec, c1)


# ---------------------------------------------------------------------------
# Period markers
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PeriodMarker:
    """Splits a chunk's rows into downsample periods.  Returns
    (bounds, period_ends): bounds is a row-index array of length P+1;
    period i covers rows [bounds[i], bounds[i+1]) and is stamped
    period_ends[i]."""

    col_id: int

    def periods(self, ts: np.ndarray, cols: Sequence, resolution_ms: int
                ) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def _time_bounds(self, ts: np.ndarray, resolution_ms: int):
        if len(ts) == 0:
            return np.zeros(1, dtype=np.int64), np.zeros(0, dtype=np.int64)
        # period p covers (p*res, (p+1)*res]; stamp = period end, like the
        # reference's timestamp normalization
        pids = (ts - 1) // resolution_ms
        uniq, starts = np.unique(pids, return_index=True)
        bounds = np.append(starts, len(ts)).astype(np.int64)
        ends = ((uniq + 1) * resolution_ms).astype(np.int64)
        return bounds, ends


class TimePeriodMarker(PeriodMarker):
    """Fixed time buckets (reference: TimeDownsamplePeriodMarker)."""

    def periods(self, ts, cols, resolution_ms):
        return self._time_bounds(ts, resolution_ms)


class CounterPeriodMarker(PeriodMarker):
    """Time buckets plus extra splits at counter resets so downsampled
    counters preserve rate correction (reference:
    CounterDownsamplePeriodMarker.scala:163: periods additionally split
    where the counter drops)."""

    def periods(self, ts, cols, resolution_ms):
        bounds, ends = self._time_bounds(ts, resolution_ms)
        vals = cols[self.col_id - 1]
        if len(vals) < 2:
            return bounds, ends
        with np.errstate(invalid="ignore"):
            drops = np.flatnonzero(np.diff(vals) < 0) + 1  # row starts a reset
        if len(drops) == 0:
            return bounds, ends
        # insert a split right before each drop row: the truncated period is
        # stamped with its last pre-reset sample ts; periods ending on a
        # time boundary keep that boundary stamp
        drop_set = set(int(d) for d in drops)
        new_bounds = np.union1d(bounds, drops).astype(np.int64)
        new_ends = np.empty(len(new_bounds) - 1, dtype=np.int64)
        for i in range(len(new_bounds) - 1):
            nxt = int(new_bounds[i + 1])
            if nxt in drop_set:
                new_ends[i] = ts[nxt - 1]
            else:
                # original time period containing these rows
                j = np.searchsorted(bounds, new_bounds[i], side="right") - 1
                new_ends[i] = ends[j]
        return new_bounds, new_ends


def parse_period_marker(spec: str) -> PeriodMarker:
    """Parse "time(0)" / "counter(1)" (reference: DownsamplePeriodMarker
    factory)."""
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(f"bad period marker spec: {spec}")
    name, col = m.group(1), int(m.group(2))
    if name == "time":
        return TimePeriodMarker(col)
    if name == "counter":
        return CounterPeriodMarker(col)
    raise ValueError(f"unknown period marker {name!r}")
