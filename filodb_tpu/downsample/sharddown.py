"""Flush-time downsampling: ShardDownsampler + publisher.

Capability match for the reference's streaming downsample path
(reference: core/src/main/scala/filodb.core/downsample/
ShardDownsampler.scala:58 — populateDownsampleRecords called from
TimeSeriesShard.doFlushSteps :915-917; DownsamplePublisher.scala — emits
RecordContainers to Kafka downsample topics, one per resolution).

Here the publisher is an in-process queue (the Kafka-compatible edge can
drain it), and records are built with the standard RecordBuilder against
the schema's downsample schema (e.g. gauge -> ds-gauge).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.core.chunk import ChunkSet, decode_chunkset
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import ColumnType, Schema
from filodb_tpu.downsample import griddown
from filodb_tpu.downsample.chunkdown import (parse_downsampler,
                                             parse_period_marker)

DEFAULT_RESOLUTIONS_MS = (60_000, 3_600_000)  # 1m / 1h (conf resolutions)


def decode_concat_with_keys(schema: Schema, pairs):
    """Group ``(tags, chunkset)`` pairs by partition, decode once, and
    concatenate in chunk-id order -> ``[(partkey, tags, ts, cols)]``.
    The keyed sibling of :meth:`ShardDownsampler._decode_concat` — the
    live rollup engine buffers decoded rows per PARTKEY across ticks,
    so it needs the key the flush-path helper drops."""
    from filodb_tpu.core.chunk import decode_partitions_batch
    by_pk: dict[bytes, list] = {}
    for tags, cs in pairs:
        by_pk.setdefault(cs.partkey, [tags, []])[1].append(cs)
    groups = []
    for _pk, (_tags, css) in by_pk.items():
        css.sort(key=lambda c: c.info.chunk_id)
        groups.append(css)
    parts = decode_partitions_batch(schema, groups)
    return [(pk, tags, ts, cols)
            for (pk, (tags, _css)), (ts, cols)
            in zip(by_pk.items(), parts)]


class DownsamplePublisher:
    """Collects downsample record containers per resolution (reference:
    DownsamplePublisher -> Kafka downsample topics)."""

    def publish(self, resolution_ms: int, shard: int,
                containers: list[bytes]) -> None:
        raise NotImplementedError


class MemoryDownsamplePublisher(DownsamplePublisher):
    """In-process sink: resolution -> list[(shard, container)]."""

    def __init__(self) -> None:
        self.published: dict[int, list[tuple[int, bytes]]] = defaultdict(list)

    def publish(self, resolution_ms, shard, containers) -> None:
        self.published[resolution_ms].extend(
            (shard, c) for c in containers)

    def drain(self, resolution_ms: int) -> list[tuple[int, bytes]]:
        out = self.published.get(resolution_ms, [])
        self.published[resolution_ms] = []
        return out


class ShardDownsampler:
    """Downsamples freshly-flushed chunksets into records at each
    resolution (reference: ShardDownsampler.populateDownsampleRecords)."""

    def __init__(self, dataset: str, shard: int, schema: Schema,
                 publisher: DownsamplePublisher,
                 resolutions_ms: Sequence[int] = DEFAULT_RESOLUTIONS_MS,
                 enabled: bool = True):
        self.dataset = dataset
        self.shard = shard
        self.schema = schema
        self.publisher = publisher
        self.resolutions = tuple(resolutions_ms)
        # downsample_schema == own name means self-downsampling (counters,
        # histograms re-aggregate into the same schema)
        self.enabled = enabled and bool(schema.data.downsamplers) \
            and schema.data.downsample_schema is not None
        if self.enabled:
            self.downsamplers = [parse_downsampler(s)
                                 for s in schema.data.downsamplers]
            self.marker = parse_period_marker(
                schema.data.downsample_period_marker)
            self.ds_schema = schema.downsample or schema

    def downsample_chunksets(self, chunksets: Sequence[tuple[dict, ChunkSet]]
                             ) -> int:
        """(tags, chunkset) pairs -> publish one container set per
        resolution.  Chunks of one partition are concatenated before period
        assignment so a period spanning a mid-flush chunk boundary yields
        ONE record, not conflicting partials.  Returns records emitted."""
        if not self.enabled or not chunksets:
            return 0
        decoded = self._decode_concat(chunksets)
        staged = self._try_stage_grid(decoded)
        emitted = 0
        for res in self.resolutions:
            builder = RecordBuilder(self.ds_schema)
            served = None
            if staged is not None:
                got = griddown.grid_outputs(staged, res, self.downsamplers,
                                            self.marker)
                if got is not None:
                    served, outs, pends, plive = got
                    emitted += self._emit_grid(builder, decoded, served,
                                               outs, pends, plive)
            for si, (tags, ts, cols) in enumerate(decoded):
                if served is not None and served[si]:
                    continue
                emitted += self._emit(builder, tags, ts, cols, res)
            containers = builder.containers()
            if containers:
                self.publisher.publish(res, self.shard, containers)
        return emitted

    def _decode_concat(self, chunksets):
        """Group (tags, chunkset) pairs by partition, decode once, and
        concatenate in chunk-id order so a period spanning a mid-flush
        chunk boundary yields ONE record, not conflicting partials."""
        return [(tags, ts, cols) for _pk, tags, ts, cols
                in decode_concat_with_keys(self.schema, chunksets)]

    def prepare_arrays(self, chunksets):
        """Decode + grid-stage ONCE for use across every resolution
        (the batch job re-uses one decode for the whole resolution
        ladder).  Returns an opaque handle for :meth:`downsample_arrays`
        or None when there is nothing to do."""
        if not self.enabled or not chunksets:
            return None
        decoded = self._decode_concat(chunksets)
        return decoded, self._try_stage_grid(decoded)

    def prepare_decoded(self, decoded):
        """:meth:`prepare_arrays` for callers that already hold decoded
        per-series arrays ``[(tags, ts, cols)]`` — the live rollup
        engine's resident buffers skip the chunkset decode but share
        the grid staging (and its resolution-ladder cascade)."""
        if not self.enabled or not decoded:
            return None
        decoded = [(tags, ts, cols) for tags, ts, cols in decoded
                   if len(ts)]
        if not decoded:
            return None
        return decoded, self._try_stage_grid(decoded)

    def downsample_planes(self, prepared, resolution_ms: int):
        """COLUMNAR batch-job output for the grid-served, fully-live
        series (the aligned common case): one shared period-end vector
        plus per-column [P, S_f] planes, ready for the contiguous 2D
        batch encode — no per-series slicing at all.  Returns
        (tags_list, pe [P] int64, planes, leftovers) where ``leftovers``
        are the per-series (tags, ts, cols) tuples for partially-live or
        unserved series (same contract as :meth:`downsample_arrays`), or
        None when this resolution can't be served from the grid (the
        caller falls back to :meth:`downsample_arrays`)."""
        if prepared is None:
            return None
        decoded, staged = prepared
        if staged is None:
            return None
        got = griddown.grid_outputs(staged, resolution_ms,
                                    self.downsamplers, self.marker)
        if got is None:
            return None
        served, outs, pends, plive = got
        outs = [np.asarray(o) if o is not None else None for o in outs]
        pends = np.asarray(pends)
        plive = np.asarray(plive)
        # the k_align row padding leaves dead periods at the grid's head
        # and tail; "fully live" is judged (and planes emitted) over the
        # live span only, or alignment pads would push EVERY series to
        # the per-series path
        row_any = plive.any(axis=1)                    # [P]
        if row_any.any():
            a = int(np.argmax(row_any))
            b = int(len(row_any) - np.argmax(row_any[::-1]))
        else:
            a = b = 0
        core = slice(a, b)
        full = served & plive[core].all(axis=0)        # [S]
        sidx = np.flatnonzero(full)
        tags_list = [decoded[int(i)][0] for i in sidx]
        planes = [out[core][:, sidx] for out in outs if out is not None]
        pe = pends[core].astype(np.int64)
        leftovers = []
        pe_cache: dict[bytes, np.ndarray] = {}
        for si, (tags, ts, cols) in enumerate(decoded):
            if full[si]:
                continue
            if served[si]:
                pm = plive[:, si]
                if not pm.any():
                    continue
                key = pm.tobytes()
                pe_s = pe_cache.get(key)
                if pe_s is None:
                    pe_s = pe_cache[key] = pends[pm].astype(np.int64)
                leftovers.append((tags, pe_s,
                                  [out[pm, si] for out in outs
                                   if out is not None]))
                continue
            got = self._series_downsample(tags, ts, cols, resolution_ms)
            if got is not None:
                leftovers.append(got)
        return tags_list, pe, planes, leftovers

    def _series_downsample(self, tags: dict, ts: np.ndarray, cols,
                           resolution_ms: int):
        """Per-series host downsample: (tags, t_col, val_cols) or None
        when the series contributes no periods.  Shared by the planar
        leftovers and the downsample_arrays fallback — the period-marker
        semantics must never diverge between the two paths."""
        if len(ts) == 0:
            return None
        bounds, ends = self.marker.periods(ts, cols, resolution_ms)
        if len(ends) == 0:
            return None
        outputs = [d.downsample(ts, cols, bounds, ends)
                   for d in self.downsamplers]
        t_col = None
        val_cols = []
        for d, out in zip(self.downsamplers, outputs):
            if d.is_time:
                t_col = np.asarray(out, dtype=np.int64)
            else:
                val_cols.append(out)
        if t_col is None:
            t_col = np.asarray(ends, dtype=np.int64)
        return tags, t_col, val_cols

    def downsample_arrays(self, prepared, resolution_ms: int):
        """Batch-job form of :meth:`downsample_chunksets`: returns
        per-series arrays ``(tags, ts [P] int64, cols)`` instead of
        building records — the direct chunk-build path of the offline
        downsampler (reference: the Spark BatchDownsampler writes
        chunksets straight to the store, DownsamplerMain.scala:43,
        never re-ingesting through a memstore).  ``cols`` entries are
        float arrays, or (buckets, rows) for histogram outputs, in
        downsample-schema column order (time column first)."""
        if prepared is None:
            return []
        decoded, staged = prepared
        served = None
        results = []
        if staged is not None:
            got = griddown.grid_outputs(staged, resolution_ms,
                                        self.downsamplers, self.marker)
            if got is not None:
                served, outs, pends, plive = got
                # ONE host readback per plane: per-series fancy-indexing
                # on device arrays would dispatch a jax op per series
                outs = [np.asarray(o) if o is not None else None
                        for o in outs]
                pends = np.asarray(pends)
                plive = np.asarray(plive)
                pe_all = pends.astype(np.int64)
                for si, (tags, _ts, _cols) in enumerate(decoded):
                    if not served[si]:
                        continue
                    pm = plive[:, si]
                    if pm.all():
                        # fully-live series (the aligned common case):
                        # column views, no mask scan/copy per column
                        pe = pe_all
                        cols = [out[:, si] for out in outs
                                if out is not None]
                    elif pm.any():
                        pe = pends[pm].astype(np.int64)
                        cols = [out[pm, si] for out in outs
                                if out is not None]
                    else:
                        continue
                    results.append((tags, pe, cols))
        for si, (tags, ts, cols) in enumerate(decoded):
            if served is not None and served[si]:
                continue
            got = self._series_downsample(tags, ts, cols, resolution_ms)
            if got is not None:
                results.append(got)
        return results

    def _try_stage_grid(self, decoded):
        """Stage the whole batch as a [B, S] bucket grid when every
        downsampler and resolution is grid-servable (griddown.py — the
        serving kernels driven as a batch downsampler, SURVEY §7)."""
        import math
        if not griddown.grid_supported(self.downsamplers):
            return None
        g = griddown.detect_gstep([ts for _, ts, _ in decoded])
        if not g or any(res % g != 0 for res in self.resolutions):
            return None
        ks = [res // g for res in self.resolutions]
        k_align = math.lcm(*ks)
        if k_align > 4096:
            return None
        from filodb_tpu.downsample.chunkdown import CounterPeriodMarker
        reset_col = self.marker.col_id - 1 \
            if isinstance(self.marker, CounterPeriodMarker) else None
        return griddown.stage_grid([ts for _, ts, _ in decoded],
                                   [cols for _, _, cols in decoded],
                                   g, k_align, reset_col=reset_col)

    def _emit_grid(self, builder: RecordBuilder, decoded, served, outs,
                   period_ends, plive) -> int:
        """Vectorized emission: one add_series per served series, only
        the periods that contain samples (host-path parity)."""
        n = 0
        for si, (tags, _ts, _cols) in enumerate(decoded):
            if not served[si]:
                continue
            pm = plive[:, si]
            if not pm.any():
                continue
            pe = period_ends[pm]
            cols = [out[pm, si] for out in outs if out is not None]
            builder.add_series(pe.tolist(), [c.tolist() for c in cols],
                               tags)
            n += len(pe)
        return n

    def _emit(self, builder: RecordBuilder, tags: dict, ts: np.ndarray,
              cols: Sequence, resolution_ms: int) -> int:
        if len(ts) == 0:
            return 0
        bounds, ends = self.marker.periods(ts, cols, resolution_ms)
        outputs = [d.downsample(ts, cols, bounds, ends)
                   for d in self.downsamplers]
        n = 0
        for p in range(len(ends)):
            t = None
            values = []
            for d, out in zip(self.downsamplers, outputs):
                if d.is_time:
                    t = int(out[p])
                elif isinstance(out, tuple):  # histogram column
                    from filodb_tpu.codecs import histcodec
                    buckets, rows = out
                    values.append(histcodec.encode_hist_value(buckets, rows[p]))
                else:
                    values.append(float(out[p]))
            if t is None:
                t = int(ends[p])
            builder.add(t, values, tags)
            n += 1
        return n
