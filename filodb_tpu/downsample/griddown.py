"""Vectorized (device-capable) batch downsampling: the grid fast path.

SURVEY §7 step 9: "downsampler = same kernels driven by a batch driver".
Raw samples on a regular scrape cadence lay out as the SAME time-major
bucket grid the serving path uses (ops/grid.py layout invariant: row c
holds the sample with ``ts in ((c-1)*g, c*g]``); a downsample period at
resolution ``res = K * g`` then covers exactly K consecutive rows, and
every per-period aggregate (dMin/dMax/dSum/dCount/dAvg/dLast) collapses
to a reshape ``[B, S] -> [P, K, S]`` + one axis-1 reduction — no
per-period loops, one jit dispatch for all series and all aggregates
(reference analog: spark-jobs BatchDownsampler.downsampleBatch applying
ChunkDownsamplers chunk-by-chunk, BatchDownsampler.scala:36; VERDICT r2
weak #6 / do-this #6).

Series that violate the one-sample-per-bucket invariant, counter series
containing resets (the counter period marker splits periods mid-bucket),
histogram columns, and re-downsampling aggregates (dAvgSc/dAvgAc) fall
back to the per-series host path in chunkdown.py — the fast path is
never wrong, only absent.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.downsample.chunkdown import (CounterPeriodMarker, DAvg,
                                             DCount, DLast, DMax, DMin, DSum,
                                             TTime)

_STD_STEPS = (1_000, 2_000, 5_000, 10_000, 15_000, 30_000, 60_000,
              120_000, 300_000, 600_000, 900_000, 1_800_000, 3_600_000)

# downsampler classes the grid path serves (others -> host fallback)
_GRID_DOWNSAMPLERS = (TTime, DMin, DMax, DSum, DCount, DAvg, DLast)


def grid_supported(downsamplers: Sequence) -> bool:
    return all(isinstance(d, _GRID_DOWNSAMPLERS) for d in downsamplers)


def detect_gstep(ts_list: Sequence[np.ndarray]) -> Optional[int]:
    """Scrape cadence across a batch: median inter-sample delta snapped
    to the nearest standard interval (same policy as the serving grid,
    memstore/devicestore.py _detect_gstep).  Samples <=64 series — the
    median over a spread subset decides the same snap as the full batch,
    and the full np.diff+median over millions of samples was a
    measurable slice of the rollup budget; stage_grid still verifies the
    one-sample-per-bucket invariant on EVERY series."""
    if len(ts_list) > 64:
        stride = max(1, len(ts_list) // 64)
        ts_list = ts_list[::stride][:64]
    deltas = [np.diff(ts) for ts in ts_list if len(ts) >= 3]
    if not deltas:
        return None
    d = np.concatenate(deltas)
    d = d[d > 0]
    if len(d) == 0:
        return None
    med = float(np.median(d))
    best = min(_STD_STEPS, key=lambda c: abs(c - med))
    if abs(best - med) <= 0.5 * best:
        return best
    return int(med) if med >= 1 else None


class StagedGrid:
    """One [B, S] staging of a batch of series, shared by every
    resolution whose K divides the alignment."""

    def __init__(self, g: int, c_start: int, vals: list[np.ndarray],
                 present: np.ndarray, eligible: np.ndarray,
                 has_reset: np.ndarray):
        self.g = g
        self.c_start = c_start          # global bucket index of row 0
        self.vals = vals                # per data column, [B, S]
        self.present = present          # bool [B, S]: a sample occupies
        self.eligible = eligible        # bool [S]: one-per-bucket held
        self.has_reset = has_reset      # bool [S]: any value drop
        # (col, K) -> reduced planes: coarser resolutions CASCADE from a
        # finer one's planes instead of re-reducing the raw grid (the
        # 1m -> 15m -> 1h rollup ladder costs ~one reduce, not three)
        self.planes_cache: dict[tuple[int, int], dict] = {}

    @property
    def nrows(self) -> int:
        return self.vals[0].shape[0]


def stage_grid(ts_list: Sequence[np.ndarray], cols_list: Sequence[Sequence],
               g: int, k_align: int, dtype=np.float64,
               reset_col: Optional[int] = None) -> Optional[StagedGrid]:
    """Scatter a batch of series into the bucket grid.  ``k_align``
    aligns row 0 so every resolution's periods tile whole rows
    (c_start = lcm-of-K boundary + 1).  ``reset_col`` is the data-column
    index the counter period marker watches for drops (None for time
    markers).  Returns None when nothing can be staged (no scalar
    columns / empty batch)."""
    S = len(ts_list)
    if S == 0 or g <= 0:
        return None
    ncols = len(cols_list[0])
    for cols in cols_list:
        for c in cols:
            if not isinstance(c, np.ndarray):
                return None                    # histogram/string: host path
    c_min = None
    c_max = None
    buckets_list = []
    for ts in ts_list:
        if len(ts) == 0:
            buckets_list.append(np.empty(0, np.int64))
            continue
        b = (ts + g - 1) // g                  # bucket c: ts in ((c-1)g, cg]
        buckets_list.append(b)
        c_min = int(b[0]) if c_min is None else min(c_min, int(b[0]))
        c_max = int(b[-1]) if c_max is None else max(c_max, int(b[-1]))
    if c_min is None:
        return None
    # align row 0 to a period boundary for every resolution
    c_start = ((c_min - 1) // k_align) * k_align + 1
    B = (-(-(c_max - c_start + 1) // k_align)) * k_align
    if B <= 0 or B * S > 64_000_000:           # batch-size guard (~0.5 GB)
        return None
    present = np.zeros((B, S), bool)
    eligible = np.ones(S, bool)
    has_reset = np.zeros(S, bool)

    def _nan_grids():
        return [np.full((B, S), np.nan, dtype) for _ in range(ncols)]
    # FAST PATH: every series on the identical timestamp vector (the
    # scrape-aligned common case) — one row-slice assignment replaces
    # the flat 2-D scatter and the per-series eligibility walk runs once
    b0 = buckets_list[0]
    if len(b0) and all(b is b0 or np.array_equal(b, b0)
                       for b in buckets_list):
        rows0 = b0 - c_start
        if rows0[0] >= 0 and not (np.diff(b0) <= 0).any():
            if reset_col is not None:
                for s, cols in enumerate(cols_list):
                    if len(cols[reset_col]) > 1:
                        with np.errstate(invalid="ignore"):
                            if (np.diff(cols[reset_col]) < 0).any():
                                has_reset[s] = True
            n = len(b0)
            contiguous = n == int(rows0[-1]) - int(rows0[0]) + 1
            if contiguous:
                # dense row block: ONE slice assignment per column into
                # an uninitialized grid (NaN-fill only the two pad
                # slabs) — the fancy-index scatter + full-grid prefill
                # doubled the staging memory traffic
                r0 = int(rows0[0])
                present[r0:r0 + n, :] = True
                vals = []
                for ci in range(ncols):
                    grid = np.empty((B, S), dtype)
                    grid[:r0] = np.nan
                    grid[r0 + n:] = np.nan
                    np.stack([cols[ci] for cols in cols_list], axis=1,
                             out=grid[r0:r0 + n])
                    vals.append(grid)
            else:
                vals = _nan_grids()
                present[rows0, :] = True
                for ci in range(ncols):
                    stacked = np.stack([cols[ci] for cols in cols_list],
                                       axis=1)          # [n, S]
                    vals[ci][rows0, :] = stacked
            return StagedGrid(g, c_start, vals, present, eligible,
                              has_reset)
    # per-series eligibility walk, then ONE scatter across the batch
    vals = _nan_grids()
    rows_parts, scol_parts, col_parts = [], [], [[] for _ in range(ncols)]
    for s, (b, cols) in enumerate(zip(buckets_list, cols_list)):
        if len(b) == 0:
            continue
        rows = b - c_start
        if rows[0] < 0 or (np.diff(b) <= 0).any():
            eligible[s] = False                # >1 sample per bucket / OOO
            continue
        if reset_col is not None and len(cols[reset_col]) > 1:
            with np.errstate(invalid="ignore"):
                if (np.diff(cols[reset_col]) < 0).any():
                    has_reset[s] = True
        rows_parts.append(rows)
        scol_parts.append(np.full(len(rows), s, np.int64))
        for ci in range(ncols):
            col_parts[ci].append(cols[ci])
    if rows_parts:
        rows_cat = np.concatenate(rows_parts)
        scol_cat = np.concatenate(scol_parts)
        present[rows_cat, scol_cat] = True     # NaN-valued samples still
        for ci in range(ncols):                # open their period (host
            vals[ci][rows_cat, scol_cat] = \
                np.concatenate(col_parts[ci])  # semantics)
    return StagedGrid(g, c_start, vals, present, eligible, has_reset)


@functools.lru_cache(maxsize=1)
def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def _period_reduce_impl(vals, P: int, K: int):
    """[B, S] -> per-period aggregates [P, S]: one reshape, one pass per
    aggregate, all fused under jit (XLA keeps the [P, K, S] view
    virtual).  Runs on whatever the default backend is — the TPU under
    the batch driver, CPU in tests."""
    _, jnp = _jax()
    S = vals.shape[1]
    v = vals.reshape(P, K, S)
    fin = jnp.isfinite(v)
    cnt = fin.sum(axis=1).astype(vals.dtype)
    vsum = jnp.where(fin, v, 0.0).sum(axis=1)
    vmin = jnp.where(fin, v, jnp.inf).min(axis=1)
    vmax = jnp.where(fin, v, -jnp.inf).max(axis=1)
    live = cnt > 0
    # last finite row per period: highest finite k index
    kidx = jnp.arange(K, dtype=jnp.int32)[None, :, None]
    last_k = jnp.where(fin, kidx, -1).max(axis=1)          # [P, S]
    lastv = jnp.take_along_axis(v, jnp.maximum(last_k, 0)[:, None, :],
                                axis=1)[:, 0, :]
    nan = jnp.nan
    return {
        "cnt": cnt,
        "sum": jnp.where(live, vsum, nan),
        "min": jnp.where(live, vmin, nan),
        "max": jnp.where(live, vmax, nan),
        "avg": jnp.where(live, vsum / jnp.maximum(cnt, 1.0), nan),
        "last": jnp.where(live, lastv, nan),
    }


def _period_reduce_np(vals: np.ndarray, P: int, K: int
                      ) -> dict[str, np.ndarray]:
    """Numpy twin of _period_reduce_impl: full float64, used whenever
    the jax backend would silently downcast (x64 off, e.g. the default
    TPU runtime) — PERSISTED downsample data must not lose precision
    relative to the per-series host path."""
    S = vals.shape[1]
    v = vals.reshape(P, K, S)
    fin = np.isfinite(v)
    cnt = fin.sum(axis=1).astype(vals.dtype)
    vsum = np.where(fin, v, 0.0).sum(axis=1)
    vmin = np.where(fin, v, np.inf).min(axis=1)
    vmax = np.where(fin, v, -np.inf).max(axis=1)
    live = cnt > 0
    kidx = np.arange(K, dtype=np.int32)[None, :, None]
    last_k = np.where(fin, kidx, -1).max(axis=1)
    lastv = np.take_along_axis(v, np.maximum(last_k, 0)[:, None, :],
                               axis=1)[:, 0, :]
    nan = np.nan
    return {
        "cnt": cnt,
        "sum": np.where(live, vsum, nan),
        "min": np.where(live, vmin, nan),
        "max": np.where(live, vmax, nan),
        "avg": np.where(live, vsum / np.maximum(cnt, 1.0), nan),
        "last": np.where(live, lastv, nan),
    }


def _cascade_planes(pl: dict[str, np.ndarray], Pc: int, Kr: int
                    ) -> dict[str, np.ndarray]:
    """Derive coarse-period planes from fine-period planes: Kr fine
    periods tile one coarse period.  min/max/count/last are EXACT
    (order-insensitive); sum (and avg, re-derived from sum/count —
    never avg-of-avgs) re-associates the floating-point summation tree,
    so it can differ from a direct reduce in the low bits — within the
    tolerance the downsample equivalence tests assert, not bit-identity."""
    import warnings

    def rs(a):
        return a.reshape(Pc, Kr, -1)

    cnt = rs(pl["cnt"]).sum(axis=1)
    live = cnt > 0
    nan = np.nan
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        vsum = np.nansum(rs(pl["sum"]), axis=1)
        vmin = np.nanmin(rs(pl["min"]), axis=1)
        vmax = np.nanmax(rs(pl["max"]), axis=1)
    lf = rs(pl["last"])
    kidx = np.arange(Kr, dtype=np.int32)[None, :, None]
    last_k = np.where(np.isfinite(lf), kidx, -1).max(axis=1)
    lastv = np.take_along_axis(lf, np.maximum(last_k, 0)[:, None, :],
                               axis=1)[:, 0, :]
    return {
        "cnt": cnt,
        "sum": np.where(live, vsum, nan),
        "min": np.where(live, vmin, nan),
        "max": np.where(live, vmax, nan),
        "avg": np.where(live, vsum / np.maximum(cnt, 1.0), nan),
        "last": np.where(live & (last_k >= 0), lastv, nan),
    }


_REDUCE_CACHE: dict = {}


def period_reduce(vals: np.ndarray, P: int, K: int) -> dict[str, np.ndarray]:
    """Returns host numpy [P, S] aggregate planes.  Uses the jitted jax
    kernel only when it preserves the input precision (x64 enabled or
    f32 input); otherwise the float64 numpy twin — identical math,
    proven by tests/test_downsample.py equivalence."""
    jax, jnp = _jax()
    if vals.dtype == np.float64 and not jax.config.jax_enable_x64:
        return _period_reduce_np(vals, P, K)
    key = "fn"
    fn = _REDUCE_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_period_reduce_impl, static_argnums=(1, 2))
        _REDUCE_CACHE[key] = fn
    out = fn(jnp.asarray(vals), P, K)
    return {k: np.asarray(v) for k, v in out.items()}


def grid_outputs(staged: StagedGrid, res: int, downsamplers: Sequence,
                 marker) -> Optional[tuple[np.ndarray, list, np.ndarray,
                                           np.ndarray]]:
    """Compute every requested downsampler over one resolution from the
    staged grid.  Returns (serve_mask [S], per-downsampler [P, S] output
    planes, period_end stamps [P], period-live [P, S]) or None when this
    resolution doesn't tile the grid."""
    g = staged.g
    if res % g != 0:
        return None
    K = res // g
    B = staged.nrows
    if B % K != 0:
        return None
    P = B // K
    serve = staged.eligible.copy()
    if isinstance(marker, CounterPeriodMarker):
        # reset splits create mid-bucket periods: host path handles them
        serve &= ~staged.has_reset
    if not serve.any():
        return None
    # column -> reduced planes: cascade from the finest already-reduced
    # resolution whose K divides this one, else reduce the raw grid
    cache = staged.planes_cache

    def planes(ci: int) -> dict[str, np.ndarray]:
        got = cache.get((ci, K))
        if got is not None:
            return got
        fine = [kf for (cj, kf) in cache
                if cj == ci and kf < K and K % kf == 0]
        if fine:
            kf = max(fine)
            got = _cascade_planes(cache[(ci, kf)], P, K // kf)
        else:
            got = period_reduce(staged.vals[ci], P, K)
        cache[(ci, K)] = got
        return got

    period_ends = (staged.c_start - 1 + (np.arange(P) + 1) * K) * g
    plive = staged.present.reshape(P, K, -1).any(axis=1)    # [P, S]
    outs = []
    for d in downsamplers:
        if isinstance(d, TTime):
            outs.append(None)                 # stamps come from period_ends
            continue
        pl = planes(d.col_id - 1)
        if isinstance(d, DMin):
            outs.append(pl["min"])
        elif isinstance(d, DMax):
            outs.append(pl["max"])
        elif isinstance(d, DSum):
            outs.append(pl["sum"])
        elif isinstance(d, DCount):
            outs.append(pl["cnt"])
        elif isinstance(d, DAvg):
            outs.append(pl["avg"])
        elif isinstance(d, DLast):
            outs.append(pl["last"])
        else:
            return None
    return serve, outs, period_ends, plive
