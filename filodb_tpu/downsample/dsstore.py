"""Downsampled dataset serving + batch downsampler job.

Capability match for:
- DownsampledTimeSeriesStore/Shard — read-only store serving downsampled
  data per resolution, index recovered from persisted partkeys
  (reference: core/src/main/scala/filodb.core/downsample/
  DownsampledTimeSeriesStore.scala:21, DownsampledTimeSeriesShard.scala:40).
- The offline Spark downsampler — batch job that reads raw chunks by
  ingestion time, applies the schema's ChunkDownsamplers, and writes
  downsample chunks to the downsample dataset (reference: spark-jobs/
  .../DownsamplerMain.scala:43, BatchDownsampler.scala:36, SURVEY.md §3.5).
  Spark's executor parallelism maps to per-(shard × time-split) work items
  that are embarrassingly parallel on host CPU.
"""

from __future__ import annotations

from typing import Optional, Sequence

from filodb_tpu.core.schemas import Schema, Schemas
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.downsample.sharddown import (DEFAULT_RESOLUTIONS_MS,
                                             MemoryDownsamplePublisher,
                                             ShardDownsampler)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.columnstore import ColumnStore
from filodb_tpu.store.metastore import MetaStore


def ds_dataset_name(dataset: str, resolution_ms: int) -> str:
    """Downsample dataset naming, e.g. prom_ds_60000 (reference: downsample
    datasets <ds>_ds_<res> convention)."""
    return f"{dataset}_ds_{resolution_ms}"


class DownsampledTimeSeriesStore:
    """Serves downsampled datasets, one memstore dataset per resolution.

    Read path is identical to the raw store (same shard/scan surface), so
    planners can route long-range queries here transparently (reference:
    DownsampledTimeSeriesStore is a read-only TimeSeriesStore)."""

    def __init__(self, raw_dataset: str,
                 column_store: Optional[ColumnStore] = None,
                 meta_store: Optional[MetaStore] = None,
                 resolutions_ms: Sequence[int] = DEFAULT_RESOLUTIONS_MS):
        self.raw_dataset = raw_dataset
        self.resolutions = tuple(sorted(resolutions_ms))
        self.memstore = TimeSeriesMemStore(column_store, meta_store)

    def setup(self, schemas: Schemas, shard_num: int,
              config: Optional[StoreConfig] = None) -> None:
        for res in self.resolutions:
            self.memstore.setup(ds_dataset_name(self.raw_dataset, res),
                                schemas, shard_num, config)

    def best_resolution(self, step_ms: int) -> int:
        """Coarsest resolution that still gives >=1 sample per step."""
        best = self.resolutions[0]
        for res in self.resolutions:
            if res <= step_ms:
                best = res
        return best

    def shard(self, resolution_ms: int, shard_num: int):
        return self.memstore.get_shard(
            ds_dataset_name(self.raw_dataset, resolution_ms), shard_num)

    def ingest_from_publisher(self, publisher: MemoryDownsamplePublisher,
                              offset: int = 0) -> int:
        """Drain published downsample containers into the serving store
        (the in-process stand-in for the Kafka downsample topics)."""
        total = 0
        for res in self.resolutions:
            for shard_num, container in publisher.drain(res):
                total += self.memstore.ingest(
                    ds_dataset_name(self.raw_dataset, res), shard_num,
                    container, offset)
        return total

    def recover(self, shard_num: int) -> int:
        """Index + data recovery for every resolution dataset."""
        n = 0
        for res in self.resolutions:
            n += self.memstore.recover_index(
                ds_dataset_name(self.raw_dataset, res), shard_num)
        return n


class BatchDownsampler:
    """Offline batch job: raw chunks -> downsample datasets (reference:
    spark-jobs BatchDownsampler.downsampleBatch)."""

    def __init__(self, raw_dataset: str, schemas: Schemas,
                 column_store: ColumnStore,
                 resolutions_ms: Sequence[int] = DEFAULT_RESOLUTIONS_MS,
                 config: Optional[StoreConfig] = None):
        self.raw_dataset = raw_dataset
        self.schemas = schemas
        self.store = column_store
        self.resolutions = tuple(resolutions_ms)
        self.config = config or StoreConfig()

    def run_shard(self, shard_num: int, ingestion_start: int,
                  ingestion_end: int) -> dict[int, int]:
        """Downsample one shard's raw chunks in [ingestion_start,
        ingestion_end] (one Spark work item; reference:
        Downsampler.run RDD over shard × time splits).

        Direct chunk-build path: downsampled series arrays are encoded
        into ChunkSets and written to the per-resolution datasets in ONE
        store call each — the reference's Spark BatchDownsampler writes
        chunksets straight to Cassandra (DownsamplerMain.scala:43,
        BatchDownsampler.downsampleBatch), never re-ingesting through a
        memstore, and the batch encode rides the native codec path.

        Returns {resolution: chunksets_written}."""
        from filodb_tpu.core.record import parse_partkey

        samplers: dict[int, ShardDownsampler] = {}
        by_schema: dict[int, list] = {}
        tags_memo: dict[bytes, dict] = {}    # partkey parses once, not
        for cs in self.store.chunksets_by_ingestion_time(  # per chunk
                self.raw_dataset, shard_num, ingestion_start, ingestion_end):
            schema = self._schema_for(cs)
            if schema is None or schema.downsample is None:
                continue
            tags = tags_memo.get(cs.partkey)
            if tags is None:
                tags = tags_memo[cs.partkey] = parse_partkey(cs.partkey)
            by_schema.setdefault(schema.schema_hash, []).append((tags, cs))
            if schema.schema_hash not in samplers:
                # publisher=None: the batch job builds chunksets
                # directly (downsample_arrays), it never publishes
                samplers[schema.schema_hash] = ShardDownsampler(
                    self.raw_dataset, shard_num, schema, None,
                    self.resolutions)

        prepared = {h: samplers[h].prepare_arrays(pairs)
                    for h, pairs in by_schema.items()}
        written: dict[int, int] = {}
        with self.store.deferred_commits():
            self._write_resolutions(shard_num, ingestion_end, by_schema,
                                    samplers, prepared, written)
        return written

    def _write_resolutions(self, shard_num, ingestion_end, by_schema,
                           samplers, prepared, written) -> None:
        from filodb_tpu.codecs import deltadelta, doublecodec
        from filodb_tpu.core.chunk import (ChunkSet, ChunkSetInfo,
                                           chunk_id,
                                           encode_chunksets_batch)
        from filodb_tpu.core.record import canonical_partkey
        from filodb_tpu.core.schemas import ColumnType
        from filodb_tpu.store.columnstore import PartKeyRecord
        # one canonical partkey per series, not per series x resolution
        # (the tags dicts are shared across the resolution ladder)
        pk_memo: dict[int, bytes] = {}

        def pk_for(tags: dict) -> bytes:
            pk = pk_memo.get(id(tags))
            if pk is None:
                pk = pk_memo[id(tags)] = canonical_partkey(tags)
            return pk

        for res in self.resolutions:
            ds_name = ds_dataset_name(self.raw_dataset, res)
            chunksets = []
            pkrecs = []
            for h in by_schema:
                sampler = samplers[h]
                if not sampler.enabled:
                    continue
                ds_schema = sampler.ds_schema
                all_dbl = all(c.ctype == ColumnType.DOUBLE
                              for c in ds_schema.data.columns[1:])
                planar = sampler.downsample_planes(prepared[h], res) \
                    if all_dbl else None
                if planar is not None:
                    # columnar fast path (the aligned common case): the
                    # shared period-end vector encodes ONCE, each value
                    # plane encodes as one contiguous [S, P] native call,
                    # and no per-series array slicing happens at all
                    tags_list, pe, planes, per_series = planar
                    if tags_list:
                        ts_blob = deltadelta.encode_batch([pe])[0]
                        col_blobs = [doublecodec.encode_batch_2d(pl.T)
                                     for pl in planes]
                        t0, t1 = int(pe[0]), int(pe[-1])
                        cid = chunk_id(t0, 0)
                        P = len(pe)
                        for i, tags in enumerate(tags_list):
                            pk = pk_for(tags)
                            vectors = [ts_blob] + [cb[i]
                                                   for cb in col_blobs]
                            chunksets.append(ChunkSet(
                                ChunkSetInfo(cid, P, t0, t1), pk,
                                vectors,
                                schema_hash=ds_schema.schema_hash))
                            pkrecs.append(PartKeyRecord(
                                pk, t0, t1, shard_num,
                                ds_schema.schema_hash))
                else:
                    per_series = sampler.downsample_arrays(
                        prepared[h], res)
                items = []
                for tags, ts_arr, cols in per_series:
                    pk = pk_for(tags)
                    items.append((pk, ts_arr, cols, 0))
                    pkrecs.append(PartKeyRecord(
                        pk, int(ts_arr[0]), int(ts_arr[-1]), shard_num,
                        ds_schema.schema_hash))
                chunksets.extend(encode_chunksets_batch(ds_schema, items))
            if chunksets:
                self.store.write_chunks(ds_name, shard_num, chunksets,
                                        ingestion_end)
                # widen, don't replace: a later ingestion window must
                # not narrow the partkey's visible time range
                self.store.merge_part_keys(ds_name, shard_num, pkrecs)
            written[res] = len(chunksets)

    def _schema_for(self, cs) -> Optional[Schema]:
        if cs.schema_hash:
            try:
                return self.schemas.by_hash(cs.schema_hash)
            except KeyError:
                return None
        ncols = len(cs.vectors)
        for s in self.schemas.all:
            if len(s.data.columns) == ncols and s.downsample is not None:
                return s
        return None
