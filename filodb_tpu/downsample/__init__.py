"""Multi-resolution downsampling (reference: core/downsample/* and the
spark-jobs offline downsampler; SURVEY.md §2.2, §2.6, §3.5)."""

from filodb_tpu.downsample.chunkdown import (  # noqa: F401
    ChunkDownsampler, parse_downsampler, parse_period_marker)
from filodb_tpu.downsample.sharddown import (  # noqa: F401
    DEFAULT_RESOLUTIONS_MS, DownsamplePublisher, MemoryDownsamplePublisher,
    ShardDownsampler)
from filodb_tpu.downsample.dsstore import (  # noqa: F401
    BatchDownsampler, DownsampledTimeSeriesStore, ds_dataset_name)
