"""PromQL front-end (reference: prometheus/src/main/scala/filodb/prometheus/
parse/Parser.scala + ast/)."""

from filodb_tpu.promql.parser import (parse_query, query_to_logical_plan,
                                      query_range_to_logical_plan)

__all__ = ["parse_query", "query_to_logical_plan",
           "query_range_to_logical_plan"]
