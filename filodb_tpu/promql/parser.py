"""PromQL parser: text -> LogicalPlan.

Replaces the reference's packrat-combinator Parser + AST + toSeriesPlan
walk (reference: prometheus/.../parse/Parser.scala:375-426, ast/Vectors.scala,
ast/Expressions.scala:120).  Hand-written lexer + Pratt parser; the AST *is*
the LogicalPlan (no separate tree), built with the same range semantics:
selectors get a lookback window (staleness default 5m), windowed functions
read [start - window - offset, end].
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from filodb_tpu.core.filters import (ColumnFilter, Equals, EqualsRegex,
                                     NotEquals, NotEqualsRegex)
from filodb_tpu.query.logical import (Aggregate, AggregationOperator,
                                      ApplyAbsentFunction,
                                      ApplyInstantFunction,
                                      ApplyMiscellaneousFunction,
                                      ApplySortFunction, BinaryJoin,
                                      BinaryOperator, Cardinality,
                                      InstantFunctionId, IntervalSelector,
                                      LogicalPlan, MiscellaneousFunctionId,
                                      PeriodicSeries,
                                      PeriodicSeriesPlan,
                                      PeriodicSeriesWithWindowing,
                                      RangeFunctionId, RawSeries,
                                      ScalarBinaryOperation,
                                      ScalarFixedDoublePlan, ScalarFunctionId,
                                      ScalarPlan, ScalarTimeBasedPlan,
                                      ScalarVaryingDoublePlan,
                                      ScalarVectorBinaryOperation,
                                      SortFunctionId, VectorPlan)

STALENESS_MS = 300_000  # Prometheus 5m lookback (reference: WindowConstants)
METRIC_COL = "_metric_"


class ParseError(Exception):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<WS>\s+)
  | (?P<DURATION>[0-9]+(?:\.[0-9]+)?(?:ms|s|m|h|d|w|y)(?:[0-9]+(?:ms|s|m|h|d|w|y))*)
  | (?P<NUMBER>(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]+)(?:[eE][+-]?[0-9]+)?|0x[0-9a-fA-F]+|(?:[Ii]nf|NaN)(?![a-zA-Z0-9_:.]))
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<OP>=~|!~|!=|==|>=|<=|->|[\[\]{}()+\-*/%^,=<>:@])
  | (?P<IDENT>[a-zA-Z_:][a-zA-Z0-9_:.]*)
""", re.VERBOSE)

_DUR_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000,
           "w": 7 * 86_400_000, "y": 365 * 86_400_000}
_DUR_PART = re.compile(r"([0-9]+(?:\.[0-9]+)?)(ms|s|m|h|d|w|y)")


def duration_ms(text: str) -> int:
    parts = _DUR_PART.findall(text)
    if not parts or "".join(n + u for n, u in parts) != text:
        raise ParseError(f"invalid duration {text!r}")
    return int(sum(float(n) * _DUR_MS[u] for n, u in parts))


@dataclasses.dataclass
class Token:
    kind: str
    text: str
    pos: int


def tokenize(query: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(query):
        m = _TOKEN_RE.match(query, pos)
        if not m:
            raise ParseError(f"unexpected character {query[pos]!r} at {pos}")
        kind = m.lastgroup
        if kind != "WS":
            out.append(Token(kind, m.group(), pos))
        pos = m.end()
    return out


# ---------------------------------------------------------------------------
# Function tables
# ---------------------------------------------------------------------------

_RANGE_FNS = {f.value: f for f in RangeFunctionId}
_RANGE_FNS["last_over_time"] = RangeFunctionId.LAST_OVER_TIME
_INSTANT_FNS = {f.value: f for f in InstantFunctionId}
_AGG_OPS = {o.value: o for o in AggregationOperator}
_MISC_FNS = {f.value: f for f in MiscellaneousFunctionId}
_SORT_FNS = {f.value: f for f in SortFunctionId}
_TIME_FNS = {"time", "hour", "minute", "month", "year", "day_of_month",
             "day_of_week", "days_in_month"}
_CMP_OPS = {"==": BinaryOperator.EQL, "!=": BinaryOperator.NEQ,
            ">": BinaryOperator.GTR, "<": BinaryOperator.LSS,
            ">=": BinaryOperator.GTE, "<=": BinaryOperator.LTE}

# precedence (Prometheus): or < and/unless < comparison < +- < */% < ^
_PRECEDENCE = {
    "or": 1,
    "and": 2, "unless": 2,
    "==": 3, "!=": 3, ">": 3, "<": 3, ">=": 3, "<=": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5,
    "^": 6,
}
_RIGHT_ASSOC = {"^"}


def _binop(text: str) -> BinaryOperator:
    return _CMP_OPS.get(text) or BinaryOperator(text)


# ---------------------------------------------------------------------------
# AST (thin, desugared into LogicalPlan at build time)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Selector:
    metric: Optional[str]
    matchers: list[tuple[str, str, str]]   # (label, op, value)
    window_ms: Optional[int] = None
    offset_ms: int = 0
    at_ms: Optional[int] = None

    def filters(self) -> tuple[ColumnFilter, ...]:
        out = []
        if self.metric is not None:
            out.append(ColumnFilter(METRIC_COL, Equals(self.metric)))
        for label, op, value in self.matchers:
            col = METRIC_COL if label == "__name__" else label
            if op == "=":
                out.append(ColumnFilter(col, Equals(value)))
            elif op == "!=":
                out.append(ColumnFilter(col, NotEquals(value)))
            elif op == "=~":
                out.append(ColumnFilter(col, EqualsRegex(value)))
            elif op == "!~":
                out.append(ColumnFilter(col, NotEqualsRegex(value)))
        return tuple(out)


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

class Parser:
    """One instance per query; ``start/step/end`` (ms) fix the output grid
    (instant query = start == end, one step)."""

    def __init__(self, tokens: list[Token], start_ms: int, step_ms: int,
                 end_ms: int):
        self.toks = tokens
        self.i = 0
        self.start = start_ms
        self.step = max(step_ms, 1)
        self.end = end_ms

    # -- token helpers ------------------------------------------------------

    def peek(self, offset: int = 0) -> Optional[Token]:
        j = self.i + offset
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of query")
        self.i += 1
        return t

    def expect(self, text: str) -> Token:
        t = self.next()
        if t.text != text:
            raise ParseError(f"expected {text!r}, got {t.text!r} at {t.pos}")
        return t

    def at(self, text: str) -> bool:
        t = self.peek()
        return t is not None and t.text == text

    # -- grammar ------------------------------------------------------------

    def parse(self) -> LogicalPlan:
        plan = self.expr(0)
        if self.peek() is not None:
            t = self.peek()
            raise ParseError(f"unexpected token {t.text!r} at {t.pos}")
        return plan

    def expr(self, min_prec: int) -> LogicalPlan:
        lhs = self.unary()
        while True:
            t = self.peek()
            if t is None or t.text not in _PRECEDENCE:
                break
            prec = _PRECEDENCE[t.text]
            if prec < min_prec:
                break
            op_text = self.next().text
            bool_mode = False
            if self.at("bool"):
                self.next()
                bool_mode = True
            on, ignoring, include = (), (), ()
            card = Cardinality.ONE_TO_ONE
            use_on = False
            if self.peek() is not None and self.peek().text in ("on", "ignoring"):
                use_on = self.next().text == "on"
                names = self.name_list()
                if use_on:
                    on = names
                else:
                    ignoring = names
                if self.peek() is not None and self.peek().text in (
                        "group_left", "group_right"):
                    side = self.next().text
                    card = (Cardinality.MANY_TO_ONE if side == "group_left"
                            else Cardinality.ONE_TO_MANY)
                    if self.at("("):
                        include = self.name_list()
            next_min = prec + 1 if op_text not in _RIGHT_ASSOC else prec
            rhs = self.expr(next_min)
            lhs = self.combine(op_text, lhs, rhs, bool_mode, on, ignoring,
                               include, card)
        return lhs

    def unary(self) -> LogicalPlan:
        if self.at("-") or self.at("+"):
            neg = self.next().text == "-"
            # '^' binds tighter than unary minus (Prometheus: -2^2 == -(2^2))
            operand = self.expr(_PRECEDENCE["^"])
            if not neg:
                return operand
            zero = ScalarFixedDoublePlan(0.0, self.start, self.step, self.end)
            if isinstance(operand, ScalarPlan):
                return ScalarBinaryOperation(BinaryOperator.SUB, 0.0, operand,
                                             self.start, self.step, self.end)
            return ScalarVectorBinaryOperation(BinaryOperator.SUB, zero,
                                               operand, scalar_is_lhs=True)
        return self.postfix(self.atom())

    def postfix(self, plan: LogicalPlan) -> LogicalPlan:
        return plan

    def atom(self) -> LogicalPlan:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of query")
        if t.kind == "NUMBER":
            self.next()
            return ScalarFixedDoublePlan(_number(t.text), self.start,
                                         self.step, self.end)
        if t.kind == "STRING":
            raise ParseError("string literal not valid as expression")
        if t.text == "(":
            self.next()
            inner = self.expr(0)
            self.expect(")")
            return inner
        if t.kind in ("IDENT",) or t.text == "{":
            return self.ident_or_call()
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    def ident_or_call(self) -> LogicalPlan:
        t = self.peek()
        name = t.text if t.kind == "IDENT" else None
        nxt = self.peek(1)
        if name is not None and nxt is not None and nxt.text == "(" and (
                name in _RANGE_FNS or name in _INSTANT_FNS or name in _AGG_OPS
                or name in _MISC_FNS or name in _SORT_FNS or name in _TIME_FNS
                or name in ("scalar", "vector", "absent", "rate", "label_replace")):
            if name in _AGG_OPS:
                return self.aggregation(name)
            return self.call(name)
        if name is not None and nxt is not None and nxt.text in ("by", "without") \
                and name in _AGG_OPS:
            return self.aggregation(name)
        # vector selector
        return self.selector_plan()

    # -- selectors ----------------------------------------------------------

    def selector(self) -> Selector:
        metric = None
        t = self.peek()
        if t is not None and t.kind == "IDENT":
            metric = self.next().text
        matchers: list[tuple[str, str, str]] = []
        if self.at("{"):
            self.next()
            while not self.at("}"):
                label = self.next().text
                op = self.next().text
                if op not in ("=", "!=", "=~", "!~"):
                    raise ParseError(f"bad matcher op {op!r}")
                val = self.string()
                matchers.append((label, op, val))
                if self.at(","):
                    self.next()
            self.expect("}")
        if metric is None and not matchers:
            raise ParseError("empty selector")
        sel = Selector(metric, matchers)
        if self.at("["):
            self.next()
            d = self.next()
            sel.window_ms = duration_ms(d.text)
            self.expect("]")
        sel.offset_ms = self.maybe_offset()
        return sel

    def maybe_offset(self) -> int:
        if self.at("offset"):
            self.next()
            neg = False
            if self.at("-"):
                self.next()
                neg = True
            d = duration_ms(self.next().text)
            return -d if neg else d
        return 0

    def selector_plan(self) -> PeriodicSeriesPlan:
        sel = self.selector()
        if sel.window_ms is not None:
            raise ParseError("range vector must be wrapped in a range function")
        return self.instant_vector(sel)

    def instant_vector(self, sel: Selector) -> PeriodicSeries:
        lookback = STALENESS_MS
        raw = RawSeries(
            IntervalSelector(self.start - lookback - sel.offset_ms,
                             self.end - sel.offset_ms),
            sel.filters(), lookback_ms=lookback,
            offset_ms=sel.offset_ms or None)
        return PeriodicSeries(raw, self.start, self.step, self.end,
                              offset_ms=sel.offset_ms or None)

    def windowed(self, sel: Selector, fn: RangeFunctionId,
                 args: tuple = ()) -> PeriodicSeriesWithWindowing:
        if sel.window_ms is None:
            raise ParseError(f"{fn.value} needs a range vector [duration]")
        raw = RawSeries(
            IntervalSelector(self.start - sel.window_ms - sel.offset_ms,
                             self.end - sel.offset_ms),
            sel.filters(), lookback_ms=sel.window_ms,
            offset_ms=sel.offset_ms or None)
        return PeriodicSeriesWithWindowing(
            raw, self.start, self.step, self.end, sel.window_ms, fn,
            function_args=args, offset_ms=sel.offset_ms or None)

    # -- calls --------------------------------------------------------------

    def call(self, name: str) -> LogicalPlan:
        self.next()  # name
        self.expect("(")
        # zero-arg time functions (hour(), month(), ...) must win over their
        # one-arg instant-function forms, which share the same names
        if name in _TIME_FNS and self.at(")"):
            self.next()
            return ScalarTimeBasedPlan(ScalarFunctionId(name), self.start,
                                       self.step, self.end)
        if name in _RANGE_FNS:
            fn = _RANGE_FNS[name]
            # arg layouts: quantile_over_time(q, sel[w]) / holt_winters(sel, sf, tf)
            pre_args: list = []
            if name == "quantile_over_time":
                pre_args.append(self.number_arg())
                self.expect(",")
            sel = self.selector()
            post_args: list = []
            while self.at(","):
                self.next()
                post_args.append(self.number_arg())
            self.expect(")")
            if fn == RangeFunctionId.LAST_OVER_TIME:
                # last_over_time == default instant selection over [w]
                raw = RawSeries(
                    IntervalSelector(self.start - sel.window_ms - sel.offset_ms,
                                     self.end - sel.offset_ms),
                    sel.filters(), lookback_ms=sel.window_ms,
                    offset_ms=sel.offset_ms or None)
                return PeriodicSeries(raw, self.start, self.step, self.end,
                                      offset_ms=sel.offset_ms or None)
            return self.windowed(sel, fn, tuple(pre_args + post_args))
        if name in _INSTANT_FNS:
            fn = _INSTANT_FNS[name]
            pre: list = []
            if name in ("histogram_quantile", "histogram_max_quantile",
                        "histogram_bucket"):
                pre.append(self.number_arg())
                self.expect(",")
            vec = self.expr(0)
            post: list = []
            while self.at(","):
                self.next()
                post.append(self.number_arg())
            self.expect(")")
            if name == "round" and post:
                args = tuple(post)
            else:
                args = tuple(pre + post)
            return ApplyInstantFunction(vec, fn, args)
        if name in _MISC_FNS:
            vec = self.expr(0)
            args: list[str] = []
            while self.at(","):
                self.next()
                args.append(self.string())
            self.expect(")")
            return ApplyMiscellaneousFunction(vec, _MISC_FNS[name], tuple(args))
        if name in _SORT_FNS:
            vec = self.expr(0)
            self.expect(")")
            return ApplySortFunction(vec, _SORT_FNS[name])
        if name == "absent":
            vec = self.expr(0)
            self.expect(")")
            filters = ()
            from filodb_tpu.query.logical import leaf_raw_series
            leaves = leaf_raw_series(vec)
            if leaves:
                filters = leaves[0].filters
            return ApplyAbsentFunction(vec, filters, self.start, self.step,
                                       self.end)
        if name == "scalar":
            vec = self.expr(0)
            self.expect(")")
            return ScalarVaryingDoublePlan(vec)
        if name == "vector":
            inner = self.expr(0)
            self.expect(")")
            if not isinstance(inner, ScalarPlan):
                raise ParseError("vector() takes a scalar expression")
            return VectorPlan(inner)
        if name in _TIME_FNS:
            if self.at(")"):
                self.next()
                return ScalarTimeBasedPlan(ScalarFunctionId(name), self.start,
                                           self.step, self.end)
            vec = self.expr(0)
            self.expect(")")
            return ApplyInstantFunction(vec, InstantFunctionId(name))
        raise ParseError(f"unknown function {name!r}")

    def aggregation(self, name: str) -> Aggregate:
        op = _AGG_OPS[name]
        self.next()  # name
        by, without = (), ()
        if self.peek() is not None and self.peek().text in ("by", "without"):
            which = self.next().text
            names = self.name_list()
            if which == "by":
                by = names
            else:
                without = names
        self.expect("(")
        params: list = []
        if op in (AggregationOperator.TOPK, AggregationOperator.BOTTOMK,
                  AggregationOperator.QUANTILE):
            params.append(self.number_arg())
            self.expect(",")
        elif op == AggregationOperator.COUNT_VALUES:
            params.append(self.string())
            self.expect(",")
        vec = self.expr(0)
        self.expect(")")
        if not (by or without) and self.peek() is not None \
                and self.peek().text in ("by", "without"):
            which = self.next().text
            names = self.name_list()
            if which == "by":
                by = names
            else:
                without = names
        return Aggregate(op, vec, tuple(params), by, without)

    # -- small pieces -------------------------------------------------------

    def name_list(self) -> tuple[str, ...]:
        self.expect("(")
        names = []
        while not self.at(")"):
            names.append(self.next().text)
            if self.at(","):
                self.next()
        self.expect(")")
        return tuple(names)

    def number_arg(self) -> float:
        neg = False
        if self.at("-"):
            self.next()
            neg = True
        t = self.next()
        if t.kind != "NUMBER":
            raise ParseError(f"expected number, got {t.text!r}")
        v = _number(t.text)
        return -v if neg else v

    def string(self) -> str:
        t = self.next()
        if t.kind != "STRING":
            raise ParseError(f"expected string, got {t.text!r}")
        return _unescape(t.text[1:-1])

    # -- binary combination -------------------------------------------------

    def combine(self, op_text: str, lhs: LogicalPlan, rhs: LogicalPlan,
                bool_mode: bool, on, ignoring, include,
                card: Cardinality) -> LogicalPlan:
        op = _binop(op_text)
        lhs_scalar = isinstance(lhs, ScalarPlan)
        rhs_scalar = isinstance(rhs, ScalarPlan)
        if lhs_scalar and rhs_scalar:
            return ScalarBinaryOperation(op, _fold(lhs), _fold(rhs),
                                         self.start, self.step, self.end)
        if lhs_scalar or rhs_scalar:
            if op.is_set_op:
                raise ParseError(f"set operator {op.value} requires vectors")
            scalar = lhs if lhs_scalar else rhs
            vector = rhs if lhs_scalar else lhs
            return ScalarVectorBinaryOperation(op, scalar, vector,
                                               scalar_is_lhs=lhs_scalar,
                                               bool_mode=bool_mode)
        return BinaryJoin(lhs, op, card, rhs, on, ignoring, include,
                          bool_mode=bool_mode)


def _fold(p: ScalarPlan):
    if isinstance(p, ScalarFixedDoublePlan):
        return p.scalar
    return p


_ESC_RE = re.compile(
    r"\\(u[0-9a-fA-F]{4}|U[0-9a-fA-F]{8}|x[0-9a-fA-F]{2}|[0-7]{1,3}|.)",
    re.DOTALL)
_ESC_MAP = {"n": "\n", "t": "\t", "r": "\r", "a": "\a", "b": "\b",
            "f": "\f", "v": "\v", "\\": "\\", '"': '"', "'": "'"}


def _unescape(body: str) -> str:
    """Decode PromQL string escapes without mangling non-ASCII text (a
    unicode_escape round-trip would read UTF-8 bytes as latin-1)."""
    def repl(m: "re.Match[str]") -> str:
        s = m.group(1)
        if s[0] in "uUx":
            return chr(int(s[1:], 16))
        if s[0] in "01234567":
            return chr(int(s, 8))
        return _ESC_MAP.get(s, s)
    return _ESC_RE.sub(repl, body)


def _number(text: str) -> float:
    t = text.lower()
    if t in ("inf", "+inf"):
        return float("inf")
    if t == "-inf":
        return float("-inf")
    if t == "nan":
        return float("nan")
    if t.startswith("0x"):
        return float(int(t, 16))
    return float(text)


# ---------------------------------------------------------------------------
# Public API (reference: Parser.queryToLogicalPlan / queryRangeToLogicalPlan,
# Parser.scala:402-426)
# ---------------------------------------------------------------------------

def parse_query(query: str, start_ms: int, step_ms: int,
                end_ms: int) -> LogicalPlan:
    return Parser(tokenize(query), start_ms, step_ms, end_ms).parse()


def query_to_logical_plan(query: str, time_ms: int) -> LogicalPlan:
    """Instant query at one evaluation timestamp."""
    return parse_query(query, time_ms, 1000, time_ms)


def query_range_to_logical_plan(query: str, start_ms: int, step_ms: int,
                                end_ms: int) -> LogicalPlan:
    return parse_query(query, start_ms, step_ms, end_ms)


def parse_selector(text: str) -> tuple[ColumnFilter, ...]:
    """A bare series selector (e.g. ``up{job="api"}``) -> column filters;
    the /api/v1/series match[] parameter (reference:
    Parser.metadataQueryToLogicalPlan)."""
    p = Parser(tokenize(text), 0, 1000, 0)
    sel = p.selector()
    if p.peek() is not None:
        raise ParseError(f"unexpected trailing tokens in selector {text!r}")
    return sel.filters()
