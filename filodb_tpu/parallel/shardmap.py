"""ShardMapper: record -> shard bit-splice, spread fan-out, shard status.

Pure-function port-of-concept of the reference's ShardMapper
(reference: coordinator/src/main/scala/filodb.coordinator/ShardMapper.scala:
26-46 — shard = f(shardKeyHash upper bits, partitionHash lower bits, spread);
queryShards returns the 2^spread shards holding one shard key) plus the
ShardStatus lifecycle (ShardStatus.scala:54-94).  TPU mapping: a shard is a
slice of the mesh's data axis; ``coord_for_shard`` is the host/device owner.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence


class ShardStatus(enum.Enum):
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    STOPPED = "Stopped"
    DOWN = "Down"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


# stable numeric codes for the filodb_shard_status_code gauge (dashboards
# need an orderable value; enum order here is the lifecycle order)
_STATUS_CODE = {
    ShardStatus.UNASSIGNED: 0, ShardStatus.ASSIGNED: 1,
    ShardStatus.RECOVERY: 2, ShardStatus.ACTIVE: 3, ShardStatus.ERROR: 4,
    ShardStatus.STOPPED: 5, ShardStatus.DOWN: 6,
}

_HEALTH_METRICS = None


def _health_m() -> dict:
    global _HEALTH_METRICS
    if _HEALTH_METRICS is None:
        from filodb_tpu.utils.observability import shard_health_metrics
        _HEALTH_METRICS = shard_health_metrics()
    return _HEALTH_METRICS


@dataclasses.dataclass
class ShardState:
    status: ShardStatus = ShardStatus.UNASSIGNED
    node: Optional[str] = None
    recovery_progress: int = 0  # percent


class ShardMapper:
    def __init__(self, num_shards: int, dataset: str = ""):
        if num_shards <= 0 or num_shards & (num_shards - 1):
            raise ValueError(f"num_shards {num_shards} must be a power of 2")
        self.num_shards = num_shards
        # named mappers (cluster-managed) emit shard-health metrics and
        # flight events on status changes; anonymous ones (benches,
        # ad-hoc tests) stay silent
        self.dataset = dataset
        self._states = [ShardState() for _ in range(num_shards)]

    # -- hashing ------------------------------------------------------------

    def shard_hash_mask(self, spread: int) -> int:
        return (self.num_shards - 1) & ~((1 << spread) - 1)

    def part_hash_mask(self, spread: int) -> int:
        return (1 << spread) - 1

    def ingestion_shard(self, shard_key_hash: int, part_hash: int,
                        spread: int) -> int:
        """Upper bits from the shard-key hash, lower ``spread`` bits from the
        partition hash (reference: ShardMapper.ingestionShard)."""
        return ((shard_key_hash & self.shard_hash_mask(spread))
                | (part_hash & self.part_hash_mask(spread)))

    def query_shards(self, shard_key_hash: int, spread: int) -> list[int]:
        """All 2^spread shards that can hold series of one shard key."""
        base = shard_key_hash & self.shard_hash_mask(spread)
        return [base | i for i in range(1 << spread)]

    # -- assignment / status ------------------------------------------------

    def register_node(self, shards: Sequence[int], node: str) -> None:
        for s in shards:
            prev = self._states[s].status
            self._states[s] = ShardState(ShardStatus.ASSIGNED, node)
            self._note_status(s, prev, ShardStatus.ASSIGNED, 0)

    def update_status(self, shard: int, status: ShardStatus,
                      progress: int = 0) -> None:
        st = self._states[shard]
        prev, prev_progress = st.status, st.recovery_progress
        st.status = status
        st.recovery_progress = progress
        if prev is not status or prev_progress != progress:
            self._note_status(shard, prev, status, progress)

    def unassign(self, shard: int) -> None:
        prev = self._states[shard].status
        self._states[shard] = ShardState()
        self._note_status(shard, prev, ShardStatus.UNASSIGNED, 0)

    def _note_status(self, shard: int, prev: ShardStatus,
                     status: ShardStatus, progress: int) -> None:
        """Shard-health emission (ISSUE 6): gauge + transition counter +
        flight event, ONLY on real changes (the status poller re-applies
        identical statuses every sweep — those must not spam the ring).
        Anonymous mappers (no dataset name) skip it entirely."""
        if not self.dataset:
            return
        m = _health_m()
        m["status_code"].set(_STATUS_CODE[status], dataset=self.dataset,
                             shard=shard)
        m["recovery_progress"].set(progress, dataset=self.dataset,
                                   shard=shard)
        if prev is not status:
            m["transitions"].inc(dataset=self.dataset, status=status.value)
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("shard.status", dataset=self.dataset, shard=shard,
                          status=status.value, prev=prev.value,
                          progress=progress)

    def coord_for_shard(self, shard: int) -> Optional[str]:
        return self._states[shard].node

    def status(self, shard: int) -> ShardStatus:
        return self._states[shard].status

    def state(self, shard: int) -> ShardState:
        """The full per-shard state row (status + owner + recovery
        progress) for health/watermark views."""
        return self._states[shard]

    def active_shards(self, shards: Optional[Sequence[int]] = None) -> list[int]:
        rng = range(self.num_shards) if shards is None else shards
        return [s for s in rng if self._states[s].status.queryable]

    def all_nodes(self) -> set:
        return {st.node for st in self._states if st.node is not None}

    def shards_for_node(self, node: str) -> list[int]:
        return [i for i, st in enumerate(self._states) if st.node == node]

    def runnable_shards_for_node(self, node: str) -> list[int]:
        """Shards this node should actually be ingesting: assigned to it
        and not held in an operator STOPPED / leader DOWN state (the one
        place this exclusion policy lives — resync and self-heal both
        consult it)."""
        return [i for i, st in enumerate(self._states)
                if st.node == node and st.status not in
                (ShardStatus.STOPPED, ShardStatus.DOWN)]

    @property
    def num_assigned(self) -> int:
        return sum(1 for st in self._states
                   if st.status != ShardStatus.UNASSIGNED)
