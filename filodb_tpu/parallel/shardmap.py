"""ShardMapper: record -> shard bit-splice, spread fan-out, shard status.

Pure-function port-of-concept of the reference's ShardMapper
(reference: coordinator/src/main/scala/filodb.coordinator/ShardMapper.scala:
26-46 — shard = f(shardKeyHash upper bits, partitionHash lower bits, spread);
queryShards returns the 2^spread shards holding one shard key) plus the
ShardStatus lifecycle (ShardStatus.scala:54-94).  TPU mapping: a shard is a
slice of the mesh's data axis; ``coord_for_shard`` is the host/device owner.

Replica groups (ISSUE 7): each shard is held by up to
``replication_factor`` DISTINCT nodes; :class:`ReplicaState` tracks
per-replica status, recovery progress, and ingest watermark (the
gossiped ``latest_offset``, feeding the group head that gates recovery
promotion and the failover router's lag ordering).  The legacy
single-copy surface (``coord_for_shard`` / ``status`` / ``state``)
reads the shard's PRIMARY (first) replica, so ``replication_factor=1``
behaves exactly as before.

Elastic resharding (ISSUE 13): because the shard is a hash bit-splice,
doubling ``num_shards`` sends every series of parent shard ``s`` to
either ``s`` or ``s + N`` (N = old count) — for EVERY spread setting
(the new mask bit comes from the shard-key hash when spread <= log2 N,
and from the modulo fold otherwise; tests/test_split.py sweeps this).
The mapper therefore carries a :class:`Topology`: the SERVING shard
count (``num_shards``, the hash-mask base queries and gateways use),
the TOTAL registered shard states (``total_shards``, which includes
in-flight split children holding Recovery replica groups), and a
monotone ``topology_generation`` every serving-path memo keyed on shard
ids must validate against (gateway series memos, result-cache routing
tokens — the ``topology-generation`` filolint rule).  All topology
transitions swap ONE immutable Topology object, so unlocked readers
always see a consistent (num_shards, generation, split-phase) triple.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence


class ShardStatus(enum.Enum):
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    STOPPED = "Stopped"
    DOWN = "Down"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


# stable numeric codes for the filodb_shard_status_code gauge (dashboards
# need an orderable value; enum order here is the lifecycle order)
_STATUS_CODE = {
    ShardStatus.UNASSIGNED: 0, ShardStatus.ASSIGNED: 1,
    ShardStatus.RECOVERY: 2, ShardStatus.ACTIVE: 3, ShardStatus.ERROR: 4,
    ShardStatus.STOPPED: 5, ShardStatus.DOWN: 6,
}

_HEALTH_METRICS = None


def _health_m() -> dict:
    global _HEALTH_METRICS
    if _HEALTH_METRICS is None:
        from filodb_tpu.utils.observability import shard_health_metrics
        _HEALTH_METRICS = shard_health_metrics()
    return _HEALTH_METRICS


@dataclasses.dataclass(frozen=True)
class Topology:
    """One immutable topology view (ISSUE 13).  ``num_shards`` is the
    SERVING count — the hash-mask base ingestion and query fan-out use;
    ``total_shards`` additionally counts in-flight split children
    (Recovery replica groups catching up but not yet routed).  The
    ``generation`` is monotone across every transition (prepare,
    cutover, retire-complete, abort) so consumers that memoize per-shard
    state can validate with one int compare, and gossip adoption is a
    simple newest-wins."""

    num_shards: int
    total_shards: int
    generation: int = 0
    # split bookkeeping while a split is in flight; phase is one of
    # "catchup" (children replaying, queries still route the parents),
    # "serving" (cutover done: 2N-way routing, parents exclude their
    # migrated half at scan time), "retire" (grace elapsed: parents
    # purge migrated data).  None = no split in flight.
    split_phase: Optional[str] = None
    split_base: Optional[int] = None
    split_spread: Optional[int] = None
    # the generation at which THIS split instance was prepared — a
    # process-wide-unique id for the split (generations are strictly
    # monotone), so per-node KV markers written during one split can
    # never satisfy a later split of the same dataset
    split_epoch: Optional[int] = None

    def query_shards(self, shard_key_hash: int, spread: int) -> list[int]:
        """All 2^spread shards that can hold one shard key under THIS
        topology view — the planner computes fan-out from its per-query
        snapshot, never from the live mapper, so a cutover committing
        mid-plan cannot mix old fan-out with new exclusions."""
        n = self.num_shards
        base = shard_key_hash & ((n - 1) & ~((1 << spread) - 1))
        return [(base | i) % n for i in range(1 << spread)]

    def parent_exclusion(self, shard: int) -> Optional[tuple[int, int]]:
        """(total_shards, ingest_spread) when ``shard`` is a split
        parent whose migrated half must be EXCLUDED from its scans —
        active from cutover until the split completes (the parent holds
        a full superset until retire purges it; serving it unfiltered
        would double-count every migrated series against its child)."""
        if self.split_phase in ("serving", "retire") \
                and self.split_base is not None \
                and shard < self.split_base:
            return self.total_shards, self.split_spread or 0
        return None

    def as_payload(self) -> dict:
        """Wire form for /__health gossip."""
        out = {"num_shards": self.num_shards,
               "total_shards": self.total_shards,
               "generation": self.generation}
        if self.split_phase is not None:
            out["split"] = {"phase": self.split_phase,
                            "base": self.split_base,
                            "spread": self.split_spread,
                            "epoch": self.split_epoch}
        return out


def shard_of_tags(tags, total: int, spread: int, options=None) -> int:
    """The shard a series' tags route to under a ``total``-shard
    topology — the SAME bit-splice the gateway uses at ingest, so split
    membership (parent half vs child half) is decided by one pure
    function everywhere (child ingest filters, parent scan exclusion,
    retire purge, the generative rehash sweep)."""
    from filodb_tpu.core.record import partition_hash, shard_key_hash
    from filodb_tpu.core.schemas import DatasetOptions
    opts = options or DatasetOptions()
    shash = shard_key_hash(tags, opts)
    phash = partition_hash(tags, opts)
    mask = (total - 1) & ~((1 << spread) - 1)
    return ((shash & mask) | (phash & ((1 << spread) - 1))) % total


@dataclasses.dataclass
class ReplicaState:
    """One node's copy of one shard."""

    node: str
    status: ShardStatus = ShardStatus.ASSIGNED
    recovery_progress: int = 0  # percent
    # last gossiped ingested offset (-1 = unknown); feeds group_head()
    watermark: int = -1


class ShardState:
    """Per-shard replica group.  The legacy single-copy attributes
    (``status`` / ``node`` / ``recovery_progress``) read the PRIMARY
    (first) replica so rf=1 callers see exactly the old shape."""

    __slots__ = ("replicas",)

    def __init__(self, status: ShardStatus = ShardStatus.UNASSIGNED,
                 node: Optional[str] = None, recovery_progress: int = 0):
        self.replicas: list[ReplicaState] = []
        if node is not None:
            self.replicas.append(ReplicaState(node, status,
                                              recovery_progress))

    def replica(self, node: str) -> Optional[ReplicaState]:
        for r in self.replicas:
            if r.node == node:
                return r
        return None

    # -- legacy single-copy view (primary replica) --------------------------

    @property
    def status(self) -> ShardStatus:
        return self.replicas[0].status if self.replicas \
            else ShardStatus.UNASSIGNED

    @property
    def node(self) -> Optional[str]:
        return self.replicas[0].node if self.replicas else None

    @property
    def recovery_progress(self) -> int:
        return self.replicas[0].recovery_progress if self.replicas else 0

    @property
    def best_status(self) -> ShardStatus:
        """The most-servable status across replicas: a shard with ANY
        Active replica serves normally even while a peer recovers."""
        best = ShardStatus.UNASSIGNED
        rank = {ShardStatus.ACTIVE: 6, ShardStatus.RECOVERY: 5,
                ShardStatus.ASSIGNED: 4, ShardStatus.STOPPED: 3,
                ShardStatus.ERROR: 2, ShardStatus.DOWN: 1,
                ShardStatus.UNASSIGNED: 0}
        for r in self.replicas:
            if rank[r.status] > rank[best]:
                best = r.status
        return best

    def serving_replica(self) -> Optional[ReplicaState]:
        """The replica holding the best (serving) status — THE
        definition every operator surface (/admin/shards,
        /api/v1/cluster status) reports, so the views cannot drift."""
        best = self.best_status
        return next((r for r in self.replicas if r.status is best), None)


class ShardMapper:
    def __init__(self, num_shards: int, dataset: str = "",
                 replication_factor: int = 1):
        if num_shards <= 0 or num_shards & (num_shards - 1):
            raise ValueError(f"num_shards {num_shards} must be a power of 2")
        if replication_factor < 1:
            raise ValueError(
                f"replication_factor {replication_factor} must be >= 1")
        self.replication_factor = replication_factor
        # named mappers (cluster-managed) emit shard-health metrics and
        # flight events on status changes; anonymous ones (benches,
        # ad-hoc tests) stay silent
        self.dataset = dataset
        self._states = [ShardState() for _ in range(num_shards)]
        # ONE atomically-swapped object carries (serving count, total
        # count, generation, split phase) — see Topology above.  All
        # split transitions happen under the ShardManager lock; readers
        # are unlocked and rely on the swap being atomic.
        self._topology = Topology(num_shards, num_shards)

    # -- topology (ISSUE 13) ------------------------------------------------

    @property
    def num_shards(self) -> int:
        """SERVING shard count — the hash-mask base for ingestion
        routing and query fan-out.  During a split this stays at the
        parent count until cutover commits."""
        return self._topology.num_shards

    @property
    def total_shards(self) -> int:
        """Registered shard states including in-flight split children —
        the range every replica/status/watermark surface (gossip,
        /__health, ledger) must sweep, or catching-up children would be
        invisible to the promotion gate."""
        return len(self._states)

    @property
    def topology(self) -> Topology:
        return self._topology

    @property
    def topology_generation(self) -> int:
        return self._topology.generation

    def begin_split(self, spread: int = 0) -> Topology:
        """PREPARE: double the registered shard space.  Child shard
        ``s + N`` is created UNASSIGNED for every parent ``s``; serving
        routing (``num_shards``) is untouched, so queries and gateways
        keep running on the parent topology while children catch up.
        Bumps the generation (shard-keyed memos revalidate)."""
        t = self._topology
        if t.split_phase is not None:
            raise ValueError(f"dataset {self.dataset!r} already has a "
                             f"split in flight (phase {t.split_phase})")
        base = t.num_shards
        self._states = self._states + [ShardState() for _ in range(base)]
        self._topology = Topology(base, 2 * base, t.generation + 1,
                                  split_phase="catchup", split_base=base,
                                  split_spread=spread,
                                  split_epoch=t.generation + 1)
        return self._topology

    def register_split_child(self, shard: int, nodes: Sequence[str]) -> None:
        """Register a child shard's replica group in RECOVERY — the
        state the PR 12 promotion gate expects a replaying copy in."""
        st = self._states[shard]
        prev = st.status
        st.replicas = [ReplicaState(n, ShardStatus.RECOVERY) for n in nodes]
        for n in nodes:
            self._note_replica(shard, n, ShardStatus.UNASSIGNED,
                               ShardStatus.RECOVERY, 0)
        if prev is not st.status:
            self._note_status(shard, prev, st.status, st.recovery_progress)

    def commit_split(self) -> Topology:
        """CUTOVER: atomically flip serving to the doubled topology.
        From this generation on, query fan-out covers the children and
        parents exclude their migrated half at scan time
        (``Topology.parent_exclusion``); gateways rehash their memos on
        the generation bump.  The parents still hold a full superset of
        the data (retire purges it later), so abort remains lossless."""
        t = self._topology
        if t.split_phase != "catchup":
            raise ValueError(f"cannot commit split from phase "
                             f"{t.split_phase!r}")
        self._topology = Topology(t.total_shards, t.total_shards,
                                  t.generation + 1, split_phase="serving",
                                  split_base=t.split_base,
                                  split_spread=t.split_spread,
                                  split_epoch=t.split_epoch)
        return self._topology

    def retire_split(self) -> Topology:
        """RETIRE: the grace window elapsed — participants purge the
        parents' migrated halves and install the parents' retain-half
        ingest filters."""
        t = self._topology
        if t.split_phase != "serving":
            raise ValueError(f"cannot retire split from phase "
                             f"{t.split_phase!r}")
        self._topology = dataclasses.replace(t, generation=t.generation + 1,
                                             split_phase="retire")
        return self._topology

    def finish_split(self) -> Topology:
        """COMPLETE: every parent purged its migrated half — drop the
        split bookkeeping (and with it the scan exclusions)."""
        t = self._topology
        self._topology = Topology(t.num_shards, len(self._states),
                                  t.generation + 1)
        return self._topology

    def abort_split(self) -> Topology:
        """ABORT, from any in-flight phase: children are dropped,
        serving flips back to the parent topology, and the parents —
        which held a full superset throughout — simply keep serving.
        Lossless by construction."""
        t = self._topology
        if t.split_phase is None:
            return t
        base = t.split_base or t.num_shards
        for s in range(base, len(self._states)):
            for r in self._states[s].replicas:
                self._note_replica(s, r.node, r.status,
                                   ShardStatus.UNASSIGNED, 0)
        self._states = self._states[:base]
        self._topology = Topology(base, base, t.generation + 1)
        return self._topology

    def adopt_topology(self, payload: dict) -> bool:
        """Gossip adoption (newest generation wins, strictly monotone):
        reconcile the local shard space + topology with a peer's
        ``Topology.as_payload()``.  Returns True when anything changed.
        Works from any peer, not just the leader — split phases are
        driven by the coordinator that owns the split record, and every
        transition only ever bumps the generation."""
        gen = int(payload.get("generation", 0))
        t = self._topology
        if gen <= t.generation:
            return False
        total = int(payload.get("total_shards", t.total_shards))
        num = int(payload.get("num_shards", t.num_shards))
        if total > len(self._states):
            self._states = self._states + [
                ShardState() for _ in range(total - len(self._states))]
        elif total < len(self._states):
            for s in range(total, len(self._states)):
                for r in self._states[s].replicas:
                    self._note_replica(s, r.node, r.status,
                                       ShardStatus.UNASSIGNED, 0)
            self._states = self._states[:total]
        sp = payload.get("split") or {}
        self._topology = Topology(num, total, gen,
                                  split_phase=sp.get("phase"),
                                  split_base=sp.get("base"),
                                  split_spread=sp.get("spread"),
                                  split_epoch=sp.get("epoch"))
        return True

    def split_parent_of(self, shard: int) -> Optional[int]:
        """The parent of an in-flight split child, else None."""
        t = self._topology
        if t.split_phase is not None and t.split_base is not None \
                and shard >= t.split_base:
            return shard - t.split_base
        return None

    # -- hashing ------------------------------------------------------------

    def shard_hash_mask(self, spread: int) -> int:
        return (self.num_shards - 1) & ~((1 << spread) - 1)

    def part_hash_mask(self, spread: int) -> int:
        return (1 << spread) - 1

    def ingestion_shard(self, shard_key_hash: int, part_hash: int,
                        spread: int) -> int:
        """Upper bits from the shard-key hash, lower ``spread`` bits from the
        partition hash (reference: ShardMapper.ingestionShard)."""
        return ((shard_key_hash & self.shard_hash_mask(spread))
                | (part_hash & self.part_hash_mask(spread)))

    def query_shards(self, shard_key_hash: int, spread: int) -> list[int]:
        """All 2^spread shards that can hold series of one shard key."""
        base = shard_key_hash & self.shard_hash_mask(spread)
        return [base | i for i in range(1 << spread)]

    # -- assignment / status ------------------------------------------------

    def register_node(self, shards: Sequence[int], node: str) -> None:
        """Add ``node`` as a replica of each shard (refreshing it to
        ASSIGNED when already present).  With ``replication_factor=1``
        the replica set is REPLACED — the legacy single-owner move
        semantics (leader-view adoption, reassignment).  With rf>1 a
        full group replaces its least-healthy non-live replica (the
        failover reassignment path) and never holds the same node
        twice."""
        for s in shards:
            st = self._states[s]
            prev = st.status
            rep = st.replica(node)
            if rep is not None:
                r_prev = rep.status
                rep.status = ShardStatus.ASSIGNED
                rep.recovery_progress = 0
                if r_prev in (ShardStatus.DOWN, ShardStatus.ERROR):
                    # rejoin: the node restarted and replays from its
                    # checkpoint — its pre-crash watermark is stale and
                    # max-only note_watermark would pin it forever,
                    # hiding the replay regression from lag views
                    rep.watermark = -1
                self._note_replica(s, node, r_prev, ShardStatus.ASSIGNED, 0)
            elif self.replication_factor == 1:
                for old in st.replicas:  # displaced: gauge row removed
                    self._note_replica(s, old.node, old.status,
                                       ShardStatus.UNASSIGNED, 0)
                st.replicas = [ReplicaState(node)]
                self._note_replica(s, node, ShardStatus.UNASSIGNED,
                                   ShardStatus.ASSIGNED, 0)
            else:
                if len(st.replicas) >= self.replication_factor:
                    # replace a dead copy; refuse to displace live ones
                    dead = [i for i, r in enumerate(st.replicas)
                            if r.status in (ShardStatus.DOWN,
                                            ShardStatus.ERROR)]
                    if not dead:
                        continue
                    old = st.replicas[dead[0]]
                    self._note_replica(s, old.node, old.status,
                                       ShardStatus.UNASSIGNED, 0)
                    # copy-swap, never in-place: /health and the
                    # watermark ledger iterate st.replicas WITHOUT the
                    # manager lock and must always see a complete group
                    reps = list(st.replicas)
                    reps[dead[0]] = ReplicaState(node)
                    st.replicas = reps
                else:
                    st.replicas = st.replicas + [ReplicaState(node)]
                self._note_replica(s, node, ShardStatus.UNASSIGNED,
                                   ShardStatus.ASSIGNED, 0)
            self._note_status(s, prev, st.status, st.recovery_progress)

    def update_status(self, shard: int, status: ShardStatus,
                      progress: int = 0, node: Optional[str] = None) -> None:
        """Update ONE replica's status: the replica owned by ``node``
        when given (ignored if that node holds no copy), else the
        primary replica (the only one at rf=1)."""
        if not 0 <= shard < len(self._states):
            return  # a discarded split child's dying consumer reporting
        st = self._states[shard]
        rep = st.replica(node) if node is not None \
            else (st.replicas[0] if st.replicas else None)
        if rep is None:
            return
        prev_shard, prev_progress_shard = st.status, st.recovery_progress
        r_prev, r_prev_progress = rep.status, rep.recovery_progress
        rep.status = status
        rep.recovery_progress = progress
        if r_prev is not status or r_prev_progress != progress:
            self._note_replica(shard, rep.node, r_prev, status, progress)
        if prev_shard is not st.status \
                or prev_progress_shard != st.recovery_progress:
            self._note_status(shard, prev_shard, st.status,
                              st.recovery_progress)

    def set_replicas(self, shard: int, rows: Sequence[dict]) -> bool:
        """Adopt a leader-snapshot replica group wholesale (gossip:
        every node caches the singleton's ShardMapper snapshots).
        ``rows``: ``[{"node", "status", "progress", "watermark"}]``.
        Membership is replaced; replicas this node already tracked keep
        their LOCAL status (per-replica liveness is per-node ground
        truth), newly-learned replicas take the leader's status.
        Returns True when membership changed."""
        st = self._states[shard]
        # shard-level prev BEFORE any mutation: kept replicas are
        # updated in place below, so reading st.status afterwards would
        # compare the new primary status with itself and never fire the
        # shard-level transition (gauge + flight event) on adoption
        prev = st.status
        want = [r for r in rows if r.get("node")]
        want_nodes = [r["node"] for r in want]
        have_nodes = [r.node for r in st.replicas]
        changed = set(want_nodes) != set(have_nodes)
        keep = {r.node: r for r in st.replicas if r.node in want_nodes}
        terminal = (ShardStatus.DOWN, ShardStatus.STOPPED)
        new_reps: list[ReplicaState] = []
        for row in want:
            node = row["node"]
            rep = keep.get(node)
            if rep is None:
                try:
                    status = ShardStatus(row.get("status"))
                except ValueError:
                    status = ShardStatus.ASSIGNED
                rep = ReplicaState(node, status,
                                   int(row.get("progress") or 0),
                                   int(row.get("watermark", -1)))
                self._note_replica(shard, node, ShardStatus.UNASSIGNED,
                                   status, rep.recovery_progress)
            else:
                rep.watermark = max(rep.watermark,
                                    int(row.get("watermark", -1)))
                try:
                    leader_status = ShardStatus(row.get("status"))
                except ValueError:
                    leader_status = None
                if leader_status is not None and \
                        (leader_status in terminal) \
                        != (rep.status in terminal):
                    # leader INTENT (demotion to Down/Stopped, or the
                    # resurrection of a rejoined node) crosses the
                    # down boundary and must propagate to followers —
                    # keeping the local stale Active would route every
                    # query at a dead replica forever.  WITHIN live
                    # states (Active/Recovery/Assigned) the local
                    # liveness view of the peer stays authoritative.
                    r_prev = rep.status
                    rep.status = leader_status
                    rep.recovery_progress = int(row.get("progress") or 0)
                    # boundary crossing also RESETS the watermark to
                    # the leader's view: a resurrected node replays
                    # from its checkpoint, and max-merging would pin
                    # its pre-crash offset forever
                    rep.watermark = int(row.get("watermark", -1))
                    self._note_replica(shard, node, r_prev, leader_status,
                                       rep.recovery_progress)
            new_reps.append(rep)
        for rep in st.replicas:
            if rep.node not in want_nodes:
                self._note_replica(shard, rep.node, rep.status,
                                   ShardStatus.UNASSIGNED, 0)
        st.replicas = new_reps
        if prev is not st.status:
            self._note_status(shard, prev, st.status, st.recovery_progress)
        else:
            # newly-learned replicas were noted BEFORE the swap, when
            # best_status couldn't see them yet — refresh after it can
            self._refresh_shard_gauge(shard)
        return changed

    def note_watermark(self, shard: int, node: str, offset: int) -> None:
        """Record a replica's gossiped ingested offset (silent: the
        watermark ledger owns the metric surface for offsets)."""
        if not 0 <= shard < len(self._states):
            return  # split child gossip racing local topology adoption
        rep = self._states[shard].replica(node)
        if rep is not None:
            rep.watermark = max(rep.watermark, int(offset))

    def group_head(self, shard: int) -> int:
        """The replica group's ingest head: the max gossiped watermark
        across the group (-1 when nothing is known).  A recovering
        replica is promoted only once its own offset reaches this.

        Split children (ISSUE 13) replay their PARENT's partition, so
        their offsets live in the parent's domain — the head folds the
        parent group in, which is exactly the PR 12 promotion gate:
        a child is promoted only once it has replayed past everything
        any parent replica has ingested."""
        if not 0 <= shard < len(self._states):
            return -1  # post-abort race: discarded child
        st = self._states[shard]
        wms = [r.watermark for r in st.replicas]
        head = max(wms) if wms else -1
        parent = self.split_parent_of(shard)
        if parent is not None:
            pwms = [r.watermark for r in self._states[parent].replicas]
            if pwms:
                head = max(head, max(pwms))
        return head

    def routing_token(self) -> int:
        """Cheap hash of the replica-routing state: membership and
        per-replica status across every shard, FOLDED with the topology
        generation (ISSUE 13 satellite) — a completed split doubles the
        shard layout without necessarily changing any replica row the
        old token hashed, and a result-cache entry sliced on the retired
        layout must not survive the cutover.  Any failover-relevant
        transition (node death, demotion, promotion, reassignment)
        changes it too, so consumers that memoize answers computed under
        one routing view (query/resultcache.py) can key validity on it
        without subscribing to shard events.  Watermarks are excluded
        on purpose — they advance with every ingested row."""
        t = self._topology
        acc = [(t.generation, t.num_shards, t.split_phase)]
        for shard, st in enumerate(self._states):
            for r in st.replicas:      # copy-swap lists: safe to iterate
                acc.append((shard, r.node, r.status.value))
        return hash(tuple(acc))

    def unassign(self, shard: int, node: Optional[str] = None) -> None:
        """Drop a replica (``node`` given) or the whole group."""
        st = self._states[shard]
        prev = st.status
        if node is not None:
            rep = st.replica(node)
            if rep is None:
                return
            # copy-swap (unlocked readers iterate st.replicas)
            st.replicas = [r for r in st.replicas if r is not rep]
            self._note_replica(shard, node, rep.status,
                               ShardStatus.UNASSIGNED, 0)
        else:
            for r in st.replicas:
                self._note_replica(shard, r.node, r.status,
                                   ShardStatus.UNASSIGNED, 0)
            st.replicas = []
        if prev is not st.status:
            self._note_status(shard, prev, st.status, st.recovery_progress)

    def _note_status(self, shard: int, prev: ShardStatus,
                     status: ShardStatus, progress: int) -> None:
        """Shard-health emission (ISSUE 6): gauge + transition counter +
        flight event, ONLY on real changes (the status poller re-applies
        identical statuses every sweep — those must not spam the ring).
        Anonymous mappers (no dataset name) skip it entirely."""
        if not self.dataset:
            return
        m = _health_m()
        self._refresh_shard_gauge(shard)
        m["recovery_progress"].set(progress, dataset=self.dataset,
                                   shard=shard)
        if prev is not status:
            # the transition COUNTER is owned by the per-replica path
            # (_note_replica) — at rf=1 replica transitions == shard
            # transitions, and at rf>1 every lost/recovered copy counts
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("shard.status", dataset=self.dataset, shard=shard,
                          status=status.value, prev=prev.value,
                          progress=progress)

    def _refresh_shard_gauge(self, shard: int) -> None:
        """filodb_shard_status_code reports the SERVING view (best
        replica), matching /admin/shards, /api/v1/cluster and /__health
        — a dead primary with a surviving Active peer must not page
        'shard down' for a fully-served shard.  Refreshed after every
        replica transition, since any copy's change can move the best."""
        if not self.dataset:
            return
        _health_m()["status_code"].set(
            _STATUS_CODE[self._states[shard].best_status],
            dataset=self.dataset, shard=shard)

    def _note_replica(self, shard: int, node: str, prev: ShardStatus,
                      status: ShardStatus, progress: int) -> None:
        """Per-replica health emission (ISSUE 7): the replica-status
        gauge row is keyed by node so operators can see ONE copy down
        while the shard gauge (serving view) stays green.  rf=1 named
        mappers emit both rows — the replica row is the per-copy truth,
        the shard row the serving view."""
        if not self.dataset:
            return
        m = _health_m()
        self._refresh_shard_gauge(shard)
        if status is ShardStatus.UNASSIGNED:
            m["replica_status_code"].remove(dataset=self.dataset,
                                            shard=shard, node=node)
        else:
            m["replica_status_code"].set(_STATUS_CODE[status],
                                         dataset=self.dataset, shard=shard,
                                         node=node)
        if prev is not status:
            m["transitions"].inc(dataset=self.dataset, status=status.value)
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("shard.replica", dataset=self.dataset, shard=shard,
                          node=node, status=status.value, prev=prev.value,
                          progress=progress)

    def coord_for_shard(self, shard: int) -> Optional[str]:
        return self._states[shard].node

    _EMPTY_STATE = ShardState()

    def replicas(self, shard: int) -> list[ReplicaState]:
        """The shard's replica group (live view; do not mutate).
        Out-of-range reads (a query planned pre-abort racing the
        shard-space truncation) see an empty group, never an error."""
        states = self._states
        return states[shard].replicas if 0 <= shard < len(states) else []

    def replica_nodes(self, shard: int) -> list[str]:
        return [r.node for r in self._states[shard].replicas]

    def live_replicas(self, shard: int) -> list[ReplicaState]:
        """Replicas not in a terminal Down/Error state — the copies the
        assignment strategy counts toward the replication factor."""
        return [r for r in self._states[shard].replicas
                if r.status not in (ShardStatus.DOWN, ShardStatus.ERROR)]

    def status(self, shard: int) -> ShardStatus:
        return self._states[shard].status

    def best_status(self, shard: int) -> ShardStatus:
        return self._states[shard].best_status

    def state(self, shard: int) -> ShardState:
        """The full per-shard state row (status + owner + recovery
        progress + replicas) for health/watermark views.  Out-of-range
        (post-abort race) returns an empty Unassigned row."""
        states = self._states
        return states[shard] if 0 <= shard < len(states) \
            else self._EMPTY_STATE

    def active_shards(self, shards: Optional[Sequence[int]] = None) -> list[int]:
        """Shards with at least one queryable replica.  A caller's
        range may briefly exceed the shard space when a split abort
        truncates it mid-query — those ids are simply not active."""
        states = self._states
        rng = range(self.num_shards) if shards is None else shards
        return [s for s in rng
                if 0 <= s < len(states) and states[s].best_status.queryable]

    def all_nodes(self) -> set:
        return {r.node for st in self._states for r in st.replicas}

    def shards_for_node(self, node: str) -> list[int]:
        """Shards where ``node`` holds a LIVE (non-Down/Error) replica
        — the same liveness rule as ``live_replicas``, so the
        assignment strategy's ``have`` and ``need`` sides can never
        disagree about one copy."""
        dead = (ShardStatus.DOWN, ShardStatus.ERROR)
        return [i for i, st in enumerate(self._states)
                if any(r.node == node and r.status not in dead
                       for r in st.replicas)]

    def runnable_shards_for_node(self, node: str) -> list[int]:
        """Shards this node should actually be ingesting: its replica
        exists and is not held in an operator STOPPED / leader DOWN
        state (the one place this exclusion policy lives — resync and
        self-heal both consult it)."""
        out = []
        for i, st in enumerate(self._states):
            rep = st.replica(node)
            if rep is not None and rep.status not in (ShardStatus.STOPPED,
                                                      ShardStatus.DOWN):
                out.append(i)
        return out

    @property
    def num_assigned(self) -> int:
        return sum(1 for st in self._states
                   if st.status != ShardStatus.UNASSIGNED)
