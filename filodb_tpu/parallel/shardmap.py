"""ShardMapper: record -> shard bit-splice, spread fan-out, shard status.

Pure-function port-of-concept of the reference's ShardMapper
(reference: coordinator/src/main/scala/filodb.coordinator/ShardMapper.scala:
26-46 — shard = f(shardKeyHash upper bits, partitionHash lower bits, spread);
queryShards returns the 2^spread shards holding one shard key) plus the
ShardStatus lifecycle (ShardStatus.scala:54-94).  TPU mapping: a shard is a
slice of the mesh's data axis; ``coord_for_shard`` is the host/device owner.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence


class ShardStatus(enum.Enum):
    UNASSIGNED = "Unassigned"
    ASSIGNED = "Assigned"
    RECOVERY = "Recovery"
    ACTIVE = "Active"
    ERROR = "Error"
    STOPPED = "Stopped"
    DOWN = "Down"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


@dataclasses.dataclass
class ShardState:
    status: ShardStatus = ShardStatus.UNASSIGNED
    node: Optional[str] = None
    recovery_progress: int = 0  # percent


class ShardMapper:
    def __init__(self, num_shards: int):
        if num_shards <= 0 or num_shards & (num_shards - 1):
            raise ValueError(f"num_shards {num_shards} must be a power of 2")
        self.num_shards = num_shards
        self._states = [ShardState() for _ in range(num_shards)]

    # -- hashing ------------------------------------------------------------

    def shard_hash_mask(self, spread: int) -> int:
        return (self.num_shards - 1) & ~((1 << spread) - 1)

    def part_hash_mask(self, spread: int) -> int:
        return (1 << spread) - 1

    def ingestion_shard(self, shard_key_hash: int, part_hash: int,
                        spread: int) -> int:
        """Upper bits from the shard-key hash, lower ``spread`` bits from the
        partition hash (reference: ShardMapper.ingestionShard)."""
        return ((shard_key_hash & self.shard_hash_mask(spread))
                | (part_hash & self.part_hash_mask(spread)))

    def query_shards(self, shard_key_hash: int, spread: int) -> list[int]:
        """All 2^spread shards that can hold series of one shard key."""
        base = shard_key_hash & self.shard_hash_mask(spread)
        return [base | i for i in range(1 << spread)]

    # -- assignment / status ------------------------------------------------

    def register_node(self, shards: Sequence[int], node: str) -> None:
        for s in shards:
            self._states[s] = ShardState(ShardStatus.ASSIGNED, node)

    def update_status(self, shard: int, status: ShardStatus,
                      progress: int = 0) -> None:
        st = self._states[shard]
        st.status = status
        st.recovery_progress = progress

    def unassign(self, shard: int) -> None:
        self._states[shard] = ShardState()

    def coord_for_shard(self, shard: int) -> Optional[str]:
        return self._states[shard].node

    def status(self, shard: int) -> ShardStatus:
        return self._states[shard].status

    def active_shards(self, shards: Optional[Sequence[int]] = None) -> list[int]:
        rng = range(self.num_shards) if shards is None else shards
        return [s for s in rng if self._states[s].status.queryable]

    def all_nodes(self) -> set:
        return {st.node for st in self._states if st.node is not None}

    def shards_for_node(self, node: str) -> list[int]:
        return [i for i, st in enumerate(self._states) if st.node == node]

    def runnable_shards_for_node(self, node: str) -> list[int]:
        """Shards this node should actually be ingesting: assigned to it
        and not held in an operator STOPPED / leader DOWN state (the one
        place this exclusion policy lives — resync and self-heal both
        consult it)."""
        return [i for i, st in enumerate(self._states)
                if st.node == node and st.status not in
                (ShardStatus.STOPPED, ShardStatus.DOWN)]

    @property
    def num_assigned(self) -> int:
        return sum(1 for st in self._states
                   if st.status != ShardStatus.UNASSIGNED)
