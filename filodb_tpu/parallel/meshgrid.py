"""HBM-resident multi-device serving: the device grid x the SPMD mesh.

VERDICT r2 #1: the round-2 mesh path re-scanned host batches and
re-uploaded them into the SPMD program on every query, while the
device-resident grid (the single-chip speed story) ran only on the
single-device planner path.  This module composes the two: each shard's
:class:`DeviceGridCache` pins its blocks to that shard's mesh device
(shard.grid_device), a query asks every local shard for a
:class:`MeshShardPlan` (resident, staged in place), and ONE
``shard_map`` program runs the grid kernels over every device's
resident lanes and ``psum``s the [G, T] partials over the ``shard``
axis — serving `sum(rate())` on an N-chip slice with zero per-query
host->device upload (reference: BlockManager.scala:142 resident serving
x SingleClusterPlanner.scala:223-258 scatter-gather).

The global input arrays are assembled with
``jax.make_array_from_single_device_arrays`` from the per-device staged
pieces — no cross-device data movement at all; the only traffic the
query generates is the psum itself riding ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.query.logical import AggregationOperator as Agg

# aggregate ops with a fused grid-mesh form (matches the single-device
# fused path, exec._GRID_AGG_OPS)
GRID_MESH_OPS = {Agg.SUM: "sum", Agg.COUNT: "count", Agg.AVG: "avg",
                 Agg.MIN: "min", Agg.MAX: "max"}

_LANE_PAD = 128


def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


@functools.lru_cache(maxsize=64)
def _grid_mesh_program(mesh_key, q, mode: str, ksub: int, nrows: int,
                       lmax: int, num_groups: int, op: str):
    """The SPMD serving program for one (mesh, query, layout) signature.

    Local body: for each of the device's ``ksub`` resident shard slices,
    run the grid kernel ([nrows, lmax] -> [T, lmax]) and segment-reduce
    lanes into [G(+drop), T] partials; accumulate across local shards;
    then one collective over the ``shard`` axis replaces the reference's
    cross-node reduce tree.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    try:
        from jax import shard_map
    except ImportError:                                  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from filodb_tpu.memstore.devicestore import _grouped_reduce_impl
    from filodb_tpu.ops.grid import rate_grid_auto

    from filodb_tpu.parallel.mesh import _MESHES
    mesh = _MESHES[mesh_key]
    lanes = 1024 if lmax % 1024 == 0 else _LANE_PAD
    G = num_groups
    two_plane = op in ("sum", "avg", "count")

    def local(ts, vals, phase, s0, garr):
        # ts/vals: [ksub, nrows, lmax]; phase: [ksub, lmax];
        # s0: [ksub]; garr: [ksub, lmax]
        acc = None
        for k in range(ksub):
            stepped = rate_grid_auto(
                ts[k] if mode == "ts" else None, vals[k], s0[k], q, lanes,
                phase=phase[k] if mode == "phase" else None)
            part = _grouped_reduce_impl(stepped, garr[k], G, op)
            if acc is None:
                acc = part
            elif two_plane:
                acc = acc + part                  # [2, G, T] sum+count
            elif op == "min":
                acc = jnp.minimum(acc, part)
            else:
                acc = jnp.maximum(acc, part)
        if two_plane:
            return lax.psum(acc, "shard")
        if op == "min":
            return lax.pmin(acc, "shard")
        return lax.pmax(acc, "shard")

    in_specs = (P("shard", None, None), P("shard", None, None),
                P("shard", None), P("shard"), P("shard", None))
    fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                   out_specs=P(None, None, None) if two_plane
                   else P(None, None))
    return jax.jit(fn)


def _pad_piece(arr, nrows: int, lmax: int, fill):
    """Device-side lane pad to the common width (stays on its device)."""
    jax, jnp = _jax()
    if arr.shape[1] == lmax:
        return arr
    return _pad_jit(arr, lmax - arr.shape[1], fill)


@functools.partial(
    __import__("functools").lru_cache(maxsize=1))
def _pad_fn():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("extra", "fill"))
    def pad(arr, *, extra, fill):
        return jnp.pad(arr, ((0, 0), (0, extra)), constant_values=fill)
    return pad


def _pad_jit(arr, extra: int, fill):
    return _pad_fn()(arr, extra=extra, fill=fill)


def serve_grid_mesh(engine, plans: Sequence, num_groups: int,
                    operator: Agg) -> Optional[dict]:
    """Run one fused grid-mesh query over per-shard resident plans.

    Returns the mergeable partial state dict ({"sum","count"} / {"min"}
    / {"max"}) like DeviceGridCache.scan_rate_grouped, or None when the
    plans cannot compose (mixed query shapes, too many shards for the
    mesh layout, unsupported op)."""
    jax, jnp = _jax()
    from jax.sharding import NamedSharding, PartitionSpec as P

    from filodb_tpu.ops.grid import DENSE_ONLY_OPS, phase_eligible

    op = GRID_MESH_OPS.get(operator)
    if op is None or not plans:
        return None
    q0 = plans[0].q
    nrows = plans[0].ts.shape[0]
    # one program serves every shard: query shapes must agree, and the
    # dense/phase specialization is the MEET across shards
    for p in plans:
        if p.ts.shape[0] != nrows:
            return None
        if p.q._replace(dense=False) != q0._replace(dense=False):
            return None
    dense = all(p.q.dense for p in plans)
    if not dense and q0.op in DENSE_ONLY_OPS:
        return None
    q = q0._replace(dense=dense)
    mode = "phase" if (phase_eligible(q)
                       and all(p.phase is not None for p in plans)) \
        else "ts"
    mesh = engine.mesh
    ndev = mesh.devices.size
    devices = list(mesh.devices.flat)
    K = len(plans)
    ksub = -(-K // ndev)
    Kp = ksub * ndev
    lmax = max(-(-max(p.ncols for p in plans) // _LANE_PAD) * _LANE_PAD,
               _LANE_PAD)

    # per-device local pieces, assembled in place (device-side pads only)
    by_dev: list[list] = [[] for _ in range(ndev)]
    for i, p in enumerate(plans):
        by_dev[i % ndev].append(p)
    ts_pieces, val_pieces, ph_pieces, s0_pieces, g_pieces = [], [], [], [], []
    for d, dev in enumerate(devices):
        ts_k, val_k, ph_k, s0_k, g_k = [], [], [], [], []
        for p in by_dev[d]:
            ts_d = jax.device_put(p.ts, dev)       # no-op when resident
            val_d = jax.device_put(p.vals, dev)
            ts_k.append(_pad_piece(ts_d, nrows, lmax, 0))
            val_k.append(_pad_piece(val_d, nrows, lmax, np.nan))
            if mode == "phase":
                ph = jax.device_put(p.phase, dev)
                ph_k.append(jnp.pad(ph, (0, lmax - ph.shape[0]),
                                    constant_values=1)
                            if ph.shape[0] != lmax else ph)
            s0_k.append(int(p.steps0_rel))
            g = np.full(lmax, num_groups, np.int32)
            g[:len(p.garr)] = p.garr
            g_k.append(g)
        while len(ts_k) < ksub:                    # filler shard slices
            ts_k.append(jax.device_put(
                np.zeros((nrows, lmax), np.int32), dev))
            val_k.append(jax.device_put(
                np.full((nrows, lmax),
                        np.nan, np.asarray(val_k[0]).dtype if val_k
                        else np.float32), dev))
            if mode == "phase":
                ph_k.append(jax.device_put(np.ones(lmax, np.int32), dev))
            s0_k.append(0)
            g_k.append(np.full(lmax, num_groups, np.int32))
        ts_pieces.append(jnp.stack(ts_k))
        val_pieces.append(jnp.stack(val_k))
        if mode == "phase":
            ph_pieces.append(jnp.stack(ph_k))
        else:
            ph_pieces.append(jax.device_put(
                np.ones((ksub, lmax), np.int32), dev))
        s0_pieces.append(jax.device_put(
            np.asarray(s0_k, np.int32), dev))
        g_pieces.append(jax.device_put(np.stack(g_k), dev))

    def assemble(pieces, trailing_shape, dtype):
        shape = (Kp, *trailing_shape)
        sharding = NamedSharding(mesh, P("shard",
                                         *([None] * len(trailing_shape))))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, pieces)

    vdt = np.asarray(val_pieces[0]).dtype
    g_ts = assemble(ts_pieces, (nrows, lmax), np.int32)
    g_vals = assemble(val_pieces, (nrows, lmax), vdt)
    g_ph = assemble(ph_pieces, (lmax,), np.int32)
    g_s0 = assemble(s0_pieces, (), np.int32)
    g_garr = assemble(g_pieces, (lmax,), np.int32)

    prog = _grid_mesh_program(engine._key, q, mode, ksub, nrows, lmax,
                              num_groups, op)
    out = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
    if op in ("sum", "avg", "count"):
        both = np.asarray(out, dtype=np.float64)       # [2, G, T]
        if op == "count":
            return {"count": both[1]}
        return {"sum": both[0], "count": both[1]}
    a = np.asarray(out, dtype=np.float64)
    return {op: np.where(np.isfinite(a), a, np.nan)}
