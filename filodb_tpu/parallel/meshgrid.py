"""HBM-resident multi-device serving: the device grid x the SPMD mesh.

VERDICT r2 #1 / r3 #1: the round-2 mesh path re-scanned host batches and
re-uploaded them into the SPMD program on every query, while the
device-resident grid (the single-chip speed story) ran only on the
single-device planner path.  This module composes the two: each shard's
:class:`DeviceGridCache` pins its blocks to that shard's mesh device
(``shard.grid_device``, assigned by MeshAggregateExec), a query asks
every local shard for a :class:`MeshShardPlan` (resident, staged in
place), and ONE ``shard_map`` program runs the grid kernels over every
device's resident lanes and ``psum``s the [G, T] partials over the mesh
— serving ``sum(rate())`` on an N-chip slice with zero per-query
host->device upload (reference: BlockManager.scala:142 resident serving
x SingleClusterPlanner.scala:223-258 scatter-gather).

The global input arrays are assembled with
``jax.make_array_from_single_device_arrays`` from the per-device staged
pieces — no cross-device data movement at all; the only traffic the
query generates is the psum itself riding ICI.  The assembled global
arrays are memoized on the staged pieces' identity, so a REPEAT query
(the dashboard-refresh case) performs no assembly, no pad, and no
host->device transfer of any kind: it re-dispatches the jitted program
on the already-assembled residents.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import numpy as np

from filodb_tpu.query.logical import AggregationOperator as Agg
from filodb_tpu.utils import devicewatch
from filodb_tpu.utils.devicewatch import LEDGER

# aggregate ops with a fused grid-mesh form.  Round 5 (VERDICT r4 #2):
# the WHOLE RowAggregator family now serves from resident lanes —
# distributive ops reduce via psum/pmin/pmax planes, stddev/stdvar ride
# 3-plane moments, group rides the count plane, topk/bottomk run the
# k-slot program with an all_gather candidate merge, quantile sketches
# per-device t-digests and merges them over the mesh, and count_values
# reads back only the [lanes, T] stepped matrix (reference:
# query/exec/aggregator/RowAggregator.scala:114-141 reducing every
# aggregator from resident block memory, BlockManager.scala:142).
GRID_MESH_OPS = {Agg.SUM: "sum", Agg.COUNT: "count", Agg.AVG: "avg",
                 Agg.MIN: "min", Agg.MAX: "max", Agg.GROUP: "count",
                 Agg.STDDEV: "moments", Agg.STDVAR: "moments"}
# k-slot / sketch / member ops: one extra static param rides the program
GRID_MESH_K_OPS = {Agg.TOPK: "topk", Agg.BOTTOMK: "bottomk"}
GRID_MESH_MEMBER_OPS = {Agg.QUANTILE: "quantile",
                        Agg.COUNT_VALUES: "values"}
GRID_MESH_ALL_OPS = {**GRID_MESH_OPS, **GRID_MESH_K_OPS,
                     **GRID_MESH_MEMBER_OPS}

_LANE_PAD = 128

# the grid-mesh program reduces over EVERY mesh device: shard slices are
# laid out over the flattened (shard, step) axes so a 2D serving mesh
# (the dryrun's (N/2, 2) shape) needs no replicated pieces
_AXES = ("shard", "step")

# observability: wiring tests and the multichip dryrun assert the
# resident path actually ran (serves), that repeat queries skipped
# assembly (memo_hits), how often composition fell back, and how many
# serves ran the fully-fused (present-on-device) fabric form
STATS = {"serves": 0, "assembles": 0, "memo_hits": 0, "fallbacks": 0,
         "fused_serves": 0}

_METRICS = None


def _mm():
    """The filodb_mesh_* metric family, registered lazily so importing
    this module never touches the registry before standalone wires it."""
    global _METRICS
    if _METRICS is None:
        from filodb_tpu.utils.observability import REGISTRY
        _METRICS = {
            "fused_serves": REGISTRY.counter(
                "filodb_mesh_fused_serves_total",
                "fully-fused single-dispatch fabric serves, by program"),
            "fallbacks": REGISTRY.counter(
                "filodb_mesh_fallbacks_total",
                "mesh fabric fallbacks to a slower serving tier, by "
                "reason"),
            "breaker": REGISTRY.gauge(
                "filodb_mesh_breaker_open",
                "1 while the fabric breaker forces scatter-gather"),
        }
    return _METRICS


def _fallback(reason: str) -> None:
    """One fabric downgrade: bump the wiring-test STATS counter and the
    exported filodb_mesh_fallbacks_total{reason=} family together."""
    STATS["fallbacks"] += 1
    _mm()["fallbacks"].inc(reason=reason)

# (mesh, layout, garr) -> assembled global arrays; holds the plan arrays
# so the id()-keys stay unambiguous while an entry lives.  LRU with BOTH
# a count cap and a byte budget: ingest invalidations (note_freeze /
# note_repin) retire the staged pieces, orphaning old entries' keys —
# without the byte bound, generations of full padded dataset copies
# would pin HBM until the count cap finally cleared them.
from collections import OrderedDict

_ASSEMBLY_MEMO: "OrderedDict[tuple, tuple]" = OrderedDict()
_ASSEMBLY_MEMO_CAP = 8
_ASSEMBLY_MEMO_BYTES = 1 << 31        # 2 GiB of assembled residents


def _memo_insert(key, value, nbytes: int) -> None:
    _ASSEMBLY_MEMO[key] = (*value, nbytes)
    total = sum(v[-1] for v in _ASSEMBLY_MEMO.values())
    while _ASSEMBLY_MEMO and (len(_ASSEMBLY_MEMO) > _ASSEMBLY_MEMO_CAP
                              or total > _ASSEMBLY_MEMO_BYTES):
        if len(_ASSEMBLY_MEMO) == 1:
            break                      # never evict the entry just added
        _k, v = _ASSEMBLY_MEMO.popitem(last=False)
        total -= v[-1]


def _jax():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def _stage_put(arr, dev):
    """Assembly-staging ``device_put``, ledger-tracked (devicewatch).  A
    put of an already-resident piece is a jax no-op and stays attributed
    to its original owner (the shard grid's mesh-staged planes); only
    the filler/pad/meta pieces assembled here are new residents."""
    return LEDGER.device_put(arr, dev, owner="meshgrid:assembly",
                             fmt="mesh-staged")


def _grouped_local(q, mode: str, ksub: int, lanes: int, num_groups: int,
                   op: str):
    """Shared local body of every grouped fabric program: for each of
    the device's ``ksub`` resident shard slices, run the grid kernel
    ([nrows, lmax] -> [T, lmax]) and segment-reduce lanes into
    [G(+drop), T] partials; accumulate across local shards; then one
    collective over the mesh replaces the reference's cross-node reduce
    tree.  Returns (local_fn, psum_planes); the partial and fused
    programs MUST build their bodies here so their reduce arithmetic
    can never drift (bit-equality across serving tiers rests on it)."""
    import jax.numpy as jnp
    from jax import lax

    from filodb_tpu.memstore.devicestore import _grouped_reduce_impl
    from filodb_tpu.ops.grid import rate_grid_auto

    G = num_groups
    psum_planes = op in ("sum", "avg", "count", "moments")

    def local(ts, vals, phase, s0, garr):
        # ts/vals: [ksub, nrows, lmax]; phase: [ksub, lmax];
        # s0: [ksub]; garr: [ksub, lmax]
        acc = None
        for k in range(ksub):
            stepped = rate_grid_auto(
                ts[k] if mode == "ts" else None, vals[k], s0[k], q, lanes,
                phase=phase[k] if mode == "phase" else None)
            part = _grouped_reduce_impl(stepped, garr[k], G, op)
            if acc is None:
                acc = part
            elif psum_planes:
                acc = acc + part                  # [2|3, G, T] planes
            elif op == "min":
                acc = jnp.minimum(acc, part)
            else:
                acc = jnp.maximum(acc, part)
        if psum_planes:
            return lax.psum(acc, _AXES)
        if op == "min":
            return lax.pmin(acc, _AXES)
        return lax.pmax(acc, _AXES)

    return local, psum_planes


def _grouped_inner(mesh, q, mode: str, ksub: int, nrows: int, lmax: int,
                   num_groups: int, op: str):
    """shard_map-wrapped grouped body at the shared lane width rule
    (devicestore._plan_locked: tall strided slices narrow the tile)."""
    from jax.sharding import PartitionSpec as P
    lanes = 1024 if (lmax % 1024 == 0 and nrows <= 256) else _LANE_PAD
    local, psum_planes = _grouped_local(q, mode, ksub, lanes, num_groups,
                                        op)
    in_specs = (P(_AXES, None, None), P(_AXES, None, None),
                P(_AXES, None), P(_AXES), P(_AXES, None))
    kw = dict(mesh=mesh, in_specs=in_specs,
              out_specs=P(None, None, None) if psum_planes
              else P(None, None))
    # Pallas kernels' ShapeDtypeStruct outputs carry no vma; the newer
    # shard_map's varying-across-mesh check rejects them — route through
    # the version-spelling-aware unchecked wrapper
    return _shard_map_unchecked(local, **kw), psum_planes


@functools.lru_cache(maxsize=64)
def _grid_mesh_program(mesh_key, q, mode: str, ksub: int, nrows: int,
                       lmax: int, num_groups: int, op: str):
    """The SPMD PARTIAL program for one (mesh, query, layout) signature:
    the mergeable [2|3, G, T] planes (or the [G, T] min/max surface)
    read back for a host-side reduce with remote/host-batch partials."""
    from filodb_tpu.parallel.mesh import _MESHES
    fn, _ = _grouped_inner(_MESHES[mesh_key], q, mode, ksub, nrows, lmax,
                           num_groups, op)
    return devicewatch.jit(fn, program="meshgrid.grouped")


# AggregationOperator -> the fused present epilogue it rides; mirrors
# MomentAggregator.present case by case (query/aggregators.py)
_PRESENT_AGGS = {Agg.SUM: "sum", Agg.COUNT: "count", Agg.AVG: "avg",
                 Agg.MIN: "min", Agg.MAX: "max", Agg.GROUP: "group",
                 Agg.STDDEV: "stddev", Agg.STDVAR: "stdvar"}


@functools.lru_cache(maxsize=64)
def _grid_mesh_present_program(mesh_key, q, mode: str, ksub: int,
                               nrows: int, lmax: int, num_groups: int,
                               op: str, agg: str):
    """The tentpole fabric program: leaf-scan -> window -> group-reduce
    -> cross-shard psum/pmin/pmax -> PRESENT, all one compiled dispatch
    returning the final [G, T] answer — the partial planes never reach
    the host.  The present epilogue mirrors MomentAggregator.present
    expression by expression in f64, so the fused answer is bit-equal
    to the scatter-gather path's on identical partials."""
    import jax.numpy as jnp

    from filodb_tpu.parallel.mesh import _MESHES
    inner, psum_planes = _grouped_inner(_MESHES[mesh_key], q, mode, ksub,
                                        nrows, lmax, num_groups, op)

    def fn(ts, vals, phase, s0, garr):
        out = inner(ts, vals, phase, s0, garr)
        if not psum_planes:                         # min / max
            return jnp.where(jnp.isfinite(out), out, jnp.nan)
        s, n = out[0], out[1]
        if agg == "sum":
            return jnp.where(n > 0, s, jnp.nan)
        if agg == "count":
            return jnp.where(n > 0, n, jnp.nan)
        if agg == "group":
            return jnp.where(n > 0, 1.0, jnp.nan)
        if agg == "avg":
            return jnp.where(n > 0, s / jnp.maximum(n, 1.0), jnp.nan)
        nsafe = jnp.maximum(n, 1.0)                 # stddev / stdvar
        mean = s / nsafe
        var = jnp.maximum(out[2] / nsafe - mean * mean, 0.0)
        if agg == "stddev":
            var = jnp.sqrt(var)
        return jnp.where(n > 0, var, jnp.nan)

    return devicewatch.jit(fn, program="meshgrid.fused")


@functools.lru_cache(maxsize=64)
def _grid_mesh_histq_program(mesh_key, q, mode: str, ksub: int,
                             nrows: int, lmax: int, num_groups: int,
                             hb: int, phi: float):
    """histogram_quantile over the fabric as ONE dispatch.  The cross-
    shard merge stays PRE-quantile — per-bucket sum/count planes psum
    over the mesh, because quantiles of sums are not sums of quantiles
    — and the interpolation then runs on the merged planes inside the
    same program, so only the final [G, T] quantile surface reads back.
    The epilogue mirrors hist_state_from_planes +
    MomentAggregator.present + InstantVectorFunctionMapper's
    hist_quantile call, expression by expression in f64."""
    import jax.numpy as jnp

    from filodb_tpu.memstore.devicestore import hist_planes_split
    from filodb_tpu.ops.histogram_ops import hist_quantile
    from filodb_tpu.parallel.mesh import _MESHES
    inner, _ = _grouped_inner(_MESHES[mesh_key], q, mode, ksub, nrows,
                              lmax, num_groups * hb, "sum")

    def fn(ts, vals, phase, s0, garr, tops):
        both = inner(ts, vals, phase, s0, garr)     # [2, G*hb, T]
        hist, n = hist_planes_split(both, num_groups, hb)
        hist = jnp.where(n[..., None] > 0, hist, jnp.nan)
        return hist_quantile(tops, hist, phi)       # [G, T]

    return devicewatch.jit(fn, program="meshgrid.fused_histq")


@functools.lru_cache(maxsize=64)
def _grid_mesh_event_topk_program(mesh_key, q, mode: str, ksub: int,
                                  nrows: int, lmax: int, num_groups: int,
                                  k: int, largest: bool):
    """Distributed event-topK merge (the PR 19 event_topk exec
    follow-up): grouped event sums are additive, so the cross-shard
    merge psums the [2, G, T] planes over the mesh FIRST and one
    on-device lax.top_k then selects the k hottest groups per step —
    exact, unlike merging per-shard topK lists, and still one dispatch
    with a [T, k] readback."""
    import jax.numpy as jnp
    from jax import lax

    from filodb_tpu.parallel.mesh import _MESHES
    inner, _ = _grouped_inner(_MESHES[mesh_key], q, mode, ksub, nrows,
                              lmax, num_groups, "sum")
    sign = 1.0 if largest else -1.0

    def fn(ts, vals, phase, s0, garr):
        both = inner(ts, vals, phase, s0, garr)     # [2, G, T]
        s, n = both[0], both[1]
        work = jnp.where(n > 0, s * sign, -jnp.inf)
        topv, topg = lax.top_k(work.T, k)           # [T, k]
        found = jnp.isfinite(topv)
        return (jnp.where(found, topv * sign, jnp.nan),
                jnp.where(found, topg, -1))

    return devicewatch.jit(fn, program="meshgrid.event_topk")


def _shard_map_unchecked(local, **kw):
    from filodb_tpu.parallel.mesh import _shard_map_unchecked as smu
    return smu(local, **kw)


def _stepped_lanes(mode, q, lanes):
    """Shared per-slice leaf: grid kernel -> [lmax, T] lane-major."""
    from filodb_tpu.ops.grid import rate_grid_auto

    def leaf(ts_k, vals_k, s0_k, phase_k):
        stepped = rate_grid_auto(ts_k if mode == "ts" else None, vals_k,
                                 s0_k, q, lanes,
                                 phase=phase_k if mode == "phase" else None)
        return stepped.T                                # [lmax, T]
    return leaf


def _mesh_gather(x, mesh):
    """all_gather over BOTH serving axes -> leading [ndev] in the same
    flattened order as ``mesh.devices.flat`` (shard-major)."""
    from jax import lax
    inner = lax.all_gather(x, "step")                   # [nst, ...]
    both = lax.all_gather(inner, "shard")               # [nsh, nst, ...]
    return both.reshape((-1,) + x.shape)


@functools.lru_cache(maxsize=64)
def _grid_mesh_topk_program(mesh_key, q, mode: str, ksub: int, nrows: int,
                            lmax: int, num_groups: int, k: int,
                            bottom: bool):
    """topk/bottomk over resident lanes: per-slice k-slot selection with
    GLOBAL lane indices, candidates merged by one all_gather + re-top-k
    (the k-heap merge of the reference's TopBottomKRowAggregator,
    RowAggregator.scala:114-141, over ICI)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from filodb_tpu.ops import aggregate as segops
    from filodb_tpu.parallel.mesh import _MESHES
    mesh = _MESHES[mesh_key]
    nst = mesh.devices.shape[1]
    lanes = 1024 if (lmax % 1024 == 0 and nrows <= 256) else _LANE_PAD
    G = num_groups
    leaf = _stepped_lanes(mode, q, lanes)
    sign = -1.0 if bottom else 1.0

    def local(ts, vals, phase, s0, garr):
        di = lax.axis_index("shard") * nst + lax.axis_index("step")
        cv, ci = [], []
        for kk in range(ksub):
            v = leaf(ts[kk], vals[kk], s0[kk],
                     phase[kk] if mode == "phase" else None)   # [lmax, T]
            vals_k, si = segops.seg_topk(v, garr[kk], G + 1, k,
                                         bottom=bottom)
            base = (di * ksub + kk) * lmax
            cv.append(vals_k[:G])
            ci.append(jnp.where(si[:G] >= 0, si[:G] + base, -1))
        V = jnp.concatenate(cv, axis=1)          # [G, ksub*k, T]
        I = jnp.concatenate(ci, axis=1)
        allv = _mesh_gather(V, mesh)             # [ndev, G, ksub*k, T]
        alli = _mesh_gather(I, mesh)
        nd = allv.shape[0]
        T = V.shape[-1]
        Vg = jnp.moveaxis(allv, 0, 1).reshape(G, nd * ksub * k, T)
        Ig = jnp.moveaxis(alli, 0, 1).reshape(G, nd * ksub * k, T)
        work = jnp.where(jnp.isfinite(Vg), Vg * sign, -jnp.inf)
        topv, topc = lax.top_k(jnp.moveaxis(work, 1, 2), k)    # [G, T, k]
        found = jnp.isfinite(topv)
        topi = jnp.take_along_axis(jnp.moveaxis(Ig, 1, 2), topc, axis=2)
        values = jnp.moveaxis(jnp.where(found, topv * sign, jnp.nan), 1, 2)
        sidx = jnp.moveaxis(jnp.where(found, topi, -1), 1, 2)
        return values, sidx                      # [G, k, T] replicated

    in_specs = (P(_AXES, None, None), P(_AXES, None, None),
                P(_AXES, None), P(_AXES), P(_AXES, None))
    fn = _shard_map_unchecked(local, mesh=mesh, in_specs=in_specs,
                              out_specs=(P(None, None, None),
                                         P(None, None, None)))
    return devicewatch.jit(fn, program="meshgrid.topk")


@functools.lru_cache(maxsize=64)
def _grid_mesh_quantile_program(mesh_key, q, mode: str, ksub: int,
                                nrows: int, lmax: int, num_groups: int,
                                compression: int):
    """quantile over resident lanes: per-slice t-digest sketches, local
    centroid merge across the device's shard slices, one all_gather of
    the [G, T, C] sketches, and a final on-device compress (the
    reference's TDigest partial rows over ICI)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from filodb_tpu.ops import tdigest_device as tdd
    from filodb_tpu.parallel.mesh import _MESHES
    mesh = _MESHES[mesh_key]
    lanes = 1024 if (lmax % 1024 == 0 and nrows <= 256) else _LANE_PAD
    G, C = num_groups, compression
    leaf = _stepped_lanes(mode, q, lanes)

    def local(ts, vals, phase, s0, garr):
        ms, ws = [], []
        for kk in range(ksub):
            v = leaf(ts[kk], vals[kk], s0[kk],
                     phase[kk] if mode == "phase" else None)   # [lmax, T]
            m, w = tdd.digest_from_series(v, garr[kk], G, C)   # [G, T, C]
            ms.append(m)
            ws.append(w)
        m = jnp.concatenate(ms, axis=-1)          # [G, T, ksub*C]
        w = jnp.concatenate(ws, axis=-1)
        if ksub > 1:
            m, w = tdd.compress(m, w, C)
        allm = _mesh_gather(m, mesh)              # [ndev, G, T, C]
        allw = _mesh_gather(w, mesh)
        nd = allm.shape[0]
        T = m.shape[1]
        M = jnp.moveaxis(allm, 0, 3).reshape(G, T, nd * m.shape[-1])
        W = jnp.moveaxis(allw, 0, 3).reshape(G, T, nd * m.shape[-1])
        return tdd.compress(M, W, C)              # [G, T, C] replicated

    in_specs = (P(_AXES, None, None), P(_AXES, None, None),
                P(_AXES, None), P(_AXES), P(_AXES, None))
    fn = _shard_map_unchecked(local, mesh=mesh, in_specs=in_specs,
                              out_specs=(P(None, None, None),
                                         P(None, None, None)))
    return devicewatch.jit(fn, program="meshgrid.quantile")


@functools.lru_cache(maxsize=64)
def _grid_mesh_values_program(mesh_key, q, mode: str, ksub: int,
                              nrows: int, lmax: int):
    """count_values leaf over resident lanes: scan+window only, stepped
    values stay device-sharded; the host reads back [slots, lmax, T] and
    builds the (value, group, step) counts (output cardinality is
    data-dependent, like the reference's CountValuesRowAggregator)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from filodb_tpu.parallel.mesh import _MESHES
    mesh = _MESHES[mesh_key]
    lanes = 1024 if (lmax % 1024 == 0 and nrows <= 256) else _LANE_PAD
    leaf = _stepped_lanes(mode, q, lanes)

    def local(ts, vals, phase, s0):
        import jax.numpy as jnp
        outs = []
        for kk in range(ksub):
            outs.append(leaf(ts[kk], vals[kk], s0[kk],
                             phase[kk] if mode == "phase" else None))
        return jnp.stack(outs)                    # [ksub, lmax, T]

    in_specs = (P(_AXES, None, None), P(_AXES, None, None),
                P(_AXES, None), P(_AXES))
    fn = _shard_map_unchecked(local, mesh=mesh, in_specs=in_specs,
                              out_specs=P(_AXES, None, None))
    return devicewatch.jit(fn, program="meshgrid.values")


def _pad_piece(arr, lmax: int, fill):
    """Device-side lane pad to the common width (stays on its device)."""
    if arr.shape[1] == lmax:
        return arr
    return _pad_fn()(arr, extra=lmax - arr.shape[1], fill=fill)


@functools.lru_cache(maxsize=1)
def _pad_fn():
    import functools as ft

    import jax
    import jax.numpy as jnp

    @ft.partial(devicewatch.jit, program="meshgrid.pad",
                static_argnames=("extra", "fill"))
    def pad(arr, *, extra, fill):
        return jnp.pad(arr, ((0, 0), (0, extra)), constant_values=fill)
    return pad


def _garr_fp(garr: np.ndarray) -> int:
    return hash(garr.tobytes())


def _compose(plans: Sequence, operator: Agg):
    """Validate that the per-shard plans run under ONE program signature.
    Returns (q, mode) or None to fall back."""
    from filodb_tpu.ops.grid import (DENSE_ONLY_OPS, max_k_for,
                                     phase_eligible)
    op = GRID_MESH_ALL_OPS.get(operator)
    if op is None or not plans:
        return None
    q0 = plans[0].q
    nrows = plans[0].vals.shape[0]
    hb0 = plans[0].hb
    if hb0 and operator is not Agg.SUM:
        return None        # only sum is defined over histogram series
    # one program serves every shard: query shapes must agree, the
    # histogram bucket scheme must match (differing widths cannot share
    # one garr layout), and dense/phase is the MEET across shards
    for p in plans:
        if p.vals.shape[0] != nrows or p.hb != hb0:
            return None
        if hb0 and not np.array_equal(p.bucket_tops, plans[0].bucket_tops):
            return None
        if p.q._replace(dense=False) != q0._replace(dense=False):
            return None
    dense = all(p.q.dense for p in plans)
    if not dense and (q0.op in DENSE_ONLY_OPS
                      or q0.kbuckets > max_k_for(q0.op, False)):
        # each shard proved its own K bound under ITS dense flag; the
        # meet downgrade must re-check the non-dense bound
        return None
    q = q0._replace(dense=dense)
    mode = "phase" if (phase_eligible(q)
                       and all(p.phase is not None for p in plans)) \
        else "ts"
    if mode == "ts" and any(p.ts is None for p in plans):
        # a uniform-phase shard staged NO ts plane (ISSUE 3); if the
        # composition meets down to ts mode it cannot serve — fall back
        # rather than feed the program a fabricated geometry
        return None
    return q, mode


def _assign_devices(plans: Sequence, devices: list,
                    local: Optional[set] = None) -> list[list]:
    """Group plans by the mesh device their staged arrays live on (the
    residency contract); plans without a recognized pin spread round-
    robin onto the least-loaded devices (device_put then copies them).
    ``local`` restricts spill targets to THIS process's addressable
    devices — a plan can never land on a device its process cannot
    stage to."""
    index = {d: i for i, d in enumerate(devices)}
    targets = [i for i, d in enumerate(devices)
               if local is None or d in local]
    by_dev: list[list] = [[] for _ in devices]
    spill = []
    for p in plans:
        i = index.get(p.device) if p.device is not None else None
        if i is None or i not in targets:
            spill.append(p)
        else:
            by_dev[i].append(p)
    for p in spill:
        by_dev[min(targets, key=lambda d: len(by_dev[d]))].append(p)
    return by_dev


class _Prepared(NamedTuple):
    """One composed-and-assembled fabric serving context: everything the
    per-op programs need, independent of WHICH program then dispatches
    (partial planes, fused present, fused quantile, event topk)."""
    q: object
    mode: str
    op: str
    stride: int            # hb bucket lanes per series slot (1 = scalar)
    groups_total: int      # num_groups * stride segments in the reduce
    ksub: int
    nrows: int
    lmax: int
    Kp: int
    by_dev: list
    arrays: tuple          # (g_ts, g_vals, g_ph, g_s0, g_garr)


def _prepare(engine, plans: Sequence, num_groups: int,
             operator: Agg) -> Optional[_Prepared]:
    """Compose + place + assemble one fabric query: validates the plans
    share one program signature, groups them by resident device, and
    assembles (or memo-recalls) the global input arrays.  Returns None
    to fall back; shared by the partial and fully-fused serve paths so
    an op switch on the same residents re-uses the assembly."""
    jax, jnp = _jax()
    from jax.sharding import NamedSharding, PartitionSpec as P

    composed = _compose(plans, operator)
    if composed is None:
        _fallback("compose")
        return None
    q, mode = composed
    op = GRID_MESH_ALL_OPS[operator]
    nrows = plans[0].vals.shape[0]
    # phase mode serves WITHOUT a ts plane (uniform-phase shards never
    # stage one): the program's ts input collapses to a 1-row dummy, so
    # assembly ships half the resident bytes of the ts-streaming form
    ts_rows = 1 if mode == "phase" else nrows
    # histogram plans: hb bucket lanes per series slot; group slots are
    # gid*hb + bucket, so the program reduces num_groups*hb segments
    stride = plans[0].hb or 1
    groups_total = num_groups * stride
    mesh = engine.mesh
    devices = list(mesh.devices.flat)
    ndev = len(devices)
    # multi-host: this process stages pieces ONLY for its addressable
    # devices; every participating process runs the SAME serve call and
    # jax assembles the global arrays from per-process shards (the
    # multi-controller contract of make_array_from_single_device_arrays).
    # The composition (q/mode/lmax/ksub/groups) must agree across
    # processes — the coordinator guarantees symmetric shard layouts,
    # like the reference's shard assignment does for its cluster specs.
    proc = jax.process_index()
    multiproc = any(d.process_index != proc for d in devices)
    if multiproc and op in ("values", "topk", "bottomk"):
        # count_values reads back a SHARDED stepped matrix (not
        # addressable across processes) and the k-slot result carries
        # lane->series references a remote process cannot resolve to
        # tags — the host-batch path + coordinator wire merge handles
        # both across nodes
        _fallback("multiproc_lane_result")
        return None
    local = {d for d in devices if d.process_index == proc} \
        if multiproc else None
    if multiproc and not local:
        # this process owns none of the mesh's devices: it cannot stage
        # resident pieces — graceful fallback, not a crash
        _fallback("multiproc_no_local")
        return None
    by_dev = _assign_devices(plans, devices, local)
    ksub = max(1, max(len(lst) for lst in by_dev))
    Kp = ksub * ndev
    lmax = max(-(-max(p.ncols for p in plans) // _LANE_PAD) * _LANE_PAD,
               _LANE_PAD)
    if multiproc:
        # fail LOUDLY (not hang) if the composition disagrees across
        # processes: every process entering the resident path does one
        # tiny host allgather of its derived shape.  Symmetric shard
        # layouts (the coordinator's contract) make this a no-op check;
        # an asymmetric layout otherwise surfaces as a distributed hang
        # inside XLA with no diagnostic.
        from jax.experimental import multihost_utils
        mine = np.array([ksub, lmax, groups_total, nrows], np.int64)
        allv = np.asarray(multihost_utils.process_allgather(mine))
        if not (allv == mine[None, :]).all():
            raise RuntimeError(
                "serve_grid_mesh: asymmetric multi-host composition "
                f"(ksub/lmax/groups/nrows per process: {allv.tolist()}) "
                "— shard layouts must be symmetric across processes")

    # op-INDEPENDENT key: the assembled residents serve every aggregator
    # family, so a dashboard switching sum -> topk re-uses the assembly
    memo_key = (engine._key, q, mode, groups_total, nrows, lmax, ksub,
                tuple((d, id(p.ts), id(p.vals),
                       id(p.phase) if p.phase is not None else 0,
                       p.steps0_rel, _garr_fp(p.garr))
                      for d, lst in enumerate(by_dev) for p in lst))
    memo = _ASSEMBLY_MEMO.get(memo_key)
    if memo is not None:
        STATS["memo_hits"] += 1
        _ASSEMBLY_MEMO.move_to_end(memo_key)
        g_ts, g_vals, g_ph, g_s0, g_garr = memo[:5]
    else:
        STATS["assembles"] += 1
        vdt = plans[0].vals.dtype
        # per-device local pieces, assembled in place (device-side pads
        # only; device_put of an already-resident array is a no-op)
        ts_pieces, val_pieces, ph_pieces, s0_pieces, g_pieces = \
            [], [], [], [], []
        for d, dev in enumerate(devices):
            if multiproc and dev.process_index != proc:
                continue          # that process stages its own pieces
            ts_k, val_k, ph_k, s0_k, g_k = [], [], [], [], []
            for p in by_dev[d]:
                if mode == "phase":
                    # no shard staged a ts plane; ship the 1-row dummy
                    ts_k.append(_stage_put(
                        np.zeros((1, lmax), np.int32), dev))
                else:
                    ts_d = _stage_put(p.ts, dev)
                    ts_k.append(_pad_piece(ts_d, lmax, 0))
                val_d = _stage_put(p.vals, dev)
                val_k.append(_pad_piece(val_d, lmax, np.nan))
                if mode == "phase":
                    ph = _stage_put(p.phase, dev)
                    ph_k.append(jnp.pad(ph, (0, lmax - ph.shape[0]),
                                        constant_values=1)
                                if ph.shape[0] != lmax else ph)
                s0_k.append(int(p.steps0_rel))
                # -1 marks unrequested lanes (devicestore.mesh_plan);
                # rewrite to THIS query's drop bucket
                g = np.full(lmax, groups_total, np.int32)
                g[:len(p.garr)] = np.where(p.garr < 0, groups_total,
                                           p.garr)
                g_k.append(g)
            while len(ts_k) < ksub:                # filler shard slices
                ts_k.append(_stage_put(
                    np.zeros((ts_rows, lmax), np.int32), dev))
                val_k.append(_stage_put(
                    np.full((nrows, lmax), np.nan, vdt), dev))
                if mode == "phase":
                    ph_k.append(_stage_put(np.ones(lmax, np.int32),
                                               dev))
                s0_k.append(0)
                g_k.append(np.full(lmax, groups_total, np.int32))
            ts_pieces.append(jnp.stack(ts_k))
            val_pieces.append(jnp.stack(val_k))
            if mode == "phase":
                ph_pieces.append(jnp.stack(ph_k))
            else:
                ph_pieces.append(_stage_put(
                    np.ones((ksub, lmax), np.int32), dev))
            s0_pieces.append(_stage_put(
                np.asarray(s0_k, np.int32), dev))
            g_pieces.append(_stage_put(np.stack(g_k), dev))

        def assemble(pieces, trailing_shape):
            shape = (Kp, *trailing_shape)
            sharding = NamedSharding(
                mesh, P(_AXES, *([None] * len(trailing_shape))))
            return jax.make_array_from_single_device_arrays(
                shape, sharding, pieces)

        g_ts = assemble(ts_pieces, (ts_rows, lmax))
        g_vals = assemble(val_pieces, (nrows, lmax))
        g_ph = assemble(ph_pieces, (lmax,))
        g_s0 = assemble(s0_pieces, ())
        g_garr = assemble(g_pieces, (lmax,))
        nbytes = sum(int(a.nbytes)
                     for a in (g_ts, g_vals, g_ph, g_s0, g_garr))
        # the memoized assembled residents are what actually pins HBM
        # between queries — ledger them (the per-piece staging arrays
        # above are transient and die once assembly completes)
        for a in (g_ts, g_vals, g_ph, g_s0, g_garr):
            LEDGER.track(a, owner="meshgrid:assembly", fmt="mesh-staged")
        _memo_insert(memo_key,
                     (g_ts, g_vals, g_ph, g_s0, g_garr, tuple(plans)),
                     nbytes)

    return _Prepared(q, mode, op, stride, groups_total, ksub, nrows,
                     lmax, Kp, by_dev, (g_ts, g_vals, g_ph, g_s0, g_garr))


def serve_grid_mesh(engine, plans: Sequence, num_groups: int,
                    operator: Agg, params: tuple = ()) -> Optional[dict]:
    """Run one fused grid-mesh query over per-shard resident plans.

    Returns the mergeable partial state dict — moment planes
    ({"sum","count"[,"sumsq"]} / {"min"} / {"max"}), k-slots
    ({"values","sidx"} plus the private "_slots"/"_lmax" lane-resolution
    keys the caller maps to series tags), t-digests
    ({"td_means","td_weights"}), or value counts
    ({"cv_vals","cv_counts"}) — or None when the plans cannot compose
    (mixed query shapes, unsupported op)."""
    prep = _prepare(engine, plans, num_groups, operator)
    if prep is None:
        return None
    q, mode, op = prep.q, prep.mode, prep.op
    stride, groups_total = prep.stride, prep.groups_total
    ksub, nrows, lmax, Kp = prep.ksub, prep.nrows, prep.lmax, prep.Kp
    by_dev = prep.by_dev
    g_ts, g_vals, g_ph, g_s0, g_garr = prep.arrays

    if op in ("topk", "bottomk"):
        k = int(float(params[0]))
        prog = _grid_mesh_topk_program(engine._key, q, mode, ksub, nrows,
                                       lmax, groups_total, k,
                                       op == "bottomk")
        v, si = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
        STATS["serves"] += 1
        pos = {id(p): i for i, p in enumerate(plans)}
        slots = tuple(pos.get(id(lst[kk]), -1) if kk < len(lst) else -1
                      for lst in by_dev for kk in range(ksub))
        return {"values": np.asarray(v, dtype=np.float64),  # host-sync-ok: topk partial values land on host for cross-shard merge
                "sidx": np.asarray(si, dtype=np.int64),  # host-sync-ok: topk partial indices ride back with the values
                "_slots": slots, "_lmax": lmax}
    if op == "quantile":
        # same compression as the host QuantileAggregator: mesh and host
        # digests merge at matched accuracy
        from filodb_tpu.query.aggregators import QuantileAggregator
        prog = _grid_mesh_quantile_program(engine._key, q, mode, ksub,
                                           nrows, lmax, groups_total,
                                           QuantileAggregator.compression)
        m, w = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
        STATS["serves"] += 1
        return {"td_means": np.asarray(m, dtype=np.float64),  # host-sync-ok: t-digest means partial lands on host for merge
                "td_weights": np.asarray(w, dtype=np.float64)}  # host-sync-ok: t-digest weights partial lands on host for merge
    if op == "values":
        from filodb_tpu.query.aggregators import count_values_state
        prog = _grid_mesh_values_program(engine._key, q, mode, ksub,
                                         nrows, lmax)
        out = prog(g_ts, g_vals, g_ph, g_s0)
        STATS["serves"] += 1
        # only the [lanes, T] stepped matrix crosses the host link — the
        # raw [nrows, lanes] residents never re-upload or read back
        stepped = np.asarray(out, dtype=np.float64)    # [Kp, lmax, T]  # host-sync-ok: only the [lanes, T] stepped matrix crosses the host link (comment below)
        garr_all = np.full((Kp, lmax), -1, np.int32)
        for d, lst in enumerate(by_dev):
            for kk, p in enumerate(lst):
                garr_all[d * ksub + kk, :len(p.garr)] = p.garr
        rows = garr_all.ravel() >= 0
        vals2d = stepped.reshape(Kp * lmax, -1)[rows]
        return count_values_state(vals2d, garr_all.ravel()[rows],
                                  num_groups)

    prog = _grid_mesh_program(engine._key, q, mode, ksub, nrows, lmax,
                              groups_total, op)
    out = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
    STATS["serves"] += 1
    if stride > 1:
        # histogram: [2, G*hb, T] -> the MomentAggregator hist state
        from filodb_tpu.memstore.devicestore import hist_state_from_planes
        both = np.asarray(out, dtype=np.float64)  # host-sync-ok: hist planes [2, G*hb, T] — the designed readback for hist state
        return hist_state_from_planes(both, num_groups, stride,
                                      np.asarray(plans[0].bucket_tops))
    if op in ("sum", "avg", "count", "moments"):
        both = np.asarray(out, dtype=np.float64)       # [2|3, G, T]  # host-sync-ok: ONE readback of the stacked [2|3, G, T] partials
        if op == "count":
            return {"count": both[1]}
        if op == "moments":
            return {"sum": both[0], "count": both[1], "sumsq": both[2]}
        return {"sum": both[0], "count": both[1]}
    a = np.asarray(out, dtype=np.float64)  # host-sync-ok: single readback of the [G, T] reduced partial
    return {op: np.where(np.isfinite(a), a, np.nan)}


def serve_grid_mesh_presented(engine, plans: Sequence, num_groups: int,
                              operator: Agg, params: tuple = (),
                              hist_phi: Optional[float] = None
                              ) -> Optional[np.ndarray]:
    """The tentpole entry: ONE compiled dispatch and ONE [G, T] readback
    of the PRESENTED answer — no partial state, no host reduce.  Serves
    the moment family (sum/count/avg/min/max/group/stddev/stdvar) and,
    with ``hist_phi`` set over histogram plans, the fused
    histogram_quantile (cross-shard merge pre-quantile via bucket psum).
    Returns the presented np.float64 [G, T] (NaN where a group is
    empty), or None when this op/shape has no fused-present form — the
    caller then serves the partial path, which shares this assembly."""
    agg = _PRESENT_AGGS.get(operator)
    if agg is None:
        return None
    prep = _prepare(engine, plans, num_groups, operator)
    if prep is None:
        return None
    g_ts, g_vals, g_ph, g_s0, g_garr = prep.arrays
    if prep.stride > 1:
        if hist_phi is None:
            return None    # hist sum presents host-side (hist batch out)
        prog = _grid_mesh_histq_program(
            engine._key, prep.q, prep.mode, prep.ksub, prep.nrows,
            prep.lmax, num_groups, prep.stride, float(hist_phi))
        _, jnp = _jax()
        tops = jnp.asarray(np.asarray(plans[0].bucket_tops))
        out = prog(g_ts, g_vals, g_ph, g_s0, g_garr, tops)
        program = "meshgrid.fused_histq"
    else:
        if hist_phi is not None:
            return None    # phi over scalar series: the mapper's problem
        prog = _grid_mesh_present_program(
            engine._key, prep.q, prep.mode, prep.ksub, prep.nrows,
            prep.lmax, num_groups, prep.op, agg)
        out = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
        program = "meshgrid.fused"
    STATS["serves"] += 1
    STATS["fused_serves"] += 1
    _mm()["fused_serves"].inc(program=program)
    return np.asarray(out, dtype=np.float64)  # host-sync-ok: THE single [G, T] readback of the fused fabric answer


def serve_event_topk(engine, plans: Sequence, num_groups: int, k: int,
                     largest: bool = True):
    """Distributed event-topK over resident plans: grouped sums psum
    over the mesh and one on-device top_k selects the k hottest groups
    per step — one dispatch, one [T, k] readback pair.  Returns
    (values [T, k] f64, group_idx [T, k] i64) with NaN/-1 in unfilled
    slots, or None when the plans cannot compose or are histograms."""
    prep = _prepare(engine, plans, num_groups, Agg.SUM)
    if prep is None:
        return None
    if prep.stride > 1:
        _fallback("event_topk_hist")
        return None
    kk = min(int(k), num_groups)
    if kk < 1:
        return None
    prog = _grid_mesh_event_topk_program(
        engine._key, prep.q, prep.mode, prep.ksub, prep.nrows, prep.lmax,
        num_groups, kk, bool(largest))
    g_ts, g_vals, g_ph, g_s0, g_garr = prep.arrays
    v, gi = prog(g_ts, g_vals, g_ph, g_s0, g_garr)
    STATS["serves"] += 1
    STATS["fused_serves"] += 1
    _mm()["fused_serves"].inc(program="meshgrid.event_topk")
    return (np.asarray(v, dtype=np.float64),  # host-sync-ok: [T, k] selected event-group values, the designed readback
            np.asarray(gi, dtype=np.int64))  # host-sync-ok: [T, k] selected group ids ride back with the values
