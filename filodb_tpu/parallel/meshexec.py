"""MeshAggregateExec: the planner's ICI-collective serving path.

Fuses every LOCAL shard's leaf pipeline of an aggregate query —
scan -> window -> per-shard aggregate -> cross-shard reduce — into ONE
SPMD mesh program (parallel/mesh.py), replacing N per-shard ExecPlan
children + host-side reduce with device collectives riding ICI
(reference: the scatter-gather tree of SingleClusterPlanner.scala:223-258
+ ReduceAggregateExec, collapsed into lax.psum/pmin/pmax).

The node emits the same mergeable AggPartialBatch the per-shard path
produces, so it composes under ReduceAggregateExec next to REMOTE
shards' HTTP-dispatched partials — one cluster query can mix both data
planes, exactly like the reference mixes local and remote children.

Compressed residents (ISSUE 3): the GRID_MESH_ALL_OPS family serves
from XOR-class packed blocks without a decode-then-requery round trip —
``shard.mesh_grid_plan`` stages the decoded value plane ON DEVICE once
(memoized; repeat queries perform zero host decode and zero re-upload),
and uniform-phase plans never stage a ts plane at all (the SPMD program
ships a 1-row dummy; see parallel/meshgrid.py and doc/kernel.md §2).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query import rangefns
from filodb_tpu.query.aggregators import AggPartialBatch, grouping_key
from filodb_tpu.query.exec import ExecContext, ExecPlan
from filodb_tpu.query.logical import (AggregationOperator, RangeFunctionId)
from filodb_tpu.query.model import QueryContext

# aggregates with a distributive psum/pmin/pmax form (mesh.partial_state_names)
MESH_OPS = (AggregationOperator.SUM, AggregationOperator.COUNT,
            AggregationOperator.AVG, AggregationOperator.MIN,
            AggregationOperator.MAX, AggregationOperator.STDDEV,
            AggregationOperator.STDVAR, AggregationOperator.GROUP)
# aggregates with a non-psum mesh partial: k-heap merge (topk/bottomk),
# t-digest merge (quantile), member pass-through (count_values) — the
# full RowAggregator family (reference RowAggregator.scala:114-141)
_K_OPS = (AggregationOperator.TOPK, AggregationOperator.BOTTOMK)
_MEMBER_OPS = (AggregationOperator.QUANTILE,
               AggregationOperator.COUNT_VALUES)


def mesh_supported(operator: AggregationOperator,
                   function: Optional[RangeFunctionId],
                   params: tuple) -> bool:
    if operator in _K_OPS:
        ok = (len(params) == 1
              and float(params[0]) == int(float(params[0]))
              and int(float(params[0])) >= 1)
    elif operator in _MEMBER_OPS:
        ok = len(params) == 1
    else:
        ok = operator in MESH_OPS and not params
    return ok and rangefns.supported(function, hist=False)


# Fabric breaker (tentpole): tripped the first time a FUSED program
# fails to build or dispatch on this backend — every later query runs
# the always-correct scatter-gather fallback instead of re-discovering
# the failure at serve time.  The fused path is an optimization, never
# a correctness dependency (same contract as devicestore._PACKED_BROKEN).
FABRIC_BREAKER = {"open": False, "trips": 0}


def trip_fabric_breaker(exc: Exception) -> None:
    from filodb_tpu.parallel import meshgrid
    from filodb_tpu.utils.devicewatch import FLIGHT
    FABRIC_BREAKER["open"] = True
    FABRIC_BREAKER["trips"] += 1
    meshgrid._mm()["breaker"].set(1.0)
    FLIGHT.record("mesh.breaker_trip", error=str(exc)[:200])


def reset_fabric_breaker() -> None:
    """Admin/test reset (e.g. after a backend or driver change)."""
    from filodb_tpu.parallel import meshgrid
    FABRIC_BREAKER["open"] = False
    meshgrid._mm()["breaker"].set(0.0)


@functools.lru_cache(maxsize=16)
def mesh_placement(generation: int, num_devices: int):
    """shard -> mesh-device slot, keyed on
    ``ShardMapper.topology_generation``: a live split commits by bumping
    the generation, so the first post-cutover query atomically computes
    placement under the NEW shard space (children land on their own
    slots) while in-flight queries planned pre-cutover keep the old
    placement — they detect the bump via ``_topology_stale`` and serve
    per-shard instead of pinning residents to slots about to move."""
    def place(shard_num: int) -> int:
        return shard_num % num_devices
    return place


class MeshAggregateExec(ExecPlan):
    """All local shards of one windowed aggregate as one mesh program."""

    def __init__(self, dataset: str, shards: Sequence[int],
                 filters: Sequence[ColumnFilter], scan_start_ms: int,
                 scan_end_ms: int, start_ms: int, step_ms: int, end_ms: int,
                 operator: AggregationOperator,
                 window_ms: Optional[int] = None,
                 function: Optional[RangeFunctionId] = None,
                 function_args: tuple = (), offset_ms: int = 0,
                 by: tuple = (), without: tuple = (),
                 params: tuple = (), stale_ms: int = 300_000,
                 query_context: Optional[QueryContext] = None,
                 engine=None, mapper=None,
                 planned_generation: Optional[int] = None):
        super().__init__(query_context)
        self.dataset = dataset
        self.shards = list(shards)
        self.filters = list(filters)
        self.scan_start_ms = scan_start_ms
        self.scan_end_ms = scan_end_ms
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.operator = operator
        self.window_ms = window_ms
        self.function = function
        self.function_args = tuple(function_args)
        self.offset_ms = offset_ms
        self.by = tuple(by)
        self.without = tuple(without)
        self.params = tuple(params)
        self.stale_ms = stale_ms
        self._engine = engine
        # topology threading (satellite: generation-keyed placement) —
        # the planner stamps its snapshot's generation so execute-time
        # can detect a split cutover racing this query
        self.mapper = mapper
        self.planned_generation = planned_generation

    def _topology_stale(self) -> Optional[str]:
        """Reason the mesh path must stand down for this query, or None.
        A query planned pre-cutover ("generation") or overlapping a
        live reshard exclusion window ("exclusion") serves per-shard
        under its PLANNED topology view — the mesh placement/assembly
        would mix topologies mid-flight."""
        if self.mapper is None:
            return None
        if self.planned_generation is not None \
                and self.mapper.topology_generation != self.planned_generation:
            return "generation"
        live = self.mapper.topology
        if any(live.parent_exclusion(s) is not None for s in self.shards):
            return "exclusion"
        return None

    def _per_shard_fallback(self, ctx: ExecContext) -> list:
        """The always-correct scatter-gather form of this node: every
        shard runs the plain per-shard host pipeline, partials merge
        downstream.

        No reshard exclusions are stamped here, deliberately: the
        planner only emits a mesh node when its topology SNAPSHOT had
        no exclusions, so ``self.shards`` is a pre-cutover fan-out (the
        split parents).  A query planned pre-cutover must keep that
        snapshot's (no-exclusion) leaf stamps even when a cutover lands
        mid-flight — the parents hold a full superset until retirement
        purges them, so unfiltered parent scans stay exactly correct,
        while stamping the LIVE exclusions onto the OLD fan-out would
        drop every migrated series (their children are not among
        ``self.shards``).  Mixing topology views is the one thing the
        per-query snapshot contract forbids (planner._topology)."""
        out: list = []
        for shard_num in self.shards:
            out.extend(self._host_shard_partial(ctx, shard_num,
                                                reshard_to=None))
        return out

    def _args_str(self):
        return (f"dataset={self.dataset}, shards={self.shards}, "
                f"op={self.operator.name}, fn="
                f"{self.function.name if self.function else None}")

    def do_execute(self, ctx: ExecContext) -> list:
        from filodb_tpu.parallel import mesh as meshmod
        from filodb_tpu.parallel import meshgrid

        engine = self._engine or meshmod.default_engine()
        steps = StepRange(self.start_ms - self.offset_ms,
                          self.end_ms - self.offset_ms, self.step_ms)
        from filodb_tpu.query.transformers import effective_window_ms
        window = effective_window_ms(self.window_ms, self.stale_ms)
        report = StepRange(self.start_ms, self.end_ms, self.step_ms)
        union: dict[tuple, int] = {}
        out: list = []
        devices = list(engine.mesh.devices.flat)

        stale = self._topology_stale()
        if stale is not None:
            # planned against a topology that moved (split cutover /
            # active reshard exclusion): the mesh placement would mix
            # topologies mid-flight — serve per-shard under the planned
            # snapshot instead (always-correct scatter-gather)
            meshgrid._fallback(stale)
            from filodb_tpu.utils.devicewatch import FLIGHT
            FLIGHT.record("mesh.fallback", dataset=self.dataset,
                          reason=stale, shards=len(self.shards))
            return self._per_shard_fallback(ctx)

        grid_eligible = self.operator in meshgrid.GRID_MESH_ALL_OPS
        place = mesh_placement(self.planned_generation or 0, len(devices))
        entries = []                       # (shard, shard_num, lookup)
        for shard_num in self.shards:
            shard = ctx.memstore.get_shard(self.dataset, shard_num)
            if grid_eligible:
                # mesh placement BEFORE any grid staging: blocks build
                # on the device the SPMD program reads them from.  Only
                # grid-capable queries pin — a host-path query must not
                # invalidate resident state it will never use.
                shard.pin_grid_device(devices[place(shard_num)])
            lookup = shard.lookup_partitions(self.filters,
                                             self.scan_start_ms,
                                             self.scan_end_ms)
            if len(lookup.part_ids) == 0:
                continue
            entries.append((shard, shard_num, lookup))

        # -- phase 1: the HBM-resident grid x mesh path (VERDICT r3 #1):
        # every shard that can stage its scan in place — scalar AND
        # first-class histogram columns — contributes a MeshShardPlan;
        # ONE shard_map program serves them all with zero per-query
        # host->device upload.  Shards that can't (irregular layouts,
        # cold data, mixed bucket schemes) fall back per-shard to the
        # host-batch mesh below.
        limit = ctx.query_context.group_by_cardinality_limit
        host_entries = entries
        if grid_eligible:
            plans, planned = [], []
            for ent in entries:
                shard, _num, lookup = ent
                gids = self._grid_group_ids(shard, lookup.part_ids, union)
                if len(union) > limit:
                    # enforce BEFORE compiling/dispatching a G-sized
                    # program (the limit protects the expensive path)
                    self._cardinality_error(ctx, len(union))
                plan = None
                if gids is not None:
                    plan = shard.mesh_grid_plan(
                        lookup.part_ids, self.function, steps.start,
                        steps.num_steps, steps.step, window, gids,
                        fargs=self.function_args)
                if plan is not None:
                    plans.append(plan)
                    planned.append(ent)
            if plans:
                num_grid_groups = len(union)
                state = meshgrid.serve_grid_mesh(engine, plans,
                                                 num_grid_groups,
                                                 self.operator,
                                                 params=self.params)
                # flight recorder: whether the resident SPMD path served
                # (or demoted to host-batch) is the first question of
                # any mesh-latency postmortem
                from filodb_tpu.utils.devicewatch import FLIGHT
                FLIGHT.record("mesh.serve", dataset=self.dataset,
                              shards=len(plans), groups=num_grid_groups,
                              resident=state is not None)
                if state is not None:
                    keys = [dict(k) for k in
                            list(union)[:num_grid_groups]]
                    tops = state.pop("bucket_tops", None)
                    series_keys = None
                    if "_slots" in state:
                        series_keys = self._resolve_k_lanes(
                            state, plans, planned)
                    out.append(AggPartialBatch(self.operator,
                                               self.params, keys,
                                               report, state,
                                               series_keys=series_keys,
                                               bucket_tops=tops))
                    served = set(id(e) for e in planned)
                    host_entries = [e for e in entries
                                    if id(e) not in served]

        # -- phase 2: host-batch mesh path for the remaining shards
        Agg = AggregationOperator
        hist_in_mesh = (self.operator is Agg.SUM and not self.params
                        and not self.function_args
                        and rangefns.supported(self.function, hist=True))
        shard_batches = []
        group_ids = []
        tags_lists = []
        hist_batches = []
        hist_gids = []
        host_partials: list = []
        for shard, shard_num, lookup in host_entries:
            tags_list, batch = shard.scan_batch(
                lookup.part_ids, self.scan_start_ms, self.scan_end_ms)
            if batch is None:
                continue                    # genuinely empty range
            if batch.hist is not None and not hist_in_mesh:
                # histogram data under a shape the hist mesh program
                # can't take must NOT be dropped — run the per-shard
                # host path and merge its partial below
                host_partials.extend(self._host_shard_partial(ctx,
                                                              shard_num))
                continue
            gids = np.empty(len(tags_list), dtype=np.int32)
            for i, tags in enumerate(tags_list):
                key = tuple(sorted(grouping_key(tags, self.by,
                                                self.without).items()))
                gids[i] = union.setdefault(key, len(union))
            if batch.hist is not None:
                hist_batches.append(batch)
                hist_gids.append(gids)
            else:
                shard_batches.append(batch)
                group_ids.append(gids)
                tags_lists.append(tags_list)
        if not out and not shard_batches and not hist_batches \
                and not host_partials:
            return []
        if len(union) > limit:
            self._cardinality_error(ctx, len(union))
        out.extend(host_partials)
        keys = [dict(k) for k in union]
        G = max(len(union), 1)
        if hist_batches:
            state, tops = engine.window_hist_partials(
                hist_batches, hist_gids, G, steps, window,
                range_fn=self.function)
            out.append(AggPartialBatch(self.operator, self.params, keys,
                                       report, state, bucket_tops=tops))
        if shard_batches:
            if self.operator in _K_OPS:
                out.append(self._topk_partial(
                    engine, shard_batches, group_ids, tags_lists, keys,
                    steps, report, window))
            elif self.operator is Agg.QUANTILE:
                m, w = engine.window_quantile_partials(
                    shard_batches, group_ids, G, steps, window,
                    range_fn=self.function,
                    extra_args=self.function_args)
                out.append(AggPartialBatch(
                    self.operator, self.params, keys, report,
                    {"td_means": m, "td_weights": w}))
            elif self.operator is Agg.COUNT_VALUES:
                out.append(self._count_values_partial(
                    engine, shard_batches, group_ids, tags_lists, keys,
                    steps, report, window))
            else:
                state = engine.window_aggregate_partials(
                    shard_batches, group_ids, G, steps, window,
                    range_fn=self.function, agg_op=self.operator,
                    extra_args=self.function_args)
                out.append(AggPartialBatch(self.operator, self.params,
                                           keys, report, state))
        return out

    def _topk_partial(self, engine, shard_batches, group_ids, tags_lists,
                      keys, steps, report, window) -> AggPartialBatch:
        """topk/bottomk via the mesh k-heap program; sidx comes back as
        global (shard, series) row indices which map onto the flattened
        series-key list the reducer/presenter resolve against."""
        from filodb_tpu.query.logical import AggregationOperator as Agg
        k = int(float(self.params[0]))
        v, si, (Kp, S) = engine.window_topk_partials(
            shard_batches, group_ids, max(len(keys), 1), steps, window,
            k, bottom=self.operator is Agg.BOTTOMK,
            range_fn=self.function, extra_args=self.function_args)
        series_keys: list[dict] = []
        for kk in range(Kp):
            tl = tags_lists[kk] if kk < len(tags_lists) else []
            series_keys.extend(tl)
            series_keys.extend({} for _ in range(S - len(tl)))
        return AggPartialBatch(self.operator, self.params, keys, report,
                               {"values": v, "sidx": si},
                               series_keys=series_keys)

    def _count_values_partial(self, engine, shard_batches, group_ids,
                              tags_lists, keys, steps, report,
                              window) -> AggPartialBatch:
        """count_values: scan+window on the mesh, vectorized
        (value, group, step) counting on host — exact values pass
        through like the reference's CountValuesRowAggregator, without
        a per-series loop or a dense member cube."""
        from filodb_tpu.query.aggregators import count_values_state
        stepped, (Kp, S) = engine.window_values(
            shard_batches, steps, window, range_fn=self.function,
            extra_args=self.function_args)
        rows = np.concatenate(
            [np.arange(len(tl), dtype=np.int64) + kk * S
             for kk, tl in enumerate(tags_lists)]) \
            if tags_lists else np.empty(0, np.int64)
        ids = np.concatenate(
            [gid[:len(tl)] for tl, gid in zip(tags_lists, group_ids)]) \
            if tags_lists else np.empty(0, np.int64)
        state = count_values_state(stepped[rows], ids, max(len(keys), 1))
        return AggPartialBatch(self.operator, self.params, keys, report,
                               state)

    def _resolve_k_lanes(self, state: dict, plans, planned) -> list[dict]:
        """Map the resident k-slot program's GLOBAL lane indices back to
        series tags: sidx value g decodes to (mesh slot g // lmax, lane
        g % lmax); the slot's MeshShardPlan carries the lane -> partition
        id map (col_pids), and the slot's shard resolves tags.  The state
        is rewritten in place to compact indices into the returned
        series-key list (the AggPartialBatch contract the host k-path
        uses).  Unresolvable lanes (partition concurrently evicted) are
        DROPPED (sidx -1) — the same thing the host path's present does
        with its padding slots."""
        slots = state.pop("_slots")
        lmax = state.pop("_lmax")
        sidx = state["sidx"]
        uniq = np.unique(sidx[sidx >= 0])
        series_keys: list[dict] = []
        remap = {}
        for g in uniq.tolist():
            slot, lane = divmod(int(g), lmax)
            tags = None
            pi = slots[slot] if slot < len(slots) else -1
            if pi >= 0:
                plan = plans[pi]
                shard = planned[pi][0]
                if plan.col_pids is not None and lane < len(plan.col_pids):
                    pid = int(plan.col_pids[lane])
                    if pid >= 0:
                        part = shard.grid_partition(pid)
                        if part is not None:
                            tags = part.tags
            if tags is None:
                remap[g] = -1
                continue
            remap[g] = len(series_keys)
            series_keys.append(tags)
        if len(remap):
            lut = np.full(int(uniq.max()) + 2, -1, np.int64)
            for g, i in remap.items():
                lut[g] = i
            state["sidx"] = np.where(sidx >= 0, lut[np.maximum(sidx, 0)],
                                     -1).astype(np.int32)
        else:
            state["sidx"] = sidx.astype(np.int32)
        # a dropped lane must not occupy a k-slot in a downstream reduce
        state["values"] = np.where(state["sidx"] >= 0, state["values"],
                                   np.nan)
        return series_keys

    def _cardinality_error(self, ctx, n: int):
        from filodb_tpu.query.model import QueryError
        limit = ctx.query_context.group_by_cardinality_limit
        raise QueryError(self.query_context.query_id,
                         f"group-by cardinality {n} exceeds "
                         f"limit {limit}")

    def _grid_group_ids(self, shard, part_ids, union: dict):
        """Group ids for the resident grid path, in ``part_ids`` order
        (the order devicestore assigns lanes).  Grows ``union`` in
        place; returns None when a partition vanished mid-query (the
        host path re-resolves via scan_batch)."""
        n = len(part_ids)
        gids = np.empty(n, dtype=np.int32)
        if not self.by and not self.without:
            gids[:] = union.setdefault((), len(union))
            return gids
        for i, pid in enumerate(part_ids):
            part = shard.grid_partition(int(pid))
            if part is None:
                return None
            key = tuple(sorted(grouping_key(part.tags, self.by,
                                            self.without).items()))
            gids[i] = union.setdefault(key, len(union))
        return gids

    def _host_shard_partial(self, ctx: ExecContext, shard_num: int,
                            reshard_to: Optional[tuple] = None) -> list:
        """Per-shard host pipeline for data the mesh program can't take
        (histogram value columns) and for topology/breaker fallbacks:
        leaf scan + PeriodicSamplesMapper + AggregateMapReduce, exactly
        the non-mesh plan shape.  ``reshard_to`` stamps the live
        topology's split-parent exclusion on the leaf (query/exec.py)."""
        from filodb_tpu.query.exec import MultiSchemaPartitionsExec
        from filodb_tpu.query.transformers import (AggregateMapReduce,
                                                   PeriodicSamplesMapper)
        leaf = MultiSchemaPartitionsExec(
            self.dataset, shard_num, self.filters, self.scan_start_ms,
            self.scan_end_ms, query_context=self.query_context,
            reshard_to=reshard_to)
        leaf.add_transformer(PeriodicSamplesMapper(
            self.start_ms, self.step_ms, self.end_ms,
            window_ms=self.window_ms, function=self.function,
            function_args=self.function_args, offset_ms=self.offset_ms))
        leaf.add_transformer(AggregateMapReduce(
            self.operator, self.params, self.by, self.without))
        return list(leaf.execute(ctx).batches)

    def _collect_plans(self, ctx: ExecContext):
        """Stage EVERY shard's resident MeshShardPlan — the
        all-or-nothing contract of the fused single-dispatch programs
        (one non-resident shard breaks the one-program story; the
        partial tier handles mixed residency instead).  Returns
        (engine, plans, union, report) or None when any shard with data
        cannot stage."""
        from filodb_tpu.parallel import mesh as meshmod
        from filodb_tpu.parallel import meshgrid
        from filodb_tpu.query.transformers import effective_window_ms

        engine = self._engine or meshmod.default_engine()
        steps = StepRange(self.start_ms - self.offset_ms,
                          self.end_ms - self.offset_ms, self.step_ms)
        window = effective_window_ms(self.window_ms, self.stale_ms)
        report = StepRange(self.start_ms, self.end_ms, self.step_ms)
        devices = list(engine.mesh.devices.flat)
        place = mesh_placement(self.planned_generation or 0, len(devices))
        limit = ctx.query_context.group_by_cardinality_limit
        union: dict[tuple, int] = {}
        plans = []
        for shard_num in self.shards:
            shard = ctx.memstore.get_shard(self.dataset, shard_num)
            shard.pin_grid_device(devices[place(shard_num)])
            lookup = shard.lookup_partitions(self.filters,
                                             self.scan_start_ms,
                                             self.scan_end_ms)
            if len(lookup.part_ids) == 0:
                continue
            gids = self._grid_group_ids(shard, lookup.part_ids, union)
            if len(union) > limit:
                self._cardinality_error(ctx, len(union))
            plan = None
            if gids is not None:
                plan = shard.mesh_grid_plan(
                    lookup.part_ids, self.function, steps.start,
                    steps.num_steps, steps.step, window, gids,
                    fargs=self.function_args)
            if plan is None:
                meshgrid._fallback("shape")
                return None
            plans.append(plan)
        return engine, plans, union, report


class MeshReduceExec(MeshAggregateExec):
    """The tentpole node: when EVERY child shard of an aggregation is
    mesh-resident on this host, the planner emits this node as the plan
    ROOT — leaf-scan -> window -> aggregate -> cross-shard reduce ->
    present compile into ONE device program (meshgrid.fused /
    meshgrid.fused_histq) and the only readback is the final [G, T]
    answer; N per-shard dispatches and the host reduce disappear.

    Serving ladder, every rung answer-equal: fused single dispatch ->
    partial mesh program + host reduce/present (non-fusable op or mixed
    residency) -> per-shard scatter-gather (breaker trip, topology
    moved mid-flight).  Unlike MeshAggregateExec this node returns
    PRESENTED batches — it IS the reduce+present, so the planner emits
    it with no ReduceAggregateExec / AggregatePresenter above it."""

    def __init__(self, *args, hist_phi: Optional[float] = None, **kwargs):
        super().__init__(*args, **kwargs)
        # histogram_quantile fusion: the planner folds the mapper's
        # static phi into the node so the quantile interpolation runs
        # inside the same device program as the bucket psum
        self.hist_phi = hist_phi

    def _args_str(self):
        phi = f", phi={self.hist_phi}" if self.hist_phi is not None else ""
        return super()._args_str() + phi

    def do_execute(self, ctx: ExecContext) -> list:
        from filodb_tpu.parallel import meshgrid
        from filodb_tpu.utils.devicewatch import FLIGHT

        stale = self._topology_stale()
        if stale is not None:
            meshgrid._fallback(stale)
            FLIGHT.record("mesh.fallback", dataset=self.dataset,
                          reason=stale, shards=len(self.shards))
            return self._present_host(self._per_shard_fallback(ctx))
        if FABRIC_BREAKER["open"]:
            meshgrid._fallback("breaker")
            FLIGHT.record("mesh.fallback", dataset=self.dataset,
                          reason="breaker", shards=len(self.shards))
            return self._present_host(self._per_shard_fallback(ctx))
        if self.operator in meshgrid._PRESENT_AGGS and not self.params:
            try:
                fused = self._fused(ctx)
            except Exception as e:
                # the fused program is an optimization, never a
                # correctness dependency: trip the breaker and serve
                # this (and every later) query scatter-gather
                trip_fabric_breaker(e)
                return self._present_host(self._per_shard_fallback(ctx))
            if fused is not None:
                return fused
        # partial-tier rung: the mesh partial program(s) + host
        # reduce/present — exactly what ReduceAggregateExec +
        # AggregatePresenter compose over a MeshAggregateExec child
        return self._present_host(super().do_execute(ctx))

    def _fused(self, ctx: ExecContext) -> Optional[list]:
        """The single-dispatch rung; None demotes to the partial tier."""
        from filodb_tpu.parallel import meshgrid
        from filodb_tpu.query.model import PeriodicBatch
        from filodb_tpu.utils.devicewatch import FLIGHT

        got = self._collect_plans(ctx)
        if got is None:
            return None
        engine, plans, union, report = got
        if not plans:
            return []                  # nothing matched on any shard
        vals = meshgrid.serve_grid_mesh_presented(
            engine, plans, len(union), self.operator,
            params=self.params, hist_phi=self.hist_phi)
        FLIGHT.record("mesh.fused", dataset=self.dataset,
                      shards=len(plans), groups=len(union),
                      served=vals is not None)
        if vals is None:
            return None
        keys = [dict(k) for k in union]
        return [PeriodicBatch(keys, report, vals)]

    def _present_host(self, batches: list) -> list:
        """Host reduce+present for the lower rungs — the same
        aggregator_for(...).reduce/present composition the
        scatter-gather plan runs (ReduceAggregateExec.compose +
        AggregatePresenter), inlined so this node ALWAYS returns
        presented batches whatever rung served."""
        from filodb_tpu.query.aggregators import aggregator_for
        parts = [b for b in batches if isinstance(b, AggPartialBatch)]
        out = [b for b in batches if not isinstance(b, AggPartialBatch)]
        if parts:
            agg = aggregator_for(self.operator)
            out.append(self._apply_phi(agg.present(agg.reduce(parts))))
        return out

    def _apply_phi(self, pb):
        """The host form of the fused quantile epilogue: identical math
        to InstantVectorFunctionMapper's HISTOGRAM_QUANTILE branch, so
        the fallback rungs stay bit-equal to the fused answer."""
        if self.hist_phi is None or getattr(pb, "hist", None) is None:
            return pb
        import jax.numpy as jnp

        from filodb_tpu.ops import histogram_ops
        from filodb_tpu.query.model import PeriodicBatch
        vals = np.asarray(histogram_ops.hist_quantile(
            jnp.asarray(pb.bucket_tops), jnp.asarray(pb.hist),
            self.hist_phi))
        return PeriodicBatch(pb.keys, pb.steps, vals)


class EventTopKExec(MeshAggregateExec):
    """ExecPlan surface for the event-topK family (the PR 19
    ``event_topk_grid_packed`` exec follow-up): the k hottest GROUPS
    per step, ranked by their aggregated (summed) event value — unlike
    topk(), which selects series WITHIN each group.

    Fused path: meshgrid.serve_event_topk — grouped sums are additive,
    so the cross-shard merge psums the group planes over the mesh FIRST
    and ONE on-device lax.top_k then selects per step (exact, where a
    merge of per-shard topK lists is not), one dispatch and one [T, k]
    readback.  Fallback (breaker / stale topology / non-resident
    shapes): per-shard scatter-gather sum partials reduce host-side and
    the same selection runs in numpy with matching tie semantics
    (stable descending argsort = lax.top_k's lower-index-first)."""

    def __init__(self, dataset: str, shards: Sequence[int],
                 filters: Sequence[ColumnFilter], scan_start_ms: int,
                 scan_end_ms: int, start_ms: int, step_ms: int,
                 end_ms: int, k: int, window_ms: Optional[int] = None,
                 function: Optional[RangeFunctionId] = None,
                 function_args: tuple = (), offset_ms: int = 0,
                 by: tuple = (), without: tuple = (),
                 largest: bool = True, stale_ms: int = 300_000,
                 query_context: Optional[QueryContext] = None,
                 engine=None, mapper=None,
                 planned_generation: Optional[int] = None):
        super().__init__(dataset, shards, filters, scan_start_ms,
                         scan_end_ms, start_ms, step_ms, end_ms,
                         AggregationOperator.SUM, window_ms=window_ms,
                         function=function, function_args=function_args,
                         offset_ms=offset_ms, by=by, without=without,
                         params=(), stale_ms=stale_ms,
                         query_context=query_context, engine=engine,
                         mapper=mapper,
                         planned_generation=planned_generation)
        self.k = int(k)
        self.largest = bool(largest)

    def _args_str(self):
        return (super()._args_str()
                + f", k={self.k}, largest={self.largest}")

    def do_execute(self, ctx: ExecContext) -> list:
        from filodb_tpu.parallel import meshgrid
        from filodb_tpu.utils.devicewatch import FLIGHT

        stale = self._topology_stale()
        if stale is None and not FABRIC_BREAKER["open"]:
            try:
                got = self._fused_topk(ctx)
            except Exception as e:
                trip_fabric_breaker(e)
                got = None
            if got is not None:
                return got
        else:
            reason = stale or "breaker"
            meshgrid._fallback(reason)
            FLIGHT.record("mesh.fallback", dataset=self.dataset,
                          reason=reason, shards=len(self.shards))
        return self._select_host(self._per_shard_fallback(ctx))

    def _fused_topk(self, ctx: ExecContext) -> Optional[list]:
        from filodb_tpu.parallel import meshgrid
        from filodb_tpu.query.model import PeriodicBatch
        from filodb_tpu.utils.devicewatch import FLIGHT

        got = self._collect_plans(ctx)
        if got is None:
            return None
        engine, plans, union, report = got
        if not plans:
            return []
        served = meshgrid.serve_event_topk(engine, plans, len(union),
                                           self.k, largest=self.largest)
        FLIGHT.record("mesh.event_topk", dataset=self.dataset,
                      shards=len(plans), groups=len(union), k=self.k,
                      served=served is not None)
        if served is None:
            return None
        vals, gidx = served                       # [T, k] each
        keys = [dict(key) for key in union]
        out = np.full((len(keys), report.num_steps), np.nan)
        tt = np.repeat(np.arange(gidx.shape[0]), gidx.shape[1])
        gg, vv = gidx.ravel(), vals.ravel()
        m = gg >= 0
        out[gg[m], tt[m]] = vv[m]
        # every group keeps its row (NaN where never selected): stable
        # result shape whatever the per-step winners are
        return [PeriodicBatch(keys, report, out)]

    def _select_host(self, batches: list) -> list:
        """Scatter-gather rung: reduce per-shard sum partials, then the
        numpy twin of the on-device selection."""
        from filodb_tpu.query.aggregators import aggregator_for
        from filodb_tpu.query.model import PeriodicBatch
        parts = [b for b in batches if isinstance(b, AggPartialBatch)]
        if not parts:
            return [b for b in batches
                    if not isinstance(b, AggPartialBatch)]
        agg = aggregator_for(AggregationOperator.SUM)
        p = agg.reduce(parts)
        s = np.asarray(p.state["sum"], dtype=np.float64)
        n = np.asarray(p.state["count"], dtype=np.float64)
        sign = 1.0 if self.largest else -1.0
        work = np.where(n > 0, s * sign, -np.inf)          # [G, T]
        kk = min(self.k, work.shape[0])
        if kk < 1:
            return []
        # stable descending argsort ranks ties lower-index-first —
        # the same order lax.top_k resolves them in the fused program
        order = np.argsort(-work, axis=0, kind="stable")[:kk]   # [k, T]
        vals = np.take_along_axis(work, order, axis=0)          # [k, T]
        out = np.full_like(work, np.nan)
        tt = np.tile(np.arange(work.shape[1]), (kk, 1))
        m = np.isfinite(vals)
        out[order[m], tt[m]] = vals[m] * sign
        return [PeriodicBatch(list(p.group_keys), p.steps, out)]
