"""Mesh-sharded distributed query execution: the ICI-collective data plane.

This replaces the reference's cross-node scatter-gather (Akka-dispatched
ExecPlan subtrees + Kryo results, reference: coordinator/src/main/scala/
filodb.coordinator/queryplanner/SingleClusterPlanner.scala:223-258 hierarchical
reduce; query/src/main/scala/filodb/query/exec/PlanDispatcher.scala:29-46)
with a single SPMD program over a `jax.sharding.Mesh`:

- **shard axis (dp)** — FiloDB shards are laid out along the mesh's ``shard``
  axis; each device scans+windows its local shards, then the cross-shard
  aggregation (the reference's ReduceAggregateExec tree) is ONE
  ``lax.psum`` riding ICI instead of actor messages riding TCP.
- **step axis (sp)** — the output step grid (time axis) is sharded along the
  ``step`` axis; this is the long-range-query analog of sequence parallelism:
  a 1h range over 1M series splits its windows across devices (the
  reference's time-splitting, SingleClusterPlanner.scala:61-78, without the
  stitch step because windows are computed from replicated row data).

Data never leaves the device between scan, window, and reduce — the entire
leaf pipeline (reference hot path, SURVEY.md §3.1) is one jitted SPMD
program per (function, aggregate) signature.

Multi-host: the same program runs unchanged over a multi-host mesh created
from ``jax.distributed.initialize`` + ``mesh_utils.create_device_mesh``;
collectives then ride ICI within a slice and DCN across slices.  The host
control plane (which process owns which FiloDB shards) is
:mod:`filodb_tpu.coordinator.cluster`.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import mesh_utils
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from filodb_tpu.utils import devicewatch
from filodb_tpu.utils.devicewatch import LEDGER

from filodb_tpu.core.chunk import ChunkBatch, TS_PAD
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.query.logical import AggregationOperator as Agg
from filodb_tpu.query import rangefns


def make_mesh(devices: Optional[Sequence] = None,
              shape: Optional[tuple[int, int]] = None) -> Mesh:
    """Build a 2D ``(shard, step)`` mesh over the given (default: all) devices.

    ``shape`` defaults to putting everything on the shard axis — the common
    case for high-cardinality queries — i.e. ``(n, 1)``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = mesh_utils.create_device_mesh(shape, devices=list(devices))
    return Mesh(arr, axis_names=("shard", "step"))


# --------------------------------------------------------------------------
# SPMD window+aggregate program
# --------------------------------------------------------------------------

# aggregations expressible as a psum-able (map, combine, present) triple.
# map: [N, T] vals -> per-group partial [G, T, C]; combine = psum; present ->
# [G, T].  Mirrors the reference's RowAggregator map/reduce/present split
# (query/src/main/scala/filodb/query/exec/aggregator/RowAggregator.scala:29).
def _seg_sum_count(vals, ids, G):
    fin = jnp.isfinite(vals)
    v = jnp.where(fin, vals, 0.0)
    s = jnp.zeros((G, vals.shape[1]), vals.dtype).at[ids].add(v)
    c = jnp.zeros((G, vals.shape[1]), vals.dtype).at[ids].add(fin.astype(vals.dtype))
    return s, c


def _seg_minmax(vals, ids, G, big, op):
    v = jnp.where(jnp.isfinite(vals), vals, big)
    out = jnp.full((G, vals.shape[1]), big, vals.dtype)
    out = out.at[ids].min(v) if op == "min" else out.at[ids].max(v)
    return out


_INF = jnp.inf


def _agg_map(op: Agg, vals, ids, G):
    """-> tuple of [G, T] partials, each combinable by a single collective."""
    if op in (Agg.SUM, Agg.COUNT, Agg.AVG, Agg.GROUP):
        return _seg_sum_count(vals, ids, G)
    if op in (Agg.STDDEV, Agg.STDVAR):
        s, c = _seg_sum_count(vals, ids, G)
        fin = jnp.isfinite(vals)
        sq = jnp.where(fin, vals * vals, 0.0)
        s2 = jnp.zeros((G, vals.shape[1]), vals.dtype).at[ids].add(sq)
        return s, c, s2
    if op == Agg.MIN:
        return (_seg_minmax(vals, ids, G, _INF, "min"),)
    if op == Agg.MAX:
        return (_seg_minmax(vals, ids, G, -_INF, "max"),)
    raise ValueError(f"aggregate {op} has no distributive psum form")


_MINMAX_COMBINE = {Agg.MIN: lax.pmin, Agg.MAX: lax.pmax}


def _agg_combine(op: Agg, partials, axis: str):
    if op in _MINMAX_COMBINE:
        return tuple(_MINMAX_COMBINE[op](p, axis) for p in partials)
    return tuple(lax.psum(p, axis) for p in partials)


def _agg_present(op: Agg, partials):
    if op == Agg.SUM:
        s, c = partials
        return jnp.where(c > 0, s, jnp.nan)
    if op == Agg.COUNT:
        s, c = partials
        return jnp.where(c > 0, c, jnp.nan)
    if op == Agg.AVG:
        s, c = partials
        return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)
    if op == Agg.GROUP:
        _s, c = partials
        return jnp.where(c > 0, 1.0, jnp.nan)
    if op in (Agg.STDDEV, Agg.STDVAR):
        s, c, s2 = partials
        mean = s / jnp.maximum(c, 1.0)
        var = s2 / jnp.maximum(c, 1.0) - mean * mean
        var = jnp.maximum(var, 0.0)
        out = var if op == Agg.STDVAR else jnp.sqrt(var)
        return jnp.where(c > 0, out, jnp.nan)
    (m,) = partials
    return jnp.where(jnp.isfinite(m), m, jnp.nan)


def partial_state_names(op: Agg) -> tuple[str, ...]:
    """Names of the raw partials each op's mesh program outputs (the
    ``_agg_map`` tuple order)."""
    if op in (Agg.SUM, Agg.COUNT, Agg.AVG, Agg.GROUP):
        return ("sum", "count")
    if op in (Agg.STDDEV, Agg.STDVAR):
        return ("sum", "count", "sumsq")
    if op == Agg.MIN:
        return ("min",)
    if op == Agg.MAX:
        return ("max",)
    raise ValueError(f"aggregate {op} has no distributive psum form")


def exported_state_names(op: Agg) -> tuple[str, ...]:
    """Subset of :func:`partial_state_names` the host aggregators expect
    in an AggPartialBatch (query/aggregators.py MomentAggregator._NEEDS).
    Exporting EXACTLY these keys matters: ``_align`` requires every
    partial in a reduce — mesh or remote — to carry the same state names."""
    if op in (Agg.COUNT, Agg.GROUP):
        return ("count",)
    return partial_state_names(op)


@functools.lru_cache(maxsize=128)
def _build_program(mesh_key, range_fn, agg_op: Agg, num_groups: int,
                   window_ms: int, wmax: int, extra_args: tuple,
                   present: bool = True):
    """Compile the SPMD scan→window→aggregate program for one signature.
    ``present=False`` returns the psum-combined partial tuple instead of
    the presented values — the form a cross-NODE ReduceAggregateExec can
    merge with remote shards' partials."""
    mesh = _MESHES[mesh_key]

    kind = rangefns.kernel_kind(range_fn)
    kernel = rangefns.raw_kernel(range_fn)

    def local(ts, vals, ids, steps):
        # ts/vals: [Kl*S, R] local shards flattened; steps: [Tl] local steps
        window = jnp.asarray(window_ms, dtype=ts.dtype)
        if kind == "last":
            stepped = kernel(ts, vals, steps, window)
        elif kind == "prefix":
            stepped = kernel(ts, vals, steps, window)
        else:
            stepped = kernel(ts, vals, steps, window, wmax, *extra_args)
        partials = _agg_map(agg_op, stepped, ids, num_groups)
        partials = _agg_combine(agg_op, partials, "shard")
        if present:
            return _agg_present(agg_op, partials)   # [G, Tl]
        return partials                              # tuple of [G, Tl]

    out_spec = P(None, "step")
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", None), P("shard"), P("step")),
        out_specs=out_spec if present
        else tuple([out_spec] * len(partial_state_names(agg_op))),
    )
    return devicewatch.jit(fn, program="mesh.agg")


def _shard_map_unchecked(fn, **kw):
    """shard_map whose outputs are replicated by construction (an
    all_gather + identical local math) — the static replication checker
    can't infer that, so disable it under whichever kwarg this jax
    spells it (check_vma on newer releases, check_rep before that; on
    versions accepting both, BOTH must be off or the remaining checker
    still rejects the uninferable replication)."""
    import inspect
    names = set()
    try:
        names = set(inspect.signature(shard_map).parameters)
    except (TypeError, ValueError):          # builtins without signatures
        pass
    flags = {k: False for k in ("check_vma", "check_rep") if k in names}
    if flags:
        try:
            return shard_map(fn, **flags, **kw)
        except TypeError:
            pass
    for k in ("check_vma", "check_rep"):
        try:
            return shard_map(fn, **{k: False}, **kw)
        except TypeError:
            continue
    return shard_map(fn, **kw)


@functools.lru_cache(maxsize=64)
def _build_topk_program(mesh_key, range_fn, num_groups: int, window_ms: int,
                        wmax: int, extra_args: tuple, k: int, bottom: bool):
    """topk/bottomk as a mesh partial: each device keeps k candidate
    (value, global series index) slots per group per step from ITS
    shards, the candidates ride one all_gather over the shard axis, and
    every device re-selects the global top-k — the k-heap merge of the
    reference's TopBottomKRowAggregator
    (query/exec/aggregator/RowAggregator.scala:114-141), done as
    lax.top_k over the gathered candidate axis."""
    mesh = _MESHES[mesh_key]
    nsh = mesh.devices.shape[0]
    kind = rangefns.kernel_kind(range_fn)
    kernel = rangefns.raw_kernel(range_fn)
    G = num_groups

    from filodb_tpu.ops import aggregate as segops

    def local(ts, vals, ids, steps):
        window = jnp.asarray(window_ms, dtype=ts.dtype)
        if kind in ("last", "prefix"):
            stepped = kernel(ts, vals, steps, window)
        else:
            stepped = kernel(ts, vals, steps, window, wmax, *extra_args)
        rows_local = stepped.shape[0]
        off = (lax.axis_index("shard") * rows_local).astype(jnp.int32)
        v, si = segops.seg_topk(stepped, ids, G + 1, k, bottom=bottom)
        v, si = v[:G], si[:G]
        si = jnp.where(si >= 0, si + off, -1)
        allv = lax.all_gather(v, "shard")          # [nsh, G, k, Tl]
        alli = lax.all_gather(si, "shard")
        Tl = stepped.shape[1]
        V = jnp.moveaxis(allv, 0, 1).reshape(G, nsh * k, Tl)
        I = jnp.moveaxis(alli, 0, 1).reshape(G, nsh * k, Tl)
        sign = -1.0 if bottom else 1.0
        work = jnp.where(jnp.isfinite(V), V * sign, -jnp.inf)
        topv, topc = lax.top_k(jnp.moveaxis(work, 1, 2), k)  # [G, Tl, k]
        found = jnp.isfinite(topv)
        topi = jnp.take_along_axis(jnp.moveaxis(I, 1, 2), topc, axis=2)
        values = jnp.moveaxis(jnp.where(found, topv * sign, jnp.nan), 1, 2)
        sidx = jnp.moveaxis(jnp.where(found, topi, -1), 1, 2)
        return values, sidx                        # [G, k, Tl] each

    fn = _shard_map_unchecked(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", None), P("shard"), P("step")),
        out_specs=(P(None, None, "step"), P(None, None, "step")))
    return devicewatch.jit(fn, program="mesh.topk")


@functools.lru_cache(maxsize=64)
def _build_quantile_program(mesh_key, range_fn, num_groups: int,
                            window_ms: int, wmax: int, extra_args: tuple,
                            compression: int):
    """quantile as a mesh partial: every device SKETCHES its local
    shards' windowed values into per-(group, step) t-digests on device,
    the [G, T, C] digests ride one all_gather, and a final on-device
    compress folds them — only the merged sketch crosses the host link
    (the reference's TDigest partial rows, RowAggregator.scala:114-141,
    over ICI instead of Kryo)."""
    mesh = _MESHES[mesh_key]
    kind = rangefns.kernel_kind(range_fn)
    kernel = rangefns.raw_kernel(range_fn)
    G, C = num_groups, compression

    from filodb_tpu.ops import tdigest_device as tdd

    def local(ts, vals, ids, steps):
        window = jnp.asarray(window_ms, dtype=ts.dtype)
        if kind in ("last", "prefix"):
            stepped = kernel(ts, vals, steps, window)
        else:
            stepped = kernel(ts, vals, steps, window, wmax, *extra_args)
        m, w = tdd.digest_from_series(stepped, ids, G, C)   # [G, Tl, C]
        allm = lax.all_gather(m, "shard")          # [nsh, G, Tl, C]
        allw = lax.all_gather(w, "shard")
        nsh, _, Tl, _ = allm.shape
        M = jnp.moveaxis(allm, 0, 2).reshape(G, Tl, nsh * C)
        W = jnp.moveaxis(allw, 0, 2).reshape(G, Tl, nsh * C)
        return tdd.compress(M, W, C)               # [G, Tl, C] each

    fn = _shard_map_unchecked(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", None), P("shard"), P("step")),
        out_specs=(P(None, "step", None), P(None, "step", None)))
    return devicewatch.jit(fn, program="mesh.quantile")


@functools.lru_cache(maxsize=64)
def _build_values_program(mesh_key, range_fn, window_ms: int, wmax: int,
                          extra_args: tuple):
    """scan+window only, stepped values stay row-sharded: the mesh leaf
    for aggregates whose output cardinality is data-dependent
    (count_values) — the host maps the readback into member partials."""
    mesh = _MESHES[mesh_key]
    kind = rangefns.kernel_kind(range_fn)
    kernel = rangefns.raw_kernel(range_fn)

    def local(ts, vals, steps):
        window = jnp.asarray(window_ms, dtype=ts.dtype)
        if kind in ("last", "prefix"):
            return kernel(ts, vals, steps, window)
        return kernel(ts, vals, steps, window, wmax, *extra_args)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", None), P("step")),
        out_specs=P("shard", "step"))
    return devicewatch.jit(fn, program="mesh.values")


@functools.lru_cache(maxsize=64)
def _build_hist_program(mesh_key, range_fn, num_groups: int,
                        window_ms: int):
    """First-class histogram columns IN the mesh program: the per-bucket
    window kernel runs over [rows, R, B] locally, bucket-wise group sums
    and the live-row count psum over the shard axis (the reference's
    HistSumRowAggregator reduce, bucket lanes riding ICI)."""
    mesh = _MESHES[mesh_key]
    kernel = rangefns.hist_kernel(range_fn)
    G = num_groups

    def local(ts, hist, ids, steps):
        window = jnp.asarray(window_ms, dtype=ts.dtype)
        stepped = kernel(ts, hist, steps, window)   # [rows, Tl, B]
        fin = jnp.isfinite(stepped[..., -1])        # live iff top bucket
        hs = jax.ops.segment_sum(
            jnp.where(fin[..., None], stepped, 0.0), ids, G + 1)[:G]
        n = jax.ops.segment_sum(fin.astype(stepped.dtype), ids, G + 1)[:G]
        return lax.psum(hs, "shard"), lax.psum(n, "shard")

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard", None, None), P("shard"),
                  P("step")),
        out_specs=(P(None, "step", None), P(None, "step")))
    return devicewatch.jit(fn, program="mesh.hist")


# shard_map needs the Mesh object at trace time but lru_cache needs hashable
# keys; registry keyed by id-like tuple.
_MESHES: dict = {}


def _mesh_key(mesh: Mesh):
    key = (tuple(d.id for d in mesh.devices.flat), mesh.devices.shape)
    _MESHES[key] = mesh
    return key


class MeshEngine:
    """Distributed leaf executor: batches per-shard data onto the mesh and
    runs the windowed aggregation as one SPMD program.

    The host-side contract mirrors the reference's scatter-gather: callers
    hand one ChunkBatch per FiloDB shard (`shard_batches`), already padded to
    a common [S, R]; the engine stacks them to [K, S*? ...] device arrays
    laid out along the mesh shard axis.
    """

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self._key = _mesh_key(self.mesh)

    @property
    def num_shard_slices(self) -> int:
        return self.mesh.devices.shape[0]

    @property
    def num_step_slices(self) -> int:
        return self.mesh.devices.shape[1]

    def _place(self, arr: np.ndarray, spec: P):
        # scratch: per-query host batches staged for one SPMD dispatch
        return LEDGER.device_put(arr, NamedSharding(self.mesh, spec),
                                 owner="mesh:batch", fmt="scratch")

    def stack_shards(self, shard_batches: Sequence[ChunkBatch],
                     group_ids: Sequence[np.ndarray], hist: bool = False):
        """[K shards of [S_k, R_k]] -> ([K, S, R] ts/vals, [K, S] ids) padded
        so K divides the shard-axis size and S, R are common.  With
        ``hist=True`` the value plane is the per-bucket matrix
        [K, S, R, B] instead (narrower cumulative schemes edge-pad to
        the widest B: the top bucket IS the total, the
        _align_hist_widths convention)."""
        K = len(shard_batches)
        kd = self.num_shard_slices
        Kp = ((K + kd - 1) // kd) * kd if K else kd
        S = max((b.num_series for b in shard_batches), default=1)
        R = max((b.max_rows for b in shard_batches), default=1)
        ts = np.full((Kp, S, R), TS_PAD, dtype=np.int64)
        if hist:
            B = max((b.hist.shape[2] for b in shard_batches), default=1)
            vals = np.full((Kp, S, R, B), np.nan, dtype=np.float64)
        else:
            vals = np.full((Kp, S, R), np.nan, dtype=np.float64)
        # group id for padded series: 0 — harmless because their stepped
        # values are NaN and every _agg_map drops non-finite entries.
        ids = np.zeros((Kp, S), dtype=np.int32)
        for k, (b, gid) in enumerate(zip(shard_batches, group_ids)):
            s, r = b.timestamps.shape
            ts[k, :s, :r] = b.timestamps
            if hist:
                h = b.hist
                if h.shape[2] < vals.shape[3]:
                    h = np.pad(h, ((0, 0), (0, 0),
                                   (0, vals.shape[3] - h.shape[2])),
                               mode="edge")
                vals[k, :s, :r] = h
            else:
                vals[k, :s, :r] = b.values
            ids[k, :len(gid)] = gid
        return ts, vals, ids

    def pad_steps(self, steps: np.ndarray) -> tuple[np.ndarray, int]:
        td = self.num_step_slices
        T = len(steps)
        Tp = ((T + td - 1) // td) * td
        if Tp == T:
            return steps, T
        # pad with steps far past the data; they produce NaNs and are trimmed.
        step = steps[-1] - steps[-2] if T > 1 else 1
        pad = steps[-1] + step * np.arange(1, Tp - T + 1)
        return np.concatenate([steps, pad]), T

    def _prepare(self, shard_batches, group_ids, srange: StepRange,
                 window_ms: int, range_fn):
        """Shared input prep: stack + flatten shards, pad steps, derive
        wmax, place onto the mesh.  Returns (d_ts, d_vals, d_ids,
        d_steps, wmax, T, (Kp, S)) — the layout tuple lets callers map
        flattened global row index k*S+s back to (shard, series)."""
        ts, vals, ids = self.stack_shards(shard_batches, group_ids)
        K, S, R = ts.shape
        ts = ts.reshape(K * S, R)
        vals = vals.reshape(K * S, R)
        ids = ids.reshape(K * S)
        steps_np = np.asarray(srange.timestamps(np.int64))
        steps_np, T = self.pad_steps(steps_np)
        wmax = 0
        if rangefns.kernel_kind(range_fn) == "gather":
            wmax = rangefns.bucket_wmax(ts, steps_np, window_ms)
        return (self._place(ts, P("shard", None)),
                self._place(vals, P("shard", None)),
                self._place(ids, P("shard")),
                self._place(steps_np, P("step")), wmax, T, (K, S))

    def window_aggregate(self, shard_batches: Sequence[ChunkBatch],
                         group_ids: Sequence[np.ndarray], num_groups: int,
                         srange: StepRange, window_ms: int,
                         range_fn=None, agg_op: Agg = Agg.SUM,
                         extra_args: tuple = ()) -> np.ndarray:
        """Full distributed pipeline -> [num_groups, T] on host."""
        d_ts, d_vals, d_ids, d_steps, wmax, T, _ = self._prepare(
            shard_batches, group_ids, srange, window_ms, range_fn)
        prog = _build_program(self._key, range_fn, agg_op, num_groups,
                              window_ms, wmax, extra_args)
        out = prog(d_ts, d_vals, d_ids, d_steps)
        return np.asarray(out)[:, :T]  # host-sync-ok: end of the SPMD pipeline: the [G, T] aggregate lands on host for serving

    def window_aggregate_partials(self, shard_batches, group_ids,
                                  num_groups: int, srange: StepRange,
                                  window_ms: int, range_fn=None,
                                  agg_op: Agg = Agg.SUM,
                                  extra_args: tuple = ()) -> dict:
        """Like :meth:`window_aggregate` but returns the MERGEABLE partial
        state dict ({"sum": [G,T], "count": [G,T]}, ...) instead of the
        presented values — the form the host-side ReduceAggregateExec
        merges with partials from remote (HTTP-dispatched) shards."""
        d_ts, d_vals, d_ids, d_steps, wmax, T, _ = self._prepare(
            shard_batches, group_ids, srange, window_ms, range_fn)
        prog = _build_program(self._key, range_fn, agg_op, num_groups,
                              window_ms, wmax, extra_args, present=False)
        outs = prog(d_ts, d_vals, d_ids, d_steps)
        names = partial_state_names(agg_op)
        export = set(exported_state_names(agg_op))
        state = {}
        for name, arr in zip(names, outs):
            if name not in export:
                continue
            a = np.asarray(arr)[:, :T]
            if name in ("min", "max"):
                # the device kernels use +/-inf fill for empty cells; host
                # reduce (np.nanmin/nanmax) expects NaN
                a = np.where(np.isfinite(a), a, np.nan)
            state[name] = a
        return state


    def window_topk_partials(self, shard_batches, group_ids,
                             num_groups: int, srange: StepRange,
                             window_ms: int, k: int, bottom: bool,
                             range_fn=None, extra_args: tuple = ()):
        """topk/bottomk mesh partial: (values [G,k,T], sidx [G,k,T]
        int32 global row index, layout (Kp, S)) — sidx indexes the
        flattened (shard, series) grid the caller maps to series keys."""
        d_ts, d_vals, d_ids, d_steps, wmax, T, layout = self._prepare(
            shard_batches, group_ids, srange, window_ms, range_fn)
        prog = _build_topk_program(self._key, range_fn, num_groups,
                                   window_ms, wmax, extra_args, int(k),
                                   bool(bottom))
        v, si = prog(d_ts, d_vals, d_ids, d_steps)
        return (np.asarray(v)[..., :T],  # host-sync-ok: topk partial values land on host for cross-shard merge
                np.asarray(si).astype(np.int32)[..., :T], layout)  # host-sync-ok: topk partial indices ride back with the values

    def window_quantile_partials(self, shard_batches, group_ids,
                                 num_groups: int, srange: StepRange,
                                 window_ms: int, range_fn=None,
                                 extra_args: tuple = (),
                                 compression: int = 128):
        """quantile mesh partial: merged t-digests (means, weights)
        [G, T, C] — the state QuantileAggregator.reduce merges with
        host/remote digest or exact-member partials."""
        d_ts, d_vals, d_ids, d_steps, wmax, T, _ = self._prepare(
            shard_batches, group_ids, srange, window_ms, range_fn)
        prog = _build_quantile_program(self._key, range_fn, num_groups,
                                       window_ms, wmax, extra_args,
                                       compression)
        m, w = prog(d_ts, d_vals, d_ids, d_steps)
        return np.asarray(m)[:, :T], np.asarray(w)[:, :T]  # host-sync-ok: t-digest partials (means+weights) land on host for merge

    def window_values(self, shard_batches, srange: StepRange,
                      window_ms: int, range_fn=None,
                      extra_args: tuple = ()):
        """scan+window on the mesh, stepped values read back [rows, T]
        (count_values: output cardinality is data-dependent, the host
        builds the member partial).  Returns (stepped, layout)."""
        zeros = [np.zeros(b.num_series, np.int32) for b in shard_batches]
        d_ts, d_vals, _ids, d_steps, wmax, T, layout = self._prepare(
            shard_batches, zeros, srange, window_ms, range_fn)
        prog = _build_values_program(self._key, range_fn, window_ms,
                                     wmax, extra_args)
        out = prog(d_ts, d_vals, d_steps)
        return np.asarray(out)[:, :T], layout  # host-sync-ok: stepped readback — count_values builds its state host-side

    def window_hist_partials(self, shard_batches, group_ids,
                             num_groups: int, srange: StepRange,
                             window_ms: int, range_fn=None):
        """First-class histogram sum as a mesh partial: per-bucket
        window kernel + bucket-wise group psum.  Returns the
        MomentAggregator hist state ({"hist_sum": [G, T, B],
        "count": [G, T]}) and the widest bucket_tops."""
        tops = max((b.bucket_tops for b in shard_batches
                    if b.bucket_tops is not None),
                   key=len, default=None)
        ts, hist, ids = self.stack_shards(shard_batches, group_ids,
                                          hist=True)
        Kp, S, R, B = hist.shape
        steps_np = np.asarray(srange.timestamps(np.int64))
        steps_np, T = self.pad_steps(steps_np)
        d_ts = self._place(ts.reshape(Kp * S, R), P("shard", None))
        d_hist = self._place(hist.reshape(Kp * S, R, B),
                             P("shard", None, None))
        d_ids = self._place(ids.reshape(Kp * S), P("shard"))
        d_steps = self._place(steps_np, P("step"))
        prog = _build_hist_program(self._key, range_fn, num_groups,
                                   window_ms)
        hs, n = prog(d_ts, d_hist, d_ids, d_steps)
        return ({"hist_sum": np.asarray(hs)[:, :T],  # host-sync-ok: hist partial readback for MomentAggregator merge
                 "count": np.asarray(n)[:, :T]},  # host-sync-ok: hist count plane rides back with the sums
                np.asarray(tops) if tops is not None else None)


_DEFAULT_ENGINE: Optional["MeshEngine"] = None
_MULTIHOST_INITIALIZED = False


def default_engine() -> "MeshEngine":
    """Process-wide engine over all visible devices (shard axis)."""
    global _DEFAULT_ENGINE
    if _DEFAULT_ENGINE is None:
        _DEFAULT_ENGINE = MeshEngine(make_mesh())
    return _DEFAULT_ENGINE


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> "MeshEngine":
    """Join a multi-host JAX runtime and build the global mesh engine —
    the DCN+ICI analog of the reference's NCCL/MPI-style scale-out
    (SURVEY.md §2.8 TPU-native equivalent).

    Wraps ``jax.distributed.initialize`` (args fall back to the standard
    JAX env vars / cloud auto-detection); afterwards ``jax.devices()``
    spans EVERY host, so :func:`default_engine`'s mesh covers the full
    pod — one SPMD serving program whose psum rides ICI within a slice
    and DCN across slices.  Each host's coordinator
    (:mod:`filodb_tpu.coordinator.cluster`) still owns shard assignment;
    call this once at process start, before any other jax use."""
    global _DEFAULT_ENGINE, _MULTIHOST_INITIALIZED
    if _MULTIHOST_INITIALIZED:
        return _DEFAULT_ENGINE          # idempotent re-init
    if _DEFAULT_ENGINE is not None:
        # fail fast with a clear message: jax.distributed.initialize
        # would raise an opaque error after any jax computation, and a
        # caller swallowing it would silently keep the single-host mesh
        raise RuntimeError(
            "init_multihost must run before the mesh engine is first "
            "used (a query already built the single-host engine)")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _DEFAULT_ENGINE = MeshEngine(make_mesh())
    _MULTIHOST_INITIALIZED = True
    return _DEFAULT_ENGINE
