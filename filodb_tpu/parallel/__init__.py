"""Parallelism & distribution: shard mapping, device-mesh scan/reduce,
multi-host dispatch (reference: coordinator/ shard layer + SURVEY.md §2.7)."""
