"""On-demand-paging query throughput (reference analog:
jmh/.../QueryOnDemandBenchmark.scala:34 — queries over data that must be
paged back from the column store).

Data is ingested, flushed to the sqlite-backed column store, then a
FRESH memstore recovers only the partkey index (partitions index-only,
no chunks in memory).  The first query pages every partition's chunks
in through the ODP read path; the repeat query serves from the page
cache."""

import os
import subprocess
import sys
import pathlib
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402


def grid_stage_main():
    """Runs on the DEFAULT backend (the TPU under the bench driver):
    warm dashboard hits over PAGED-IN history must serve from the
    device grid (reference: DemandPagedChunkStore pages into block
    memory and serves identically).  Emits the warm grid-served rate."""
    import json
    import time

    import bench
    err = bench._probe_backend(
        int(os.environ.get("FILODB_BENCH_PROBE_TIMEOUT_S", "120")))
    if err is not None:
        # flush before os._exit: piped stdout is block-buffered and
        # os._exit skips interpreter cleanup
        print(json.dumps({"error": f"backend unavailable: {err}"}),
              flush=True)
        os._exit(3)      # a dead TPU tunnel hangs init; exit fast instead

    import jax

    from filodb_tpu.core.filters import ColumnFilter, Equals
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.core.storeconfig import StoreConfig
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.query.logical import RangeFunctionId
    from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

    # 102400 lanes (1024-tile aligned) x 300 rows: a large paged-in
    # dashboard working set, so the per-query dispatch floor of the
    # tunnel-attached device amortizes over ~26M scanned samples
    n_series, n_rows, step = 102_400, 300, 60_000
    base = 1_700_000_040_000
    with tempfile.TemporaryDirectory() as tmp:
        disk = DiskColumnStore(str(pathlib.Path(tmp) / "c.db"))
        meta = DiskMetaStore(str(pathlib.Path(tmp) / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        cfg = StoreConfig(grid_step_ms=step, max_chunks_size=n_rows,
                          max_data_per_shard_query=1 << 30,
                          device_cache_bytes=2 << 30,
                          # the 102400-series x 300-row working set is
                          # ~600 MB with decoded planes accounted; the
                          # grid can only build from paged history that
                          # is still IN the page cache
                          page_cache_bytes=2 << 30)
        sh = store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                          container_size=8 << 20)
        ts = base + np.arange(n_rows, dtype=np.int64) * step
        rng = np.random.default_rng(0)
        for i in range(n_series):
            b.add_series(ts, [np.cumsum(rng.random(n_rows))],
                         {"_metric_": "odp_grid", "inst": f"i{i}",
                          "_ws_": "w", "_ns_": "n"})
        for off, c in enumerate(b.containers()):
            sh.ingest_container(c, off)
        sh.flush_all(ingestion_time=1000)
        sh.evict_partitions(n_series)
        filters = [ColumnFilter("_metric_", Equals("odp_grid"))]
        res = sh.lookup_partitions(filters, 0, 2**62)
        sh.scan_batch(res.part_ids, 0, 2**62)       # page everything in
        window = 300_000
        steps0 = base + window
        # nrows = (nsteps-1) + K = 255 <= 256: the kernels tile 1024
        # lanes wide instead of 128
        nsteps = 251
        gids = [0] * len(res.part_ids)

        def serve():
            # the dashboard shape: sum(rate(...)) fused on device, only
            # [G, T] partials cross the host link
            got = sh.scan_grid_grouped(res.part_ids, RangeFunctionId.RATE,
                                       steps0, nsteps, step, window,
                                       gids, 1, "sum")
            assert got is not None, "grid did not serve paged partitions"
            return got

        serve()                                     # compile + stage
        times = []
        for _ in range(5):
            a = time.perf_counter()
            serve()
            times.append(time.perf_counter() - a)
        el = float(np.median(times))
        K = window // step
        total = n_series * (nsteps - 1 + K)      # rows the query scans
        print(json.dumps({"rate": total / el,
                          "backend": jax.default_backend()}))


if os.environ.get("FILODB_ODP_GRID") == "1":
    grid_stage_main()
    sys.exit(0)

force_cpu_x64()

from filodb_tpu.core.filters import ColumnFilter, Equals  # noqa: E402
from filodb_tpu.core.record import RecordBuilder  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.core.storeconfig import StoreConfig  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.ops.windows import StepRange  # noqa: E402
from filodb_tpu.query import rangefns  # noqa: E402
from filodb_tpu.query.logical import RangeFunctionId  # noqa: E402
from filodb_tpu.store.persistence import (DiskColumnStore,  # noqa: E402
                                          DiskMetaStore)

N_SERIES = 2_000
N_ROWS = 300
T0 = 1_700_000_000_000
STEP = 10_000
WINDOW = 60_000


def main():
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmp:
        disk = DiskColumnStore(str(pathlib.Path(tmp) / "c.db"))
        meta = DiskMetaStore(str(pathlib.Path(tmp) / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                          container_size=4 << 20)
        ts = T0 + np.arange(N_ROWS, dtype=np.int64) * STEP
        for i in range(N_SERIES):
            b.add_series(ts, [np.cumsum(rng.random(N_ROWS))],
                         {"_metric_": "odp_metric", "inst": f"i{i}",
                          "_ws_": "w", "_ns_": "n"})
        sh = store.get_shard("prom", 0)
        for off, c in enumerate(b.containers()):
            sh.ingest_container(c, off)
        sh.flush_all(ingestion_time=1000)
        total = N_SERIES * N_ROWS
        log(f"{total} samples persisted; fresh store pages them back")

        filters = [ColumnFilter("_metric_", Equals("odp_metric"))]
        steps0 = T0 + WINDOW
        end = T0 + (N_ROWS - 1) * STEP
        sr = StepRange(steps0, end, STEP)
        import time

        # cold: median over FRESH index-only stores (every rep pages the
        # whole working set from disk; the shared 1-core host is noisy,
        # so a single shot under- or over-states by 3-5x)
        shard = None
        colds = []
        for _ in range(5):
            cold = TimeSeriesMemStore(disk, meta)
            cold.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
            assert cold.recover_index("prom", 0) == N_SERIES
            shard = cold.get_shard("prom", 0)
            a = time.perf_counter()
            res = shard.lookup_partitions(filters, 0, 2**62)
            tags, batch = shard.scan_batch(
                list(res.part_ids) + res.missing_partkeys, 0, 2**62)
            colds.append(time.perf_counter() - a)
            assert len(tags) == N_SERIES
            assert shard.stats.partitions_paged >= N_SERIES
        t_cold = float(np.median(colds))
        emit("ODP cold scan (pages chunks from disk)", total / t_cold,
             "samples/sec", paged=int(shard.stats.partitions_paged),
             best=round(total / min(colds)))

        def scan():
            res = shard.lookup_partitions(filters, 0, 2**62)
            tags, batch = shard.scan_batch(
                list(res.part_ids) + res.missing_partkeys, 0, 2**62)
            return tags, batch
        t_warm = timed(scan)
        emit("ODP warm scan (page cache)", total / t_warm, "samples/sec")
        # full query incl. the windowed kernel, for end-to-end context
        def query():
            tags, batch = scan()
            return np.asarray(rangefns.apply_range_function(
                batch, sr, WINDOW, RangeFunctionId.RATE))
        query()
        t_q = timed(query)
        emit("ODP warm query incl. rate kernel (CPU)", total / t_q,
             "samples/sec")

    # warm GRID-served stage on the default backend (subprocess: this
    # process already forced CPU)
    import json
    env = dict(os.environ, FILODB_ODP_GRID="1")
    try:
        proc = subprocess.run([sys.executable, __file__], env=env,
                              capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        log("grid stage timed out; CPU metrics above still stand")
        return
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() \
        else ""
    try:
        got = json.loads(line)
        emit("ODP warm dashboard served from device grid", got["rate"],
             "samples/sec", backend=got["backend"])
    except (ValueError, KeyError):
        log(f"grid stage failed: {proc.stderr[-400:]}")


if __name__ == "__main__":
    main()
