"""On-demand-paging query throughput (reference analog:
jmh/.../QueryOnDemandBenchmark.scala:34 — queries over data that must be
paged back from the column store).

Data is ingested, flushed to the sqlite-backed column store, then a
FRESH memstore recovers only the partkey index (partitions index-only,
no chunks in memory).  The first query pages every partition's chunks
in through the ODP read path; the repeat query serves from the page
cache."""

import sys
import pathlib
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.filters import ColumnFilter, Equals  # noqa: E402
from filodb_tpu.core.record import RecordBuilder  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.core.storeconfig import StoreConfig  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.ops.windows import StepRange  # noqa: E402
from filodb_tpu.query import rangefns  # noqa: E402
from filodb_tpu.query.logical import RangeFunctionId  # noqa: E402
from filodb_tpu.store.persistence import (DiskColumnStore,  # noqa: E402
                                          DiskMetaStore)

N_SERIES = 2_000
N_ROWS = 300
T0 = 1_700_000_000_000
STEP = 10_000
WINDOW = 60_000


def main():
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmp:
        disk = DiskColumnStore(str(pathlib.Path(tmp) / "c.db"))
        meta = DiskMetaStore(str(pathlib.Path(tmp) / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                          container_size=4 << 20)
        ts = T0 + np.arange(N_ROWS, dtype=np.int64) * STEP
        for i in range(N_SERIES):
            b.add_series(ts, [np.cumsum(rng.random(N_ROWS))],
                         {"_metric_": "odp_metric", "inst": f"i{i}",
                          "_ws_": "w", "_ns_": "n"})
        sh = store.get_shard("prom", 0)
        for off, c in enumerate(b.containers()):
            sh.ingest_container(c, off)
        sh.flush_all(ingestion_time=1000)
        total = N_SERIES * N_ROWS
        log(f"{total} samples persisted; fresh store pages them back")

        # fresh store: index-only partitions, chunks on disk
        cold = TimeSeriesMemStore(disk, meta)
        cold.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        assert cold.recover_index("prom", 0) == N_SERIES
        shard = cold.get_shard("prom", 0)
        filters = [ColumnFilter("_metric_", Equals("odp_metric"))]
        steps0 = T0 + WINDOW
        end = T0 + (N_ROWS - 1) * STEP
        sr = StepRange(steps0, end, STEP)

        def scan():
            res = shard.lookup_partitions(filters, 0, 2**62)
            tags, batch = shard.scan_batch(
                list(res.part_ids) + res.missing_partkeys, 0, 2**62)
            return tags, batch

        import time
        a = time.perf_counter()
        tags, batch = scan()
        t_cold = time.perf_counter() - a
        assert len(tags) == N_SERIES
        assert shard.stats.partitions_paged >= N_SERIES
        emit("ODP cold scan (pages chunks from disk)", total / t_cold,
             "samples/sec", paged=int(shard.stats.partitions_paged))
        t_warm = timed(scan)
        emit("ODP warm scan (page cache)", total / t_warm, "samples/sec")
        # full query incl. the windowed kernel, for end-to-end context
        def query():
            tags, batch = scan()
            return np.asarray(rangefns.apply_range_function(
                batch, sr, WINDOW, RangeFunctionId.RATE))
        query()
        t_q = timed(query)
        emit("ODP warm query incl. rate kernel (CPU)", total / t_q,
             "samples/sec")


if __name__ == "__main__":
    main()
