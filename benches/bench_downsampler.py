"""Batch downsampler rollup throughput (BASELINE config 4).

The offline raw -> 1m -> 15m -> 1h rollup the reference runs as a Spark
job (reference: spark-jobs/.../DownsamplerMain.scala:43 ->
BatchDownsampler.downsampleBatch): pages raw chunks from the column
store, applies the per-schema ChunkDownsamplers, writes downsample
datasets back.  Here the same kernels run under the in-repo batch
driver over (shard x ingestion-time) splits."""

import sys
import pathlib
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.record import RecordBuilder  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.downsample import BatchDownsampler  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.store.persistence import (DiskColumnStore,  # noqa: E402
                                          DiskMetaStore)

N_SERIES = 200
N_ROWS = 720             # 1h of 5s scrapes per series
T0 = 1_600_000_000_000
STEP = 5_000
RESOLUTIONS = (60_000, 900_000, 3_600_000)   # 1m / 15m / 1h


def main():
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmp:
        disk = DiskColumnStore(str(pathlib.Path(tmp) / "c.db"))
        meta = DiskMetaStore(str(pathlib.Path(tmp) / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
        ts = T0 + np.arange(N_ROWS, dtype=np.int64) * STEP
        for i in range(N_SERIES):
            tags = {"_metric_": "disk_io", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            b.add_series(ts, [rng.random(N_ROWS) * 100], tags)
        for off, c in enumerate(b.containers()):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all(ingestion_time=1000)
        total = N_SERIES * N_ROWS
        log(f"{total} raw samples flushed; rolling up to "
            f"{[r // 60000 for r in RESOLUTIONS]} min resolutions")

        def rollup():
            job = BatchDownsampler("prom", DEFAULT_SCHEMAS, disk,
                                   resolutions_ms=RESOLUTIONS)
            written = job.run_shard(0, 0, 2**62)
            assert all(written[r] > 0 for r in RESOLUTIONS)
            return written

        t = timed(rollup, reps=3)
        emit("batch downsampler rollup (raw->1m/15m/1h)", total / t,
             "raw samples/sec")


if __name__ == "__main__":
    main()
