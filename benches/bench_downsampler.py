"""Batch downsampler rollup throughput (BASELINE config 4).

The offline raw -> 1m -> 15m -> 1h rollup the reference runs as a Spark
job (reference: spark-jobs/.../DownsamplerMain.scala:43 ->
BatchDownsampler.downsampleBatch): pages raw chunks from the column
store, applies the per-schema ChunkDownsamplers, writes downsample
datasets back.  Here the same kernels run under the in-repo batch
driver over (shard x ingestion-time) splits.

Two metrics:
- downsample kernels (griddown.period_reduce — the reshape segment
  reduce serving ALL of dMin/dMax/dSum/dCount/dAvg/dLast in one
  dispatch), measured in a subprocess on the DEFAULT jax backend (the
  TPU under the bench driver);
- the full rollup end-to-end on CPU, including record build, re-ingest
  into the downsample datasets, chunk encode, and the sqlite column
  store write — the Spark-job analog, dominated by persistence.
"""

import os
import subprocess
import sys
import pathlib
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402


def kernel_main():
    """Runs on the default backend: measure the period segment-reduce
    (bench.py timing protocol: on-device gen, unrolled iterations,
    readback-forced, 1-iter variant subtracted)."""
    import json
    import time

    import bench
    err = bench._probe_backend(
        int(os.environ.get("FILODB_BENCH_PROBE_TIMEOUT_S", "120")))
    if err is not None:
        # flush before os._exit: piped stdout is block-buffered and
        # os._exit skips interpreter cleanup
        print(json.dumps({"error": f"backend unavailable: {err}"}),
              flush=True)
        os._exit(3)      # a dead TPU tunnel hangs init; exit fast instead

    import jax
    import jax.numpy as jnp

    from filodb_tpu.downsample.griddown import _period_reduce_impl

    B, S, K = 720, 16_384, 12          # 1h of 5s scrapes -> 1m periods
    ITERS = 20
    P = B // K

    def gen(seed):
        return jax.random.uniform(jax.random.PRNGKey(seed), (B, S),
                                  jnp.float32 if jax.default_backend()
                                  != "cpu" else jnp.float64)

    def build(iters):
        def f(seed):
            vals = gen(seed)
            acc = 0.0
            for i in range(iters):
                out = _period_reduce_impl(vals + i, P, K)
                acc = acc + out["sum"][0, 0] + out["min"][P // 2, 7] \
                    + out["last"][P - 1, 1]
            return acc
        return jax.jit(f)

    f1, fN = build(1), build(1 + ITERS)
    float(f1(0)); float(fN(0))

    def t(f, reps=5):
        best = []
        for _ in range(reps):
            a = time.perf_counter()
            float(f(0))
            best.append(time.perf_counter() - a)
        return float(np.median(best))

    el = max(t(fN) - t(f1), 1e-9)
    rate = B * S * ITERS / el
    print(json.dumps({"rate": rate, "backend": jax.default_backend()}))


if os.environ.get("FILODB_DS_KERNEL") == "1":
    kernel_main()
    sys.exit(0)

force_cpu_x64()

from filodb_tpu.core.record import RecordBuilder  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.downsample import BatchDownsampler  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.store.persistence import (DiskColumnStore,  # noqa: E402
                                          DiskMetaStore)

N_SERIES = 500
N_ROWS = 4320            # 6h of 5s scrapes: the reference downsampler's
#                          typical batch window (userTimeOverride 6h
#                          batches, DownsamplerMain.scala)
T0 = 1_600_000_000_000
STEP = 5_000
RESOLUTIONS = (60_000, 900_000, 3_600_000)   # 1m / 15m / 1h


def main():
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmp:
        from filodb_tpu.core.storeconfig import StoreConfig
        disk = DiskColumnStore(str(pathlib.Path(tmp) / "c.db"))
        meta = DiskMetaStore(str(pathlib.Path(tmp) / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        # hourly raw chunks (720 rows at 5s cadence), the reference's
        # flush-interval chunk geometry
        store.setup("prom", DEFAULT_SCHEMAS, 0,
                    StoreConfig(max_chunks_size=720))
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
        ts = T0 + np.arange(N_ROWS, dtype=np.int64) * STEP
        for i in range(N_SERIES):
            tags = {"_metric_": "disk_io", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            b.add_series(ts, [rng.random(N_ROWS) * 100], tags)
        for off, c in enumerate(b.containers()):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all(ingestion_time=1000)
        total = N_SERIES * N_ROWS
        log(f"{total} raw samples flushed; rolling up to "
            f"{[r // 60000 for r in RESOLUTIONS]} min resolutions")

        def rollup():
            job = BatchDownsampler("prom", DEFAULT_SCHEMAS, disk,
                                   resolutions_ms=RESOLUTIONS)
            written = job.run_shard(0, 0, 2**62)
            assert all(written[r] > 0 for r in RESOLUTIONS)
            return written

        t = timed(rollup, reps=3)
        emit("batch downsampler rollup incl. persistence (raw->1m/15m/1h)",
             total / t, "raw samples/sec")

    # kernel-stage metric on the default backend (subprocess: this
    # process already forced CPU)
    import json
    env = dict(os.environ, FILODB_DS_KERNEL="1")
    proc = subprocess.run([sys.executable, __file__], env=env,
                          capture_output=True, text=True, timeout=600)
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        got = json.loads(line)
        emit("downsample period-reduce kernels", got["rate"],
             "raw samples/sec", backend=got["backend"])
    except (ValueError, KeyError):
        log(f"kernel subprocess failed: {proc.stderr[-400:]}")


if __name__ == "__main__":
    main()
