"""High-cardinality query: many series, few samples each, through the
full engine (parse -> plan -> scan -> window -> aggregate).

Reference analog: jmh/.../QueryHiCardInMemoryBenchmark.scala:34 and
QueryAndIngestBenchmark.scala:38 (concurrent ingest+query)."""

import sys
import pathlib
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.coordinator.planner import SingleClusterPlanner  # noqa: E402
from filodb_tpu.core.record import RecordBuilder, decode_container  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.core.storeconfig import StoreConfig  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus  # noqa: E402
from filodb_tpu.promql.parser import query_range_to_logical_plan  # noqa: E402
from filodb_tpu.query.exec import ExecContext  # noqa: E402
from filodb_tpu.query.model import QueryContext  # noqa: E402

BASE = 1_700_000_000_000
N_SERIES = 5_000
N_ROWS = 60
STEP = 10_000


def main():
    mapper = ShardMapper(4)
    mapper.register_node(range(4), "local")
    ms = TimeSeriesMemStore()
    cfg = StoreConfig(batch_series_pad=1024)
    for s in range(4):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("bench", DEFAULT_SCHEMAS, s, cfg)
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], container_size=4 << 20)
    for i in range(N_SERIES):
        tags = {"__name__": "hc_total", "instance": f"i{i}",
                "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.arange(N_ROWS) * STEP
        vals = np.cumsum(rng.random(N_ROWS))
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        per = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 2) % 4
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard("bench", sh).ingest(recs, off)
    log(f"{N_SERIES} series x {N_ROWS} rows across 4 shards")

    planner = SingleClusterPlanner("bench", mapper, DatasetOptions(),
                                   spread_default=2)
    plan = query_range_to_logical_plan(
        'sum(rate(hc_total{_ws_="w",_ns_="n"}[2m]))',
        BASE + 200_000, STEP, BASE + 550_000)
    qctx = QueryContext(sample_limit=10_000_000)
    ep = planner.materialize(plan, qctx)

    def run_query():
        return ep.execute(ExecContext(ms, qctx))

    run_query()  # warm compile caches
    t_q = timed(run_query)
    emit("hi-cardinality query samples scanned/sec",
         N_SERIES * N_ROWS / t_q, "samples/sec", series=N_SERIES)

    # count_values at 100k series: the vectorized (value, group, step)
    # counting must stay within 5x of the SUM aggregation over the same
    # stepped matrix (VERDICT r4 #8; reference CountValuesRowAggregator
    # passes exact values through mergeable rows)
    from filodb_tpu.ops.windows import StepRange
    from filodb_tpu.query.aggregators import aggregator_for
    from filodb_tpu.query.logical import AggregationOperator as Agg
    from filodb_tpu.query.model import PeriodicBatch

    S_CV, T_CV = 100_000, 20
    rng2 = np.random.default_rng(1)
    # realistic count_values payload: quantized values, modest distinct set
    cv_vals = rng2.integers(0, 50, size=(S_CV, T_CV)).astype(np.float64)
    cv_vals[rng2.random((S_CV, T_CV)) < 0.05] = np.nan
    keys = [{"instance": f"i{i}", "grp": f"g{i % 16}"}
            for i in range(S_CV)]
    srange = StepRange(BASE, BASE + (T_CV - 1) * STEP, STEP)
    pb = PeriodicBatch(keys, srange, cv_vals)

    def run_sum():
        agg = aggregator_for(Agg.SUM)
        return agg.present(agg.map(pb, ("grp",), (), (), 10_000_000))

    def run_cv():
        agg = aggregator_for(Agg.COUNT_VALUES)
        return agg.present(agg.map(pb, ("grp",), (), ("v",), 10_000_000))

    run_sum(), run_cv()                    # warm jit/compile caches
    t_sum = timed(run_sum)
    t_cv = timed(run_cv)
    emit("count_values 100k-series aggregation samples/sec",
         S_CV * T_CV / t_cv, "samples/sec", vs_sum_path=round(t_cv / t_sum, 2))
    log(f"sum: {t_sum * 1e3:.1f} ms, count_values: {t_cv * 1e3:.1f} ms "
        f"(ratio {t_cv / t_sum:.2f}x; target <=5x)")

    # concurrent ingest + query (QueryAndIngestBenchmark shape)
    stop = threading.Event()
    ingested = [0]

    def ingest_loop():
        off = 10_000
        while not stop.is_set():
            bb = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
            t0 = BASE + (off * 7919) % 10**9
            bb.add(t0 + 10**9, [1.0],
                   {"__name__": "live_total", "instance": f"x{off}",
                    "_ws_": "w", "_ns_": "n"})
            for c in bb.containers():
                ms.ingest("bench", 0, c, offset=off)
            ingested[0] += 1
            off += 1

    th = threading.Thread(target=ingest_loop, name="ingest-bench-0",
                          daemon=True)
    th.start()
    t_q2 = timed(run_query)
    stop.set()
    th.join(timeout=2)
    emit("query under concurrent ingest", N_SERIES * N_ROWS / t_q2,
         "samples/sec", ingests_during=ingested[0])


if __name__ == "__main__":
    main()
