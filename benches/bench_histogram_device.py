"""Device-grid histogram query throughput (BASELINE config 2).

Times the fused kernel pipeline the serving path dispatches for
``histogram_quantile(0.99, sum(rate(latency_bucket[5m])) by (le))`` on
first-class histogram columns: per-bucket counter-corrected rate (the
scalar dense-lane grid kernel over hb bucket lanes per series), the
bucket-wise one-hot-matmul sum on device, then histogram_quantile over
the [T, hb] partials — only the [T] quantile series is read back.

Reference analog: jmh/.../HistogramQueryBenchmark.scala:36 (quantile
query over HistogramColumn); the reference iterates row-by-row through
section-encoded hist vectors, this runs one fused device program.

Runs on JAX's default backend (TPU under the driver; CPU elsewhere —
shapes are scaled down on CPU so the suite stays fast).
"""

import sys
import pathlib
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, log  # noqa: E402

STEP_MS = 60_000
WINDOW_MS = 300_000
K = WINDOW_MS // STEP_MS
HB = 16                 # buckets per histogram
T0 = 600_000
REPS = 5


def main():
    import jax
    import jax.numpy as jnp

    from filodb_tpu.ops import histogram_ops
    from filodb_tpu.ops.grid import GridQuery

    on_tpu = jax.default_backend() in ("tpu", "axon")
    # CPU shape must stay large enough that the timed full-minus-base
    # difference is well above timer noise (a too-small shape reports
    # a nonsense rate)
    n_series = 64_000 if on_tpu else 8_192
    nb = 64             # padded bucket-row axis
    n_rows = 60
    ncols = n_series * HB
    log(f"histogram device bench: {n_series} series x {HB} buckets "
        f"({jax.default_backend()})")

    steps_np = np.arange(T0 + WINDOW_MS, T0 + n_rows * STEP_MS, STEP_MS,
                         dtype=np.int32)
    T = len(steps_np)
    q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP_MS, is_rate=True,
                  dense=True)
    tops = np.cumsum(np.full(HB, 2.0)) ** 2.0
    tops[-1] = np.inf

    def gen(seed):
        """[nb, ncols] cumulative bucket counters, dense rows."""
        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        base = (jnp.arange(nb, dtype=jnp.int32) * STEP_MS
                + T0 - STEP_MS + 1)[:, None]
        ts = base + jax.random.randint(k1, (nb, ncols), 0, 30_000, jnp.int32)
        incr = jax.random.uniform(k2, (nb, ncols), jnp.float32, 0.0, 4.0)
        # cumulative over buckets (monotone in bucket axis) and over time
        per_bucket = jnp.cumsum(incr.reshape(nb, n_series, HB), axis=2)
        vals = jnp.cumsum(per_bucket, axis=0).reshape(nb, ncols)
        live = (jnp.arange(nb) < n_rows)[:, None]
        return ts[1:], jnp.where(live, vals, jnp.nan)[1:]

    # group lanes so bucket j of every series lands in group j: the
    # serving path (devicestore.scan_rate_grouped) builds garr the same
    # way; here series*HB columns -> HB groups needs a transposed
    # layout, so generate with buckets contiguous per series and reduce
    # with a one-hot matmul like _grouped_reduce does
    garr = jnp.asarray(np.tile(np.arange(HB, dtype=np.int32), n_series))
    onehot = (garr[:, None] == jnp.arange(HB)[None, :]).astype(jnp.float32)

    def pipeline(ts, vals, bump):
        # per-bucket rate on the scalar dense kernel: [T, ncols].
        # group_lanes must divide ncols; use 1024-wide tiles with the
        # per-column group map applied in the reduce (not the kernel).
        from filodb_tpu.ops.grid import rate_grid_auto
        stepped = rate_grid_auto(ts, vals + bump, int(steps_np[0]), q,
                                 lanes=1024)
        fin = jnp.isfinite(stepped)
        vz = jnp.where(fin, stepped, 0.0)
        hp = jax.lax.Precision.HIGHEST
        sums = jnp.matmul(vz, onehot, precision=hp)          # [T, HB]
        quant = histogram_ops.hist_quantile(jnp.asarray(tops),
                                            sums[None], 0.99)[0]
        return quant                                          # [T]

    def build(iters):
        def f(seed):
            ts, vals = gen(seed)
            acc = jnp.float32(0.0)
            for i in range(iters):
                out = pipeline(ts, vals, jnp.float32(i))
                # every step must stay live or XLA prunes the reduce +
                # quantile down to the handful of steps read back
                acc = acc + jnp.nansum(out)
            return acc
        return jax.jit(f)

    iters = 10 if on_tpu else 2
    f_base, f_full = build(1), build(1 + iters)
    log("compiling...")
    _ = float(f_base(0))
    _ = float(f_full(0))
    best = []
    for _ in range(REPS):
        a = time.perf_counter()
        _ = float(f_full(0))
        b = time.perf_counter()
        _ = float(f_base(0))
        c = time.perf_counter()
        best.append(max((b - a) - (c - b), 1e-9))
    elapsed = float(np.median(best))
    hist_samples = n_series * (n_rows - 1) * iters
    bucket_samples = hist_samples * HB
    emit("hist device-grid sum(rate)+quantile", hist_samples / elapsed,
         "hist samples/sec", bucket_samples_per_sec=round(
             bucket_samples / elapsed, 1))


if __name__ == "__main__":
    main()
