"""End-to-end SERVED query throughput on the real device.

The kernel bench (bench.py) times the raw aligned-grid Pallas kernel;
this bench times the pipeline the server actually runs for a query:
planner -> shard index lookup -> device-resident grid (devicestore) ->
fused kernel -> host materialization -> Prometheus JSON, for
``sum(rate(metric[5m]))`` over aligned dashboard data.

Reference analog: jmh/QueryInMemoryBenchmark.scala:45-249 measures the
full in-memory query stack, not just the inner loop; VERDICT r1 weak #4
called out that the repo's headline number skipped the served path.

Runs on whatever JAX's default backend is (the TPU under the driver;
CPU elsewhere).  x64 stays OFF to match the server's device fast path
(the grid rebases timestamps to on-device int32).
"""

import sys
import pathlib
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, log  # noqa: E402

N_SERIES = int(__import__("os").environ.get("FILODB_SERVED_SERIES", 20_000))
N_ROWS = 60
STEP_MS = 60_000
WINDOW_MS = 300_000
# minute-aligned epoch: the device grid snaps its bucket epoch to the
# scrape cadence, and dashboard queries step on those boundaries
BASE = 1_700_000_040_000
assert BASE % STEP_MS == 0
REPS = 7


def main():
    import jax

    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.core.storeconfig import StoreConfig
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.http.model import to_prom_matrix
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.shardmap import ShardMapper
    from filodb_tpu.promql.parser import query_range_to_logical_plan
    from filodb_tpu.query.exec import ExecContext
    from filodb_tpu.query.model import QueryContext

    log(f"backend: {jax.default_backend()} "
        f"({jax.devices()[0].device_kind}); {N_SERIES} series")

    ms = TimeSeriesMemStore()
    cfg = StoreConfig(grid_step_ms=STEP_MS, max_chunks_size=N_ROWS)
    ms.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
    sh = ms.get_shard("prom", 0)

    t0 = time.perf_counter()
    ts_row = [BASE + r * STEP_MS + 1 for r in range(N_ROWS)]
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                      container_size=4 << 20)
    for s in range(N_SERIES):
        vals = np.cumsum(rng.random(N_ROWS)).tolist()
        b.add_series(ts_row, [vals],
                     {"_metric_": "served_metric", "inst": f"i{s}",
                      "_ws_": "w", "_ns_": "n"})
    for off, c in enumerate(b.containers()):
        sh.ingest_container(c, off)
    sh.flush_all()   # freeze buffers so the device grid serves chunks
    log(f"ingested {sh.stats.rows_ingested} rows in "
        f"{time.perf_counter() - t0:.1f}s")
    assert sh.stats.rows_ingested == N_SERIES * N_ROWS

    planner = SingleClusterPlanner("prom", ShardMapper(1), DatasetOptions(),
                                   spread_default=0)
    promql = 'sum(rate(served_metric{_ws_="w",_ns_="n"}[5m]))'
    start = BASE + WINDOW_MS
    end = BASE + (N_ROWS - 1) * STEP_MS
    plan = query_range_to_logical_plan(promql, start, STEP_MS, end)

    def run_query():
        qctx = QueryContext(sample_limit=10_000_000)
        ep = planner.materialize(plan, qctx)
        result = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(result)

    log("warming (grid build + compile)...")
    first = time.perf_counter()
    out = run_query()
    warm_s = time.perf_counter() - first
    assert out["status"] == "success" and out["data"]["result"], out
    npoints = len(out["data"]["result"][0]["values"])
    log(f"first query (build+compile): {warm_s:.2f}s; {npoints} points")

    times = []
    for _ in range(REPS):
        a = time.perf_counter()
        out = run_query()
        times.append(time.perf_counter() - a)
    t_med = float(np.median(times))
    samples = N_SERIES * N_ROWS
    emit("served query_range latency (planner->grid->JSON)",
         t_med * 1000, "ms", series=N_SERIES,
         backend=__import__("jax").default_backend())
    emit("served samples scanned/sec", samples / t_med, "samples/sec",
         note="end-to-end per query incl. planning + JSON")
    # sanity: repeat queries must not re-upload chunks
    cache = next(iter(sh.device_caches.values()), None)
    if cache is not None:
        emit("device grid blocks resident", len(cache.blocks), "blocks")


if __name__ == "__main__":
    main()
