"""Multi-column event scan + topK throughput (BASELINE config 5).

The GDELT-style workload the reference served through its (dormant)
Spark DataSource: a wide event schema, column-selected scan over every
partition, and topK ranking by a chosen numeric column (reference:
doc/FiloDB_GDELT.snb "top actors" analysis; SURVEY §2.6 maps the
capability onto the multi-schema columnar core)."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.filters import ColumnFilter, Equals  # noqa: E402
from filodb_tpu.core.record import RecordBuilder, decode_container  # noqa: E402
from filodb_tpu.core.schemas import DatasetOptions, Schemas  # noqa: E402
from filodb_tpu.core.storeconfig import StoreConfig  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.query.exec import (ExecContext,  # noqa: E402
                                   MultiSchemaPartitionsExec,
                                   ReduceAggregateExec)
from filodb_tpu.query.logical import (AggregationOperator,  # noqa: E402
                                      RangeFunctionId)
from filodb_tpu.query.model import QueryContext  # noqa: E402
from filodb_tpu.query.transformers import (AggregateMapReduce,  # noqa: E402
                                           AggregatePresenter,
                                           PeriodicSamplesMapper)

SCHEMAS = Schemas.from_config({
    "gdelt-event": {
        "columns": ["timestamp:ts", "avg_tone:double", "num_mentions:double",
                    "num_articles:double"],
        "value-column": "avg_tone",
        "downsamplers": [],
    },
})

N_ACTORS = 1_000
N_EVENTS = 200           # events per actor
T0 = 1_600_000_000_000
STEP = 3_600_000         # hourly events
WINDOW = N_EVENTS * STEP
STEPS0 = T0 + (N_EVENTS - 1) * STEP + 1


def main():
    rng = np.random.default_rng(0)
    ms = TimeSeriesMemStore()
    shard = ms.setup("gdelt", SCHEMAS, 0, StoreConfig())
    b = RecordBuilder(SCHEMAS["gdelt-event"], DatasetOptions())
    ts = T0 + np.arange(N_EVENTS, dtype=np.int64) * STEP
    for ai in range(N_ACTORS):
        tags = {"_metric_": "events", "actor": f"A{ai:04d}", "_ws_": "g",
                "_ns_": "news"}
        b.add_series(ts, [rng.normal(0, 3, N_EVENTS),
                          rng.integers(1, 50, N_EVENTS).astype(float),
                          rng.integers(1, 20, N_EVENTS).astype(float)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, SCHEMAS), off)
    shard.flush_all()
    total = N_ACTORS * N_EVENTS
    log(f"{total} events across {N_ACTORS} actors ingested")

    def topk_query():
        leaf = MultiSchemaPartitionsExec(
            "gdelt", 0, [ColumnFilter("_metric_", Equals("events"))],
            T0, STEPS0, column="num_mentions")
        leaf.add_transformer(PeriodicSamplesMapper(
            start_ms=STEPS0, step_ms=STEP, end_ms=STEPS0,
            window_ms=WINDOW, function=RangeFunctionId.SUM_OVER_TIME))
        leaf.add_transformer(AggregateMapReduce(
            AggregationOperator.TOPK, params=(10,)))
        root = ReduceAggregateExec([leaf], AggregationOperator.TOPK, (10,))
        root.add_transformer(AggregatePresenter(
            AggregationOperator.TOPK, (10,)))
        res = root.execute(ExecContext(ms, QueryContext()))
        return res

    topk_query()     # warm jit
    t = timed(topk_query)
    emit("gdelt multi-column scan + top10", total / t, "events/sec")


if __name__ == "__main__":
    main()
