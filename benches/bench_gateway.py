"""Gateway Influx-protocol parse + ingest throughput.

Reference analog: jmh/src/main/scala/filodb.jmh/GatewayBenchmark.scala:19
(influxToRecords / promToRecords over a canned 2000-series payload).
Measures the batch parser (C-level splits + series-prefix memoization),
the per-line parser it falls back to, and the full parse -> shard ->
RecordBuilder ingest path.
"""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from benches.common import emit, log, timed  # noqa: E402

N_SERIES = 2_000
N_BATCHES = 10
BASE_NS = 1_700_000_000_000_000_000


def payload(batch: int) -> str:
    lines = []
    ts = BASE_NS + batch * 10_000_000_000
    for i in range(N_SERIES):
        lines.append(
            f"node_cpu_seconds,host=h{i % 200},core=c{i % 16},"
            f"dc=dc{i % 4},_ws_=demo,_ns_=App-{i % 8} "
            f"value={i * 0.25 + batch} {ts + i}")
    return "\n".join(lines)


def main():
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.influx import (parse_batch_columns, parse_line,
                                           parse_lines_fast)
    from filodb_tpu.gateway.server import ShardingPublisher
    from filodb_tpu.parallel.shardmap import ShardMapper

    batches = [payload(b) for b in range(N_BATCHES)]
    total = N_SERIES * N_BATCHES

    def run_cols():
        for text in batches:
            assert parse_batch_columns(text) is not None

    t = timed(run_cols)
    emit("influx columnar batch parse (cold)", total / t, "lines/sec")

    # steady-state scrape: the same series set arrives every interval;
    # the head resolution short-circuits on a byte compare
    bmemo: dict = {}
    parse_batch_columns(batches[0], bmemo)

    def run_cols_steady():
        for text in batches:
            assert parse_batch_columns(text, bmemo) is not None

    t = timed(run_cols_steady)
    emit("influx columnar batch parse (steady-state)", total / t,
         "lines/sec")

    # record-building parser with a warm prefix memo
    memo: dict = {}
    parse_lines_fast(batches[0], memo)

    def run_fast():
        for text in batches:
            parse_lines_fast(text, memo)

    t = timed(run_fast)
    emit("influx parse to records (warm memo)", total / t, "lines/sec")

    def run_slow():
        for line in batches[0].splitlines():
            parse_line(line)

    t = timed(run_slow)
    emit("influx per-line parse", N_SERIES / t, "lines/sec")

    # full ingest: parse -> shard route -> RecordBuilder
    pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], ShardMapper(32),
                            publish=lambda shard, container: None,
                            spread=3)

    def run_ingest():
        for text in batches:
            pub.ingest_influx_batch(text)
        pub.flush()

    t = timed(run_ingest)
    emit("gateway ingest (parse+route+build)", total / t, "samples/sec")
    log(f"parse_errors={pub.parse_errors}")
    assert pub.parse_errors == 0


if __name__ == "__main__":
    main()
