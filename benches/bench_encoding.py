"""Codec encode/decode throughput, native vs numpy paths.

Reference analog: jmh/.../EncodingBenchmark.scala:23,
BasicFiloBenchmark.scala:22, IntSumReadBenchmark.scala:30."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, log, timed  # noqa: E402

from filodb_tpu import native  # noqa: E402
from filodb_tpu.codecs import deltadelta, doublecodec  # noqa: E402

N = 100_000
BASE = 1_700_000_000_000


def main():
    rng = np.random.default_rng(0)
    ts = (BASE + np.cumsum(rng.integers(9_000, 11_000, N))).astype(np.int64)
    gauge = rng.normal(50, 10, N)

    dd_blob = deltadelta.encode(ts)
    dbl_blob = doublecodec.encode(gauge)
    log(f"delta2: {len(dd_blob)}B for {N} ts "
        f"({8 * N / len(dd_blob):.1f}x), xor: {len(dbl_blob)}B "
        f"({8 * N / len(dbl_blob):.1f}x)")
    emit("delta2 compression ratio", 8 * N / len(dd_blob), "x")
    emit("xor-double compression ratio (iid noise)",
         8 * N / len(dbl_blob), "x")

    # realistic gauge streams (the Gorilla paper's production shape:
    # ~half the samples repeat, moves are small and quantized) — the
    # bit-level Gorilla/XOR selector must land >=2x here
    steps = rng.choice([0.0, 0.0, 0.0, 0.5, -0.5, 1.0, -1.0, 0.25],
                       size=N,
                       p=[.3, .15, .1, .12, .12, .08, .08, .05])
    walk = 100.0 + np.cumsum(steps)
    walk_blob = doublecodec.encode(walk)
    emit("double compression ratio (gauge walk)",
         8 * N / len(walk_blob), "x")
    flat = np.repeat(rng.normal(40, 5, 600),
                     rng.integers(100, 250, 600))[:N] + 0.125
    flat_blob = doublecodec.encode(flat)
    emit("double compression ratio (flat gauge)",
         8 * N / len(flat_blob), "x")
    t = timed(lambda: doublecodec.encode(walk))
    emit("double encode (gauge walk)", N / t, "samples/sec")
    t = timed(lambda: doublecodec.decode(walk_blob))
    emit("double decode (gauge walk)", N / t, "samples/sec")
    from filodb_tpu.codecs.wire import WireType
    assert flat_blob[0] == WireType.GORILLA_DOUBLE, \
        "selector regressed: flat gauge no longer picks GORILLA_DOUBLE"
    t = timed(lambda: doublecodec.decode(flat_blob))
    emit("gorilla decode (flat gauge)", N / t, "samples/sec")

    t_enc = timed(lambda: deltadelta.encode(ts))
    emit("delta2 encode", N / t_enc, "samples/sec")

    have_native = native.enable()
    if have_native:
        t = timed(lambda: deltadelta.decode(dd_blob))
        emit("delta2 decode (native)", N / t, "samples/sec")
        t = timed(lambda: doublecodec.decode(dbl_blob))
        emit("xor-double decode (native)", N / t, "samples/sec")

    native.disable()
    small = deltadelta.encode(ts[:5_000])
    t = timed(lambda: deltadelta.decode(small))
    emit("delta2 decode (numpy fallback)", 5_000 / t, "samples/sec")
    if have_native:
        native.enable()


if __name__ == "__main__":
    main()
