"""Codec encode/decode throughput, native vs numpy paths.

Reference analog: jmh/.../EncodingBenchmark.scala:23,
BasicFiloBenchmark.scala:22, IntSumReadBenchmark.scala:30."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, log, timed  # noqa: E402

from filodb_tpu import native  # noqa: E402
from filodb_tpu.codecs import deltadelta, doublecodec  # noqa: E402

N = 100_000
BASE = 1_700_000_000_000


def main():
    rng = np.random.default_rng(0)
    ts = (BASE + np.cumsum(rng.integers(9_000, 11_000, N))).astype(np.int64)
    gauge = rng.normal(50, 10, N)

    dd_blob = deltadelta.encode(ts)
    dbl_blob = doublecodec.encode(gauge)
    log(f"delta2: {len(dd_blob)}B for {N} ts "
        f"({8 * N / len(dd_blob):.1f}x), xor: {len(dbl_blob)}B "
        f"({8 * N / len(dbl_blob):.1f}x)")
    emit("delta2 compression ratio", 8 * N / len(dd_blob), "x")
    emit("xor-double compression ratio", 8 * N / len(dbl_blob), "x")

    t_enc = timed(lambda: deltadelta.encode(ts))
    emit("delta2 encode", N / t_enc, "samples/sec")

    have_native = native.enable()
    if have_native:
        t = timed(lambda: deltadelta.decode(dd_blob))
        emit("delta2 decode (native)", N / t, "samples/sec")
        t = timed(lambda: doublecodec.decode(dbl_blob))
        emit("xor-double decode (native)", N / t, "samples/sec")

    native.disable()
    small = deltadelta.encode(ts[:5_000])
    t = timed(lambda: deltadelta.decode(small))
    emit("delta2 decode (numpy fallback)", 5_000 / t, "samples/sec")
    if have_native:
        native.enable()


if __name__ == "__main__":
    main()
