"""Ingestion throughput: record build + container decode + shard ingest.

Reference analog: jmh/src/main/scala/filodb.jmh/IngestionBenchmark.scala:28
(BinaryRecord build + shard ingest records/sec) and the ingest hot loop
SURVEY.md §3.2."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.record import RecordBuilder, decode_container  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402

N_SERIES = 200
N_ROWS = 500
BASE = 1_700_000_000_000


def main():
    rng = np.random.default_rng(0)
    tag_sets = [{"__name__": "bench_metric", "instance": f"i{i}",
                 "host": f"h{i % 10}", "_ws_": "w", "_ns_": "n"}
                for i in range(N_SERIES)]
    ts_cols = [BASE + np.cumsum(rng.integers(5_000, 15_000, N_ROWS))
               for _ in range(N_SERIES)]
    val_cols = [rng.random(N_ROWS) for _ in range(N_SERIES)]
    total = N_SERIES * N_ROWS

    def build_perrow():
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for tags, ts, vals in zip(tag_sets, ts_cols, val_cols):
            for t, v in zip(ts, vals):
                b.add(int(t), [float(v)], tags)
        return b.containers()

    def build():
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for tags, ts, vals in zip(tag_sets, ts_cols, val_cols):
            b.add_series(ts, [vals], tags)
        return b.containers()

    t_build = timed(lambda: build_perrow())
    emit("record build throughput (per-row)", total / t_build, "records/sec")
    t_build = timed(lambda: build())
    emit("record build throughput (add_series)", total / t_build,
         "records/sec")
    assert build() == build_perrow(), "add_series diverged from per-row build"

    containers = build()

    def decode():
        n = 0
        for c in containers:
            for _ in decode_container(c, DEFAULT_SCHEMAS):
                n += 1
        return n

    t_dec = timed(decode)
    emit("container decode throughput", total / t_dec, "records/sec")

    def ingest():
        ms = TimeSeriesMemStore()
        ms.setup("bench", DEFAULT_SCHEMAS, 0)
        for off, c in enumerate(containers):
            ms.ingest("bench", 0, c, offset=off)
        return ms

    t_ing = timed(ingest)
    emit("shard ingest throughput (incl. decode+index)", total / t_ing,
         "records/sec")

    def ingest_pipelined():
        ms = TimeSeriesMemStore()
        ms.setup("bench", DEFAULT_SCHEMAS, 0)
        ms.ingest_stream("bench", 0, enumerate(containers),
                         flush_interval_ms=600_000, flush_parallelism=2)
        return ms

    t_pipe = timed(ingest_pipelined)
    emit("stream ingest w/ pipelined time-boundary flushes",
         total / t_pipe, "records/sec")

    ms = ingest()
    sh = ms.get_shard("bench", 0)
    t_flush = timed(lambda: sh.flush_all())  # first rep does the real work
    log(f"ingested {sh.stats.rows_ingested} rows; flush {t_flush * 1e3:.1f}ms")


if __name__ == "__main__":
    main()
