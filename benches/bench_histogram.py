"""Histogram ingest + quantile query throughput.

Reference analog: jmh/.../HistogramIngestBenchmark.scala:29,
HistogramQueryBenchmark.scala:36."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, timed  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.filters import ColumnFilter, Equals  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS  # noqa: E402
from filodb_tpu.gateway.producer import TestTimeseriesProducer  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.ops import histogram_ops  # noqa: E402

N_SERIES = 50
N_SAMPLES = 200


def main():
    producer = TestTimeseriesProducer(DEFAULT_SCHEMAS)
    containers = producer.histogram_containers(
        n_series=N_SERIES, n_samples=N_SAMPLES, num_buckets=16)
    total = N_SERIES * N_SAMPLES

    def ingest():
        ms = TimeSeriesMemStore()
        ms.setup("hist", DEFAULT_SCHEMAS, 0)
        for off, c in enumerate(containers):
            ms.ingest("hist", 0, c, offset=off)
        return ms

    t_ing = timed(ingest)
    emit("histogram ingest throughput", total / t_ing, "records/sec")

    ms = ingest()
    sh = ms.get_shard("hist", 0)
    res = sh.lookup_partitions(
        [ColumnFilter("_metric_", Equals("request_latency"))], 0, 2**62)

    def scan_quantile():
        tags, batch = sh.scan_batch(res.part_ids, 0, 2**62)
        q = histogram_ops.hist_quantile(np.asarray(batch.bucket_tops),
                                   np.asarray(batch.hist), 0.95)
        return np.asarray(q)

    scan_quantile()  # warm jit if any
    t_q = timed(scan_quantile)
    emit("histogram scan+p95 quantile", total / t_q, "hist samples/sec")


if __name__ == "__main__":
    main()
