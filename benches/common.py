"""Shared helpers for the benchmark suite (reference: jmh/ module's
common setup — TestTimeseriesProducer-style data, timed sections).

Each bench prints one JSON line per measured metric:
    {"metric": ..., "value": ..., "unit": ...}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def emit(metric: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": unit, **extra}), flush=True)


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def timed(fn, reps: int = 3) -> float:
    """Median wall time of fn() over reps."""
    outs = []
    for _ in range(reps):
        a = time.perf_counter()
        fn()
        outs.append(time.perf_counter() - a)
    return float(np.median(outs))


def force_cpu_x64() -> None:
    """Host-side benches must not touch the (shared) TPU tunnel."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
