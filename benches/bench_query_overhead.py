"""Tracing/metrics overhead bench: full query path, host-side.

Measures the end-to-end latency of a planner->ExecPlan->JSON query loop
(the path ISSUE 2's tracing instrumented: spans on every plan node,
per-stage stats accumulation, request histogram).  The acceptance bar
is <= 3% median overhead vs the untraced seed — record before/after in
BASELINE.md.

ISSUE 4 guard: the same loop is additionally measured with the
devicewatch layer (HBM ledger wrappers, jit compile telemetry, flight
recorder) toggled OFF vs ON; the bench EXITS NONZERO when the
instrumentation overhead exceeds the 3% budget (with a 0.5 ms absolute
floor so host-noise on a fast loop cannot trip CI spuriously).

ISSUE 5 guard: a third leg runs the loop through the workload
admission path (deadline mint -> cost estimate -> admit permit ->
calibration observe) vs without it, under the SAME 3% / 0.5 ms budget —
overload defense must be free when there is no overload.

Env: FILODB_OVH_SERIES (default 512), FILODB_OVH_ITERS (default 60).
"""

import os
import statistics
import sys
import pathlib
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log  # noqa: E402

force_cpu_x64()

from filodb_tpu.core.record import RecordBuilder, decode_container  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.coordinator.planner import SingleClusterPlanner  # noqa: E402
from filodb_tpu.http.model import to_prom_matrix  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus  # noqa: E402
from filodb_tpu.promql.parser import query_range_to_logical_plan  # noqa: E402
from filodb_tpu.query.exec import ExecContext  # noqa: E402
from filodb_tpu.query.model import QueryContext  # noqa: E402

N_SERIES = int(os.environ.get("FILODB_OVH_SERIES", 512))
ITERS = int(os.environ.get("FILODB_OVH_ITERS", 60))
BASE = 1_700_000_000_000
STEP = 10_000
N_ROWS = 360


def main():
    num_shards = 4
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], DatasetOptions(),
                      container_size=4 << 20)
    ts = BASE + np.arange(N_ROWS, dtype=np.int64) * STEP
    log(f"ingesting {N_SERIES} series x {N_ROWS} rows...")
    for i in range(N_SERIES):
        vals = np.cumsum(rng.random(N_ROWS))
        b.add_series(ts, [vals], {"__name__": "ovh_total",
                                  "instance": f"i{i}", "_ws_": "demo",
                                  "_ns_": "App-0"})
    spread = 2
    for off, c in enumerate(b.containers()):
        per_shard = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                           spread) % num_shards
            per_shard.setdefault(shard, []).append(rec)
        for shard, recs in per_shard.items():
            ms.get_shard("prom", shard).ingest(recs, off)

    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=spread)
    query = 'sum(rate(ovh_total{_ws_="demo",_ns_="App-0"}[2m]))'
    start, end = BASE + 600_000, BASE + 3_000_000

    def once():
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = planner.materialize(lp, qctx)
        res = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(res)

    def measure():
        lat = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            once()
            lat.append(time.perf_counter() - t0)
        return statistics.median(lat), sorted(lat)[int(0.9 * len(lat))]

    body = once()  # warm compile/caches
    assert body["data"]["result"], "query returned nothing"
    med, p90 = measure()
    samples = N_SERIES * (end - start) // STEP
    log(f"median {med * 1e3:.2f} ms  p90 {p90 * 1e3:.2f} ms  "
        f"({samples / med / 1e6:.1f}M samples/s)")
    emit("query_overhead_median", med * 1e3, "ms",
         p90_ms=round(p90 * 1e3, 3), iters=ITERS, series=N_SERIES)

    # devicewatch instrumentation guard (ISSUE 4): same loop with the
    # ledger/compile/flight layer off vs on; both arms re-warmed
    from filodb_tpu.utils import devicewatch
    devicewatch.set_enabled(False)
    try:
        once()
        med_off, p90_off = measure()
    finally:
        devicewatch.set_enabled(True)
    once()
    med_on, p90_on = measure()
    overhead = (med_on - med_off) / med_off
    log(f"devicewatch off {med_off * 1e3:.2f} ms  "
        f"on {med_on * 1e3:.2f} ms  overhead {overhead * 100:+.2f}%")
    emit("devicewatch_overhead_median", overhead * 100, "%",
         off_ms=round(med_off * 1e3, 3), on_ms=round(med_on * 1e3, 3),
         p90_off_ms=round(p90_off * 1e3, 3),
         p90_on_ms=round(p90_on * 1e3, 3))
    if overhead > 0.03 and (med_on - med_off) > 5e-4:
        log(f"FAIL: devicewatch overhead {overhead * 100:.2f}% exceeds "
            f"the 3% budget")
        return 1

    # kernel-timer guard (ISSUE 15): sampling at 1-in-1 — the WORST
    # case, every wrapped launch pays a block_until_ready wait plus the
    # shape-key + EWMA fold — vs sampling fully off, INTERLEAVED A/B so
    # host drift hits both arms equally.  The shipped default (1-in-64)
    # costs ~1/64th of whatever this measures on the sampled launches
    # and one counter inc on the rest.
    kt = devicewatch.KERNEL_TIMER
    old_rate = kt.sample_1_in
    kt.configure(sample_1_in=0)
    once()
    kt.configure(sample_1_in=1)
    once()
    lat_kt_off, lat_kt_on = [], []
    try:
        for _ in range(ITERS):
            kt.configure(sample_1_in=0)
            t0 = time.perf_counter()
            once()
            lat_kt_off.append(time.perf_counter() - t0)
            kt.configure(sample_1_in=1)
            t0 = time.perf_counter()
            once()
            lat_kt_on.append(time.perf_counter() - t0)
    finally:
        kt.configure(sample_1_in=old_rate)
    med_kt_off = statistics.median(lat_kt_off)
    med_kt_on = statistics.median(lat_kt_on)
    kt_delta = statistics.median(
        on - off for on, off in zip(lat_kt_on, lat_kt_off))
    kt_overhead = kt_delta / med_kt_off
    log(f"kernel timer off {med_kt_off * 1e3:.2f} ms  "
        f"1-in-1 {med_kt_on * 1e3:.2f} ms  paired delta "
        f"{kt_delta * 1e6:+.0f} us ({kt_overhead * 100:+.2f}%)")
    emit("kernel_timer_overhead_median", kt_overhead * 100, "%",
         off_ms=round(med_kt_off * 1e3, 3),
         on_ms=round(med_kt_on * 1e3, 3),
         paired_delta_us=round(kt_delta * 1e6, 1))
    if kt_overhead > 0.03 and kt_delta > 5e-4:
        log(f"FAIL: kernel-timer 1-in-1 overhead "
            f"{kt_overhead * 100:.2f}% exceeds the 3% budget")
        return 1

    # mesh-fabric guard (ISSUE 18): the same loop planned through the
    # SPMD mesh fabric — MeshReduceExec root, ONE compiled shard_map
    # launch, a single [G, T] readback — interleaved A/B against the
    # scatter-gather planner.  On a one-host bench the fabric's win is
    # launches and readbacks, not wall-clock, so the guard is that its
    # host-side orchestration (placement lookup, staging memo, fused
    # dispatch, presented-batch assembly) stays within <=3% / 0.5 ms
    # of the path it replaces.
    from filodb_tpu.parallel import meshgrid
    from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
    mesh_engine = MeshEngine(make_mesh())
    planner_mesh = SingleClusterPlanner(
        "prom", mapper, DatasetOptions(), spread_default=spread,
        mesh_engine_provider=lambda: mesh_engine)

    def once_mesh():
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = planner_mesh.materialize(lp, qctx)
        res = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(res)

    body = once_mesh()
    assert body["data"]["result"], "mesh fabric leg returned nothing"
    serves0 = meshgrid.STATS["fused_serves"]
    once_mesh()                            # warm the fused program
    if meshgrid.STATS["fused_serves"] <= serves0:
        log("FAIL: mesh-fabric leg fell back to scatter-gather — the "
            "bench would time the wrong path")
        return 1
    once()
    lat_sg, lat_mesh = [], []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        once()
        lat_sg.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once_mesh()
        lat_mesh.append(time.perf_counter() - t0)
    med_sg = statistics.median(lat_sg)
    med_mesh = statistics.median(lat_mesh)
    mesh_delta = statistics.median(
        m - s for m, s in zip(lat_mesh, lat_sg))
    mesh_overhead = mesh_delta / med_sg
    log(f"mesh fabric scatter-gather {med_sg * 1e3:.2f} ms  "
        f"fused {med_mesh * 1e3:.2f} ms  paired delta "
        f"{mesh_delta * 1e6:+.0f} us ({mesh_overhead * 100:+.2f}%)")
    emit("mesh_fabric_overhead_median", mesh_overhead * 100, "%",
         scatter_ms=round(med_sg * 1e3, 3),
         fused_ms=round(med_mesh * 1e3, 3),
         paired_delta_us=round(mesh_delta * 1e6, 1))
    if mesh_overhead > 0.03 and mesh_delta > 5e-4:
        log(f"FAIL: mesh-fabric overhead {mesh_overhead * 100:.2f}% "
            f"exceeds the 3% budget")
        return 1

    # admission-control guard (ISSUE 5): the same loop routed through
    # the workload front door — deadline mint, index-priced cost
    # estimate, admit permit, calibration observe on release — vs the
    # bare loop.  Budget large enough that nothing is shed: this
    # measures the DECISION cost, not the shedding.
    from filodb_tpu.workload import deadline as wdl
    from filodb_tpu.workload.admission import AdmissionController
    from filodb_tpu.workload.cost import CostModel
    ctrl = AdmissionController(CostModel(), dataset="bench",
                               max_inflight_cost=1e12, workers=1)

    def once_admitted():
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = wdl.mint(QueryContext(
            submit_time_ms=int(time.time() * 1000)))
        ep = planner.materialize(lp, qctx)
        cost = ctrl.cost_model.estimate(ep, ms)
        with ctrl.admit(qctx, cost):
            res = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(res)

    # INTERLEAVED A/B: alternate bare and admitted iterations so host
    # drift (thermal, GC, page cache) hits both legs equally — the
    # ~25us decision cost would otherwise drown in between-leg noise
    once()
    once_admitted()
    lat_base, lat_adm = [], []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        once()
        lat_base.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once_admitted()
        lat_adm.append(time.perf_counter() - t0)
    med_base = statistics.median(lat_base)
    med_adm = statistics.median(lat_adm)
    p90_adm = sorted(lat_adm)[int(0.9 * len(lat_adm))]
    adm_overhead = (med_adm - med_base) / med_base
    log(f"admission off {med_base * 1e3:.2f} ms  "
        f"on {med_adm * 1e3:.2f} ms  overhead {adm_overhead * 100:+.2f}%")
    emit("admission_overhead_median", adm_overhead * 100, "%",
         off_ms=round(med_base * 1e3, 3), on_ms=round(med_adm * 1e3, 3),
         p90_on_ms=round(p90_adm * 1e3, 3))
    if adm_overhead > 0.03 and (med_adm - med_base) > 5e-4:
        log(f"FAIL: admission overhead {adm_overhead * 100:.2f}% exceeds "
            f"the 3% budget")
        return 1

    # data-plane observability guard (ISSUE 6): the same query loop
    # with the background data-plane services running hot — watermark
    # sampling (which takes shard/meta reads and refreshes the tenant
    # cardinality gauges each pass) and a self-scrape loop parsing the
    # full exposition into a gateway publisher — vs without them.  The
    # ingest-side churn notes are O(1) and off the query path; what
    # could tax serving is the samplers' lock traffic and CPU, so the
    # bench runs them far faster than production defaults (20 Hz / 10 Hz
    # vs one sample per 10 s) and still demands the ≤3% / 0.5 ms budget.
    from filodb_tpu.gateway.selfscrape import SelfScraper
    from filodb_tpu.gateway.server import ShardingPublisher
    from filodb_tpu.memstore.watermarks import (WatermarkLedger,
                                                WatermarkSampler)
    once()
    med_off2, p90_off2 = measure()
    ledger = WatermarkLedger(stall_window_s=3600.0, node="bench")
    ledger.watch("prom", ms, mapper=mapper,
                 end_offset_fn=lambda s: 10_000)
    sampler = WatermarkSampler(ledger, interval_s=0.05)
    pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], ShardMapper(1),
                            lambda s, c: None, spread=0)
    scraper = SelfScraper(pub, interval_s=0.1,
                          default_tags={"_ws_": "filodb", "_ns_": "bench"})
    sampler.start()
    scraper.start()
    try:
        once()
        med_on2, p90_on2 = measure()
    finally:
        sampler.stop()
        scraper.stop()
    dp_overhead = (med_on2 - med_off2) / med_off2
    log(f"dataplane off {med_off2 * 1e3:.2f} ms  "
        f"on {med_on2 * 1e3:.2f} ms  overhead {dp_overhead * 100:+.2f}%")
    emit("dataplane_overhead_median", dp_overhead * 100, "%",
         off_ms=round(med_off2 * 1e3, 3), on_ms=round(med_on2 * 1e3, 3),
         p90_off_ms=round(p90_off2 * 1e3, 3),
         p90_on_ms=round(p90_on2 * 1e3, 3))
    if dp_overhead > 0.03 and (med_on2 - med_off2) > 5e-4:
        log(f"FAIL: data-plane instrumentation overhead "
            f"{dp_overhead * 100:.2f}% exceeds the 3% budget")
        return 1

    # replication guard (ISSUE 7): rf=2 must be ~free when nothing is
    # failing.  (a) QUERY leg: the same loop with every shard routed
    # through a ReplicaDispatcher over an rf=2 group (local replica +
    # phantom peer) — measures ReplicaSet.pick + the failover wrapper
    # per leaf, interleaved A/B against the single-copy planner.
    # (b) INGEST leg: the gateway publisher dual-writing every container
    # through ReplicaFanout to two sinks vs one direct sink.
    from filodb_tpu.coordinator.dispatch import dispatcher_factory
    from filodb_tpu.gateway.server import ReplicaFanout
    rep_mapper = ShardMapper(num_shards, replication_factor=2)
    rep_mapper.register_node(range(num_shards), "local")
    rep_mapper.register_node(range(num_shards), "peer")
    for s in range(num_shards):
        rep_mapper.update_status(s, ShardStatus.ACTIVE, node="local")
        rep_mapper.update_status(s, ShardStatus.ACTIVE, node="peer")
    planner_rep = SingleClusterPlanner(
        "prom", rep_mapper, DatasetOptions(), spread_default=spread,
        dispatcher_for_shard=dispatcher_factory(rep_mapper, {},
                                                local_node="local"))

    def once_replicated():
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = planner_rep.materialize(lp, qctx)
        res = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(res)

    body = once_replicated()
    assert body["data"]["result"], "replicated routing returned nothing"
    once()
    lat_single, lat_rep = [], []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        once()
        lat_single.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once_replicated()
        lat_rep.append(time.perf_counter() - t0)
    med_single = statistics.median(lat_single)
    med_rep = statistics.median(lat_rep)
    rep_overhead = (med_rep - med_single) / med_single
    log(f"replica routing rf=1 {med_single * 1e3:.2f} ms  "
        f"rf=2 {med_rep * 1e3:.2f} ms  overhead {rep_overhead * 100:+.2f}%")
    emit("replication_query_overhead_median", rep_overhead * 100, "%",
         rf1_ms=round(med_single * 1e3, 3),
         rf2_ms=round(med_rep * 1e3, 3))
    if rep_overhead > 0.03 and (med_rep - med_single) > 5e-4:
        log(f"FAIL: replica-routing overhead {rep_overhead * 100:.2f}% "
            f"exceeds the 3% budget")
        return 1

    from filodb_tpu.gateway.server import ShardingPublisher as _SP

    def _sink(shard, container):
        pass   # delivery cost is the replica's own; the EDGE is timed

    pub_one = _SP(DEFAULT_SCHEMAS["gauge"], rep_mapper, _sink,
                  spread=spread)
    pub_two = _SP(DEFAULT_SCHEMAS["gauge"], rep_mapper,
                  ReplicaFanout("prom", rep_mapper,
                                {"local": _sink, "peer": _sink},
                                local_node="local"),
                  spread=spread)
    lines = "\n".join(
        f"bench_rep,host=h{i % 64} value={float(i)} "
        f"{(BASE + i) * 1_000_000}" for i in range(2000)) + "\n"

    def batch_once(pub):
        t0 = time.perf_counter()
        pub.ingest_influx_batch(lines)
        pub.flush()
        return time.perf_counter() - t0

    batch_once(pub_one)            # warm memos/plans both ways
    batch_once(pub_two)
    # INTERLEAVED A/B (like the admission leg): host drift hits both
    # arms equally — the per-container fanout cost is microseconds
    lat_w1, lat_w2 = [], []
    for _ in range(max(ITERS, 30)):
        lat_w1.append(batch_once(pub_one))
        lat_w2.append(batch_once(pub_two))
    med_w1 = statistics.median(lat_w1)
    med_w2 = statistics.median(lat_w2)
    w_overhead = (med_w2 - med_w1) / med_w1
    log(f"dual-write single {med_w1 * 1e3:.3f} ms  "
        f"rf=2 {med_w2 * 1e3:.3f} ms/batch  "
        f"overhead {w_overhead * 100:+.2f}%")
    emit("replication_dualwrite_overhead_median", w_overhead * 100, "%",
         single_ms=round(med_w1 * 1e3, 4), rf2_ms=round(med_w2 * 1e3, 4))
    # the absolute floor scales to THIS leg's sub-ms batches (a 0.5 ms
    # floor on a 0.4 ms batch could never fail) — 50 us tolerates
    # scheduler noise yet catches any real per-batch regression
    if w_overhead > 0.03 and (med_w2 - med_w1) > 5e-5:
        log(f"FAIL: dual-write overhead {w_overhead * 100:.2f}% exceeds "
            f"the 3% budget")
        return 1

    # elastic-resharding guard (ISSUE 13): the same query loop with a
    # CATCH-UP SPLIT permanently in flight — children registered as
    # Recovery replicas, topology generation bumped, every materialize
    # snapshotting the topology and checking parent exclusions (None
    # until cutover).  A/B interleave against the plain mapper; the
    # split must be invisible to serving until it commits.
    split_mapper = ShardMapper(num_shards)
    split_mapper.register_node(range(num_shards), "local")
    for s in range(num_shards):
        split_mapper.update_status(s, ShardStatus.ACTIVE)
    split_mapper.begin_split(spread=spread)
    for parent in range(num_shards):
        split_mapper.register_split_child(parent + num_shards, ["local"])
    planner_split = SingleClusterPlanner("prom", split_mapper,
                                         DatasetOptions(),
                                         spread_default=spread)

    def once_split():
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = planner_split.materialize(lp, qctx)
        res = ep.execute(ExecContext(ms, qctx))
        return to_prom_matrix(res)

    body = once_split()
    assert body["data"]["result"], "split-in-flight routing lost data"
    once()
    lat_plain, lat_split = [], []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        once()
        lat_plain.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once_split()
        lat_split.append(time.perf_counter() - t0)
    med_plain = statistics.median(lat_plain)
    med_split = statistics.median(lat_split)
    sp_overhead = (med_split - med_plain) / med_plain
    log(f"split-in-flight plain {med_plain * 1e3:.2f} ms  "
        f"catchup {med_split * 1e3:.2f} ms  "
        f"overhead {sp_overhead * 100:+.2f}%")
    emit("split_catchup_overhead_median", sp_overhead * 100, "%",
         plain_ms=round(med_plain * 1e3, 3),
         catchup_ms=round(med_split * 1e3, 3))
    if sp_overhead > 0.03 and (med_split - med_plain) > 5e-4:
        log(f"FAIL: split-in-flight overhead {sp_overhead * 100:.2f}% "
            f"exceeds the 3% budget")
        return 1

    # rule-engine guard (ISSUE 9): a LIVE rule group ticking at high
    # frequency (250 ms vs the 15 s production default — 60x) against
    # the same query loop.  The group carries an incremental windowed
    # recording rule and a full-path alerting rule, evaluates through
    # the normal planner -> admission -> scheduler path under the
    # dedicated "rules" class, and writes back through a gateway
    # publisher.  A/B/A interleave (off, on, off) cancels host drift;
    # continuous evaluation must cost the query loop <=3% / 0.5 ms.
    # (Each full-path eval costs ~5 ms of GIL — the query fabric's own
    # scatter-gather thread spawn, not engine bloat — so cadence is the
    # honest lever: at 4 Hz the steal budget is ~2%.)
    from filodb_tpu.rules.config import parse_rule_config
    from filodb_tpu.rules.engine import RuleEngine

    class _RuleBinding:
        pass

    # REAL cost-model admission under the "rules" class: the budget
    # must cover the pricing + share arithmetic that guards starvation,
    # not just plan+execute.  The scheduler HOP is deliberately not
    # wired here: this bench's foreground loop runs inline (all legs
    # do, for low variance), and a background pool hop convoys that
    # single CPU-bound thread on GIL handoffs (+15% measured at 4 Hz)
    # — an artifact of the bench topology, not the engine: with the
    # foreground itself pool-scheduled as in the real server, the
    # engine's marginal cost measures below noise (-1.6 ms observed).
    rbind = _RuleBinding()
    rbind.dataset = "prom"
    rbind.memstore = ms
    rbind.planner = planner
    rbind.scheduler = None
    rbind.admission = AdmissionController(CostModel(), dataset="prom",
                                          max_inflight_cost=1e12,
                                          workers=2)
    rule_pub = _SP(DEFAULT_SCHEMAS["gauge"], ShardMapper(1),
                   lambda s, c: None, spread=0)
    rule_groups, rule_errs = parse_rule_config({"groups": [{
        "name": "bench-rules", "interval": "250ms", "dataset": "prom",
        "rules": [
            {"record": "bench:ovh:rate",
             "expr": 'rate(ovh_total{instance=~"i[0-7]"}[2m])'},
            {"alert": "BenchHot",
             "expr": 'sum(rate(ovh_total{instance="i0"}[2m])) > 0',
             "for": "1s"},
        ]}]})
    assert not rule_errs, rule_errs
    eng = RuleEngine(rule_groups, binding_for=lambda d: rbind,
                     publisher_for=lambda d: rule_pub,
                     default_dataset="prom")
    assert eng._groups[0].rules[0].incremental is not None
    once()
    med_r_off1, _p = measure()
    eng.run_group_once("bench-rules")   # warm kernels + window state
    eng.start()
    try:
        once()
        med_r_on, p90_r_on = measure()
    finally:
        eng.stop()
        rbind.admission.shutdown()
    once()
    med_r_off2, _p = measure()
    med_r_off = (med_r_off1 + med_r_off2) / 2
    r_overhead = (med_r_on - med_r_off) / med_r_off
    log(f"rule engine off {med_r_off * 1e3:.2f} ms  "
        f"on {med_r_on * 1e3:.2f} ms  overhead {r_overhead * 100:+.2f}%")
    emit("rule_engine_overhead_median", r_overhead * 100, "%",
         off_ms=round(med_r_off * 1e3, 3), on_ms=round(med_r_on * 1e3, 3),
         p90_on_ms=round(p90_r_on * 1e3, 3))
    if r_overhead > 0.03 and (med_r_on - med_r_off) > 5e-4:
        log(f"FAIL: rule-engine overhead {r_overhead * 100:.2f}% "
            f"exceeds the 3% budget")
        return 1

    # rollup guard (ISSUE 11): a LIVE rollup engine tiering a separate
    # dataset at accelerated cadence (500 ms ticks vs the 30 s
    # production default — 60x) against the query loop, with a feeder
    # thread ingesting + flushing fresh chunks at the same cadence so
    # every tick does REAL consume->grid-reduce->emit work.  A/B/A
    # interleave (off, on, off) cancels host drift; continuous tiering
    # must cost the query loop <=3% / 0.5 ms.  (At 4 Hz tick+feed the
    # same leg measured +6% — like the rule-engine leg, cadence is the
    # honest lever; the GIL steal is the feeder+engine's own CPU, not
    # per-query overhead.)
    from filodb_tpu.downsample.dsstore import ds_dataset_name
    from filodb_tpu.rollup.config import RollupConfig
    from filodb_tpu.rollup.engine import RollupEngine
    from filodb_tpu.utils.observability import PeriodicThread
    RRES = (60_000, 900_000)
    rms = TimeSeriesMemStore()
    rshard = rms.setup("rollup_src", DEFAULT_SCHEMAS, 0)
    for r in RRES:
        rms.setup(ds_dataset_name("rollup_src", r), DEFAULT_SCHEMAS, 0)
    roff: dict = {}

    def _rpub(r):
        rname = ds_dataset_name("rollup_src", r)

        def pub(s, c):
            o = roff.get((rname, s), -1) + 1
            roff[(rname, s)] = o
            rms.ingest(rname, s, c, o)
        return pub

    reng = RollupEngine("bench")
    reng.watch("rollup_src", rms, DEFAULT_SCHEMAS,
               RollupConfig(resolutions_ms=RRES, tick_interval_s=0.5,
                            idle_close_s=None),
               {r: _rpub(r) for r in RRES})
    feed_rng = np.random.default_rng(123)
    feed_state = {"t": BASE, "off": 0}
    feed_tags = [{"__name__": "rs", "inst": f"i{i}", "_ws_": "w",
                  "_ns_": "n"} for i in range(32)]

    def feed():
        fb = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
        t0f = feed_state["t"]
        feed_state["t"] = t0f + 60_000
        ts_f = t0f + np.arange(0, 60_000, 5_000, dtype=np.int64) + 1
        for tg in feed_tags:
            fb.add_series(ts_f, [feed_rng.normal(50, 5, len(ts_f))], tg)
        for c in fb.containers():
            rms.ingest("rollup_src", 0, c, feed_state["off"])
            feed_state["off"] += 1
        rshard.flush_all(ingestion_time=feed_state["off"])

    once()
    med_ro_off1, _p = measure()
    feed()
    reng.run_once("rollup_src")      # warm the reduce kernels
    feeder = PeriodicThread(feed, 0.5, "bench-rollup-feed")
    feeder.start()
    reng.start()
    try:
        once()
        med_ro_on, p90_ro_on = measure()
    finally:
        reng.stop()
        feeder.stop()
    once()
    med_ro_off2, _p = measure()
    med_ro_off = (med_ro_off1 + med_ro_off2) / 2
    ro_overhead = (med_ro_on - med_ro_off) / med_ro_off
    log(f"rollup engine off {med_ro_off * 1e3:.2f} ms  "
        f"on {med_ro_on * 1e3:.2f} ms  overhead {ro_overhead * 100:+.2f}%")
    emit("rollup_overhead_median", ro_overhead * 100, "%",
         off_ms=round(med_ro_off * 1e3, 3),
         on_ms=round(med_ro_on * 1e3, 3),
         p90_on_ms=round(p90_ro_on * 1e3, 3))
    if ro_overhead > 0.03 and (med_ro_on - med_ro_off) > 5e-4:
        log(f"FAIL: rollup overhead {ro_overhead * 100:.2f}% "
            f"exceeds the 3% budget")
        return 1

    # result-cache guard (ISSUE 12, query/resultcache.py).  Two legs:
    # (a) MISS path: a stream of NEVER-REPEATING queries through the
    #     cached planner — the production worst case.  The doorkeeper
    #     admission keeps it to one fingerprint+set probe per query
    #     (first sight never splits/digests/stores), interleaved A/B
    #     against the bare planner under the same <=3% / 0.5 ms budget.
    #     The store is flushed first so segments would otherwise
    #     qualify (an all-open range short-circuits anyway).
    # (b) HIT path (the dashboard-refresh shape): the same query
    #     repeated against a warm cache — only the partial head/tail
    #     segments recompute.  Records the hit-path speedup and
    #     ASSERTS the >=10x samples-scanned reduction (the ISSUE 12
    #     acceptance bar); exits nonzero below it.
    from filodb_tpu.query.resultcache import (ResultCache,
                                              ResultCachingPlanner)
    for sh in ms.shards("prom"):
        sh.flush_all()
    # segment = 2 min over the 40-min query: the partial head/tail
    # segments re-scan ~13 of 241 steps on a warm refresh — the same
    # ~5% coverage fraction a 24h dashboard gets from 1h segments
    rc_cache = ResultCache("prom", enabled=True, max_bytes=256 << 20)
    rc_planner = ResultCachingPlanner(
        "prom", SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=spread),
        ms, rc_cache, segment_ms=120_000,
        routing_token_fn=mapper.routing_token)

    def q_unique(i):
        # unique fingerprint per iteration, identical result set
        return (f'sum(rate(ovh_total{{_ws_="demo",_ns_="App-0",'
                f'instance!~"zz{i}"}}[2m]))')

    def run_query(planner_, q):
        lp = query_range_to_logical_plan(q, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = planner_.materialize(lp, qctx)
        return ep.execute(ExecContext(ms, qctx))

    run_query(planner, q_unique(-1))         # re-warm on flushed chunks
    run_query(rc_planner, q_unique(-2))
    lat_bare, lat_miss = [], []
    for i in range(ITERS):
        t0 = time.perf_counter()
        run_query(planner, q_unique(i))
        lat_bare.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_query(rc_planner, q_unique(1000 + i))
        lat_miss.append(time.perf_counter() - t0)
    med_bare = statistics.median(lat_bare)
    med_miss = statistics.median(lat_miss)
    # the iterations are PAIRED (each bare run has an adjacent miss
    # run), so the median of per-pair deltas is the drift-robust
    # estimator — difference-of-medians reads host drift between the
    # interleaved halves as overhead (measured ±0.8 ms on an idle run)
    rc_delta = statistics.median(
        m - b for m, b in zip(lat_miss, lat_bare))
    rc_overhead = rc_delta / med_bare
    log(f"result-cache miss path: bare {med_bare * 1e3:.2f} ms  "
        f"miss {med_miss * 1e3:.2f} ms  paired delta "
        f"{rc_delta * 1e6:+.0f} us ({rc_overhead * 100:+.2f}%)")
    emit("resultcache_miss_overhead_median", rc_overhead * 100, "%",
         bare_ms=round(med_bare * 1e3, 3),
         miss_ms=round(med_miss * 1e3, 3),
         paired_delta_us=round(rc_delta * 1e6, 1))
    if rc_overhead > 0.03 and rc_delta > 5e-4:
        log(f"FAIL: result-cache miss-path overhead "
            f"{rc_overhead * 100:.2f}% exceeds the 3% budget")
        return 1

    run_query(rc_planner, query)             # sight 1: doorkeeper only
    cold_res = run_query(rc_planner, query)  # sight 2: split + store
    cold_scanned = cold_res.stats.samples_scanned
    lat_hit = []
    warm_res = None
    for _ in range(ITERS):
        t0 = time.perf_counter()
        warm_res = run_query(rc_planner, query)
        lat_hit.append(time.perf_counter() - t0)
    warm_scanned = warm_res.stats.samples_scanned
    med_hit = statistics.median(lat_hit)
    speedup = med_bare / med_hit if med_hit > 0 else float("inf")
    scan_ratio = cold_scanned / max(warm_scanned, 1)
    log(f"result-cache hit path: {med_hit * 1e3:.2f} ms "
        f"({speedup:.1f}x vs bare)  samples scanned "
        f"{cold_scanned} -> {warm_scanned} ({scan_ratio:.0f}x fewer)  "
        f"cached={warm_res.stats.resultcache_cached_samples}")
    emit("resultcache_hit_speedup", speedup, "x",
         hit_ms=round(med_hit * 1e3, 3),
         cold_samples=int(cold_scanned),
         warm_samples=int(warm_scanned),
         scan_reduction_x=round(scan_ratio, 1))
    if warm_scanned * 10 > cold_scanned:
        log(f"FAIL: warm re-scan {warm_scanned} samples is not >=10x "
            f"below the cold scan {cold_scanned}")
        return 1

    # cold-tier guard (ISSUE 16, filodb_tpu/coldstore): a flushed
    # dataset is re-opened per iteration (recover_index + ODP page-in
    # of every chunk) against the bare DiskColumnStore vs the SAME
    # store wrapped in TieredColumnStore over an EMPTY bucket — the
    # steady state before anything ages out.  The wrapper's extra
    # bucket probe + two-tier merge must be free when there are no
    # cold misses: <=3% / 0.5 ms, interleaved A/B, paired-delta.
    import tempfile
    from filodb_tpu.coldstore import (ColdChunkStore, LocalFSBucket,
                                      TieredColumnStore)
    from filodb_tpu.core.storeconfig import StoreConfig
    from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

    tmp = tempfile.mkdtemp(prefix="filodb-bench-cold-")
    disk = DiskColumnStore(os.path.join(tmp, "chunks.db"))
    meta_store = DiskMetaStore(os.path.join(tmp, "meta.db"))
    cms = TimeSeriesMemStore(disk, meta_store)
    csh = cms.setup("cold", DEFAULT_SCHEMAS, 0, StoreConfig())
    cb = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    cts = BASE + np.arange(240, dtype=np.int64) * STEP
    for i in range(32):
        cb.add_series(cts, [rng.random(240) + i],
                      {"_metric_": "cold_g", "inst": f"i{i}",
                       "_ws_": "demo", "_ns_": "App-0"})
    for off, c in enumerate(cb.containers()):
        csh.ingest_container(c, off)
    csh.flush_all(ingestion_time=1000)
    tiered = TieredColumnStore(
        disk, ColdChunkStore(LocalFSBucket(os.path.join(tmp, "bucket"))))
    cold_mapper = ShardMapper(1)
    cold_mapper.register_node(range(1), "local")
    cold_mapper.update_status(0, ShardStatus.ACTIVE)
    cold_planner = SingleClusterPlanner("cold", cold_mapper,
                                        DatasetOptions(), spread_default=0)
    cq = 'cold_g{_ws_="demo",_ns_="App-0"}'
    c_start, c_end = int(cts[0]), int(cts[-1])

    def once_cold(colstore):
        fresh = TimeSeriesMemStore(colstore, meta_store)
        fresh.setup("cold", DEFAULT_SCHEMAS, 0, StoreConfig())
        fresh.recover_index("cold", 0)
        lp = query_range_to_logical_plan(cq, c_start, STEP, c_end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        ep = cold_planner.materialize(lp, qctx)
        return ep.execute(ExecContext(fresh, qctx))

    assert to_prom_matrix(once_cold(disk))["data"]["result"], \
        "cold-tier bench query returned nothing"
    once_cold(tiered)                          # warm sqlite page cache
    lat_loc, lat_tier = [], []
    for _ in range(min(ITERS, 30)):
        t0 = time.perf_counter()
        once_cold(disk)
        lat_loc.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        once_cold(tiered)
        lat_tier.append(time.perf_counter() - t0)
    med_loc = statistics.median(lat_loc)
    med_tier = statistics.median(lat_tier)
    ct_delta = statistics.median(
        t - l for t, l in zip(lat_tier, lat_loc))
    ct_overhead = ct_delta / med_loc
    log(f"cold tier hot path: local {med_loc * 1e3:.2f} ms  "
        f"tiered {med_tier * 1e3:.2f} ms  paired delta "
        f"{ct_delta * 1e6:+.0f} us ({ct_overhead * 100:+.2f}%)")
    emit("coldtier_hot_path_overhead_median", ct_overhead * 100, "%",
         local_ms=round(med_loc * 1e3, 3),
         tiered_ms=round(med_tier * 1e3, 3),
         paired_delta_us=round(ct_delta * 1e6, 1))
    if ct_overhead > 0.03 and ct_delta > 5e-4:
        log(f"FAIL: cold-tier hot-path overhead "
            f"{ct_overhead * 100:.2f}% exceeds the 3% budget")
        return 1

    # year-long panel (ISSUE 16 acceptance): 1 series x 1y @30s through
    # the M4 ?downsample=4096 mapper — a 4k panel gets <= 4*4096
    # pixel-exact points, >=50x fewer samples on the wire; exits
    # nonzero below the bar.
    from filodb_tpu.ops.windows import StepRange
    from filodb_tpu.query.model import PeriodicBatch
    from filodb_tpu.query.transformers import DownsampleMapper
    year_t = 365 * 24 * 3600 // 30             # 1,051,200 samples
    yvals = rng.normal(10, 3, (1, year_t))
    yvals[:, ::97] = np.nan                    # sprinkle gaps
    yb = PeriodicBatch([{"inst": "i0"}],
                       StepRange(BASE, BASE + (year_t - 1) * 30_000,
                                 30_000), yvals)
    t0 = time.perf_counter()
    [yout] = DownsampleMapper(pixels=4096).apply([yb], None)
    m4_ms = (time.perf_counter() - t0) * 1e3
    pts_in = int(np.isfinite(yvals).sum())
    pts_out = int(np.isfinite(yout.np_values()).sum())
    reduction = pts_in / max(pts_out, 1)
    log(f"m4 year panel: {pts_in} -> {pts_out} points "
        f"({reduction:.0f}x) in {m4_ms:.1f} ms")
    emit("m4_year_panel_reduction", reduction, "x",
         points_in=pts_in, points_out=pts_out, pixels=4096,
         mapper_ms=round(m4_ms, 1))
    if pts_out > 4 * 4096 or reduction < 50:
        log(f"FAIL: m4 year panel kept {pts_out} points "
            f"({reduction:.0f}x) — below the 50x bar")
        return 1

    # fleet-batching guard (ISSUE 20): the same loop with a live
    # QueryBatcher attached to every shard.  The bench is SEQUENTIAL —
    # one query in flight at a time — so every dispatch is a cold-key
    # passthrough: the batcher's whole cost at concurrency=1 is one
    # lock round-trip + inflight bookkeeping per device dispatch, and
    # a lone query must never wait out a co-arrival window.  A/B
    # interleaved under the same <=3% / 0.5 ms budget.
    from filodb_tpu.batching import QueryBatcher, reset_batch_breaker
    reset_batch_breaker()
    bat = QueryBatcher(enabled=True, window_ms=3.0, max_batch=8,
                       dataset="prom")
    bat_shards = list(ms.shards("prom"))
    try:
        for sh in bat_shards:
            sh.query_batcher = bat
        once()
        lat_nobat, lat_bat = [], []
        for _ in range(ITERS):
            for sh in bat_shards:
                sh.query_batcher = None
            t0 = time.perf_counter()
            once()
            lat_nobat.append(time.perf_counter() - t0)
            for sh in bat_shards:
                sh.query_batcher = bat
            t0 = time.perf_counter()
            once()
            lat_bat.append(time.perf_counter() - t0)
    finally:
        for sh in bat_shards:
            sh.query_batcher = None
    med_nobat = statistics.median(lat_nobat)
    med_bat = statistics.median(lat_bat)
    bat_delta = statistics.median(
        b - n for b, n in zip(lat_bat, lat_nobat))
    bat_overhead = bat_delta / med_nobat
    log(f"batching off {med_nobat * 1e3:.2f} ms  "
        f"on {med_bat * 1e3:.2f} ms  paired delta "
        f"{bat_delta * 1e6:+.0f} us ({bat_overhead * 100:+.2f}%)")
    emit("batching_overhead_median", bat_overhead * 100, "%",
         off_ms=round(med_nobat * 1e3, 3), on_ms=round(med_bat * 1e3, 3),
         paired_delta_us=round(bat_delta * 1e6, 1))
    if bat_overhead > 0.03 and bat_delta > 5e-4:
        log(f"FAIL: query-batching single-stream overhead "
            f"{bat_overhead * 100:.2f}% exceeds the 3% budget")
        return 1
    if bat.snapshot()["realized_peak"] > 0:
        log("FAIL: sequential bench formed a batch group — the "
            "co-arrival gate is waiting on lone queries")
        return 1

    # fleet-insights guard (ISSUE 19): the same loop with the full
    # per-query insights accounting the server does in _exec /
    # _note_insight — plan_keys (canonical fingerprint + batch key),
    # co-arrival note, the ledger fold, and an SLO tracker observe —
    # vs the bare loop, interleaved A/B under the same <=3% / 0.5 ms
    # budget.  Workload analytics must be free at serving cadence.
    from filodb_tpu.insights.ledger import WorkloadLedger, plan_keys
    from filodb_tpu.insights.slo import SloObjective, SloTracker
    ins = WorkloadLedger(node="bench")
    slo = SloTracker([SloObjective(name="bench", latency_threshold_s=1.0,
                                   target=0.999)], node="bench")

    def once_insighted():
        t_in = time.perf_counter()
        lp = query_range_to_logical_plan(query, start, STEP, end)
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        fp, bk = plan_keys("prom", lp, query)
        ins.note_arrival(bk)
        ep = planner.materialize(lp, qctx)
        res = ep.execute(ExecContext(ms, qctx))
        out = to_prom_matrix(res)
        took = time.perf_counter() - t_in
        ins.note(fp, query=query, dataset="prom", tenant="bench",
                 latency_s=took, samples=res.stats.samples_scanned,
                 resultcache="miss", batch_key=bk)
        slo.observe("bench", "default", took)
        return out

    try:
        once()
        once_insighted()
        lat_bare, lat_ins = [], []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            once()
            lat_bare.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            once_insighted()
            lat_ins.append(time.perf_counter() - t0)
    finally:
        slo.close()
    med_bare = statistics.median(lat_bare)
    med_ins = statistics.median(lat_ins)
    ins_delta = statistics.median(
        i - b for i, b in zip(lat_ins, lat_bare))
    ins_overhead = ins_delta / med_bare
    log(f"insights off {med_bare * 1e3:.2f} ms  "
        f"on {med_ins * 1e3:.2f} ms  paired delta "
        f"{ins_delta * 1e6:+.0f} us ({ins_overhead * 100:+.2f}%)")
    emit("insights_overhead_median", ins_overhead * 100, "%",
         off_ms=round(med_bare * 1e3, 3), on_ms=round(med_ins * 1e3, 3),
         paired_delta_us=round(ins_delta * 1e6, 1),
         fingerprints=ins.fingerprints())
    if ins_overhead > 0.03 and ins_delta > 5e-4:
        log(f"FAIL: insights/SLO accounting overhead "
            f"{ins_overhead * 100:.2f}% exceeds the 3% budget")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
