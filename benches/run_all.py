"""Run every bench as a subprocess; aggregate their JSON lines.

Reference analog: the jmh runner (README.md:878-897)."""

import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent


def main() -> int:
    ok = True
    for bench in sorted(HERE.glob("bench_*.py")):
        print(f"=== {bench.name} ===", file=sys.stderr, flush=True)
        proc = subprocess.run([sys.executable, str(bench)], timeout=600)
        ok = ok and proc.returncode == 0
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
