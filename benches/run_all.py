"""Run every bench as a subprocess; aggregate their JSON lines.

Reference analog: the jmh runner (README.md:878-897)."""

import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent


def main() -> int:
    ok = True
    for bench in sorted(HERE.glob("bench_*.py")):
        print(f"=== {bench.name} ===", file=sys.stderr, flush=True)
        try:
            # bench_resident ingests a 24h x 100k-series working set in
            # Python before it measures — give it headroom; a timeout
            # must fail THAT bench, not abort the rest of the suite
            proc = subprocess.run([sys.executable, str(bench)],
                                  timeout=1800)
            ok = ok and proc.returncode == 0
        except subprocess.TimeoutExpired:
            print(f"=== {bench.name} TIMED OUT ===", file=sys.stderr,
                  flush=True)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
