"""Compressed HBM residents: a 24 h x 100k-series dashboard served FULLY
resident (round-5 VERDICT #4).

The reference's defining trick is serving compressed BinaryVectors in
place from bounded block memory (memory/BlockManager.scala:142,
doc/compression.md).  Here grid blocks hold XOR-class value planes and
elide uniform-phase ts planes; the serving program decodes them ON
DEVICE.  This bench stages a full day of minutely integer-valued gauges
for >=100k series, asserts the whole window is resident (no rebuilds on
repeat queries), and reports resident bytes/sample + the window
multiplier vs the decoded layout.

Env: FILODB_RES_SERIES (default 102400), FILODB_RES_HOURS (default 24),
FILODB_RES_BACKEND=tpu to serve from the real device (default: CPU so
the staging ingest never holds the shared tunnel).
"""

import os
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, force_cpu_x64, log, timed  # noqa: E402

if os.environ.get("FILODB_RES_BACKEND") != "tpu":
    force_cpu_x64()

from filodb_tpu.core.filters import ColumnFilter, Equals  # noqa: E402
from filodb_tpu.core.record import RecordBuilder  # noqa: E402
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions  # noqa: E402
from filodb_tpu.core.storeconfig import StoreConfig  # noqa: E402
from filodb_tpu.memstore.devicestore import BLOCK_BUCKETS  # noqa: E402
from filodb_tpu.memstore.memstore import TimeSeriesMemStore  # noqa: E402
from filodb_tpu.query.logical import RangeFunctionId as F  # noqa: E402

N_SERIES = int(os.environ.get("FILODB_RES_SERIES", 102_400))
HOURS = int(os.environ.get("FILODB_RES_HOURS", 24))
STEP = 60_000
BASE = 1_700_000_040_000
N_ROWS = HOURS * 60
WINDOW = 300_000
K = WINDOW // STEP


def main():
    store = TimeSeriesMemStore()
    cfg = StoreConfig(grid_step_ms=STEP, max_chunks_size=N_ROWS,
                      device_cache_bytes=8 << 30,
                      max_data_per_shard_query=1 << 40)
    sh = store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                      container_size=8 << 20)
    rng = np.random.default_rng(0)
    ts = BASE + np.arange(N_ROWS, dtype=np.int64) * STEP
    log(f"ingesting {N_SERIES} series x {N_ROWS} rows "
        f"({N_SERIES * N_ROWS / 1e6:.0f}M samples)...")
    for i in range(N_SERIES):
        # integer-valued gauge walk (bytes/requests/connections — the
        # common production shape)
        vals = (1_000_000
                + np.cumsum(rng.integers(-500, 500, size=N_ROWS))
                ).astype(np.float64)
        b.add_series(ts, [vals],
                     {"_metric_": "res_dash", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"})
        if (i + 1) % (1 << 14) == 0:
            for off, c in enumerate(b.containers()):
                sh.ingest_container(c, off)
            log(f"  {i + 1}/{N_SERIES}")
    for off, c in enumerate(b.containers()):
        sh.ingest_container(c, off)
    sh.flush_all(ingestion_time=1000)

    res = sh.lookup_partitions([ColumnFilter("_metric_", Equals("res_dash"))],
                               0, 2**62)
    assert len(res.part_ids) == N_SERIES
    steps0 = BASE + (K + 1) * STEP
    nsteps = N_ROWS - K - 2
    gids = [0] * N_SERIES

    # the TPU grid serves <=1024 input rows per program (VMEM tile
    # bound, ops/grid.py MAX_GRID_ROWS); a full day at 1-min cadence is
    # 1440 rows, which the query layer time-splits.  Serve the window
    # as panel queries the way the planner would — every panel must hit
    # the SAME resident blocks with zero rebuilds.
    panel = min(nsteps, 1024 - K)
    panels = []
    s = 0
    while s < nsteps:
        n = min(panel, nsteps - s)
        panels.append((steps0 + s * STEP, n))
        s += n

    def serve():
        outs = []
        for st0, n in panels:
            got = sh.scan_grid_grouped(res.part_ids, F.RATE, st0, n,
                                       STEP, WINDOW, gids, 1, "sum")
            assert got is not None, "dashboard fell off the resident path"
            outs.append(got)
        return outs

    serve()                                    # stage + compile
    cache = next(iter(sh.device_caches.values()))
    builds = cache.builds
    t = timed(serve, reps=3)
    assert cache.builds == builds, "repeat queries rebuilt blocks"
    assert cache.evictions == 0, "window did not fit the budget"

    resident = sum(blk.nbytes for blk in cache.blocks.values())
    raw_cells = sum(BLOCK_BUCKETS * blk.width
                    for blk in cache.blocks.values())
    decoded_layout = raw_cells * (4 + 8)       # int32 ts + f64 vals
    samples = N_SERIES * N_ROWS
    total = N_SERIES * (nsteps - 1 + K)
    emit("resident dashboard serve (24h window, fully resident)",
         total / t, "samples/sec", series=N_SERIES, hours=HOURS)
    emit("resident HBM bytes per sample", resident / samples, "bytes",
         resident_mb=round(resident / 2**20, 1))
    emit("resident window multiplier vs decoded layout",
         decoded_layout / resident, "x",
         note="ts plane elided + XOR-class value planes")


if __name__ == "__main__":
    main()
