"""Part-key tag index ops: add / filter lookup / label values at 1M keys.

Reference analog: jmh/.../PartKeyIndexBenchmark.scala:20 (Lucene index
ops/sec).  VERDICT r2 do-this #4 targets: >=1e5 equals-lookups/s,
>=1e4 regex/s at 1M keys; COLD 1M-series dashboard lookup < 10 ms."""

import os
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, timed  # noqa: E402

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex  # noqa: E402
from filodb_tpu.core.record import canonical_partkey  # noqa: E402
from filodb_tpu.memstore.index import PartKeyIndex  # noqa: E402

N = int(os.environ.get("FILODB_BENCH_INDEX_KEYS", 1_000_000))


def main():
    tag_sets = [{"_metric_": f"metric_{i % 100}", "instance": f"i{i}",
                 "host": f"h{i % 500}", "_ws_": "w", "_ns_": f"ns{i % 8}"}
                for i in range(N)]
    pks = [canonical_partkey(t) for t in tag_sets]

    def build():
        idx = PartKeyIndex()
        for pid, (pk, tags) in enumerate(zip(pks, tag_sets)):
            idx.add_partkey(pid, pk, tags, start_time=pid)
        return idx

    t_add = timed(build, reps=1)
    emit("index add_partkey", N / t_add, "keys/sec", keys=N)

    # COLD dashboard lookup: fresh index, first filter ever (pays the
    # posting materialization) — the reference bar is Lucene's cold seek
    idx = build()
    eq = [ColumnFilter("_metric_", Equals("metric_42"))]
    t_cold = timed(lambda: idx.part_ids_from_filters(eq, 0, 2**62), reps=1)
    emit("index cold equals lookup", t_cold * 1000, "ms", keys=N)

    n_eq = len(idx.part_ids_from_filters(eq, 0, 2**62))
    t_eq = timed(lambda: idx.part_ids_from_filters(eq, 0, 2**62), reps=5)
    emit("index equals lookup (wide)", 1.0 / t_eq, "lookups/sec",
         matched=n_eq)

    # narrow lookup: one series out of N (the alerting shape)
    nr = [ColumnFilter("instance", Equals(f"i{N * 3 // 4}"))]
    t_nr = timed(lambda: idx.part_ids_from_filters(nr, 0, 2**62), reps=5)
    emit("index equals lookup (narrow)", 1.0 / t_nr, "lookups/sec",
         matched=len(idx.part_ids_from_filters(nr, 0, 2**62)))

    # two-filter intersection (the dashboard shape: metric AND namespace)
    eq2 = eq + [ColumnFilter("_ns_", Equals("ns2"))]
    t_eq2 = timed(lambda: idx.part_ids_from_filters(eq2, 0, 2**62), reps=5)
    emit("index equals+equals lookup", 1.0 / t_eq2, "lookups/sec",
         matched=len(idx.part_ids_from_filters(eq2, 0, 2**62)))

    rx = [ColumnFilter("host", EqualsRegex("h1.?"))]
    t_rx_cold = timed(lambda: idx.part_ids_from_filters(rx, 0, 2**62),
                      reps=1)
    emit("index cold regex lookup", t_rx_cold * 1000, "ms")
    t_rx = timed(lambda: idx.part_ids_from_filters(rx, 0, 2**62), reps=5)
    emit("index regex lookup", 1.0 / t_rx, "lookups/sec")

    # the reference benchmark's 4-filter shape (PartKeyIndexBenchmark
    # partIdsLookupWithSuffixRegexFilters): equals x3 + regex
    ref4 = [ColumnFilter("_ns_", Equals("ns2")),
            ColumnFilter("_ws_", Equals("w")),
            ColumnFilter("_metric_", Equals("metric_42")),
            ColumnFilter("host", EqualsRegex("h1.*"))]
    t_ref = timed(lambda: idx.part_ids_from_filters(ref4, 0, 2**62), reps=5)
    emit("index equals x3 + regex lookup", 1.0 / t_ref, "lookups/sec",
         matched=len(idx.part_ids_from_filters(ref4, 0, 2**62)))

    t_lv = timed(lambda: idx.label_values("host", (), 0, 2**62), reps=5)
    emit("index label_values", 1.0 / t_lv, "ops/sec")


if __name__ == "__main__":
    main()
