"""Part-key tag index ops: add / filter lookup / label values.

Reference analog: jmh/.../PartKeyIndexBenchmark.scala:20 (Lucene index
ops/sec)."""

import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, timed  # noqa: E402

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex  # noqa: E402
from filodb_tpu.core.record import canonical_partkey  # noqa: E402
from filodb_tpu.memstore.index import PartKeyIndex  # noqa: E402

N = 50_000


def main():
    tag_sets = [{"_metric_": f"metric_{i % 100}", "instance": f"i{i}",
                 "host": f"h{i % 500}", "_ws_": "w", "_ns_": f"ns{i % 8}"}
                for i in range(N)]
    pks = [canonical_partkey(t) for t in tag_sets]

    def build():
        idx = PartKeyIndex()
        for pid, (pk, tags) in enumerate(zip(pks, tag_sets)):
            idx.add_partkey(pid, pk, tags, start_time=pid)
        return idx

    t_add = timed(build)
    emit("index add_partkey", N / t_add, "keys/sec")

    idx = build()
    eq = [ColumnFilter("_metric_", Equals("metric_42"))]
    t_eq = timed(lambda: idx.part_ids_from_filters(eq, 0, 2**62), reps=5)
    n_eq = len(idx.part_ids_from_filters(eq, 0, 2**62))
    emit("index equals lookup", 1.0 / t_eq, "lookups/sec", matched=n_eq)

    rx = [ColumnFilter("host", EqualsRegex("h1.?"))]
    t_rx = timed(lambda: idx.part_ids_from_filters(rx, 0, 2**62), reps=5)
    emit("index regex lookup", 1.0 / t_rx, "lookups/sec")

    t_lv = timed(lambda: idx.label_values("host", (), 0, 2**62), reps=5)
    emit("index label_values", 1.0 / t_lv, "ops/sec")


if __name__ == "__main__":
    main()
