"""Part-key tag index ops: add / filter lookup / label values at 1M keys.

Reference analog: jmh/.../PartKeyIndexBenchmark.scala:20 (Lucene index
ops/sec).  VERDICT r2 do-this #4 targets: >=1e5 equals-lookups/s,
>=1e4 regex/s at 1M keys; COLD 1M-series dashboard lookup < 10 ms."""

import os
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402

from benches.common import emit, timed  # noqa: E402

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex  # noqa: E402
from filodb_tpu.core.record import canonical_partkey  # noqa: E402
from filodb_tpu.memstore.index import PartKeyIndex  # noqa: E402

N = int(os.environ.get("FILODB_BENCH_INDEX_KEYS", 1_000_000))


def main():
    tag_sets = [{"_metric_": f"metric_{i % 100}", "instance": f"i{i}",
                 "host": f"h{i % 500}", "_ws_": "w", "_ns_": f"ns{i % 8}"}
                for i in range(N)]
    pks = [canonical_partkey(t) for t in tag_sets]

    def build(auto_apply=True):
        idx = PartKeyIndex(auto_apply=auto_apply)
        for pid, (pk, tags) in enumerate(zip(pks, tag_sets)):
            idx.add_partkey(pid, pk, tags, start_time=pid)
        return idx

    # label writes are deferred off the ingest path (the reference pays
    # them on a background Lucene flush thread): measure the INGEST-
    # THREAD cost and the off-thread apply separately, plus the legacy
    # combined walltime (applier racing the add loop on one core)
    idx0 = None

    def build_deferred():
        nonlocal idx0
        idx0 = build(auto_apply=False)

    t_ing = timed(build_deferred, reps=1)
    emit("index add_partkey (ingest-thread)", N / t_ing, "keys/sec",
         keys=N)
    t_apply = timed(idx0.apply_pending, reps=1)
    emit("index label apply (off-thread)", N / max(t_apply, 1e-9),
         "keys/sec", keys=N)
    del idx0                      # ~1M-series: release before lookups
    combined = None

    def build_combined():
        nonlocal combined
        combined = build()

    t_add = timed(build_combined, reps=1)
    emit("index add_partkey (combined single-core)", N / t_add,
         "keys/sec", keys=N)
    # settle its applier backlog NOW: a still-draining daemon thread
    # would otherwise contend with the lookup timings below
    combined.apply_pending()
    del combined

    # COLD dashboard lookup: fresh index, first filter ever (pays the
    # posting materialization) — the reference bar is Lucene's cold seek.
    # Pending label writes are drained first: steady-state serving keeps
    # the applier caught up, so cold = materialization, not backlog.
    idx = build()
    idx.apply_pending()
    eq = [ColumnFilter("_metric_", Equals("metric_42"))]
    t_cold = timed(lambda: idx.part_ids_from_filters(eq, 0, 2**62), reps=1)
    emit("index cold equals lookup", t_cold * 1000, "ms", keys=N)

    n_eq = len(idx.part_ids_from_filters(eq, 0, 2**62))
    t_eq = timed(lambda: idx.part_ids_from_filters(eq, 0, 2**62), reps=5)
    emit("index equals lookup (wide)", 1.0 / t_eq, "lookups/sec",
         matched=n_eq)

    # narrow lookup: one series out of N (the alerting shape)
    nr = [ColumnFilter("instance", Equals(f"i{N * 3 // 4}"))]
    t_nr = timed(lambda: idx.part_ids_from_filters(nr, 0, 2**62), reps=5)
    emit("index equals lookup (narrow)", 1.0 / t_nr, "lookups/sec",
         matched=len(idx.part_ids_from_filters(nr, 0, 2**62)))

    # two-filter intersection (the dashboard shape: metric AND namespace)
    eq2 = eq + [ColumnFilter("_ns_", Equals("ns2"))]
    t_eq2 = timed(lambda: idx.part_ids_from_filters(eq2, 0, 2**62), reps=5)
    emit("index equals+equals lookup", 1.0 / t_eq2, "lookups/sec",
         matched=len(idx.part_ids_from_filters(eq2, 0, 2**62)))

    rx = [ColumnFilter("host", EqualsRegex("h1.?"))]
    t_rx_cold = timed(lambda: idx.part_ids_from_filters(rx, 0, 2**62),
                      reps=1)
    emit("index cold regex lookup", t_rx_cold * 1000, "ms")
    t_rx = timed(lambda: idx.part_ids_from_filters(rx, 0, 2**62), reps=5)
    emit("index regex lookup", 1.0 / t_rx, "lookups/sec")

    # the reference benchmark's 4-filter shape (PartKeyIndexBenchmark
    # partIdsLookupWithSuffixRegexFilters): equals x3 + regex
    ref4 = [ColumnFilter("_ns_", Equals("ns2")),
            ColumnFilter("_ws_", Equals("w")),
            ColumnFilter("_metric_", Equals("metric_42")),
            ColumnFilter("host", EqualsRegex("h1.*"))]
    t_ref = timed(lambda: idx.part_ids_from_filters(ref4, 0, 2**62), reps=5)
    emit("index equals x3 + regex lookup", 1.0 / t_ref, "lookups/sec",
         matched=len(idx.part_ids_from_filters(ref4, 0, 2**62)))

    t_lv = timed(lambda: idx.label_values("host", (), 0, 2**62), reps=5)
    emit("index label_values", 1.0 / t_lv, "ops/sec")


if __name__ == "__main__":
    main()
