"""Driver-entry robustness: the CPU dryrun must NEVER initialize a backend
in the calling process, and bench.py's probe must turn a hung/dead TPU
backend into a fast explicit failure (round-4 VERDICT weak #1 / next #1).
"""

import os
import subprocess
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_never_inits_backend_in_parent():
    """Simulate the driver environment: jax imported but NO backend
    initialized (sitecustomize may have registered a dead TPU plugin).
    dryrun_multichip must complete via the CPU-forced subprocess without
    ever touching jax.devices()/default_backend() in the parent — during
    a tunnel outage that call is a hang, not an exception."""
    code = textwrap.dedent("""
        import sys
        import jax
        from jax._src import xla_bridge
        assert not xla_bridge._backends, "backend already initialized"
        def _boom(*a, **k):
            raise SystemExit("FAIL: parent tried to initialize a backend")
        jax.devices = _boom
        jax.default_backend = _boom
        xla_bridge.backends = _boom
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)
        print("DRYRUN_OK")
    """)
    env = dict(os.environ)
    # the grandchild re-forces cpu itself; the parent must not rely on this
    env.pop("JAX_PLATFORMS", None)
    # keep the in-code watchdog BELOW this test's own subprocess timeout so
    # a wedge fails through the watchdog (clean RuntimeError), not an
    # orphaning outer kill
    env["FILODB_DRYRUN_TIMEOUT_S"] = "300"
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-3000:]
    assert "DRYRUN_OK" in res.stdout


def test_dryrun_inline_when_cpu_backend_initialized(monkeypatch):
    """Under the conftest's initialized 8-device CPU backend the dryrun must
    take the inline path — a subprocess re-exec here means the private-attr
    probe (jax._src.xla_bridge._default_backend) broke, e.g. on a jax
    upgrade, and every CI caller silently pays a ~30s re-exec."""
    import jax

    import __graft_entry__

    assert len(jax.devices()) >= 8  # conftest initialized the CPU mesh

    def _no_subprocess(*a, **k):
        raise AssertionError("dryrun re-execed instead of running inline")

    monkeypatch.setattr(subprocess, "run", _no_subprocess)
    __graft_entry__.dryrun_multichip(8)


def test_bench_probe_reports_init_error(monkeypatch):
    import jax

    import bench

    def _raise():
        raise RuntimeError("no backend for you")

    monkeypatch.setattr(jax, "devices", _raise)
    err = bench._probe_backend(30)
    assert err is not None and "no backend for you" in err


def test_bench_probe_times_out_on_hang(monkeypatch):
    import jax

    import bench

    monkeypatch.setattr(jax, "devices", lambda: time.sleep(20))
    a = time.perf_counter()
    err = bench._probe_backend(1)
    took = time.perf_counter() - a
    assert err is not None and "timed out" in err
    assert took < 10, took


def test_bench_probe_passes_on_live_backend():
    import bench

    assert bench._probe_backend(60) is None
