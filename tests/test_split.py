"""Elastic resharding units (ISSUE 13, coordinator/split.py).

Covers the mapper topology machine (generations, adopt, abort), the
generative rehash-invariant sweep across every spread setting, the
gateway series-memo rehash regression, the routing-token fold, the
wire round-trip of the parent-exclusion stamp, the topology-generation
lint rule, and — over a real single-node broker-backed FiloServer —
the full phase machine: lossless 4->8 split under checkpointed data,
bit-equal serving across cutover and retire, crash-resume from the
persisted record, and first-class abort from catch-up AND from the
post-cutover grace window.
"""

import json
import shutil
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

import filodb_tpu.analysis as A
from filodb_tpu.core.record import (RecordBuilder, canonical_partkey,
                                    partition_hash, shard_key_hash)
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.parallel.shardmap import (ShardMapper, ShardStatus,
                                          shard_of_tags)

BASE = 1_700_000_000_000


# ---------------------------------------------------------------------------
# mapper topology machine
# ---------------------------------------------------------------------------


class TestTopologyMachine:
    def test_phases_and_generations(self):
        m = ShardMapper(4)
        m.register_node(range(4), "a")
        assert m.topology_generation == 0
        t = m.begin_split(spread=1)
        assert (m.num_shards, m.total_shards) == (4, 8)
        assert t.split_phase == "catchup" and t.generation == 1
        m.register_split_child(6, ["a"])
        assert m.state(6).best_status is ShardStatus.RECOVERY
        t = m.commit_split()
        assert m.num_shards == 8 and t.split_phase == "serving"
        assert t.parent_exclusion(2) == (8, 1)
        assert t.parent_exclusion(6) is None
        t = m.retire_split()
        assert t.split_phase == "retire" and t.parent_exclusion(2)
        t = m.finish_split()
        assert t.split_phase is None and m.num_shards == 8
        assert m.topology_generation == 4

    def test_abort_restores_parent_topology(self):
        m = ShardMapper(4, dataset="")
        m.register_node(range(4), "a")
        m.begin_split(spread=1)
        m.register_split_child(5, ["a"])
        t = m.abort_split()
        assert (m.num_shards, m.total_shards) == (4, 4)
        assert t.split_phase is None
        # double split / commit from wrong phase refuse loudly
        m.begin_split(spread=1)
        with pytest.raises(ValueError):
            m.begin_split(spread=1)
        m.abort_split()
        with pytest.raises(ValueError):
            m.commit_split()

    def test_routing_token_folds_generation(self):
        # ISSUE 13 satellite: a completed split must invalidate cached
        # results even when no replica row changed
        m = ShardMapper(4)
        m.register_node(range(4), "a")
        tokens = {m.routing_token()}
        m.begin_split(spread=1)
        tokens.add(m.routing_token())
        m.commit_split()
        tokens.add(m.routing_token())
        m.retire_split()
        tokens.add(m.routing_token())
        m.finish_split()
        tokens.add(m.routing_token())
        assert len(tokens) == 5, "every topology transition must change " \
                                 "the routing token"

    def test_adopt_topology_newest_wins(self):
        owner = ShardMapper(4, dataset="")
        owner.register_node(range(4), "a")
        owner.begin_split(spread=1)
        follower = ShardMapper(4, dataset="")
        follower.register_node(range(4), "a")
        assert follower.adopt_topology(owner.topology.as_payload())
        assert follower.total_shards == 8 and follower.num_shards == 4
        assert follower.topology.split_phase == "catchup"
        # stale payloads are ignored (strictly monotone)
        stale = follower.topology.as_payload()
        owner.commit_split()
        assert follower.adopt_topology(owner.topology.as_payload())
        assert follower.num_shards == 8
        assert not follower.adopt_topology(stale)
        assert follower.num_shards == 8
        # abort shrinks the follower's shard space too
        owner.abort_split()
        assert follower.adopt_topology(owner.topology.as_payload())
        assert follower.total_shards == 4

    def test_group_head_folds_parent_for_children(self):
        m = ShardMapper(2, replication_factor=2)
        m.register_node(range(2), "a")
        m.register_node(range(2), "b")
        m.note_watermark(0, "a", 100)
        m.begin_split(spread=0)
        m.register_split_child(2, ["a", "b"])
        assert m.group_head(2) == 100   # parent head gates the child
        m.note_watermark(2, "b", 120)
        assert m.group_head(2) == 120


# ---------------------------------------------------------------------------
# generative rehash-invariant sweep (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


def _random_tags(rng, i):
    tags = {"_metric_": f"m{rng.integers(6)}_total",
            "_ws_": f"ws{rng.integers(3)}", "_ns_": f"ns{rng.integers(8)}",
            "instance": f"i{i}"}
    if rng.integers(2):
        tags["zone"] = f"z{rng.integers(4)}"
    return tags


class TestRehashInvariantSweep:
    def test_post_split_shard_is_parent_or_sibling(self):
        """For random tag sets across EVERY spread setting: the
        post-split shard is its parent s or s+N, and exactly one child
        half claims each series."""
        rng = np.random.default_rng(11)
        for n in (2, 4, 8, 16):
            for spread in range(0, 5):
                for i in range(200):
                    tags = _random_tags(rng, i)
                    old = shard_of_tags(tags, n, spread)
                    new = shard_of_tags(tags, 2 * n, spread)
                    assert new in (old, old + n), (n, spread, tags)
                    claims = [c for c in (old, old + n)
                              if shard_of_tags(tags, 2 * n, spread) == c]
                    assert len(claims) == 1

    def test_children_partition_parent_and_merge_cardinality(self):
        """Ingest one parent's containers through both child filters:
        each series lands in exactly one child, and re-merging the
        children's cardinality_snapshots reproduces the parent's."""
        from filodb_tpu.memstore.shard import TimeSeriesShard
        rng = np.random.default_rng(5)
        spread = 1
        n, total = 4, 8
        parent_num = 2
        parent = TimeSeriesShard("t", DEFAULT_SCHEMAS, parent_num)
        low = TimeSeriesShard("t", DEFAULT_SCHEMAS, parent_num)
        low.split_ingest_filter = \
            lambda tags: shard_of_tags(tags, total, spread) == parent_num
        hi = TimeSeriesShard("t", DEFAULT_SCHEMAS, parent_num + n)
        hi.split_ingest_filter = \
            lambda tags: shard_of_tags(tags, total, spread) \
            == parent_num + n
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 16)
        n_series = 0
        for i in range(400):
            tags = _random_tags(rng, i)
            if shard_of_tags(tags, n, spread) != parent_num:
                continue
            n_series += 1
            b.add(BASE + i, [float(i)], tags)
        assert n_series > 50
        for off, c in enumerate(b.containers()):
            for sh in (parent, low, hi):
                sh.ingest_container(c, off)
        assert low.num_partitions + hi.num_partitions \
            == parent.num_partitions == n_series
        assert low.stats.rows_split_filtered \
            == hi.num_partitions
        p_active, p_labels = parent.index.cardinality_snapshot()
        merged: dict = {}
        m_active = 0
        for sh in (low, hi):
            a, labels = sh.index.cardinality_snapshot()
            m_active += a
            for lab, vals in labels.items():
                row = merged.setdefault(lab, {})
                for v, cnt in vals.items():
                    row[v] = row.get(v, 0) + cnt
        assert m_active == p_active
        assert merged == p_labels

    def test_scan_exclusion_slices_exactly_the_migrated_half(self):
        from filodb_tpu.memstore.shard import TimeSeriesShard
        rng = np.random.default_rng(7)
        spread, n = 1, 4
        sh = TimeSeriesShard("t", DEFAULT_SCHEMAS, 1)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 16)
        kept = moved = 0
        for i in range(300):
            tags = _random_tags(rng, i)
            if shard_of_tags(tags, n, spread) != 1:
                continue
            if shard_of_tags(tags, 2 * n, spread) == 1:
                kept += 1
            else:
                moved += 1
            b.add(BASE + i, [float(i)], tags)
        for off, c in enumerate(b.containers()):
            sh.ingest_container(c, off)
        assert kept and moved
        lookup = sh.lookup_partitions([], 0, BASE + 10_000)
        assert len(lookup.part_ids) == kept + moved
        sliced = sh.filter_resharded(lookup, 2 * n, spread)
        assert len(sliced.part_ids) == kept
        # purge drops exactly the migrated half, and what remains plus
        # what was purged is the original set
        purged = sh.purge_resharded(2 * n, spread)
        assert len(purged) == moved
        assert sh.num_partitions == kept


# ---------------------------------------------------------------------------
# gateway memo rehash regression (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


class TestGatewayMemoRehash:
    def _publisher(self, mapper, captured):
        from filodb_tpu.gateway.server import ShardingPublisher
        return ShardingPublisher(
            DEFAULT_SCHEMAS["gauge"], mapper,
            lambda shard, c, _cap=captured: _cap.append(shard), spread=1)

    def _batch(self, series, t_ns):
        # varied measurements -> varied shard keys, so both halves of
        # the split see traffic
        return "\n".join(
            f"churn{i % 5},host=h{i},zone=z{i % 7} "
            f"value={float(i)} {t_ns + i}"
            for i in series) + "\n"

    def test_split_under_label_churn_rehashes_memos(self):
        mapper = ShardMapper(4)
        mapper.register_node(range(4), "n")
        captured: list = []
        pub = self._publisher(mapper, captured)
        t_ns = BASE * 1_000_000
        # churn: several batches, new series appearing each time, so
        # the series memo and the replayable group plan are hot
        for r in range(4):
            pub.ingest_influx_batch(self._batch(range(r * 20,
                                                      r * 20 + 40), t_ns))
        pub.flush()
        opts = DatasetOptions()

        def expected_shard(i, total):
            tags = {"_metric_": f"churn{i % 5}", "host": f"h{i}",
                    "zone": f"z{i % 7}"}
            return shard_of_tags(tags, total, 1)

        mapper.begin_split(spread=1)
        mapper.commit_split()
        captured.clear()
        # same series again (memo hits before the fix) + fresh churn
        pub.ingest_influx_batch(self._batch(range(0, 60),
                                            t_ns + 10_000_000))
        pub.flush()
        # every delivered container went to the NEW topology's shard:
        # both halves converge, the retired parent receives nothing
        # from its migrated half
        routed = set(captured)
        want = {expected_shard(i, 8) for i in range(60)}
        assert routed == want
        migrated = {expected_shard(i, 8) for i in range(60)
                    if expected_shard(i, 8) >= 4}
        assert migrated, "fixture degenerate: nothing migrated"
        stale_parents = {s - 4 for s in migrated} - \
            {expected_shard(i, 8) for i in range(60)
             if expected_shard(i, 8) < 4}
        for s in stale_parents:
            assert s not in routed, \
                f"retired parent {s} still receives its migrated half"

    def test_generation_check_is_cheap_noop_when_stable(self):
        mapper = ShardMapper(4)
        mapper.register_node(range(4), "n")
        captured: list = []
        pub = self._publisher(mapper, captured)
        t_ns = BASE * 1_000_000
        pub.ingest_influx_batch(self._batch(range(40), t_ns))
        memo_id = id(pub._series_memo)
        plan = pub._group_plan
        pub.ingest_influx_batch(self._batch(range(40), t_ns + 1_000_000))
        assert id(pub._series_memo) == memo_id
        assert pub._group_plan is plan or pub._group_plan is not None


# ---------------------------------------------------------------------------
# wire round-trip of the parent-exclusion stamp
# ---------------------------------------------------------------------------


def test_wire_roundtrip_reshard_to():
    from filodb_tpu.query.exec import MultiSchemaPartitionsExec, PartKeysExec
    from filodb_tpu.query.model import QueryContext
    from filodb_tpu.query.wire import deserialize_plan, serialize_plan
    leaf = MultiSchemaPartitionsExec("ds", 2, [], BASE, BASE + 1000,
                                     query_context=QueryContext(),
                                     reshard_to=(8, 1))
    got = deserialize_plan(serialize_plan(leaf))
    assert got.reshard_to == (8, 1)
    pk = PartKeysExec("ds", 2, [], BASE, BASE + 1000,
                      query_context=QueryContext(), reshard_to=(8, 1))
    assert deserialize_plan(serialize_plan(pk)).reshard_to == (8, 1)
    bare = MultiSchemaPartitionsExec("ds", 2, [], BASE, BASE + 1000,
                                     query_context=QueryContext())
    assert deserialize_plan(serialize_plan(bare)).reshard_to is None


# ---------------------------------------------------------------------------
# topology-generation lint rule (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


BAD_PUBLISHER = """
class MyPublisher:
    def __init__(self, mapper):
        self.mapper = mapper
        self._series_memo = {}
    def route(self, key, shash, phash):
        got = self._series_memo.get(key)
        if got is None:
            if len(self._series_memo) > 1000:
                self._series_memo.clear()
            got = self._series_memo[key] = self.mapper.ingestion_shard(
                shash, phash, 1) % self.mapper.num_shards
        return got
"""

GOOD_PUBLISHER = BAD_PUBLISHER.replace(
    "    def route(self",
    "    def _check(self):\n"
    "        if self.mapper.topology_generation != self._gen:\n"
    "            self._series_memo.clear()\n"
    "    def route(self")


class TestTopologyGenerationLint:
    def _run(self, src):
        return A.unsuppressed(A.run_source(
            src, rules=["topology-generation"],
            rel="filodb_tpu/gateway/fake.py"))

    def test_catches_unvalidated_shard_memo(self):
        findings = self._run(BAD_PUBLISHER)
        assert len(findings) == 1
        assert "topology_generation" in findings[0].message

    def test_passes_generation_validated_memo(self):
        assert not self._run(GOOD_PUBLISHER)

    def test_off_serving_path_is_exempt(self):
        assert not A.unsuppressed(A.run_source(
            BAD_PUBLISHER, rules=["topology-generation"],
            rel="benches/fake.py"))

    def test_tree_is_clean(self):
        # the full-tree tier-1 gate in test_analysis covers every rule;
        # this pins the NEW rule specifically so a regression names it
        from filodb_tpu.analysis.__main__ import main as lint_main
        import pathlib
        pkg = pathlib.Path(__file__).resolve().parents[1] / "filodb_tpu"
        assert lint_main(["--rules", "topology-generation",
                          str(pkg)]) == 0


# ---------------------------------------------------------------------------
# full single-node lifecycle over a real FiloServer + broker
# ---------------------------------------------------------------------------


N_SERIES = 24
N_SAMPLES = 90
WINDOW = (BASE, BASE + N_SAMPLES * 1000)

# duplicate-sensitive legs: one dropped or double-counted row changes
# them.  Samples are INTEGER-valued (see _produce), so the cross-shard
# float reduce is exact in ANY grouping and bit-equality survives the
# cutover's regrouped reduce tree; the rate leg (division by the
# window) is checked to 1e-9 relative instead — cross-shard float-sum
# order legitimately regroups when the shard count doubles.
RATE_Q = 'sum(rate(sp_total[2m]))'
COUNT_Q = 'sum(count_over_time(sp_total[1m]))'
SUM_Q = 'sum(sum_over_time(sp_total[1m]))'
COUNT_BY_Q = 'count(sp_total)'


def _series_tags(i):
    return {"_metric_": "sp_total", "_ws_": f"w{i % 3}",
            "_ns_": f"n{i % 5}", "instance": f"i{i}"}


def _produce(client, topic, num_shards, metric="sp_total"):
    opts = DatasetOptions()
    rm = ShardMapper(num_shards)
    rng = np.random.default_rng(17)
    by_shard = {s: RecordBuilder(DEFAULT_SCHEMAS["gauge"],
                                 container_size=1 << 13)
                for s in range(num_shards)}
    for i in range(N_SERIES):
        tags = dict(_series_tags(i), _metric_=metric)
        s = rm.ingestion_shard(shard_key_hash(tags, opts),
                               partition_hash(tags, opts),
                               1) % num_shards
        # integer-valued samples: cross-shard sums stay exact under any
        # reduce grouping (doubles are exact integers far below 2^53)
        vals = np.cumsum(rng.integers(1, 1000, N_SAMPLES))
        for k in range(N_SAMPLES):
            by_shard[s].add(BASE + k * 1000, [float(vals[k])], tags)
    n = 0
    for s, b in by_shard.items():
        for c in b.containers():
            client.produce(topic, s, c)
            n += 1
    return n


def _get(port, path, timeout=20, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _query(port, ds, promql, metric=None):
    q = promql if metric is None else promql.replace("sp_total", metric)
    return _get(port, f"/promql/{ds}/api/v1/query_range", query=q,
                start=WINDOW[0] / 1000, end=WINDOW[1] / 1000, step="15s")


def _canon(body):
    return sorted((tuple(sorted(s["metric"].items())),
                   tuple((t, v) for t, v in s["values"]))
                  for s in body["data"]["result"])


def _near(canon_a, canon_b, rel=1e-9):
    """Same series/steps, values within rel — the float-sum legs, where
    a regrouped cross-shard reduce legitimately moves the last ulp."""
    import math
    if len(canon_a) != len(canon_b):
        return False
    for (ka, va), (kb, vb) in zip(canon_a, canon_b):
        if ka != kb or len(va) != len(vb):
            return False
        for (ta, xa), (tb, xb) in zip(va, vb):
            if ta != tb or not math.isclose(float(xa), float(xb),
                                            rel_tol=rel, abs_tol=1e-12):
                return False
    return True


def _config(tmp, broker_port):
    return {
        "node": "s0", "http-port": 0, "data-dir": str(tmp),
        "dataplane": {"watermark-sample-interval-s": 3600},
        "datasets": [
            {"name": "prom", "num-shards": 4, "min-num-nodes": 1,
             "schema": "gauge", "spread": 1,
             "source": {"factory": "broker", "port": broker_port,
                        "topic": "prom"},
             "store": {"flush-interval": "1h", "groups-per-shard": 4}},
            {"name": "ab", "num-shards": 2, "min-num-nodes": 1,
             "schema": "gauge", "spread": 1,
             "source": {"factory": "broker", "port": broker_port,
                        "topic": "ab"},
             "store": {"flush-interval": "1h", "groups-per-shard": 2}},
            {"name": "ro", "num-shards": 2, "min-num-nodes": 1,
             "schema": "gauge", "spread": 1,
             "source": {"factory": "broker", "port": broker_port,
                        "topic": "ro"},
             "rollup": {"resolutions": ["1m"], "tick-interval-s": 0.3},
             "store": {"flush-interval": "1h", "groups-per-shard": 2}},
        ],
    }


def _wait(cond, timeout_s=30.0, every_s=0.1):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(every_s)
    return False


@pytest.fixture(scope="module")
def split_server(tmp_path_factory):
    from filodb_tpu.ingest.broker import BrokerClient, BrokerServer
    from filodb_tpu.standalone import FiloServer
    broker = BrokerServer(port=0)
    broker.start()
    client = BrokerClient(port=broker.port)
    client.create_topic("prom", 4)
    client.create_topic("ab", 2)
    client.create_topic("ro", 2)
    _produce(client, "prom", 4)
    _produce(client, "ab", 2, metric="ab_total")
    _produce(client, "ro", 2, metric="ro_total")
    tmp = tmp_path_factory.mktemp("split-node")
    srv = FiloServer(_config(tmp, broker.port))
    port = srv.start()
    total = N_SERIES * N_SAMPLES
    assert _wait(lambda: sum(sh.stats.rows_ingested
                             for sh in srv.memstore.shards("prom"))
                 >= total), "prom never ingested"
    assert _wait(lambda: sum(sh.stats.rows_ingested
                             for sh in srv.memstore.shards("ab"))
                 >= total), "ab never ingested"
    assert _wait(lambda: sum(sh.stats.rows_ingested
                             for sh in srv.memstore.shards("ro"))
                 >= total), "ro never ingested"
    state = {"server": srv, "port": port, "broker": broker,
             "client": client, "tmp": tmp}
    yield state
    state["server"].shutdown()
    broker.shutdown()


class TestSingleNodeLifecycle:
    """Ordered scenario over the module fixture."""

    def test_1_full_split_is_lossless(self, split_server):
        srv, port = split_server["server"], split_server["port"]
        oracles = {}
        for q in (RATE_Q, COUNT_Q, SUM_Q, COUNT_BY_Q):
            code, body = _query(port, "prom", q)
            assert code == 200 and body["data"]["result"], (q, body)
            oracles[q] = _canon(body)
        split_server["oracles"] = oracles
        srv.flush_all()
        code, body = _get(port, "/admin/split/prom", timeout=10,
                          action="start", **{"grace-s": 0.5})
        # urllib GET: use the HTTP POST surface through the controller
        # directly when the GET route refuses the action
        if code != 200:
            srv.split_controller.trigger("prom", grace_s=0.5)
        assert _wait(lambda: (srv.split_controller.status("prom") or {})
                     .get("phase") == "complete", 45), \
            srv.split_controller.status("prom")
        m = srv.manager.mapper("prom")
        assert m.num_shards == 8 and m.topology.split_phase is None
        # duplicate-sensitive legs bit-equal after cutover + retire
        # purge; the float-sum rate leg to 1e-9 (regrouped reduce)
        for q, want in oracles.items():
            code, body = _query(port, "prom", q)
            assert code == 200
            if q == RATE_Q:
                assert _near(_canon(body), want), \
                    f"post-split diverged for {q}"
            else:
                assert _canon(body) == want, \
                    f"post-split diverged for {q}"
        # the parents physically dropped their migrated half
        parents = [sh for sh in srv.memstore.shards("prom")
                   if sh.shard_num < 4]
        assert sum(sh.stats.partitions_purged for sh in parents) > 0
        # rows: children + parents together hold every series once
        code, body = _get(port, "/admin/shards", timeout=10)
        assert code == 200
        ds = body["data"]["datasets"]["prom"]
        assert ds["topology"]["num_shards"] == 8

    def test_2_post_split_ingest_routes_to_children(self, split_server):
        """Live ingest AFTER the split lands on the new topology: the
        write publisher rehashed its memos (generation bump)."""
        srv = split_server["server"]
        pub = srv.write_publishers["prom"]
        opts = DatasetOptions()
        routed = []
        for i in range(N_SERIES):
            tags = _series_tags(i)
            t = {k: v for k, v in tags.items() if k != "_metric_"}
            shard = pub.add_sample("sp_total", t,
                                   WINDOW[1] + 60_000 + i, float(i))
            routed.append((tags, shard))
        m = srv.manager.mapper("prom")
        for tags, shard in routed:
            assert shard == m.ingestion_shard(
                shard_key_hash(tags, opts), partition_hash(tags, opts),
                1) % 8

    def test_3_restart_resumes_completed_topology(self, split_server):
        """A restart over the same data-dir reconstructs the doubled
        topology from the persisted split record and serves bit-equal
        (checkpoint replay per shard, cloned checkpoints included)."""
        from filodb_tpu.standalone import FiloServer
        old = split_server["server"]
        old.shutdown()
        srv = FiloServer(_config(split_server["tmp"],
                                 split_server["broker"].port))
        port = srv.start()
        split_server["server"] = srv
        split_server["port"] = port
        m = srv.manager.mapper("prom")
        assert m.num_shards == 8 and m.total_shards == 8
        assert m.topology.split_phase is None

        def settled():
            code, body = _query(port, "prom", COUNT_Q)
            return code == 200 and \
                _canon(body) == split_server["oracles"][COUNT_Q]
        assert _wait(settled, 30), "restarted node never served the " \
                                   "oracle window bit-equal"

    def test_4_abort_from_catchup_restores_serving_state(self,
                                                         split_server):
        srv, port = split_server["server"], split_server["port"]
        oracle = {}
        for q in (COUNT_Q, RATE_Q):
            code, body = _query(port, "ab", q, metric="ab_total")
            assert code == 200 and body["data"]["result"]
            oracle[q] = _canon(body)
        srv.flush_all()
        ctrl = srv.split_controller
        ctrl.hold("cutover")
        try:
            ctrl.trigger("ab", grace_s=30.0)
            m = srv.manager.mapper("ab")
            assert m.total_shards == 4 and m.num_shards == 2
            # children exist + clones landed, but cutover is held
            assert _wait(lambda: srv.metastore.read_kv(
                "splitclone::ab::2") is not None, 10)
            st = ctrl.status("ab")
            assert st["phase"] == "catchup"
            ctrl.abort("ab", reason="unit test")
            assert _wait(lambda: (ctrl.status("ab") or {})
                         .get("phase") == "aborted", 15)
        finally:
            ctrl.release("cutover")
        m = srv.manager.mapper("ab")
        assert m.num_shards == 2 and m.total_shards == 2
        # child shards dropped everywhere: memstore, store, checkpoints
        assert _wait(lambda: all(sh.shard_num < 2
                                 for sh in srv.memstore.shards("ab")), 10)
        assert srv.colstore.num_chunks("ab", 2) == 0
        assert srv.colstore.num_chunks("ab", 3) == 0
        assert not srv.metastore.read_checkpoints("ab", 2)
        for q, want in oracle.items():
            code, body = _query(port, "ab", q, metric="ab_total")
            assert code == 200 and _canon(body) == want

    def test_5_abort_from_grace_window_is_lossless(self, split_server):
        """Abort AFTER cutover (inside the grace window): topology
        reverts, children discarded, the parents' untouched superset
        keeps serving bit-equal."""
        srv, port = split_server["server"], split_server["port"]
        oracle = {}
        for q in (COUNT_Q, RATE_Q):
            code, body = _query(port, "ab", q, metric="ab_total")
            oracle[q] = _canon(body)
        ctrl = srv.split_controller
        ctrl.trigger("ab", grace_s=120.0)   # long grace: abort window
        assert _wait(lambda: (ctrl.status("ab") or {})
                     .get("phase") == "serving", 30), ctrl.status("ab")
        m = srv.manager.mapper("ab")
        assert m.num_shards == 4
        # serving is already on the doubled topology: duplicate-
        # sensitive legs exact, the float-sum rate leg to 1e-9
        for q, want in oracle.items():
            code, body = _query(port, "ab", q, metric="ab_total")
            assert code == 200
            if q == RATE_Q:
                assert _near(_canon(body), want)
            else:
                assert _canon(body) == want
        ctrl.abort("ab", reason="grace-window abort")
        assert _wait(lambda: (ctrl.status("ab") or {})
                     .get("phase") == "aborted", 15)
        m = srv.manager.mapper("ab")
        assert m.num_shards == 2 and m.total_shards == 2
        for q, want in oracle.items():
            code, body = _query(port, "ab", q, metric="ab_total")
            assert code == 200 and _canon(body) == want

    def test_6a_repeat_split_purges_again(self, split_server):
        """A SECOND split of the same dataset must re-run its own clone
        and retire purge: the first split's KV markers are scoped to its
        prepare-generation epoch and cannot satisfy the next one (the
        stale-marker double-count regression)."""
        srv, port = split_server["server"], split_server["port"]
        ctrl = srv.split_controller
        oracle = {}
        for q in (COUNT_Q, SUM_Q):
            code, body = _query(port, "ab", q, metric="ab_total")
            oracle[q] = _canon(body)
        srv.flush_all()
        # first full split: 2 -> 4
        ctrl.trigger("ab", grace_s=0.3)
        assert _wait(lambda: (ctrl.status("ab") or {})
                     .get("phase") == "complete", 45), ctrl.status("ab")
        purged_first = sum(sh.stats.partitions_purged
                           for sh in srv.memstore.shards("ab"))
        # second full split: 4 -> 8, over the same metastore markers
        srv.flush_all()
        ctrl.trigger("ab", grace_s=0.3)
        assert _wait(lambda: (ctrl.status("ab") or {})
                     .get("phase") == "complete", 45), ctrl.status("ab")
        m = srv.manager.mapper("ab")
        assert m.num_shards == 8
        # the second retire actually purged (no parent still holds a
        # partition that rehashes to its child)
        from filodb_tpu.parallel.shardmap import shard_of_tags
        for sh in srv.memstore.shards("ab"):
            for part in sh.partitions.values():
                assert shard_of_tags(part.tags, 8, 1) == sh.shard_num, \
                    (sh.shard_num, part.tags, purged_first)
        for q, want in oracle.items():
            code, body = _query(port, "ab", q, metric="ab_total")
            assert code == 200 and _canon(body) == want, \
                f"double split diverged for {q}"

    def test_6b_abort_adopted_from_elsewhere_retires_record(
            self, split_server):
        """An abort that arrives as an ADOPTED topology (issued on a
        peer) must retire the owner's record too — otherwise its gates
        march vacuously and a restart resurrects the aborted split."""
        srv = split_server["server"]
        ctrl = srv.split_controller
        srv.flush_all()
        ctrl.hold("cutover")
        try:
            ctrl.trigger("ab", grace_s=30.0)
            # simulate the abort landing via gossip: revert the mapper
            # directly, as adopt_topology would
            with srv.manager._lock:
                srv.manager.mapper("ab").abort_split()
            assert _wait(lambda: (ctrl.status("ab") or {})
                         .get("phase") == "aborted", 15), \
                ctrl.status("ab")
        finally:
            ctrl.release("cutover")
        m = srv.manager.mapper("ab")
        assert m.num_shards == 8 and m.total_shards == 8
        assert _wait(lambda: all(sh.shard_num < 8
                                 for sh in srv.memstore.shards("ab")), 10)

    def test_6_abort_refused_after_retire(self, split_server):
        srv = split_server["server"]
        ctrl = srv.split_controller
        # the prom split completed in test_1: no abort possible
        with pytest.raises(ValueError):
            ctrl.abort("prom")

    def test_7_rollup_tiers_split_in_lockstep(self, split_server):
        """Splitting a rolled dataset doubles its tier datasets in the
        same phase machine; tier children rebuild from the source
        children's rollup emissions while the router's conservative
        boundary keeps queries correct."""
        srv, port = split_server["server"], split_server["port"]
        oracle = {}
        for q in (COUNT_Q, SUM_Q):
            code, body = _query(port, "ro", q, metric="ro_total")
            assert code == 200 and body["data"]["result"]
            oracle[q] = _canon(body)
        srv.flush_all()
        ctrl = srv.split_controller
        st = ctrl.trigger("ro", grace_s=0.5)
        assert st["tiers"] == ["ro_ds_60000"]
        assert _wait(lambda: (ctrl.status("ro") or {})
                     .get("phase") == "complete", 45), ctrl.status("ro")
        tm = srv.manager.mapper("ro_ds_60000")
        assert tm.num_shards == 4 and tm.topology.split_phase is None
        assert tm.topology_generation >= 4
        for q, want in oracle.items():
            code, body = _query(port, "ro", q, metric="ro_total")
            assert code == 200 and _canon(body) == want

    def test_8_tier_dataset_cannot_split_directly(self, split_server):
        srv = split_server["server"]
        with pytest.raises(ValueError):
            srv.split_controller.trigger("ro_ds_60000")

    def test_9_cli_split_status(self, split_server, capsys):
        from filodb_tpu.cli import main as cli_main
        port = split_server["port"]
        rc = cli_main(["split-status", "--server",
                       f"http://127.0.0.1:{port}", "--dataset", "prom"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "phase complete" in out or "complete" in out
