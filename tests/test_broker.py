"""Broker ingest transport: protocol, durability, and checkpointed
recovery replaying from broker offsets end-to-end.

Reference capabilities being matched: kafka/KafkaIngestionStream.scala:24-63
(shard = topic partition, messages = RecordContainer bytes, offsets =
checkpoints), KafkaDownsamplePublisher.scala:17 (downsample re-publish),
and the multi-jvm IngestionAndRecoverySpec flow (produce -> ingest ->
flush/checkpoint -> crash -> recover from offsets without duplicates).
"""

import threading

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.ingest.broker import (BrokerClient, BrokerDownsamplePublisher,
                                      BrokerError, BrokerIngestionStream,
                                      BrokerIngestionStreamFactory,
                                      BrokerProducer, BrokerServer)
from filodb_tpu.ingest.stream import source_factory
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

BASE = 1_700_000_000_000
MAX = np.iinfo(np.int64).max


@pytest.fixture
def broker():
    srv = BrokerServer()
    srv.start()
    yield srv
    srv.shutdown()


@pytest.fixture
def client(broker):
    c = BrokerClient(port=broker.port)
    yield c
    c.close()


class TestProtocol:
    def test_produce_fetch_roundtrip(self, client):
        client.create_topic("t", 4)
        assert client.num_partitions("t") == 4
        offs = [client.produce("t", 2, f"m{i}".encode()) for i in range(5)]
        assert offs == [0, 1, 2, 3, 4]
        assert client.end_offset("t", 2) == 5
        assert client.end_offset("t", 0) == 0
        batch = client.fetch("t", 2, 0, wait_ms=0)
        assert [(o, m.decode()) for o, m in batch] == \
            [(i, f"m{i}") for i in range(5)]
        # fetch from mid-offset
        batch = client.fetch("t", 2, 3, wait_ms=0)
        assert [o for o, _ in batch] == [3, 4]

    def test_unknown_topic_partition_errors(self, client):
        with pytest.raises(BrokerError):
            client.produce("nope", 0, b"x")
        client.create_topic("t", 2)
        with pytest.raises(BrokerError):
            client.produce("t", 7, b"x")

    def test_max_bytes_batching(self, client):
        client.create_topic("t", 1)
        for i in range(10):
            client.produce("t", 0, bytes(100))
        batch = client.fetch("t", 0, 0, max_bytes=250, wait_ms=0)
        assert len(batch) == 2  # first always included, then cap applies

    def test_long_poll_wakes_on_produce(self, broker, client):
        client.create_topic("t", 1)
        got = []

        def consume():
            got.extend(client2.fetch("t", 0, 0, wait_ms=5_000))

        client2 = BrokerClient(port=broker.port)
        t = threading.Thread(target=consume)
        t.start()
        client.produce("t", 0, b"wake")
        t.join(timeout=6)
        assert not t.is_alive() and [m for _, m in got] == [b"wake"]
        client2.close()

    def test_create_topic_idempotent_and_growable(self, client):
        assert client.create_topic("t", 2) == 2
        assert client.create_topic("t", 2) == 2
        assert client.create_topic("t", 4) == 4  # grow only


class TestDurability:
    def test_log_survives_restart(self, tmp_path):
        d = str(tmp_path / "broker")
        srv = BrokerServer(data_dir=d)
        srv.start()
        c = BrokerClient(port=srv.port)
        c.create_topic("ds", 2)
        for i in range(7):
            c.produce("ds", 1, f"msg{i}".encode())
        c.close()
        srv.shutdown()
        # restart on the same dir: offsets and data must be intact
        srv2 = BrokerServer(data_dir=d)
        srv2.start()
        c2 = BrokerClient(port=srv2.port)
        assert c2.num_partitions("ds") == 2
        assert c2.end_offset("ds", 1) == 7
        batch = c2.fetch("ds", 1, 5, wait_ms=0)
        assert [(o, m.decode()) for o, m in batch] == [(5, "msg5"), (6, "msg6")]
        assert c2.produce("ds", 1, b"post") == 7
        c2.close()
        srv2.shutdown()

    def test_torn_tail_write_truncated(self, tmp_path):
        d = str(tmp_path / "broker")
        srv = BrokerServer(data_dir=d)
        srv.start()
        c = BrokerClient(port=srv.port)
        c.create_topic("ds", 1)
        c.produce("ds", 0, b"good")
        c.close()
        srv.shutdown()
        # simulate a crash mid-append
        import os
        path = os.path.join(d, "ds-p0.log")
        with open(path, "ab") as f:
            f.write(b"\xff\xff\xff\x7f partial")
        srv2 = BrokerServer(data_dir=d)
        srv2.start()
        c2 = BrokerClient(port=srv2.port)
        assert c2.end_offset("ds", 0) == 1
        c2.close()
        srv2.shutdown()


def _produce_containers(client, topic, num_shards, n_series=6, n_rows=40):
    """Build gauge containers and produce them per shard (series s ->
    shard s % num_shards).  Returns expected {shard: {inst: (ts, vals)}}."""
    producer = BrokerProducer(client, topic, num_shards)
    expect = {s: {} for s in range(num_shards)}
    rng = np.random.default_rng(3)
    for s in range(n_series):
        shard = s % num_shards
        tags = {"__name__": "m", "inst": f"i{s}", "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.arange(n_rows) * 10_000
        vals = np.round(rng.random(n_rows) * 50, 9)
        expect[shard][f"i{s}"] = (ts, vals)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=2048)
        b.add_series(ts.tolist(), [vals.tolist()], tags)
        for c in b.containers():
            producer.publish(shard, c)
    return expect


def _check_shard(sh, expected):
    for inst, (ets, evals) in expected.items():
        pids = [pid for pid, p in sh.partitions.items()
                if p.tags.get("inst") == inst]
        assert len(pids) == 1, f"{inst}: {len(pids)} partitions"
        ts, vals = sh.partitions[pids[0]].read_range(0, MAX)
        np.testing.assert_array_equal(ts, ets)
        np.testing.assert_array_equal(vals, evals)


class TestEndToEndRecovery:
    def test_ingest_flush_crash_recover(self, broker, client, tmp_path):
        """The IngestionAndRecoverySpec flow on one node, two shards."""
        from filodb_tpu.coordinator.node import NodeCoordinator

        num_shards = 2
        expect = _produce_containers(client, "prom", num_shards)
        col = DiskColumnStore(str(tmp_path / "chunks.db"))
        meta = DiskMetaStore(str(tmp_path / "meta.db"))
        factory = BrokerIngestionStreamFactory(
            port=broker.port, topic="prom", stop_at_end=True)

        ms = TimeSeriesMemStore(column_store=col, meta_store=meta)
        node = NodeCoordinator("n1", ms)
        ic = node.setup_dataset("prom", DEFAULT_SCHEMAS, factory)
        for s in range(num_shards):
            ic.start_ingestion(s, blocking=True)
        for s in range(num_shards):
            sh = ms.get_shard("prom", s)
            _check_shard(sh, expect[s])
            sh.flush_all()  # persists chunks+partkeys+checkpoints
        cps0 = meta.read_checkpoints("prom", 0)
        assert cps0 and max(cps0.values()) >= 0

        # produce MORE data after the flush (arrives while "down")
        rng = np.random.default_rng(9)
        post = {}
        for s in range(num_shards):
            tags = {"__name__": "m", "inst": f"late{s}", "_ws_": "w",
                    "_ns_": "n"}
            ts = BASE + 10_000_000 + np.arange(10) * 10_000
            vals = np.round(rng.random(10), 9)
            post[s] = (ts, vals)
            b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=4096)
            b.add_series(ts.tolist(), [vals.tolist()], tags)
            for c in b.containers():
                client.produce("prom", s, c)

        # "crash": drop the memstore entirely; recover from broker offsets
        ms2 = TimeSeriesMemStore(column_store=col, meta_store=meta)
        node2 = NodeCoordinator("n1", ms2)
        ic2 = node2.setup_dataset("prom", DEFAULT_SCHEMAS, factory)
        for s in range(num_shards):
            ic2.start_ingestion(s, blocking=True)
        for s in range(num_shards):
            sh = ms2.get_shard("prom", s)
            # recovered partitions hold the replayed (unflushed-at-crash)
            # rows; flushed rows live in the column store; no duplicates
            late_pids = [pid for pid, p in sh.partitions.items()
                         if p.tags.get("inst") == f"late{s}"]
            assert len(late_pids) == 1
            ts, vals = sh.partitions[late_pids[0]].read_range(0, MAX)
            np.testing.assert_array_equal(ts, post[s][0])
            np.testing.assert_array_equal(vals, post[s][1])
            # recovery seeks to min(checkpoint)+1: only post-checkpoint
            # rows were replayed — no duplicates of flushed data
            assert sh.stats.rows_ingested == 10
        col.shutdown()
        meta.shutdown()

    def test_source_factory_registry(self, broker):
        f = source_factory("kafka", port=broker.port, topic="x",
                           stop_at_end=True)
        assert isinstance(f, BrokerIngestionStreamFactory)


class TestGatewayToBroker:
    def test_influx_edge_to_shard(self, broker, client):
        """The reference's full ingest edge: Influx line -> gateway
        sharding publisher -> broker topic partitions -> per-shard
        ingestion streams -> memstore (GatewayServer.scala:58 publishes
        to Kafka; KafkaIngestionStream consumes per shard)."""
        from filodb_tpu.gateway.server import ShardingPublisher
        from filodb_tpu.parallel.shardmap import ShardMapper

        num_shards = 4
        producer = BrokerProducer(client, "prom", num_shards)
        mapper = ShardMapper(num_shards)
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], mapper,
                                producer.publish, spread=1)
        n = 0
        for i in range(20):
            n += pub.ingest_influx_line(
                f"cpu,host=h{i} usage={i}.5 {(BASE + i * 1000) * 1_000_000}")
        assert n == 20
        pub.flush()

        ms = TimeSeriesMemStore()
        factory = BrokerIngestionStreamFactory(port=broker.port,
                                               topic="prom",
                                               stop_at_end=True)
        got = 0
        for s in range(num_shards):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
            sh = ms.get_shard("prom", s)
            stream = factory.create("prom", s)
            for off, c in stream.get():
                got += sh.ingest_container(c, off)
        assert got == 20
        # every series landed on the shard the mapper routed it to
        for s in range(num_shards):
            for p in ms.get_shard("prom", s).partitions.values():
                assert pub._shard_of(p.tags) == s


class TestStandaloneWithBroker:
    def test_server_with_embedded_broker_source(self, tmp_path):
        """FiloServer configured with an embedded broker and a
        kafka-style dataset source: Influx TCP edge -> broker topic ->
        per-shard consumers -> PromQL over HTTP (the production wiring
        of the reference: gateway -> Kafka -> IngestionActor)."""
        import json as _json
        import socket
        import time
        import urllib.parse
        import urllib.request

        from filodb_tpu.standalone import FiloServer

        config = {
            "node": "n0",
            "gateway-port": 0,
            "broker": {"port": 0, "data-dir": str(tmp_path / "broker")},
            "datasets": [{"name": "prom", "num-shards": 2,
                          "schema": "gauge", "spread": 1,
                          "source": {"factory": "kafka"},
                          "store": {"groups-per-shard": 2}}],
        }
        srv = FiloServer(config)
        port = srv.start()
        try:
            gw_port = srv.gateways[0].port
            lines = [f"gw_metric,_ws_=w,_ns_=n,inst=i{i} value={i}.0 "
                     f"{(BASE + k * 10_000) * 1_000_000}"
                     for i in range(4) for k in range(20)]
            with socket.create_connection(("127.0.0.1", gw_port),
                                          timeout=10) as sk:
                sk.sendall(("\n".join(lines) + "\n").encode())
            deadline = time.time() + 15
            rows = 0
            while time.time() < deadline and rows < 80:
                rows = sum(sh.stats.rows_ingested
                           for sh in srv.memstore.shards("prom"))
                time.sleep(0.05)
            assert rows == 80
            qs = urllib.parse.urlencode({
                "query": 'count(gw_metric{_ws_="w",_ns_="n"})',
                "start": BASE / 1000, "end": (BASE + 190_000) / 1000,
                "step": "30s"})
            url = (f"http://127.0.0.1:{port}/promql/prom/api/v1/"
                   f"query_range?{qs}")
            body = _json.loads(
                urllib.request.urlopen(url, timeout=60).read())
            assert body["status"] == "success"
            vals = body["data"]["result"][0]["values"]
            assert any(v == "4" for _, v in vals)
            # the broker's durable log really carried the containers
            assert srv.broker is not None
            c = BrokerClient(port=srv.broker.port)
            ends = [c.end_offset("prom", s) for s in range(2)]
            assert sum(ends) > 0
            c.close()
        finally:
            srv.shutdown()


class TestDownsamplePublish:
    def test_flush_publishes_downsample_containers(self, broker, client):
        pub = BrokerDownsamplePublisher(client, "prom",
                                        resolutions_ms=(60_000,),
                                        num_shards=2)
        ms = TimeSeriesMemStore()
        ms.setup("prom", DEFAULT_SCHEMAS, 1)
        sh = ms.get_shard("prom", 1)
        sh.enable_downsampling(pub, (60_000,))
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 20)
        tags = {"__name__": "m", "inst": "i0", "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.arange(120) * 5_000
        vals = np.arange(120.0)
        b.add_series(ts.tolist(), [vals.tolist()], tags)
        for off, c in enumerate(b.containers()):
            sh.ingest_container(c, off)
        sh.flush_all()
        batch = client.fetch(pub.topic_for(60_000), 1, 0, wait_ms=0)
        assert batch, "no downsample containers published"
        ds_schema = DEFAULT_SCHEMAS["gauge"].downsample
        recs = [r for _, m in batch
                for r in decode_container(m, DEFAULT_SCHEMAS)]
        assert recs
        assert all(r.schema_hash == ds_schema.schema_hash for r in recs)
        # ds-gauge columns: min, max, sum, count, avg
        for r in recs:
            dmin, dmax, dsum, dcount, davg = r.values[:5]
            assert dmin <= davg <= dmax