"""Data-plane observability e2e (ISSUE 6 acceptance criteria):

- 2-node broker-backed cluster: /admin/shards watermark lag converges
  to zero during recovery replay while recovery progress advances, and
  a stalled shard produces an ``ingest.stall`` flight-recorder event;
- self-telemetry: with self-scrape enabled, PromQL ``rate()`` over a
  ``filodb_*`` counter in the ``_system`` dataset returns non-empty,
  correct results through the normal query path."""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from filodb_tpu.coordinator.cluster import RecoveryInProgress, ShardManager
from filodb_tpu.coordinator.node import IngestionCoordinator
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.ingest.broker import (BrokerClient,
                                      BrokerIngestionStreamFactory,
                                      BrokerServer)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.watermarks import WatermarkLedger
from filodb_tpu.parallel.shardmap import ShardStatus

BASE = 1_700_000_000_000


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _container(i: int) -> bytes:
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 14)
    b.add(BASE + i * 1000, [float(i)],
          {"__name__": "dp_m", "u": f"s{i % 37}", "_ws_": "w",
           "_ns_": "n"})
    (out,) = b.containers()
    return out


@pytest.fixture(scope="module")
def broker():
    srv = BrokerServer(port=0)
    srv.start()
    yield srv
    srv.shutdown()


class TestTwoNodeWatermarks:
    N_REPLAY = 800
    CHECKPOINT = 600

    def test_lag_converges_during_recovery_and_stall_fires(self, broker):
        client = BrokerClient(port=broker.port)
        client.create_topic("dp", 2)
        for i in range(self.N_REPLAY):
            client.produce("dp", 0, _container(i))
        for i in range(100):
            client.produce("dp", 1, _container(i))

        manager = ShardManager()
        mapper = manager.setup_dataset("dp", 2, 2).mapper
        mapper.register_node([0], "node-a")
        mapper.register_node([1], "node-b")
        progress_events = []
        manager.subscribe(lambda e: progress_events.append(e)
                          if isinstance(e, RecoveryInProgress) else None)

        factory = BrokerIngestionStreamFactory(port=broker.port, topic="dp")
        stores = {"node-a": TimeSeriesMemStore(),
                  "node-b": TimeSeriesMemStore()}
        # node-a pretends a prior run checkpointed: the first half of
        # the groups persisted up to CHECKPOINT, the rest from 0 —
        # recovery replays [1, CHECKPOINT] with progress events,
        # watermark-skipping the checkpointed groups' rows
        from filodb_tpu.core.storeconfig import StoreConfig
        cfg = StoreConfig()
        for g in range(cfg.groups_per_shard):
            stores["node-a"].meta.write_checkpoint(
                "dp", 0, g,
                self.CHECKPOINT if g < cfg.groups_per_shard // 2 else 0)

        ics = {}
        ledgers = {}
        servers = {}
        ports = {}
        for node, shard in (("node-a", 0), ("node-b", 1)):
            # set up the shard before ingestion starts so the FIRST
            # /admin/shards sample already shows the full replay lag
            # (start_ingestion tolerates the existing setup)
            stores[node].setup("dp", DEFAULT_SCHEMAS, shard, cfg)
            ics[node] = IngestionCoordinator(
                node, "dp", DEFAULT_SCHEMAS, stores[node], factory,
                config=cfg, event_sink=manager.publish_event)
            ledgers[node] = WatermarkLedger(stall_window_s=0.3, node=node)
            ledgers[node].watch(
                "dp", stores[node], mapper=mapper,
                end_offset_fn=lambda s, _c=client: _c.end_offset("dp", s))
            srv = FiloHttpServer(node_name=node, watermarks=ledgers[node])
            srv.bind_dataset(DatasetBinding("dp", stores[node],
                                            planner=None))
            servers[node] = srv
            ports[node] = srv.start()
        try:
            # BEFORE ingestion: full lag visible on node-a's shard 0
            code, body = _get(ports["node-a"], "/admin/shards")
            assert code == 200
            row0 = body["data"]["datasets"]["dp"]["shards"][0]
            assert row0["lag"]["rows"] == self.N_REPLAY
            assert row0["status"] == "Assigned"
            assert row0["queryable"] is False

            ics["node-a"].start_ingestion(0)
            ics["node-b"].start_ingestion(1)
            lags = [row0["lag"]["rows"]]
            deadline = time.time() + 30
            while time.time() < deadline:
                code, body = _get(ports["node-a"], "/admin/shards")
                row0 = body["data"]["datasets"]["dp"]["shards"][0]
                lags.append(row0["lag"]["rows"])
                if row0["lag"]["rows"] == 0 \
                        and row0["status"] == "Active":
                    break
                time.sleep(0.05)
            # the acceptance criterion: lag converged to zero during
            # replay, and recovery progress advanced while it did
            assert lags[0] == self.N_REPLAY and lags[-1] == 0, lags
            assert any(a > b for a, b in zip(lags, lags[1:])), lags
            assert row0["status"] == "Active" and row0["queryable"]
            pcts = [e.progress_pct for e in progress_events
                    if e.shard == 0]
            assert any(0 < p < 100 for p in pcts), pcts
            assert mapper.status(0) is ShardStatus.ACTIVE
            # group-0 rows below the checkpoint were watermark-skipped
            sh_a = stores["node-a"].get_shard("dp", 0)
            assert sh_a.stats.rows_skipped > 0
            # watermark chain stays monotone on the converged shard
            wmks = row0["watermarks"]
            assert wmks["broker_end"] - 1 == wmks["ingested"] \
                == self.N_REPLAY - 1
            assert wmks["flushed"] <= wmks["ingested"]
            assert wmks["checkpoint"] <= wmks["ingested"]

            # ---- stalled shard: stop node-b's consumer, produce more,
            # watch the ledger raise ingest.stall exactly once
            deadline = time.time() + 20
            while time.time() < deadline:
                code, body = _get(ports["node-b"], "/admin/shards")
                row1 = body["data"]["datasets"]["dp"]["shards"][0]
                if row1["lag"]["rows"] == 0:
                    break
                time.sleep(0.05)
            assert row1["lag"]["rows"] == 0
            ics["node-b"].stop_ingestion(1)
            for i in range(50):
                client.produce("dp", 1, _container(i))
            from filodb_tpu.utils.devicewatch import FLIGHT
            from filodb_tpu.utils.observability import REGISTRY
            stalls = REGISTRY.counter("filodb_ingest_stalls_total")
            before = stalls.value(dataset="dp", shard=1, node="node-b")
            code, body = _get(ports["node-b"], "/admin/shards")
            row1 = body["data"]["datasets"]["dp"]["shards"][0]
            assert row1["lag"]["rows"] == 50
            assert row1["stalled"] is False     # window not elapsed yet
            time.sleep(0.35)
            code, body = _get(ports["node-b"], "/admin/shards")
            row1 = body["data"]["datasets"]["dp"]["shards"][0]
            assert row1["stalled"] is True
            assert body["data"]["datasets"]["dp"]["totals"]["stalled"] == 1
            assert stalls.value(dataset="dp", shard=1,
                                node="node-b") == before + 1
            evs = [e for e in FLIGHT.events(kind="ingest.stall")
                   if e.get("dataset") == "dp" and e.get("shard") == 1]
            assert evs and evs[-1]["lag_rows"] == 50
            assert evs[-1]["node"] == "node-b"
        finally:
            for ic in ics.values():
                ic.stop_all()
            for srv in servers.values():
                srv.shutdown()
            client.close()


class TestSelfTelemetry:
    def test_promql_rate_over_system_dataset(self, tmp_path):
        """Acceptance criterion: with self-scrape on, a PromQL rate()
        over a filodb_* counter in the _system dataset returns
        non-empty, correct results through the normal query path."""
        from filodb_tpu.standalone import FiloServer
        from filodb_tpu.utils.observability import REGISTRY
        config = {
            "node": "tele-node",
            "datasets": [{"name": "prom", "num-shards": 2,
                          "min-num-nodes": 1, "schema": "gauge",
                          "spread": 1}],
            "dataplane": {
                "watermark-sample-interval-s": 0.5,
                "ingest-stall-window-s": 5.0,
                "self-scrape": {"enabled": True, "interval-s": 0.2,
                                "dataset": "_system"},
            },
        }
        srv = FiloServer(config)
        port = srv.start()
        try:
            assert "_system" in srv.manager.datasets()
            # wait until several scrapes landed as ingested rows
            deadline = time.time() + 20
            rows = 0
            while time.time() < deadline and rows < 200:
                rows = sum(sh.stats.rows_ingested
                           for sh in srv.memstore.shards("_system"))
                time.sleep(0.05)
            assert rows >= 200, "self-scrape rows never arrived"
            # let a few more scrape intervals land so the counter has
            # several distinct timestamps for rate() to work over
            time.sleep(3.0)
            now_s = time.time()
            # raw counter series through the normal query path
            code, body = _get(
                port, "/promql/_system/api/v1/query_range",
                query='filodb_selfscrape_samples_total'
                      '{_ws_="filodb",_ns_="tele-node"}',
                start=now_s - 30, end=now_s, step="1s")
            assert code == 200 and body["status"] == "success"
            series = body["data"]["result"]
            assert len(series) == 1
            raw = [float(v) for _, v in series[0]["values"]]
            assert len(raw) >= 2
            assert all(b >= a for a, b in zip(raw, raw[1:]))
            # the ingested counter matches the live registry value
            # (scraped earlier, so <= the current reading)
            live = REGISTRY.counter(
                "filodb_selfscrape_samples_total").value()
            assert 0 < raw[-1] <= live
            # rate() over the counter: non-empty, positive, and
            # consistent with the raw series' own increase
            code, body = _get(
                port, "/promql/_system/api/v1/query_range",
                query='rate(filodb_selfscrape_samples_total'
                      '{_ws_="filodb"}[10s])',
                start=now_s - 10, end=now_s, step="1s")
            assert code == 200 and body["status"] == "success"
            result = body["data"]["result"]
            assert result, "rate() over _system returned empty"
            rates = [float(v) for _, v in result[0]["values"]]
            assert any(r > 0 for r in rates)
            assert all(r >= 0 for r in rates)
            # correctness: the counter grows by one exposition's worth
            # of samples per 0.2s scrape; the measured rate must sit in
            # the same regime as the raw series' increase
            span_s = (len(raw) - 1) * 1.0
            avg_increase = (raw[-1] - raw[0]) / max(span_s, 1.0)
            assert max(rates) <= avg_increase * 10
            assert max(rates) >= avg_increase / 10
            # the watermark sampler is live too: /admin/shards covers
            # both datasets, including the synthesized one.  The lag
            # check POLLS briefly: the dataset is being scraped every
            # 200ms, so a single snapshot can legitimately catch one
            # pushed-but-not-yet-consumed row in flight
            deadline = time.time() + 5
            lag = None
            while time.time() < deadline:
                code, body = _get(port, "/admin/shards")
                assert code == 200
                assert set(body["data"]["datasets"]) >= {"prom",
                                                         "_system"}
                sys_rows = body["data"]["datasets"]["_system"]["shards"]
                lag = sys_rows[0]["lag"]["rows"]
                if lag == 0:
                    break
                time.sleep(0.1)
            assert lag == 0
        finally:
            srv.shutdown()
