"""Worker for the 2-process control-plane test: one FiloServer node in
its OWN OS process.  Joins the peer via status gossip, waits for shard
assignment convergence, ingests the deterministic series that route to
ITS shards, then reports READY and serves until killed — the process
analog of one forked JVM in the reference's multi-jvm cluster specs
(reference: standalone/src/multi-jvm/.../ClusterSingletonFailoverSpec).

Usage: python mp_node_worker.py <name> <my_port> <peer_name> <peer_port>
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NUM_SHARDS = 4
N_SERIES = 16
BASE = 1_700_000_000_000


def main() -> None:
    name, my_port, peer_name, peer_port = sys.argv[1:5]
    from filodb_tpu.core.record import (RecordBuilder, decode_container,
                                        partition_hash, shard_key_hash)
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.standalone import FiloServer

    spread = 2
    srv = FiloServer({
        "node": name,
        "http-port": int(my_port),
        "status-poll-interval-s": 0.3,
        "datasets": [{"name": "prom", "num-shards": NUM_SHARDS,
                      "min-num-nodes": 2, "schema": "gauge",
                      "spread": spread}],
        "peers": {peer_name: f"http://127.0.0.1:{peer_port}"},
    })
    srv.start()
    mapper = srv.manager.mapper("prom")
    deadline = time.time() + 90
    owned: list = []
    while time.time() < deadline:
        owned = sorted(mapper.shards_for_node(name))
        other = sorted(mapper.shards_for_node(peer_name))
        running = sorted(
            srv.coordinator.ingestion["prom"].running_shards())
        active = sorted(mapper.active_shards())
        # readiness needs BOTH planes converged: assignment (who owns
        # what) AND status gossip (every shard ACTIVE in THIS node's
        # view — the planner serves only active shards)
        if owned and other and sorted(owned + other) == \
                list(range(NUM_SHARDS)) and running == owned \
                and active == list(range(NUM_SHARDS)):
            break
        time.sleep(0.2)
    else:
        print(f"NEVER_CONVERGED owned={owned} "
              f"active={sorted(mapper.active_shards())}", flush=True)
        sys.exit(2)

    # shared deterministic series set; ingest only those routed to
    # shards THIS node owns
    opts = DatasetOptions()
    ms = srv.coordinator.ingestion["prom"].memstore
    import numpy as np
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts)
    for i in range(N_SERIES):
        tags = {"_metric_": "mpm", "inst": f"i{i}", "_ws_": "w",
                "_ns_": "n"}
        shard = mapper.ingestion_shard(
            shard_key_hash(tags, opts), partition_hash(tags, opts),
            spread) % NUM_SHARDS
        if shard not in owned:
            continue
        ts = BASE + np.arange(40, dtype=np.int64) * 10_000
        b.add_series(ts, [np.cumsum(np.ones(40))], tags)
    for off, c in enumerate(b.containers()):
        per: dict = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                        spread) % NUM_SHARDS
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard("prom", sh).ingest(recs, off)

    print(f"READY {','.join(map(str, owned))}", flush=True)
    while True:
        time.sleep(1)


if __name__ == "__main__":
    main()
