"""Rule engine (ISSUE 9): config validation, the ``rules-check`` CLI
verb, recording-rule write-back + stale-series discipline, the alert
state machine, the webhook notifier's bounded retry, admission under
the dedicated ``rules`` priority class, and the generative
incremental-window sweep proving warm state bit-equal to a cold
full-range evaluation AND to the normal query path."""

import json
import time

import numpy as np
import pytest

from filodb_tpu.cli import main as cli_main
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.rules.config import (RuleConfigError, load_rule_config,
                                     parse_rule_config,
                                     validate_rule_config)
from filodb_tpu.rules.engine import (RuleEngine, RuleEvaluator,
                                     render_template)
from filodb_tpu.rules.incremental import WindowState, window_spec
from filodb_tpu.rules.notifier import WebhookNotifier
from filodb_tpu.rules.selfmon import selfmon_pack

BASE = 1_700_000_000_000


# ---------------------------------------------------------------------------
# shared in-process harness: memstore + planner + binding-shaped object
# ---------------------------------------------------------------------------


class _Binding:
    def __init__(self, dataset, memstore, planner, scheduler=None,
                 admission=None):
        self.dataset = dataset
        self.memstore = memstore
        self.planner = planner
        self.scheduler = scheduler
        self.admission = admission


class _CapturePublisher:
    """Collects write-backs as (metric, tags, ts, value)."""

    def __init__(self):
        self.samples = []
        self.flushes = 0

    def add_sample(self, metric, tags, ts, value):
        self.samples.append((metric, dict(tags), int(ts), float(value)))

    def flush(self):
        self.flushes += 1
        return 0

    def of(self, metric):
        return [s for s in self.samples if s[0] == metric]


def _harness(num_shards=2, spread=1):
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=spread)
    return mapper, ms, _Binding("prom", ms, planner)


def _ingest(mapper, ms, metric, series_vals, ts, offset=0, spread=1):
    """series_vals: {tags_key: np.ndarray} aligned with ts."""
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                      container_size=1 << 20)
    for tags, vals in series_vals:
        full = dict(tags)
        full["__name__"] = metric
        b.add_series(np.asarray(ts, dtype=np.int64),
                     [np.asarray(vals, dtype=np.float64)], full)
    n = mapper.num_shards
    for off, c in enumerate(b.containers()):
        per = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                        spread) % n
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard("prom", sh).ingest(recs, offset + off)


def _engine(binding, pub, groups_cfg, **kw):
    groups, errs = parse_rule_config(groups_cfg)
    assert not errs, errs
    return RuleEngine(groups, binding_for=lambda d: binding,
                      publisher_for=lambda d: pub,
                      default_dataset="prom", **kw)


# ---------------------------------------------------------------------------
# config validation + rules-check CLI
# ---------------------------------------------------------------------------


class TestRuleConfig:
    def test_valid_config_parses(self):
        groups, errs = parse_rule_config({"groups": [{
            "name": "g", "interval": "30s", "dataset": "prom",
            "rules": [
                {"record": "a:b:c", "expr": "sum(rate(m[5m]))"},
                {"alert": "A", "expr": "up == 0", "for": "1m30s",
                 "labels": {"sev": "page"},
                 "annotations": {"summary": "down"}}]}]})
        assert errs == []
        g = groups[0]
        assert g.interval_ms == 30_000
        assert g.rules[0].kind == "recording"
        assert g.rules[1].for_ms == 90_000
        # exprs are canonicalized through the renderer for the API
        assert g.rules[0].rendered == "sum(rate(m[5m]))"

    @pytest.mark.parametrize("cfg,needle", [
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "expr": "rate(m[5m"}]}]},
         "does not parse"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "bad name", "expr": "m"}]}]},
         "invalid recorded metric name"),
        ({"groups": [{"name": "g", "interval": "nope",
                      "rules": [{"record": "r", "expr": "m"}]}]},
         "bad interval"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"alert": "A", "expr": "m",
                                 "for": "-3x"}]}]},
         "bad for"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "expr": "m",
                                 "fro": "1m"}]}]},
         "unknown field"),
        ({"groups": [{"name": "g", "interval": "15s", "wat": 1,
                      "rules": [{"record": "r", "expr": "m"}]}]},
         "unknown field"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "expr": "m"},
                                {"record": "r", "expr": "m"}]}]},
         "duplicate recording rule"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "expr": "m"}]},
                     {"name": "g", "interval": "15s",
                      "rules": [{"record": "r2", "expr": "m"}]}]},
         "duplicate group name"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "alert": "a",
                                 "expr": "m"}]}]},
         "exactly one of"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": "r", "expr": "m",
                                 "for": "1m"}]}]},
         "only valid on alerting"),
        # a JSON null name must not stringify into a rule named "None"
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"record": None, "expr": "m"}]}]},
         "must be a string"),
        ({"groups": [{"name": "g", "interval": "15s",
                      "rules": [{"alert": None, "expr": "m"}]}]},
         "must be a string"),
    ])
    def test_invalid_configs_are_errors(self, cfg, needle):
        errs = validate_rule_config(cfg)
        assert any(needle in e for e in errs), (needle, errs)

    def test_all_errors_collected_not_failfast(self):
        errs = validate_rule_config({"groups": [{
            "name": "g", "interval": "bad",
            "rules": [{"record": "x y", "expr": "("},
                      {"alert": "", "expr": "m"}]}]})
        assert len(errs) >= 3

    def test_load_raises_on_errors(self):
        with pytest.raises(RuleConfigError) as ei:
            load_rule_config({"groups": [{"name": "g",
                                          "interval": "15s",
                                          "rules": []}]})
        assert ei.value.errors

    def test_builtin_selfmon_pack_is_valid(self):
        assert validate_rule_config(selfmon_pack()) == []


class TestRulesCheckCli:
    def test_ok_and_bad_files(self, tmp_path, capsys):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(selfmon_pack()))
        assert cli_main(["rules-check", str(good)]) == 0
        assert "OK" in capsys.readouterr().out
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"groups": [{
            "name": "g", "interval": "15s",
            "rules": [{"record": "r", "expr": "rate(m["}]}]}))
        assert cli_main(["rules-check", str(bad)]) == 1
        out = capsys.readouterr().out
        assert "FAILED" in out and "does not parse" in out

    def test_builtin_flag_and_empty_invocation(self, capsys):
        assert cli_main(["rules-check", "--builtin"]) == 0
        assert "builtin:self-monitoring: OK" in capsys.readouterr().out
        assert cli_main(["rules-check"]) == 2

    def test_unreadable_file_fails(self, tmp_path):
        assert cli_main(["rules-check",
                         str(tmp_path / "missing.json")]) == 1


# ---------------------------------------------------------------------------
# recording rules: write-back, labels, stale-series discipline
# ---------------------------------------------------------------------------


class TestRecordingRules:
    def test_write_back_labels_and_flush(self):
        mapper, ms, binding = _harness()
        ts = BASE + np.arange(30, dtype=np.int64) * 10_000
        _ingest(mapper, ms, "m_total",
                [({"inst": f"i{i}", "_ws_": "w", "_ns_": "n"},
                  np.cumsum(np.ones(30)) * (i + 1)) for i in range(3)],
                ts)
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "10s", "rules": [
                {"record": "job:m:rate", "expr": "rate(m_total[2m])",
                 "labels": {"source": "rules"}}]}]})
        eng.run_group_once("g", eval_ms=BASE + 200_000)
        rows = pub.of("job:m:rate")
        assert len(rows) == 3 and pub.flushes == 1
        for _m, tags, t, v in rows:
            # metric name dropped, overrides applied, inputs preserved
            assert "__name__" not in tags and "_metric_" not in tags
            assert tags["source"] == "rules" and tags["inst"].startswith("i")
            assert t == BASE + 200_000 and v > 0

    def test_vanished_series_stops_exporting_and_drops_state(self):
        """The stale-series regression (PR 11 tenant-gauge lesson): an
        output series absent this eval gets NO sample — never a
        re-exported last value — and its window state dies with it."""
        mapper, ms, binding = _harness()
        ts = BASE + np.arange(12, dtype=np.int64) * 1000
        _ingest(mapper, ms, "g1",
                [({"inst": "a"}, np.ones(12)),
                 ({"inst": "b"}, 2 * np.ones(12))], ts)
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "5s", "rules": [
                {"record": "out:sum",
                 "expr": "sum_over_time(g1[5s])"}]}]})
        t1 = BASE + 12_000
        eng.run_group_once("g", eval_ms=t1)
        assert len(pub.of("out:sum")) == 2
        rs = eng._groups[0].rules[0]
        assert rs.incremental is not None  # the windowed shape is
        # incremental, so this regression covers that path too
        # only series a keeps receiving data
        ts2 = BASE + (13 + np.arange(10, dtype=np.int64)) * 1000
        _ingest(mapper, ms, "g1", [({"inst": "a"}, np.ones(10))], ts2,
                offset=50)
        pub.samples.clear()
        t2 = BASE + 23_000   # b's samples all aged out of the 5s window
        eng.run_group_once("g", eval_ms=t2)
        rows = pub.of("out:sum")
        assert len(rows) == 1 and rows[0][1]["inst"] == "a"
        # b's buffered state is gone, not retained forever
        assert rs.incremental.resident_series == 1
        from filodb_tpu.utils.observability import REGISTRY
        assert REGISTRY.counter(
            "filodb_rule_series_stale_total").value(group="g") >= 1

    def test_full_path_used_for_unsupported_shapes(self):
        mapper, ms, binding = _harness()
        ts = BASE + np.arange(20, dtype=np.int64) * 1000
        _ingest(mapper, ms, "m_total",
                [({"inst": "a"}, np.cumsum(np.ones(20))),
                 ({"inst": "b"}, np.cumsum(np.ones(20)) * 2)], ts)
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "10s", "rules": [
                {"record": "out:agg",
                 "expr": "sum(rate(m_total[10s]))"},
                {"record": "out:topk",
                 "expr": "topk(1, rate(m_total[10s]))"}]}]})
        from filodb_tpu.rules.incremental import AggWindowState
        agg_rs, topk_rs = eng._groups[0].rules
        # moment aggregations over windows are incremental now (the
        # shape recorded dashboards use most); rank-based reduces
        # still fall back to full evaluation
        assert isinstance(agg_rs.incremental, AggWindowState)
        assert topk_rs.incremental is None
        eng.run_group_once("g", eval_ms=BASE + 20_000)
        assert len(pub.of("out:agg")) == 1
        assert len(pub.of("out:topk")) == 1

    def test_failed_rule_marks_health_and_resets_state(self):
        mapper, ms, binding = _harness()
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "10s", "rules": [
                {"record": "out:r", "expr": "rate(m_total[1m])"}]}]})
        rs = eng._groups[0].rules[0]
        rs.incremental.series["fake"] = object()
        orig = binding.planner.materialize
        binding.planner.materialize = lambda *a, **k: (_ for _ in ()) \
            .throw(RuntimeError("boom"))
        try:
            eng.run_group_once("g", eval_ms=BASE + 60_000)
        finally:
            binding.planner.materialize = orig
        assert rs.health == "err" and "boom" in rs.last_error
        # a failed eval may have holes: state is cold again
        assert rs.incremental.fetched_through_ms is None
        assert rs.incremental.resident_series == 0


# ---------------------------------------------------------------------------
# alert state machine + notifier
# ---------------------------------------------------------------------------


class TestAlertStateMachine:
    def _eng(self, for_="10s", notifier=None):
        mapper, ms, binding = _harness()
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "5s", "rules": [
                {"alert": "Hot", "expr": "gauge_x > 5", "for": for_,
                 "labels": {"sev": "page"},
                 "annotations": {
                     "summary": "x={{ $value }} on {{ $labels.inst }}"
                 }}]}]}, notifier=notifier)
        return mapper, ms, pub, eng

    def test_full_lifecycle(self):
        sent = []
        notifier = WebhookNotifier("http://unused", send_fn=lambda b:
                                   sent.extend(json.loads(b)))
        try:
            mapper, ms, pub, eng = self._eng(notifier=notifier)
            ts = BASE + np.arange(10, dtype=np.int64) * 1000
            _ingest(mapper, ms, "gauge_x",
                    [({"inst": "a"}, 9 * np.ones(10))], ts)
            t1 = BASE + 10_000
            eng.run_group_once("g", eval_ms=t1)          # -> pending
            rs = eng._groups[0].rules[0]
            (inst,) = rs.alerts.values()
            assert inst.state == "pending"
            assert inst.active_at_ms == t1
            assert pub.of("ALERTS")[0][1]["alertstate"] == "pending"
            assert pub.of("ALERTS_FOR_STATE")[0][3] == t1 / 1000.0
            assert inst.annotations["summary"] == "x=9 on a"
            # still failing past the hold -> firing
            _ingest(mapper, ms, "gauge_x",
                    [({"inst": "a"}, 9 * np.ones(10))],
                    BASE + (11 + np.arange(10, dtype=np.int64)) * 1000,
                    offset=30)
            t2 = t1 + 11_000
            eng.run_group_once("g", eval_ms=t2)          # -> firing
            assert inst.state == "firing"
            assert eng.rules_payload()["groups"][0]["rules"][0][
                "state"] == "firing"
            assert eng.alerts_payload()["alerts"][0]["state"] == "firing"
            # series clears (value drops under threshold) -> resolved
            _ingest(mapper, ms, "gauge_x",
                    [({"inst": "a"}, np.ones(5))],
                    t2 + 1000 + np.arange(5, dtype=np.int64) * 1000,
                    offset=60)
            t3 = t2 + 7_000
            eng.run_group_once("g", eval_ms=t3)
            assert inst.state == "resolved"
            assert inst.resolved_at_ms == t3
            # exactly one delivery per notifying transition
            notifier.drain()
            statuses = [p["status"] for p in sent]
            assert statuses == ["firing", "resolved"]
            assert sent[0]["labels"]["alertname"] == "Hot"
            assert sent[0]["labels"]["sev"] == "page"
        finally:
            notifier.close()

    def test_pending_that_clears_goes_inactive_silently(self):
        sent = []
        notifier = WebhookNotifier("http://unused",
                                   send_fn=lambda b: sent.append(b))
        try:
            mapper, ms, pub, eng = self._eng(notifier=notifier)
            ts = BASE + np.arange(5, dtype=np.int64) * 1000
            _ingest(mapper, ms, "gauge_x",
                    [({"inst": "a"}, 9 * np.ones(5))], ts)
            eng.run_group_once("g", eval_ms=BASE + 5_000)
            rs = eng._groups[0].rules[0]
            assert len(rs.alerts) == 1
            # past the 5m lookback with no fresh samples: vector empty
            eng.run_group_once("g", eval_ms=BASE + 400_000)
            assert rs.alerts == {}
            notifier.drain()
            assert sent == []    # pending never notifies
        finally:
            notifier.close()

    def test_for_zero_fires_immediately(self):
        mapper, ms, pub, eng = self._eng(for_="0s")
        ts = BASE + np.arange(5, dtype=np.int64) * 1000
        _ingest(mapper, ms, "gauge_x", [({"inst": "a"}, 9 * np.ones(5))],
                ts)
        eng.run_group_once("g", eval_ms=BASE + 5_000)
        (inst,) = eng._groups[0].rules[0].alerts.values()
        assert inst.state == "firing"


class TestNotifier:
    def test_bounded_retry_then_delivered(self):
        calls = []

        def flaky(body):
            calls.append(body)
            if len(calls) < 3:
                raise OSError("conn refused")

        n = WebhookNotifier("http://x", retries=3, backoff_s=0.001,
                            send_fn=flaky)
        try:
            assert n.notify({"status": "firing", "labels": {}})
            assert n.drain()
            assert len(calls) == 3     # 2 failures + 1 success
        finally:
            n.close()

    def test_gives_up_after_bounded_retries(self):
        calls = []

        def dead(body):
            calls.append(body)
            raise OSError("nope")

        from filodb_tpu.utils.observability import REGISTRY
        failed0 = REGISTRY.counter(
            "filodb_rule_notifications_total").value(outcome="failed")
        n = WebhookNotifier("http://x", retries=2, backoff_s=0.001,
                            send_fn=dead)
        try:
            n.notify({"status": "firing", "labels": {}})
            assert n.drain()
            assert len(calls) == 3     # 1 + 2 retries, then give up
            assert REGISTRY.counter(
                "filodb_rule_notifications_total").value(
                outcome="failed") == failed0 + 1
        finally:
            n.close()

    def test_full_queue_drops_counted(self):
        import threading
        gate = threading.Event()
        n = WebhookNotifier("http://x", max_queued=1,
                            send_fn=lambda b: gate.wait(5))
        try:
            from filodb_tpu.utils.observability import REGISTRY
            drop0 = REGISTRY.counter(
                "filodb_rule_notifications_total").value(
                outcome="dropped")
            n.notify({"status": "firing", "labels": {}})
            time.sleep(0.05)           # worker picks up the first
            n.notify({"status": "firing", "labels": {}})
            dropped = not n.notify({"status": "firing", "labels": {}})
            gate.set()
            assert dropped
            assert REGISTRY.counter(
                "filodb_rule_notifications_total").value(
                outcome="dropped") == drop0 + 1
        finally:
            gate.set()
            n.close()

    def test_template_rendering(self):
        out = render_template("v={{ $value }} i={{ $labels.inst }} "
                              "x={{ $labels.missing }}",
                              {"inst": "i0"}, 2.5)
        assert out == "v=2.5 i=i0 x="
        # a non-finite value (zero-denominator rate ratio) must render,
        # not raise OverflowError and kill the rule's evaluation
        assert render_template("{{ $value }}", {}, float("inf")) == "inf"
        assert render_template("{{ $value }}", {}, 3.0) == "3"


# ---------------------------------------------------------------------------
# workload integration: the dedicated low-priority rules class
# ---------------------------------------------------------------------------


class TestRuleWorkloadClass:
    def test_rules_priority_has_its_own_share(self):
        from filodb_tpu.workload.admission import DEFAULT_PRIORITY_SHARES
        assert DEFAULT_PRIORITY_SHARES["rules"] < \
            DEFAULT_PRIORITY_SHARES["low"]

    def test_saturated_admission_sheds_rule_eval_not_engine(self):
        from filodb_tpu.workload.admission import AdmissionController
        from filodb_tpu.workload.cost import CostModel
        mapper, ms, binding = _harness()
        ts = BASE + np.arange(30, dtype=np.int64) * 1000
        _ingest(mapper, ms, "m_total",
                [({"inst": f"i{i}"}, np.cumsum(np.ones(30)))
                 for i in range(4)], ts)
        ctrl = AdmissionController(CostModel(), dataset="prom",
                                   max_inflight_cost=100.0)
        binding.admission = ctrl
        # eat the rules class's entire 40% share with a fake inflight
        ctrl._inflight_cost = 99.0
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "g", "interval": "10s", "rules": [
                {"record": "out:r", "expr": "rate(m_total[10s])"}]}]})
        eng.run_group_once("g", eval_ms=BASE + 30_000)
        rs = eng._groups[0].rules[0]
        assert rs.health == "err"
        assert "shed by admission control" in rs.last_error
        from filodb_tpu.utils.observability import REGISTRY
        assert REGISTRY.counter(
            "filodb_admission_rejected_total").value(
            dataset="prom", priority="rules", reason="overload") >= 1
        # headroom restored -> the engine recovers on the next tick
        ctrl._inflight_cost = 0.0
        eng.run_group_once("g", eval_ms=BASE + 30_000)
        assert rs.health == "ok" and pub.of("out:r")

    def test_evaluator_mints_deadline_and_priority(self):
        _mapper, _ms, binding = _harness()
        ev = RuleEvaluator(binding)
        qctx = ev._qctx(12_000)
        assert qctx.priority == "rules" and qctx.tenant == "_rules"
        assert qctx.deadline_ms > 0
        assert qctx.deadline_ms - qctx.submit_time_ms == 12_000


# ---------------------------------------------------------------------------
# incremental window state: the generative bit-equality sweep
# ---------------------------------------------------------------------------

_SWEEP_FNS = ["rate", "increase", "sum_over_time", "count_over_time",
              "avg_over_time", "max_over_time", "min_over_time",
              "delta", "last_over_time"]


class TestIncrementalWindows:
    def test_window_spec_recognition(self):
        from filodb_tpu.promql.parser import query_to_logical_plan
        ok = window_spec(query_to_logical_plan("rate(m[5m])", BASE))
        assert ok is not None and ok.window_ms == 300_000
        for expr in ("sum(rate(m[5m]))", "m", "rate(m[5m] offset 1m)",
                     "rate(m[5m]) > 0"):
            assert window_spec(
                query_to_logical_plan(expr, BASE)) is None, expr

    @pytest.mark.parametrize("seed", range(6))
    def test_generative_bit_equality(self, seed):
        """Warm incremental state after N random ingest/tick rounds is
        BIT-equal to (a) a cold full-range evaluation of the same state
        machine and (b) the normal query path's answer for the same
        expr at the same instant."""
        rng = np.random.default_rng(seed)
        mapper, ms, binding = _harness()
        ev = RuleEvaluator(binding)
        fn = _SWEEP_FNS[seed % len(_SWEEP_FNS)]
        window_s = int(rng.integers(5, 30))
        expr = f"{fn}(gen_m[{window_s}s])"
        from filodb_tpu.promql.parser import query_to_logical_plan
        spec = window_spec(query_to_logical_plan(expr, BASE))
        assert spec is not None
        warm = WindowState(spec)
        series = [{"inst": f"i{i}"} for i in range(3)]
        now = BASE
        offset = 0
        fetch = lambda f, s, e: ev.raw_series(f, s, e, 30_000)  # noqa: E731
        for _round in range(5):
            # ingest a random in-order slab for a random subset
            step = int(rng.integers(200, 1500))
            count = int(rng.integers(1, 15))
            ts = now + np.arange(count, dtype=np.int64) * step
            batch = []
            for tags in series:
                if rng.random() < 0.8:
                    batch.append((tags,
                                  np.cumsum(rng.random(count)) * 10))
            if batch:
                _ingest(mapper, ms, "gen_m", batch, ts, offset=offset)
                offset += 10
            now = int(ts[-1] + rng.integers(0, 2000))
            got_warm = {tuple(sorted(t.items())): v
                        for t, v in warm.tick(now, fetch)}
            cold = WindowState(spec)
            got_cold = {tuple(sorted(t.items())): v
                        for t, v in cold.tick(now, fetch)}
            direct = {}
            for tags, v in ev.instant_vector(expr, now, 30_000):
                direct[tuple(sorted(tags.items()))] = v
            assert set(got_warm) == set(got_cold) == set(direct), \
                (expr, _round)
            for k, v in got_warm.items():
                assert np.float64(v).tobytes() \
                    == np.float64(got_cold[k]).tobytes(), (expr, _round)
                assert np.float64(v).tobytes() \
                    == np.float64(direct[k]).tobytes(), (expr, _round)

    @pytest.mark.parametrize("seed", range(4))
    def test_generative_agg_bit_equality(self, seed):
        """The NEW aggregated incremental shape (``agg by (..)(fn(
        sel[w]))``): warm state after N random ingest/tick rounds is
        BIT-equal to a cold pass of the same machine AND to the normal
        query path's scatter-gather (per-shard map -> AggPartialBatch
        reduce -> present) at the same instant."""
        from filodb_tpu.rules.incremental import (AggWindowState,
                                                  agg_window_spec)
        rng = np.random.default_rng(seed + 100)
        mapper, ms, binding = _harness()
        ev = RuleEvaluator(binding)
        fn = ["rate", "increase", "sum_over_time", "max_over_time"][seed % 4]
        agg, by = [("sum", ""), ("avg", ""), ("sum", " by (grp)"),
                   ("max", " by (grp)")][seed % 4]
        window_s = int(rng.integers(5, 20))
        expr = f"{agg}{by}({fn}(gen_agg[{window_s}s]))"
        from filodb_tpu.promql.parser import query_to_logical_plan
        spec = agg_window_spec(query_to_logical_plan(expr, BASE))
        assert spec is not None
        warm = AggWindowState(spec)
        series = [{"inst": f"i{i}", "grp": f"g{i % 2}"} for i in range(4)]
        now = BASE
        offset = 0
        fetch = lambda f, s, e: ev.raw_series_sharded(f, s, e, 30_000)  # noqa: E731
        for _round in range(5):
            step = int(rng.integers(200, 1500))
            count = int(rng.integers(1, 15))
            ts = now + np.arange(count, dtype=np.int64) * step
            batch = []
            for tags in series:
                if rng.random() < 0.8:
                    batch.append((tags,
                                  np.cumsum(rng.random(count)) * 10))
            if batch:
                _ingest(mapper, ms, "gen_agg", batch, ts, offset=offset)
                offset += 10
            now = int(ts[-1] + rng.integers(0, 2000))

            def unpack(b):
                if b is None:
                    return {}
                vals = b.np_values()
                return {tuple(sorted(b.keys[i].items())):
                        np.float64(vals[i, 0]).tobytes()
                        for i in range(len(b.keys))
                        if not np.isnan(vals[i, 0])}

            got_warm = unpack(warm.tick(now, fetch))
            got_cold = unpack(AggWindowState(spec).tick(now, fetch))
            direct = {tuple(sorted(t.items())): np.float64(v).tobytes()
                      for t, v in ev.instant_vector(expr, now, 30_000)}
            assert got_warm == got_cold, (expr, _round)
            assert got_warm == direct, (expr, _round)

    def test_each_tick_consumes_only_new_samples(self):
        mapper, ms, binding = _harness()
        ev = RuleEvaluator(binding)
        from filodb_tpu.promql.parser import query_to_logical_plan
        spec = window_spec(
            query_to_logical_plan("sum_over_time(inc_m[60s])", BASE))
        state = WindowState(spec)
        ts = BASE + np.arange(50, dtype=np.int64) * 1000
        _ingest(mapper, ms, "inc_m", [({"inst": "a"}, np.ones(50))], ts)
        fetch = lambda f, s, e: ev.raw_series(f, s, e, 30_000)  # noqa: E731
        state.tick(BASE + 50_000, fetch)
        assert state.samples_consumed == 50
        _ingest(mapper, ms, "inc_m", [({"inst": "a"}, np.ones(5))],
                BASE + (51 + np.arange(5, dtype=np.int64)) * 1000,
                offset=10)
        state.tick(BASE + 56_000, fetch)
        # the 50 already-buffered samples were NOT re-consumed
        assert state.samples_consumed == 55
        # eviction keeps the state bounded to ~the window
        state.tick(BASE + 300_000, fetch)
        assert state.resident_samples == 0


# ---------------------------------------------------------------------------
# HTTP API payloads over a live server
# ---------------------------------------------------------------------------


class TestRulesHttpApi:
    def test_rules_alerts_admin_routes(self):
        import urllib.request
        from filodb_tpu.http.server import FiloHttpServer
        mapper, ms, binding = _harness()
        ts = BASE + np.arange(10, dtype=np.int64) * 1000
        _ingest(mapper, ms, "gauge_x", [({"inst": "a"}, 9 * np.ones(10))],
                ts)
        pub = _CapturePublisher()
        eng = _engine(binding, pub, {"groups": [{
            "name": "api-g", "interval": "5s", "rules": [
                {"record": "out:r", "expr": "sum_over_time(gauge_x[10s])"},
                {"alert": "Hot", "expr": "gauge_x > 5", "for": "0s"}]}]})
        eng.run_group_once("api-g", eval_ms=BASE + 10_000)
        srv = FiloHttpServer(port=0)
        srv.rules = eng
        port = srv.start()
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                    return json.loads(r.read())
            body = get("/api/v1/rules")
            g = body["data"]["groups"][0]
            assert g["name"] == "api-g"
            kinds = {r["name"]: r for r in g["rules"]}
            assert kinds["out:r"]["type"] == "recording"
            assert kinds["out:r"]["health"] == "ok"
            # the expr is served in its RENDERED canonical form
            assert kinds["out:r"]["query"] == "sum_over_time(gauge_x[10s])"
            assert kinds["Hot"]["state"] == "firing"
            assert kinds["Hot"]["alerts"][0]["labels"]["alertname"] == "Hot"
            body = get("/api/v1/alerts")
            assert body["data"]["alerts"][0]["state"] == "firing"
            body = get("/admin/rules")
            row = body["data"]["groups"][0]
            assert row["evals"] == 1 and row["missed"] == 0
            assert row["incremental"][0]["rule"] == "out:r"
            assert body["data"]["priority_class"] == "rules"
        finally:
            srv.shutdown()

    def test_routes_empty_without_engine(self):
        import urllib.error
        import urllib.request
        from filodb_tpu.http.server import FiloHttpServer
        srv = FiloHttpServer(port=0)
        port = srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/rules",
                    timeout=10) as r:
                assert json.loads(r.read())["data"] == {"groups": []}
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/admin/rules", timeout=10)
            assert ei.value.code == 404
        finally:
            srv.shutdown()
