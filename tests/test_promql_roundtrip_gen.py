"""Generative PromQL render/parse round-trip sweep.

`logical_plan_to_promql` is load-bearing for distribution: HA failover
and federation re-render plans and ship them to replicas
(coordinator/planners.py), so every renderable construct must parse
back to an equivalent plan.  The fixed list in test_planners.py covers
known shapes; this sweep composes random expressions from a grammar of
supported constructs (selectors with all four matcher types, range and
instant functions, grouped aggregations, topk/quantile, arithmetic and
comparison binaries, scalar operands) and asserts the render fixpoint —
render(parse(render(parse(q)))) == render(parse(q)) — plus preserved
plan type and time range.

Reference analog: coordinator/src/test/.../queryplanner/
LogicalPlanParserSpec.scala (render/parse round-trip assertions).
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planners import logical_plan_to_promql
from filodb_tpu.promql.parser import parse_query
from filodb_tpu.query import logical as lp

BASE = 1_700_000_000_000
STEP = 10_000
HOUR = 3_600_000

RANGE_FNS = ["rate", "increase", "avg_over_time", "max_over_time",
             "min_over_time", "sum_over_time", "count_over_time",
             "last_over_time", "delta", "deriv"]
INSTANT_FNS = ["abs", "ceil", "floor", "exp", "ln", "sqrt"]
AGGS = ["sum", "min", "max", "avg", "count", "stddev", "stdvar"]
WINDOWS = ["1m", "2m", "5m", "90s", "1h", "1m30s"]
BIN_OPS = ["+", "-", "*", "/", ">", "<", ">=", "<=", "=="]
MATCHERS = [('job', '=', '"api"'), ('job', '!=', '"web"'),
            ('inst', '=~', '"i.*"'), ('inst', '!~', '"x[0-9]+"')]


def _selector(rng):
    name = rng.choice(["http_req_total", "mem_bytes", "up"])
    k = int(rng.integers(0, 3))
    if not k:
        return name
    picks = rng.choice(len(MATCHERS), size=k, replace=False)
    ms = ",".join(f"{MATCHERS[i][0]}{MATCHERS[i][1]}{MATCHERS[i][2]}"
                  for i in sorted(picks))
    return f"{name}{{{ms}}}"


def _vector(rng, depth):
    roll = rng.random()
    if depth <= 0 or roll < 0.25:
        fn = rng.choice(RANGE_FNS)
        return f"{fn}({_selector(rng)}[{rng.choice(WINDOWS)}])"
    if roll < 0.45:
        return f"{rng.choice(INSTANT_FNS)}({_vector(rng, depth - 1)})"
    if roll < 0.75:
        op = rng.choice(AGGS)
        inner = _vector(rng, depth - 1)
        if rng.random() < 0.25:
            # nested aggregations with independent grouping clauses —
            # the shape /api/v1/rules must render (ISSUE 9)
            inner = f"{rng.choice(AGGS)}({inner}) without (inst)"
        grp = rng.random()
        if grp < 0.33:
            return f"{op}({inner}) by (g)"
        if grp < 0.5:
            return f"{op}({inner}) without (inst)"
        return f"{op}({inner})"
    if roll < 0.85:
        return f"topk(3, {_vector(rng, depth - 1)})"
    if roll < 0.9:
        return f"quantile(0.9, {_vector(rng, depth - 1)})"
    op = rng.choice(BIN_OPS)
    lhs = _vector(rng, depth - 1)
    rhs = str(round(float(rng.uniform(0.5, 9)), 2)) \
        if rng.random() < 0.5 else _vector(rng, depth - 1)
    return f"({lhs}) {op} ({rhs})"


@pytest.mark.parametrize("seed", range(8))
def test_generated_leaf_plans_survive_wire(seed):
    """Every leaf ExecPlan a planner would dispatch for a generated
    query must survive serialize -> real JSON -> deserialize ->
    serialize unchanged (the HTTP wire-dispatch path,
    client/SerializationSpec analog)."""
    import json

    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import DatasetOptions
    from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
    from filodb_tpu.query import wire

    mapper = ShardMapper(2)
    mapper.register_node(range(2), "local")
    for s in range(2):
        mapper.update_status(s, ShardStatus.ACTIVE)
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=0)
    rng = np.random.default_rng(100 + seed)
    checked = 0
    for _ in range(6):
        query = _vector(rng, depth=int(rng.integers(1, 3)))
        ep = planner.materialize(
            parse_query(query, BASE, STEP, BASE + HOUR))
        stack = [ep]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            if not node.children:       # leaf: what HTTP dispatch ships
                try:
                    d = wire.serialize_plan(node)
                except wire.WireError:
                    continue            # intentionally local-only plans
                d2 = json.loads(json.dumps(d))
                node2 = wire.deserialize_plan(d2)
                assert wire.serialize_plan(node2) == d, query
                checked += 1
    assert checked > 0


# the expression shapes the rules API serves (ISSUE 9): every rule's
# expr is exposed through logical_plan_to_promql on /api/v1/rules, so
# these exact forms — nested aggregations, by/without clauses,
# composite durations, threshold comparisons — must hold the render
# fixpoint the sweep asserts
RULE_API_EXPRS = [
    "sum by (dataset) (rate(filodb_ingest_samples_total[90s]))",
    "max by (g) (sum(rate(http_req_total[5m])) without (inst))",
    "sum(avg(max_over_time(mem_bytes[1h30m])) by (g))",
    "avg without (inst) (increase(http_req_total[1h]))",
    "sum by (dataset, shard) (delta(mem_bytes[2m30s]))",
    "(sum(rate(http_req_total[2m])) by (g)) > (3.5)",
    "quantile(0.99, sum(rate(http_req_total[5m])) by (g))",
    "count(count(up) by (inst)) by (g)",
]


def _selfmon_exprs():
    from filodb_tpu.rules.selfmon import selfmon_pack
    return [r["expr"] for g in selfmon_pack()["groups"]
            for r in g["rules"]]


@pytest.mark.parametrize("query",
                         RULE_API_EXPRS + _selfmon_exprs())
def test_rule_api_expr_shapes_roundtrip(query):
    """The renderer the rules API depends on: render(parse(q)) must be
    a fixpoint with preserved plan type and time range for every shape
    a rule file can carry."""
    start, end = BASE, BASE + HOUR
    plan = parse_query(query, start, STEP, end)
    rendered = logical_plan_to_promql(plan)
    plan2 = parse_query(rendered, start, STEP, end)
    assert type(plan2) is type(plan), query
    assert logical_plan_to_promql(plan2) == rendered, query
    assert lp.time_range(plan2) == lp.time_range(plan), query


@pytest.mark.parametrize("seed", range(16))
def test_generated_roundtrip(seed):
    rng = np.random.default_rng(seed)
    start, end = BASE, BASE + HOUR
    for _ in range(8):
        query = _vector(rng, depth=int(rng.integers(1, 4)))
        plan = parse_query(query, start, STEP, end)
        rendered = logical_plan_to_promql(plan)
        plan2 = parse_query(rendered, start, STEP, end)
        assert type(plan2) is type(plan), query
        assert logical_plan_to_promql(plan2) == rendered, query
        assert lp.time_range(plan2) == lp.time_range(plan), query
