"""Tiered-resolution rollup subsystem (filodb_tpu/rollup).

Oracle strategy: the OFFLINE downsample path (``downsample/``'s
ShardDownsampler full pass over every persisted raw chunk) is ground
truth; the live engine's incrementally-emitted tiers must be BIT-equal
to it over closed periods, across randomized multi-round ingest/tick
schedules, counter resets, restarts, and the raw/rolled stitch.
"""

import time

import numpy as np
import pytest

from filodb_tpu.core.record import (RecordBuilder, canonical_partkey,
                                    parse_partkey)
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.downsample.dsstore import ds_dataset_name
from filodb_tpu.downsample.sharddown import ShardDownsampler
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext
from filodb_tpu.rollup.config import RollupConfig, RollupConfigError
from filodb_tpu.rollup.engine import RollupEngine
from filodb_tpu.rollup.planner import (RollupRouterPlanner,
                                       parse_resolution_pref,
                                       resolution_limit_ms)
from filodb_tpu.store.columnstore import InMemoryColumnStore
from filodb_tpu.utils.observability import rollup_metrics

BASE = 1_700_000_000_000
RES = (60_000, 900_000)


class Harness:
    """Raw dataset + tier datasets in ONE memstore, engine wired the
    way standalone wires it (flush listener + tier publish fns)."""

    def __init__(self, resolutions=RES, store=None, meta=None,
                 idle_close_s=None, admission=None, stall_after_s=120.0,
                 schema="gauge"):
        self.resolutions = tuple(resolutions)
        self.store = store if store is not None else InMemoryColumnStore()
        self.ms = TimeSeriesMemStore(self.store, meta)
        self.shard = self.ms.setup("prom", DEFAULT_SCHEMAS, 0)
        self.schema = schema
        self.offsets: dict = {}
        for r in self.resolutions:
            self.ms.setup(ds_dataset_name("prom", r), DEFAULT_SCHEMAS, 0)
        self.publish_for = {r: self._pub(r) for r in self.resolutions}
        self.engine = RollupEngine(node="test")
        self.cfg = RollupConfig(resolutions_ms=self.resolutions,
                                idle_close_s=idle_close_s,
                                stall_after_s=stall_after_s)
        self.engine.watch("prom", self.ms, DEFAULT_SCHEMAS, self.cfg,
                          self.publish_for, column_store=self.store,
                          meta_store=self.ms.meta, admission=admission)
        self.offset = 0
        self.itime = 1000
        self.raw_planner = SingleClusterPlanner(
            "prom", ShardMapper(1), DatasetOptions(), spread_default=0)
        tiers = {r: SingleClusterPlanner(
            ds_dataset_name("prom", r), ShardMapper(1), DatasetOptions(),
            spread_default=0) for r in self.resolutions}
        self.router = RollupRouterPlanner(
            "prom", self.raw_planner, tiers,
            rolled_through_fn=lambda r: self.engine.rolled_through(
                "prom", r))

    def _pub(self, r):
        name = ds_dataset_name("prom", r)

        def pub(shard, container):
            off = self.offsets.get((name, shard), -1) + 1
            self.offsets[(name, shard)] = off
            self.ms.ingest(name, shard, container, off)
        return pub

    def ingest(self, series_rows: dict) -> None:
        """{tags_key: (tags, ts, vals)} appended as one batch."""
        b = RecordBuilder(DEFAULT_SCHEMAS[self.schema])
        for tags, ts, vals in series_rows:
            for t, v in zip(ts, vals):
                b.add(int(t), [float(v)], tags)
        for c in b.containers():
            self.ms.ingest("prom", 0, c, self.offset)
            self.offset += 1

    def ingest_hist(self, series_rows) -> None:
        """[(tags, ts, (buckets, rows [n, hb]))] prom-histogram batches
        (sum/count columns derived from the total bucket)."""
        from filodb_tpu.codecs import histcodec
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"])
        for tags, ts, (buckets, rows) in series_rows:
            for t, r in zip(ts, rows):
                blob = histcodec.encode_hist_value(buckets, r)
                b.add(int(t), (float(r[-1]), float(r[-1]), blob), tags)
        for c in b.containers():
            self.ms.ingest("prom", 0, c, self.offset)
            self.offset += 1

    def flush_tick(self) -> None:
        self.itime += 1
        self.shard.flush_all(ingestion_time=self.itime)
        self.engine.run_once("prom")

    # ------------------------------------------------------------ oracles

    def oracle_outputs(self, res):
        """Offline full-pass downsample over EVERY persisted raw chunk
        — the ground-truth ``downsample/`` path."""
        pairs = [(parse_partkey(cs.partkey), cs) for _it, cs in
                 self.store.chunksets_with_ingestion_time(
                     "prom", 0, 0, 1 << 62)]
        samp = ShardDownsampler("prom", 0, DEFAULT_SCHEMAS[self.schema],
                                None, self.resolutions)
        prepared = samp.prepare_arrays(pairs)
        return samp.downsample_arrays(prepared, res)

    def assert_tier_matches_oracle(self, res, last_ts_by_pk,
                                   closed=True) -> int:
        """Every tier series' persisted+resident rows must be byte-
        equal to the oracle restricted to closed periods."""
        tier_sh = self.ms.get_shard(ds_dataset_name("prom", res), 0)
        checked = 0
        for tags, pe, cols in self.oracle_outputs(res):
            pk = canonical_partkey(tags)
            pe = np.asarray(pe, dtype=np.int64)
            if closed:
                bound = ((last_ts_by_pk[pk] - 1) // res) * res
                m = pe <= bound
            else:
                m = np.ones(len(pe), bool)
            pid = tier_sh.part_set.get(pk)
            assert pid is not None, (res, tags)
            part = tier_sh.partitions[pid]
            got_ts, _ = part.read_range(0, 1 << 62, 1)
            assert np.asarray(got_ts).tobytes() == pe[m].tobytes(), \
                (res, tags)
            for ci in range(1, len(part.schema.data.columns)):
                _, got = part.read_range(0, 1 << 62, ci)
                want = cols[ci - 1]
                if isinstance(want, tuple):      # histogram column
                    wb, wr = want
                    gb, gr = got
                    assert np.asarray(gb.bucket_tops()).tobytes() == \
                        np.asarray(wb.bucket_tops()).tobytes(), (res, tags)
                    assert np.asarray(gr, np.float64).tobytes() == \
                        np.asarray(wr, np.float64)[m].tobytes(), \
                        (res, tags, ci)
                else:
                    assert np.asarray(got).tobytes() == \
                        np.asarray(want)[m].tobytes(), (res, tags, ci)
            checked += 1
        assert checked
        return checked

    def run_query(self, promql, start, step, end, planner=None,
                  resolution=""):
        qctx = QueryContext(sample_limit=10 ** 9,
                            resolution_pref=resolution)
        plan = query_range_to_logical_plan(promql, start, step, end)
        ep = (planner or self.router).materialize(plan, qctx)
        res = ep.execute(ExecContext(self.ms, qctx))
        out = {}
        for b in res.batches:
            vals = b.np_values()
            for i, tags in enumerate(b.keys):
                out[tags.get("inst", "")] = (
                    np.asarray(b.steps.timestamps()), vals[i])
        return out, res, qctx


class TestConfig:
    def test_ladder_validation(self):
        RollupConfig()   # defaults valid
        with pytest.raises(RollupConfigError):
            RollupConfig(resolutions_ms=())
        with pytest.raises(RollupConfigError):
            RollupConfig(resolutions_ms=(900_000, 60_000))
        with pytest.raises(RollupConfigError):
            RollupConfig(resolutions_ms=(60_000, 100_000))  # not multiple
        with pytest.raises(RollupConfigError):
            RollupConfig(resolutions_ms=(500,))
        with pytest.raises(RollupConfigError):
            RollupConfig(tick_interval_s=0)

    def test_from_config_refuses_unknown_keys(self):
        # a misspelled knob silently applying defaults is the broken-
        # rule-config failure mode this refuses at startup
        with pytest.raises(RollupConfigError):
            RollupConfig.from_config({"tick-interval": 5})
        with pytest.raises(RollupConfigError):
            RollupConfig.from_config({"idle_close": "4h"})
        RollupConfig.from_config({"enabled": True, "store": {},
                                  "query": {"workers": 2}})

    def test_from_config_idle_close_must_cover_coarsest(self):
        # an idle window shorter than the coarsest period would force-
        # close every open coarse period mid-way (partial records the
        # complete ones could never replace): refused at startup
        with pytest.raises(RollupConfigError):
            RollupConfig.from_config({"resolutions": ["1m", "1h"],
                                      "idle-close": "30m"})
        RollupConfig.from_config({"resolutions": ["1m", "1h"],
                                  "idle-close": "2h"})

    def test_from_config_durations(self):
        cfg = RollupConfig.from_config({
            "resolutions": ["1m", "15m", "1h"], "tick-interval-s": 5,
            "raw-retention": "6h", "idle-close": "0"})
        assert cfg.resolutions_ms == (60_000, 900_000, 3_600_000)
        assert cfg.raw_retention_ms == 6 * 3_600_000
        assert cfg.idle_close_s is None
        with pytest.raises(RollupConfigError):
            RollupConfig.from_config({"resolutions": ["bogus"]})

    def test_resolution_pref_parsing(self):
        assert parse_resolution_pref("") is None
        assert parse_resolution_pref("auto") is None
        assert parse_resolution_pref("raw") == 0
        assert parse_resolution_pref("1m") == 60_000

    def test_resolution_limit(self):
        plan = query_range_to_logical_plan(
            'sum_over_time(m[5m])', BASE, 3_600_000, BASE + 10 ** 7)
        assert resolution_limit_ms(plan, 3_600_000) == 300_000
        plan = query_range_to_logical_plan(
            'm', BASE, 3_600_000, BASE + 10 ** 7)
        # instant selector: the staleness lookback bounds the tier
        assert resolution_limit_ms(plan, 3_600_000) == 300_000
        plan = query_range_to_logical_plan(
            'sum_over_time(m[30m])', BASE, 900_000, BASE + 10 ** 7)
        assert resolution_limit_ms(plan, 900_000) == 900_000


def _mk_rows(rng, series_last, n_series, rows, span_ms, counter=False):
    batch = []
    for i in range(n_series):
        lo = series_last.get(i, BASE)
        ts = lo + np.sort(rng.integers(1, span_ms, rows))
        ts = np.unique(ts)
        series_last[i] = int(ts[-1])
        if counter:
            vals = np.cumsum(rng.random(len(ts)) * 3)
            if rng.random() < 0.4:          # occasional reset
                vals[len(vals) // 2:] -= vals[len(vals) // 2] * 0.95
        else:
            vals = rng.normal(10, 3, len(ts))
        name = "c_total" if counter else "m"
        tags = {"__name__": name, "inst": f"i{i}", "_ws_": "w",
                "_ns_": "n"}
        batch.append((tags, ts, vals))
    return batch


class TestLiveRollupEquivalence:
    """(a) of the equivalence satellite: warm incremental emission ==
    the offline downsample oracle, bit-equal over closed periods."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_gauge_generative(self, seed):
        rng = np.random.default_rng(seed)
        h = Harness()
        last: dict = {}
        for rnd in range(4):
            h.ingest(_mk_rows(rng, last, n_series=4, rows=150,
                              span_ms=20 * 60_000))
            h.flush_tick()
            if rng.random() < 0.3:
                h.engine.run_once("prom")   # extra no-op tick
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)
        tier_sh = h.ms.get_shard(ds_dataset_name("prom", RES[0]), 0)
        # per-series closure means no period is ever emitted twice
        assert tier_sh.stats.out_of_order_dropped == 0

    @pytest.mark.parametrize("seed", [5, 6])
    def test_histogram_generative_with_widening(self, seed):
        """ISSUE 14 satellite (ROADMAP 2 follow-up a): prom-histogram
        series roll through the hLast period oracle into the tiers,
        bit-equal to the offline downsample pass — including a
        MID-STREAM bucket widening (8 -> 12) whose narrow rows edge-pad
        under the widest scheme on both sides."""
        from filodb_tpu.core.histogram import GeometricBuckets
        rng = np.random.default_rng(seed)
        h = Harness(schema="prom-histogram")
        last: dict = {}
        t = BASE
        step = 10_000
        cums = {}
        for rnd in range(4):
            hb = 8 if rnd < 2 else 12          # widen mid-stream
            buckets = GeometricBuckets(2.0, 2.0, hb)
            rows_n = int(rng.integers(30, 90))
            batch = []
            for i in range(3):
                tags = {"_metric_": "lat", "inst": f"i{i}",
                        "_ws_": "w", "_ns_": "n"}
                cum = cums.get(i, np.zeros(hb, np.int64))
                if len(cum) < hb:              # carry totals forward
                    cum = np.pad(cum, (0, hb - len(cum)), mode="edge")
                rows = np.empty((rows_n, hb), np.int64)
                for r in range(rows_n):
                    cum = cum + rng.integers(0, 5, hb)
                    rows[r] = np.cumsum(cum)
                cums[i] = cum
                ts = t + np.arange(rows_n, dtype=np.int64) * step
                batch.append((tags, ts, (buckets, rows)))
                last[i] = int(ts[-1])
            h.ingest_hist(batch)
            t += rows_n * step
            h.flush_tick()
        last_by_pk = {
            canonical_partkey({"_metric_": "lat", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)

    @pytest.mark.parametrize("seed", [3, 4])
    def test_counter_with_resets_generative(self, seed):
        rng = np.random.default_rng(seed)
        h = Harness(schema="prom-counter")
        last: dict = {}
        for rnd in range(4):
            h.ingest(_mk_rows(rng, last, n_series=3, rows=120,
                              span_ms=15 * 60_000, counter=True))
            h.flush_tick()
        last_by_pk = {
            canonical_partkey({"_metric_": "c_total", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)

    def test_idle_close_emits_open_periods(self):
        h = Harness(idle_close_s=0.0)
        rng = np.random.default_rng(9)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, n_series=2, rows=100,
                          span_ms=10 * 60_000))
        h.flush_tick()
        # second tick: no new data -> every silent series force-closes
        h.engine.run_once("prom")
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk, closed=False)
        # state dropped after force-close
        st = h.engine.admin_state()["datasets"][0]["shards"][0]
        assert st["buffered_series"] == 0

    def test_resumed_series_never_recollides_with_forced_close(self):
        """A series resuming INSIDE a force-closed period must not
        re-emit that period's stamp (the tier's first-copy dedupe
        would keep the partial record and silently drop the re-emit):
        the idle-close sweep persists the emitted stamps as restart
        seeds, so the resumed state picks up where it closed."""
        h = Harness(idle_close_s=0.0)
        tags = {"__name__": "m", "inst": "i0", "_ws_": "w", "_ns_": "n"}
        res = RES[0]
        p_start = ((BASE // res) + 1) * res          # a period boundary
        first = p_start + np.arange(1, 20_000, 10_000, dtype=np.int64)
        h.ingest([(tags, first, np.ones(len(first)))])
        h.flush_tick()
        h.engine.run_once("prom")     # idle sweep: period force-closed
        tier_sh = h.ms.get_shard(ds_dataset_name("prom", res), 0)
        assert tier_sh.stats.rows_ingested >= 1
        # resume inside the SAME period, then past it
        later = p_start + np.arange(30_001, 3 * res, 10_000,
                                    dtype=np.int64)
        h.ingest([(tags, later, np.full(len(later), 2.0))])
        h.flush_tick()
        pk = canonical_partkey({"_metric_": "m", "inst": "i0",
                                "_ws_": "w", "_ns_": "n"})
        part = tier_sh.partitions[tier_sh.part_set[pk]]
        got_ts, counts = part.read_range(0, 1 << 62, 4)
        got_ts = np.asarray(got_ts)
        # stamps strictly increasing, the forced period never re-sent
        assert (np.diff(got_ts) > 0).all()
        assert tier_sh.stats.out_of_order_dropped == 0
        # the force-closed period keeps its (partial) count of 2; the
        # resumed rows inside it are the documented idle-close loss
        assert int(np.asarray(counts)[0]) == 2

    def test_resume_in_the_condemning_tick_is_not_force_closed(self):
        """A series whose resume flush lands in the very tick the idle
        scan first condemns it must NOT be force-closed: the fresh
        rows re-arm it, and its periods emit under normal closure."""
        h = Harness(idle_close_s=0.0)
        tags = {"__name__": "m", "inst": "i0", "_ws_": "w", "_ns_": "n"}
        res = RES[0]
        p_start = ((BASE // res) + 1) * res
        h.ingest([(tags, p_start + np.arange(1, 20_000, 10_000,
                                             dtype=np.int64),
                   np.ones(2))])
        h.flush_tick()
        # resume INSIDE the open period, consumed by the same tick the
        # idle scan would condemn the state
        h.ingest([(tags, p_start + np.arange(30_001, 60_000, 10_000,
                                             dtype=np.int64),
                   np.ones(3))])
        h.flush_tick()
        tier_sh = h.ms.get_shard(ds_dataset_name("prom", res), 0)
        pk = canonical_partkey({"_metric_": "m", "inst": "i0",
                                "_ws_": "w", "_ns_": "n"})
        pid = tier_sh.part_set.get(pk)
        if pid is not None:
            part = tier_sh.partitions[pid]
            got_ts, _ = part.read_range(0, 1 << 62, 1)
            # the open period (end p_start + res) must NOT be emitted
            assert p_start + res not in set(
                int(x) for x in np.asarray(got_ts))
        # close it normally and check the COMPLETE record landed
        h.ingest([(tags, np.asarray([p_start + res + 1], np.int64),
                   np.ones(1))])
        h.flush_tick()
        part = tier_sh.partitions[tier_sh.part_set[pk]]
        got_ts, counts = part.read_range(0, 1 << 62, 4)
        by_stamp = dict(zip((int(x) for x in np.asarray(got_ts)),
                            np.asarray(counts)))
        assert by_stamp[p_start + res] == 5.0   # all 2+3 rows counted

    def test_consume_failure_requeues_and_heals(self):
        """A decode/staging failure mid-consume must not LOSE the
        drained flush batches: they requeue and the next tick replays
        them losslessly."""
        import unittest.mock as mock
        from filodb_tpu.downsample import sharddown
        h = Harness()
        rng = np.random.default_rng(23)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 100, 10 * 60_000))
        with mock.patch.object(sharddown, "decode_concat_with_keys",
                               side_effect=RuntimeError("poisoned")):
            h.flush_tick()
        sr = h.engine._datasets["prom"].shards[0]
        assert sr.queue, "failed batches must requeue"
        assert h.engine._datasets["prom"].tier_errors
        tier_sh = h.ms.get_shard(ds_dataset_name("prom", RES[0]), 0)
        assert tier_sh.stats.rows_ingested == 0
        h.engine.run_once("prom")        # healed: replay the backlog
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)

    def test_stop_detaches_flush_listeners(self):
        h = Harness()
        assert h.shard.rollup_listener is not None
        h.engine.stop()
        assert h.shard.rollup_listener is None
        # a post-stop flush must not accumulate into dead queues
        rng = np.random.default_rng(24)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 1, 50, 5 * 60_000))
        h.shard.flush_all(ingestion_time=99)
        assert not h.engine._datasets["prom"].shards[0].queue

    def test_idle_drop_held_back_by_a_failed_tier_emission(self):
        """An idle (force-closed) series may only drop once EVERY tier
        emitted AND delivered — a transient reduce failure on one tier
        must not discard the rows the retry still needs."""
        import unittest.mock as mock
        h = Harness(idle_close_s=0.0)
        rng = np.random.default_rng(31)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        h.flush_tick()
        orig = ShardDownsampler.downsample_arrays

        def flaky(self, prepared, res):
            if res == RES[1]:
                raise RuntimeError("coarse tier reduce down")
            return orig(self, prepared, res)
        with mock.patch.object(ShardDownsampler, "downsample_arrays",
                               flaky):
            h.engine.run_once("prom")   # idle sweep, coarse tier fails
        sr = h.engine._datasets["prom"].shards[0]
        assert sr.series, "idle states dropped despite a failed tier"
        h.engine.run_once("prom")       # healed: force-close completes
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk, closed=False)

    def test_wedged_shard_trips_stall_despite_healthy_peer(self):
        """Per-shard stall clocks: one healthy shard must not mask a
        permanently wedged one."""
        h2 = Harness(stall_after_s=0.01)
        # second raw shard alongside the harness's shard 0
        shard1 = h2.ms.setup("prom", DEFAULT_SCHEMAS, 1)
        h2.engine.attach_shard("prom", shard1)
        good = h2.publish_for[RES[0]]

        def shard1_down(shard, container):
            if shard == 1:
                raise RuntimeError("shard-1 tier sink down")
            good(shard, container)
        h2.engine._datasets["prom"].publish_for[RES[0]] = shard1_down
        rng = np.random.default_rng(32)
        last: dict = {}
        rows = _mk_rows(rng, last, 2, 120, 10 * 60_000)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for tags, ts, vals in rows:
            for t, v in zip(ts, vals):
                b.add(int(t), [float(v)], tags)
        for c in b.containers():
            h2.ms.ingest("prom", 0, c, 0)
            h2.ms.ingest("prom", 1, c, 0)
        h2.shard.flush_all(ingestion_time=1)
        shard1.flush_all(ingestion_time=1)
        h2.engine.run_once("prom")
        time.sleep(0.05)
        h2.engine.run_once("prom")      # shard 0 idle-fine, shard 1 wedged
        stalled = rollup_metrics()["stalled"]
        assert stalled.value(dataset="prom",
                             resolution=str(RES[0])) == 1.0
        h2.engine.stop()

    def test_failing_schema_not_masked_by_healthy_one(self):
        """A counter schema wedged on a tier must keep the tier error
        visible and trip the stall gauge even while the gauge schema
        keeps emitting happily for the same resolution."""
        import unittest.mock as mock
        h = Harness(stall_after_s=0.01)
        rng = np.random.default_rng(41)
        glast: dict = {}
        clast: dict = {}
        orig = ShardDownsampler.downsample_arrays
        chash = DEFAULT_SCHEMAS["prom-counter"].schema_hash

        def flaky(self, prepared, res):
            if self.schema.schema_hash == chash:
                raise RuntimeError("counter reduce wedged")
            return orig(self, prepared, res)

        def ingest_both():
            h.ingest(_mk_rows(rng, glast, 2, 60, 8 * 60_000))
            b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
            for tags, ts, vals in _mk_rows(rng, clast, 2, 60,
                                           8 * 60_000, counter=True):
                for t, v in zip(ts, vals):
                    b.add(int(t), [float(v)], tags)
            for c in b.containers():
                h.ms.ingest("prom", 0, c, h.offset)
                h.offset += 1
        with mock.patch.object(ShardDownsampler, "downsample_arrays",
                               flaky):
            ingest_both()
            h.flush_tick()
            time.sleep(0.05)
            ingest_both()
            h.flush_tick()   # gauge advances again; counter still wedged
            assert h.engine._datasets["prom"].tier_errors, \
                "healthy schema cleared the wedged schema's error"
            stalled = rollup_metrics()["stalled"]
            assert stalled.value(dataset="prom",
                                 resolution=str(RES[0])) == 1.0
        h.engine.stop()

    def test_queue_overflow_recovers_via_store_replay(self):
        """A flush-queue overflow drops the handoff but flips the shard
        to the store-replay path: nothing persisted is lost."""
        import filodb_tpu.rollup.engine as eng_mod
        h = Harness()
        rng = np.random.default_rng(33)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 100, 10 * 60_000))
        h.flush_tick()                   # persists the replay floor
        old_cap = eng_mod._QUEUE_CAP
        eng_mod._QUEUE_CAP = 0
        try:
            h.ingest(_mk_rows(rng, last, 2, 100, 10 * 60_000))
            h.itime += 1
            h.shard.flush_all(ingestion_time=h.itime)   # overflows
        finally:
            eng_mod._QUEUE_CAP = old_cap
        sr = h.engine._datasets["prom"].shards[0]
        assert sr.lost and not sr.active
        assert h.engine._datasets["prom"].tier_errors
        h.engine.run_once("prom")        # restore replays from the store
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)

    def test_start_after_stop_reattaches_listeners(self):
        h = Harness()
        rng = np.random.default_rng(34)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        h.flush_tick()
        h.engine.stop()
        assert h.shard.rollup_listener is None
        h.engine.start()
        assert h.shard.rollup_listener is not None
        # flushes land again and the stopped gap replays from the store
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        h.itime += 1
        h.shard.flush_all(ingestion_time=h.itime)
        h.engine.run_once("prom")
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            h.assert_tier_matches_oracle(res, last_by_pk)
        h.engine.stop()

    def test_ownership_loss_removes_shard_gauge_rows(self):
        """A frozen lag row from before a failover must not keep an
        alert latched on the OLD owner forever."""
        h = Harness()
        rng = np.random.default_rng(35)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        h.flush_tick()
        lag = rollup_metrics()["lag"]
        assert any('dataset="prom"' in line and 'shard="0"' in line
                   for line in lag.expose())
        # the shard fails over: this node no longer owns it
        h.engine._datasets["prom"].owner_fn = lambda s: False
        h.engine.run_once("prom")
        assert not any('dataset="prom"' in line and 'shard="0"' in line
                       for line in lag.expose())
        h.engine.stop()

    def test_pure_replica_routes_from_delivered_tier_data(self):
        """A node that rolls nothing (owner_fn False) still routes from
        the rolled stamps DELIVERED to its tier replica — and a lagging
        delivery floors the stitch boundary instead of leaving holes."""
        h = Harness()
        h.engine._datasets["prom"].owner_fn = lambda s: False
        assert h.engine.rolled_through("prom", RES[0]) < 0
        # simulate the fanout delivering peer-rolled records
        b = RecordBuilder(DEFAULT_SCHEMAS["ds-gauge"])
        pe = (((BASE // RES[0]) + 1 + np.arange(5)) * RES[0]).astype(
            np.int64)
        b.add_series([int(x) for x in pe],
                     [[1.0] * 5, [1.0] * 5, [5.0] * 5, [5.0] * 5,
                      [1.0] * 5],
                     {"_metric_": "m", "inst": "i0", "_ws_": "w",
                      "_ns_": "n"})
        for off, c in enumerate(b.containers()):
            h.ms.ingest(ds_dataset_name("prom", RES[0]), 0, c, off)
        h.engine.run_once("prom")
        assert h.engine.rolled_through("prom", RES[0]) == int(pe[-1])


class TestStitchedServing:
    """(b) of the equivalence satellite: raw/rolled stitching at the
    tier boundary is continuous — no gap, no double-counted boundary
    step — across randomized ranges/steps."""

    @pytest.fixture(scope="class")
    def served(self):
        rng = np.random.default_rng(7)
        h = Harness()
        last: dict = {}
        for rnd in range(3):
            h.ingest(_mk_rows(rng, last, n_series=3, rows=200,
                              span_ms=40 * 60_000))
            h.flush_tick()
        return h, last

    @pytest.mark.parametrize("trial", range(6))
    def test_count_continuity_randomized(self, served, trial):
        h, last = served
        rng = np.random.default_rng(100 + trial)
        step = int(rng.choice([60_000, 120_000, 300_000]))
        lo = BASE + int(rng.integers(0, 20)) * 60_000
        hi = max(last.values()) + int(rng.integers(-10, 10)) * 60_000
        start = (lo // step + 1) * step
        end = (hi // step) * step
        q = f'count_over_time(m{{_ws_="w",_ns_="n"}}[{step // 1000}s])'
        got, res, qctx = h.run_query(q, start, step, end)
        want, res_raw, _ = h.run_query(q, start, step, end,
                                       planner=h.raw_planner)
        assert qctx.rollup_resolution_ms in (0,) + RES
        assert set(got) == set(want)
        for inst, (ts_w, vals_w) in want.items():
            ts_g, vals_g = got[inst]
            # full step grid answered — no gap at the stitch boundary
            np.testing.assert_array_equal(ts_g, ts_w)
            # counts are integers: rolled-region windows (sums of
            # per-period counts) must equal raw counts EXACTLY — a
            # double-counted or dropped boundary step cannot hide
            gw = np.nan_to_num(vals_g, nan=-1.0)
            ww = np.nan_to_num(vals_w, nan=-1.0)
            np.testing.assert_array_equal(gw, ww)

    def test_rolled_region_bitequal_to_offline_store(self, served):
        """A served rolled-tier answer over aligned windows is
        bit-equal to the same PromQL against a ds store built by the
        OFFLINE downsample path (the oracle serving arm)."""
        h, last = served
        res = RES[0]
        bound = min(((ts - 1) // res) * res for ts in last.values())
        step = 300_000
        start = (BASE // step + 2) * step
        end = (bound // step) * step
        q = f'sum_over_time(m{{_ws_="w",_ns_="n"}}[5m])'
        got, qres, qctx = h.run_query(q, start, step, end)
        assert qctx.rollup_resolution_ms == res
        # offline arm: BatchDownsampler-style store from the oracle
        # outputs, served through the plain tier planner
        oms = TimeSeriesMemStore()
        oname = ds_dataset_name("prom", res)
        osh = oms.setup(oname, DEFAULT_SCHEMAS, 0)
        b = RecordBuilder(DEFAULT_SCHEMAS["ds-gauge"])
        for tags, pe, cols in h.oracle_outputs(res):
            b.add_series([int(x) for x in pe],
                         [np.asarray(c).tolist() for c in cols], tags)
        for off, c in enumerate(b.containers()):
            oms.ingest(oname, 0, c, off)
        oplanner = SingleClusterPlanner(oname, ShardMapper(1),
                                        DatasetOptions(), spread_default=0)
        plan = query_range_to_logical_plan(q, start, step, end)
        ep = oplanner.materialize(plan, QueryContext(sample_limit=10 ** 9))
        ores = ep.execute(ExecContext(oms))
        want = {}
        for batch in ores.batches:
            vals = batch.np_values()
            for i, tags in enumerate(batch.keys):
                want[tags.get("inst", "")] = vals[i]
        assert set(got) == set(want)
        for inst, w in want.items():
            g = got[inst][1]
            assert np.asarray(g).tobytes() == np.asarray(w).tobytes(), \
                inst

    def test_min_scan_profit(self, served):
        """The acceptance ratio: a long-range rolled query scans >=10x
        fewer samples than the raw-pinned path."""
        h, last = served
        step = 300_000
        start = (BASE // step + 1) * step
        end = (max(last.values()) // step) * step
        q = f'sum_over_time(m{{_ws_="w",_ns_="n"}}[5m])'
        _, res_rolled, qctx = h.run_query(q, start, step, end)
        _, res_raw, _ = h.run_query(q, start, step, end,
                                    resolution="raw")
        assert qctx.rollup_resolution_ms == RES[0]
        assert res_rolled.stats.resolution_ms == 0  # stamped by HTTP layer
        assert res_raw.stats.samples_scanned >= \
            10 * res_rolled.stats.samples_scanned


class TestRouter:
    @pytest.fixture(scope="class")
    def harness(self):
        rng = np.random.default_rng(11)
        h = Harness(resolutions=(60_000, 900_000))
        last: dict = {}
        # regular cadence over ~6h so the 15m tier closes periods
        b = []
        for i in range(2):
            ts = BASE + np.arange(0, 6 * 3_600_000, 30_000) + 1
            b.append(({"__name__": "m", "inst": f"i{i}", "_ws_": "w",
                       "_ns_": "n"}, ts, rng.normal(5, 1, len(ts))))
            last[i] = int(ts[-1])
        h.ingest(b)
        h.flush_tick()
        return h, last

    def _materialize(self, h, q, start, step, end, resolution=""):
        qctx = QueryContext(sample_limit=10 ** 9,
                            resolution_pref=resolution)
        plan = query_range_to_logical_plan(q, start, step, end)
        h.router.materialize(plan, qctx)
        return qctx.rollup_resolution_ms

    def test_tier_selection(self, harness):
        h, last = harness
        end = (max(last.values()) // 3_600_000) * 3_600_000
        sel = 'm{_ws_="w",_ns_="n"}'
        # 15s step: no tier fits -> raw
        assert self._materialize(h, f'sum_over_time({sel}[15s])',
                                 BASE, 15_000, end) == 0
        # 5m window bounds the tier at 60s even at 1h step
        assert self._materialize(h, f'sum_over_time({sel}[5m])',
                                 BASE, 3_600_000, end) == 60_000
        # 30m window + 30m step -> the 15m tier
        assert self._materialize(h, f'sum_over_time({sel}[30m])',
                                 BASE, 1_800_000, end) == 900_000
        # explicit pins
        assert self._materialize(h, f'sum_over_time({sel}[30m])',
                                 BASE, 1_800_000, end,
                                 resolution="raw") == 0
        assert self._materialize(h, f'sum_over_time({sel}[30m])',
                                 BASE, 1_800_000, end,
                                 resolution="1m") == 60_000
        # an explicit pin OUTSIDE the ladder is a client error (400),
        # never a silent fall-through to raw
        with pytest.raises(ValueError):
            self._materialize(h, f'sum_over_time({sel}[30m])',
                              BASE, 1_800_000, end, resolution="5m")
        routed = rollup_metrics()["routed"]
        assert routed.value(dataset="prom", resolution="60000") >= 1
        assert routed.value(dataset="prom", resolution="raw") >= 1

    def test_retention_past_rolled_watermark_serves_raw_not_holes(
            self, harness):
        """raw-retention is a routing knob, not a deleter: when the
        tier's rolled watermark trails the retention floor, the raw
        side serves the gap — fresh steps must never come back empty."""
        h, last = harness
        end = (max(last.values()) // 300_000) * 300_000
        start = end - 3_600_000
        rolled_hwm = start + 600_000      # tier far behind retention
        router = RollupRouterPlanner(
            "prom", h.raw_planner,
            {60_000: h.router.tiers[60_000]},
            rolled_through_fn=lambda r: rolled_hwm,
            raw_retention_ms=1,           # "retention" = now-1ms
            now_ms_fn=lambda: end)
        qctx = QueryContext(sample_limit=10 ** 9)
        plan = query_range_to_logical_plan(
            'count_over_time(m{_ws_="w",_ns_="n"}[5m])',
            start, 300_000, end)
        ep = router.materialize(plan, qctx)
        res = ep.execute(ExecContext(h.ms, qctx))
        got = {}
        for b in res.batches:
            vals = b.np_values()
            for i, tags in enumerate(b.keys):
                got.setdefault(tags["inst"], {}).update(
                    zip((int(t) for t in b.steps.timestamps()), vals[i]))
        # raw-pinned twin for comparison
        plan2 = query_range_to_logical_plan(
            'count_over_time(m{_ws_="w",_ns_="n"}[5m])',
            start, 300_000, end)
        ep2 = h.raw_planner.materialize(plan2,
                                        QueryContext(sample_limit=10 ** 9))
        res2 = ep2.execute(ExecContext(h.ms))
        want = {}
        for b in res2.batches:
            vals = b.np_values()
            for i, tags in enumerate(b.keys):
                want.setdefault(tags["inst"], {}).update(
                    zip((int(t) for t in b.steps.timestamps()), vals[i]))
        assert set(got) == set(want)
        for inst in want:
            g = {t: (-1 if np.isnan(v) else v)
                 for t, v in got[inst].items()}
            w = {t: (-1 if np.isnan(v) else v)
                 for t, v in want[inst].items()}
            assert g == w, inst

    def test_retention_forces_finest_tier(self, harness):
        h, last = harness
        end = max(last.values())
        # raw retention of 1ms: everything is past retention; even a
        # 15s-step query must route (finest tier, best effort)
        tiers = {60_000: h.raw_planner}
        router = RollupRouterPlanner(
            "prom", h.raw_planner, tiers,
            rolled_through_fn=lambda r: end + 10 ** 9,
            raw_retention_ms=1)
        qctx = QueryContext(sample_limit=10 ** 9)
        plan = query_range_to_logical_plan(
            'sum_over_time(m[15s])', BASE, 15_000, end)
        router.materialize(plan, qctx)
        assert qctx.rollup_resolution_ms == 60_000


class TestOperational:
    def test_admission_defers_and_recovers(self):
        from filodb_tpu.workload.admission import AdmissionController
        from filodb_tpu.workload.cost import CostModel
        ctrl = AdmissionController(CostModel(), dataset="prom",
                                   max_inflight_cost=0.1, workers=1)
        h = Harness(admission=ctrl)
        rng = np.random.default_rng(5)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        before = rollup_metrics()["deferred"].value(dataset="prom")
        h.flush_tick()      # cost >= 1 > 0.3 * 0.1 ceiling -> shed
        assert rollup_metrics()["deferred"].value(dataset="prom") \
            == before + 1
        tier_sh = h.ms.get_shard(ds_dataset_name("prom", RES[0]), 0)
        assert tier_sh.stats.rows_ingested == 0
        # overload clears: the requeued batch is consumed next tick
        ctrl.configure(max_inflight_cost=1e9)
        h.engine.run_once("prom")
        assert tier_sh.stats.rows_ingested > 0
        ctrl.shutdown()

    def test_publish_failure_stalls_then_recovers(self):
        h = Harness(stall_after_s=0.01)
        boom = RuntimeError("tier sink down")
        good = h.publish_for[RES[0]]

        def bad(shard, container):
            raise boom
        h.engine._datasets["prom"].publish_for[RES[0]] = bad
        rng = np.random.default_rng(6)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 120, 10 * 60_000))
        errs = rollup_metrics()["errors"]
        before = errs.value(dataset="prom", resolution=str(RES[0]))
        h.flush_tick()
        assert errs.value(dataset="prom",
                          resolution=str(RES[0])) == before + 1
        time.sleep(0.05)
        h.engine.run_once("prom")   # still failing? no new data, but
        # the stall clock on the broken tier has not advanced
        stalled = rollup_metrics()["stalled"]
        assert stalled.value(dataset="prom",
                             resolution=str(RES[0])) == 1.0
        # cursors never advanced past the failed publish: healing the
        # sink re-emits everything, losslessly
        h.engine._datasets["prom"].publish_for[RES[0]] = good
        h.ingest(_mk_rows(rng, last, 2, 40, 4 * 60_000))
        h.flush_tick()
        assert stalled.value(dataset="prom",
                             resolution=str(RES[0])) == 0.0
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        h.assert_tier_matches_oracle(RES[0], last_by_pk)

    def test_admin_endpoint_and_stop_removes_gauges(self):
        from filodb_tpu.http.server import FiloHttpServer
        h = Harness()
        rng = np.random.default_rng(8)
        last: dict = {}
        h.ingest(_mk_rows(rng, last, 2, 80, 10 * 60_000))
        h.flush_tick()
        srv = FiloHttpServer(rollup=h.engine)
        code, body = srv._admin_rollup()
        assert code == 200
        ds = body["data"]["datasets"][0]
        assert ds["dataset"] == "prom"
        assert ds["passes"] >= 1
        sh0 = ds["shards"][0]
        assert sh0["buffered_series"] == 2
        assert sh0["tiers"][str(RES[0])]["emitted_through_ms"] is not None
        assert int(ds["samples_written"][str(RES[0])]) > 0
        # CLI text renderer consumes the same payload without raising
        import io
        from contextlib import redirect_stdout
        from filodb_tpu import cli
        import unittest.mock as mock

        class A:
            server = "http://x"
            json = False
        with mock.patch.object(cli, "_http_get",
                               return_value={"status": "success",
                                             "data": body["data"]}):
            buf = io.StringIO()
            with redirect_stdout(buf):
                assert cli.cmd_rollup_status(A()) == 0
            assert "dataset prom" in buf.getvalue()
        # stop() removes every exported gauge row (Gauge.remove contract)
        lag = rollup_metrics()["lag"]
        assert any("filodb_rollup_lag_seconds{" in line
                   for line in lag.expose())
        h.engine.stop()
        rows = [line for line in lag.expose()
                if 'dataset="prom"' in line]
        assert not rows

    def test_no_rollup_endpoint_404(self):
        from filodb_tpu.http.server import FiloHttpServer
        srv = FiloHttpServer()
        code, _ = srv._admin_rollup()
        assert code == 404


class TestRestart:
    def test_resumes_from_persisted_hwm(self, tmp_path):
        from filodb_tpu.store.persistence import (DiskColumnStore,
                                                  DiskMetaStore)
        store = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        rng = np.random.default_rng(13)
        last: dict = {}

        h1 = Harness(store=store, meta=meta)
        for rnd in range(2):
            h1.ingest(_mk_rows(rng, last, 3, 120, 15 * 60_000))
            h1.flush_tick()
        # persist the TIER datasets too (their chunk stamps are the
        # restart cursors), then "crash"
        seeded = {}
        for r in RES:
            tsh = h1.ms.get_shard(ds_dataset_name("prom", r), 0)
            tsh.flush_all(ingestion_time=5000)
            seeded[r] = {pk: tsh.partitions[pid].latest_timestamp
                         for pk, pid in tsh.part_set.items()}
        offset, itime = h1.offset, h1.itime
        h1.engine.stop()
        h1.ms.reset()

        h2 = Harness(store=store, meta=meta)
        h2.offset, h2.itime = offset, itime
        h2.ingest(_mk_rows(rng, last, 3, 120, 15 * 60_000))
        h2.flush_tick()
        last_by_pk = {
            canonical_partkey({"_metric_": "m", "inst": f"i{i}",
                               "_ws_": "w", "_ns_": "n"}): ts
            for i, ts in last.items()}
        for res in RES:
            tier_sh = h2.ms.get_shard(ds_dataset_name("prom", res), 0)
            # the fresh node re-emitted NOTHING the old node persisted
            for pk, pid in tier_sh.part_set.items():
                part = tier_sh.partitions.get(pid)
                if part is None:
                    continue
                ts_new, _ = part.read_range(0, 1 << 62, 1)
                if len(ts_new) and pk in seeded[res]:
                    assert int(ts_new[0]) > seeded[res][pk], (res, pk)
            # persisted (pre-crash) + resident (post-restart) rows
            # together equal the continuous-run oracle
            samp_pairs = {}
            for _it, cs in store.chunksets_with_ingestion_time(
                    ds_dataset_name("prom", res), 0, 0, 1 << 62):
                from filodb_tpu.core.chunk import decode_chunkset
                ts_c, cols_c = decode_chunkset(
                    DEFAULT_SCHEMAS["ds-gauge"], cs)
                entry = samp_pairs.setdefault(cs.partkey, [])
                entry.append((np.asarray(ts_c), [np.asarray(c)
                                                 for c in cols_c]))
            checked = 0
            for tags, pe, cols in h2.oracle_outputs(res):
                pk = canonical_partkey(tags)
                bound = ((last_by_pk[pk] - 1) // res) * res
                pe = np.asarray(pe, dtype=np.int64)
                m = pe <= bound
                got_ts = []
                got_cols = [[] for _ in cols]
                for ts_c, cols_c in samp_pairs.get(pk, []):
                    got_ts.append(ts_c)
                    for ci, c in enumerate(cols_c):
                        got_cols[ci].append(c)
                pid = tier_sh.part_set.get(pk)
                if pid is not None and pid in tier_sh.partitions:
                    part = tier_sh.partitions[pid]
                    ts_r, _ = part.read_range(0, 1 << 62, 1)
                    if len(ts_r):
                        got_ts.append(np.asarray(ts_r))
                        for ci in range(len(cols)):
                            _, v = part.read_range(0, 1 << 62, ci + 1)
                            got_cols[ci].append(np.asarray(v))
                all_ts = np.concatenate(got_ts) if got_ts else \
                    np.empty(0, np.int64)
                order = np.argsort(all_ts, kind="stable")
                all_ts = all_ts[order]
                # no duplicates across the restart boundary
                assert (np.diff(all_ts) > 0).all(), (res, tags)
                assert all_ts.astype(np.int64).tobytes() == \
                    pe[m].tobytes(), (res, tags)
                for ci in range(len(cols)):
                    v = np.concatenate(got_cols[ci])[order]
                    assert v.tobytes() == \
                        np.asarray(cols[ci])[m].tobytes(), (res, tags)
                checked += 1
            assert checked == 3
        h2.engine.stop()
