"""Chaos e2e for elastic resharding (ISSUE 13 acceptance criteria).

A 3-node rf=2 broker-backed cluster under live ingest + a query loop
runs a live 4 -> 8 shard split and takes, mid-flight:

1. a HARD KILL of a node holding child replicas, mid-catch-up — the
   split keeps serving (children are invisible to fan-out, parent
   groups fail over exactly as PR 12 proved), and
2. a PARTITION of the coordinator during cutover — the phase machine
   stalls (the cutover gate requires every fresh peer to have adopted
   the phase generation), serving continues from the surviving view,
   and the split RESUMES to completion after heal.

Every answer across both faults is HTTP 200 and equal to a no-fault
unsplit oracle: BIT-equal on the duplicate-sensitive legs
(``count_over_time`` / ``sum_over_time`` over integer-valued samples —
one dropped or double-counted row changes them), and 1e-9-relative on
the float-sum rate leg (doubling the shard count legitimately regroups
the cross-shard reduce by the last ulp).  After completion the children
serve, ``/admin/shards`` + ``/admin/split`` report the doubled
topology, and the retired parents hold none of the migrated half.

Kept in tier-1: this is THE acceptance test for elastic resharding.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import math
import numpy as np
import pytest

from filodb_tpu.core.record import (RecordBuilder, partition_hash,
                                    shard_key_hash)
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.ingest.broker import BrokerClient, BrokerServer
from filodb_tpu.integrity.faultinject import (FlakyTcpProxy,
                                              NodeChaosController)
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.standalone import FiloServer

BASE = 1_700_000_000_000
NUM_SHARDS = 4
NODES = ("sp-a", "sp-b", "sp-c")   # sp-a is the lowest name -> leader
N_INSTANCES = 12
N_SAMPLES = 240
WINDOW = (BASE + 60_000, BASE + 180_000)

RATE_Q = 'sum(rate(sp_total[2m]))'
COUNT_Q = 'sum(count_over_time(sp_total[1m]))'
SUM_Q = 'sum(sum_over_time(sp_total[1m]))'


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, timeout=30, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read()), dict(e.headers)
        except Exception:
            return e.code, {"error": str(e)}, {}


def _query(port, promql):
    return _get(port, "/promql/sp/api/v1/query_range", timeout=25,
                query=promql, start=WINDOW[0] / 1000, end=WINDOW[1] / 1000,
                step="15s")


def _canon(body):
    return sorted((tuple(sorted(s["metric"].items())),
                   tuple((t, v) for t, v in s["values"]))
                  for s in body["data"]["result"])


def _near(canon_a, canon_b, rel=1e-9):
    if len(canon_a) != len(canon_b):
        return False
    for (ka, va), (kb, vb) in zip(canon_a, canon_b):
        if ka != kb or len(va) != len(vb):
            return False
        for (ta, xa), (tb, xb) in zip(va, vb):
            if ta != tb or not math.isclose(float(xa), float(xb),
                                            rel_tol=rel, abs_tol=1e-12):
                return False
    return True


def _equalish(q, got, want):
    return _near(got, want) if q == RATE_Q else got == want


def _node_config(node, http_port, broker_port, data_dir, peer_endpoints):
    return {
        "node": node,
        "http-port": http_port,
        "data-dir": str(data_dir),
        "peers": dict(peer_endpoints),
        "status-poll-interval-s": 0.25,
        "failure-detector-timeout-ms": 1_500,
        "dataplane": {"watermark-sample-interval-s": 3600},
        "datasets": [{
            "name": "sp", "num-shards": NUM_SHARDS, "min-num-nodes": 3,
            "replication-factor": 2, "schema": "gauge", "spread": 1,
            "source": {"factory": "broker", "port": broker_port,
                       "topic": "sp"},
            "store": {"flush-interval": "1h", "groups-per-shard": 4},
            "workload": {"dispatch": {"retries": 1, "backoff-s": 0.01,
                                      "timeout-cap-s": 10}},
        }],
    }


def _series_tags(i):
    return {"_metric_": "sp_total", "instance": f"i{i}",
            "_ws_": "w", "_ns_": "n"}


def _produce_frozen(client, route_mapper):
    """The oracle window: INTEGER-valued cumulative series, routed by
    the same bit-splice the cluster uses (exact float sums under any
    reduce grouping — the bit-equality substrate)."""
    by_shard = {s: RecordBuilder(DEFAULT_SCHEMAS["gauge"],
                                 container_size=1 << 16)
                for s in range(NUM_SHARDS)}
    opts = DatasetOptions()
    rng = np.random.default_rng(7)
    n = 0
    for i in range(N_INSTANCES):
        tags = _series_tags(i)
        shard = route_mapper.ingestion_shard(
            shard_key_hash(tags, opts), partition_hash(tags, opts),
            1) % NUM_SHARDS
        vals = np.cumsum(rng.integers(1, 1000, N_SAMPLES))
        for k in range(N_SAMPLES):
            by_shard[shard].add(BASE + k * 1000, [float(vals[k])], tags)
            n += 1
    for s, b in by_shard.items():
        for c in b.containers():
            client.produce("sp", s, c)
    return n


def _bg_container(i):
    """Live-ingest traffic: timestamps BEYOND the frozen window so the
    oracle comparison is never perturbed, varied shard keys so both
    halves of the split see traffic."""
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 13)
    b.add(BASE + 400_000 + i * 250, [float(i)],
          {"__name__": f"sp_bg{i % 5}", "instance": f"bg{i % 11}",
           "_ws_": "w", "_ns_": "n"})
    (out,) = b.containers()
    return out


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    broker = BrokerServer(port=0)
    broker.start()
    client = BrokerClient(port=broker.port)
    client.create_topic("sp", NUM_SHARDS)

    route_mapper = ShardMapper(NUM_SHARDS)
    n_frozen = _produce_frozen(client, route_mapper)

    ports = {n: _free_port() for n in NODES}
    proxies = {n: FlakyTcpProxy(backend_port=ports[n]) for n in NODES}
    for p in proxies.values():
        p.start()
    peer_eps = {n: f"http://127.0.0.1:{proxies[n].port}" for n in NODES}

    dirs = {n: tmp_path_factory.mktemp(n) for n in NODES}
    servers = {}
    chaos = NodeChaosController()
    for n in NODES:
        servers[n] = FiloServer(_node_config(n, ports[n], broker.port,
                                             dirs[n], peer_eps))
        servers[n].start()
        chaos.register(
            n,
            kill_fn=(lambda _s=servers[n]: (_s.http.shutdown(),
                                            _s.shutdown())),
            proxy=proxies[n])
        chaos.attach_split_controller(n, servers[n].split_controller)

    # convergence: rf=2 groups live + all frozen rows ingested
    deadline = time.time() + 60
    converged = False
    while time.time() < deadline:
        m = servers[NODES[0]].manager.mapper("sp")
        groups_ok = all(len(m.live_replicas(s)) == 2
                        for s in range(NUM_SHARDS))
        statuses_ok = all(
            r.status.value == "Active"
            for s in range(NUM_SHARDS) for r in m.live_replicas(s))
        rows_ok = all(
            sum(sh.stats.rows_ingested
                for sh in servers[n].memstore.shards("sp")) > 0
            for n in NODES)
        totals = sum(sh.stats.rows_ingested
                     for n in NODES
                     for sh in servers[n].memstore.shards("sp"))
        if groups_ok and statuses_ok and rows_ok \
                and totals >= 2 * n_frozen:   # rf=2: every row twice
            converged = True
            break
        time.sleep(0.1)
    assert converged, "3-node rf=2 cluster never converged"

    yield {"servers": servers, "ports": ports, "proxies": proxies,
           "chaos": chaos, "client": client, "broker": broker,
           "dirs": dirs, "peer_eps": peer_eps, "n_frozen": n_frozen}

    for n, srv in servers.items():
        if not chaos.killed(n):
            try:
                srv.shutdown()
            except Exception:
                pass
    for p in proxies.values():
        p.shutdown()
    client.close()
    broker.shutdown()


class TestChaosSplit:
    """One ordered scenario (pytest runs methods in definition order
    within the module-scoped cluster)."""

    def test_1_oracle_then_kill_child_node_mid_catchup(self, cluster):
        servers, ports, chaos = (cluster["servers"], cluster["ports"],
                                 cluster["chaos"])
        client = cluster["client"]

        # ---- no-fault, unsplit oracle on the coordinator
        oracles = {}
        for q in (RATE_Q, COUNT_Q, SUM_Q):
            code, body, headers = _query(ports["sp-a"], q)
            assert code == 200 and body["status"] == "success", body
            assert body["data"]["result"], f"oracle empty for {q}"
            assert headers.get("X-FiloDB-Partial-Data") is None
            oracles[q] = _canon(body)
        cluster["oracles"] = oracles

        # checkpoints exist -> children clone + replay from them
        for n in NODES:
            servers[n].flush_all()

        # ---- live ingest while the split runs
        stop_produce = threading.Event()

        def produce_loop():
            i = 0
            while not stop_produce.is_set():
                try:
                    client.produce("sp", i % NUM_SHARDS, _bg_container(i))
                except Exception:
                    pass
                i += 1
                time.sleep(0.002)

        producer = threading.Thread(target=produce_loop, daemon=True)
        producer.start()
        cluster["stop_produce"] = stop_produce
        cluster["producer"] = producer

        # ---- trigger the split on the leader, cutover held so the
        # kill deterministically lands mid-catch-up
        ctrl = servers["sp-a"].split_controller
        ctrl.hold("cutover")
        st = ctrl.trigger("sp", grace_s=2.0)
        assert st["phase"] == "catchup" and st["total_shards"] == 8

        # children registered on the parents' replica nodes, Recovery
        m = servers["sp-a"].manager.mapper("sp")
        assert m.total_shards == 8 and m.num_shards == NUM_SHARDS
        for child in range(NUM_SHARDS, 8):
            assert m.replicas(child), f"child {child} has no replicas"

        # wait until sp-b actually participates (clone marker or child
        # consumer running), so the kill hits real mid-catch-up state
        def b_participates():
            srv_b = servers["sp-b"]
            if any(srv_b.metastore.read_kv(f"splitclone::sp::{c}")
                   for c in range(NUM_SHARDS, 8)):
                return True
            return any(s >= NUM_SHARDS
                       for s in srv_b._running_shards("sp"))
        deadline = time.time() + 30
        while time.time() < deadline and not b_participates():
            time.sleep(0.1)
        assert b_participates(), "sp-b never joined the catch-up"

        # ---- queries in flight while a child-holding node dies
        results = []

        def query_loop(seconds):
            t_end = time.time() + seconds
            while time.time() < t_end:
                q = (RATE_Q, COUNT_Q, SUM_Q)[len(results) % 3]
                code, body, headers = _query(ports["sp-a"], q)
                results.append((q, code, body, headers))
                time.sleep(0.05)

        qt = threading.Thread(target=query_loop, args=(5.0,), daemon=True)
        qt.start()
        time.sleep(0.8)
        chaos.kill("sp-b")          # hard kill mid-catch-up
        qt.join(timeout=30)

        assert len(results) > 20
        bad = [(q, code) for q, code, body, _h in results if code != 200
               or body.get("status") != "success"]
        assert not bad, f"client-visible failures across the kill: {bad}"
        partial = [h for _q, _c, _b, h in results
                   if h.get("X-FiloDB-Partial-Data")]
        assert not partial, "partial results despite a live replica"
        # pre-cutover topology: every answer BIT-equal (unchanged
        # reduce tree), duplicate-sensitive legs included
        for q, _code, body, _h in results:
            assert _canon(body) == oracles[q], \
                f"mid-kill result diverged from oracle for {q}"
        # the split is still in catch-up (cutover held + b down)
        assert ctrl.status("sp")["phase"] == "catchup"

    def test_2_rejoin_then_partition_coordinator_mid_cutover(self, cluster):
        servers, ports, chaos = (cluster["servers"], cluster["ports"],
                                 cluster["chaos"])
        oracles = cluster["oracles"]

        # ---- sp-b rejoins (replays from its checkpoints, re-clones /
        # resumes its children) — PR 12 machinery end to end
        def start_b():
            srv = FiloServer(_node_config(
                "sp-b", ports["sp-b"], cluster["broker"].port,
                cluster["dirs"]["sp-b"], cluster["peer_eps"]))
            srv.start()
            servers["sp-b"] = srv
            chaos.register("sp-b",
                           kill_fn=(lambda _s=srv: (_s.http.shutdown(),
                                                    _s.shutdown())),
                           proxy=cluster["proxies"]["sp-b"])
            chaos.attach_split_controller("sp-b", srv.split_controller)
            return srv

        chaos.restart("sp-b", start_b)

        # sp-b adopts the in-flight topology from gossip AND its parent
        # replicas promote back to Active (otherwise a later fault on
        # another replica has no healthy peer to fail over to)
        deadline = time.time() + 45
        rejoined = False
        while time.time() < deadline:
            m = servers["sp-a"].manager.mapper("sp")
            b_parents = [m.state(s).replica("sp-b")
                         for s in range(NUM_SHARDS)
                         if m.state(s).replica("sp-b") is not None]
            if servers["sp-b"].manager.mapper("sp").total_shards == 8 \
                    and b_parents \
                    and all(r.status.value == "Active"
                            for r in b_parents):
                rejoined = True
                break
            time.sleep(0.1)
        assert rejoined, "rejoined node never promoted back to Active"

        # ---- partition the coordinator at the cutover window.  The
        # chaos proxy cuts sp-a's INBOUND edge (peers cannot see it),
        # the classic asymmetric partition: the coordinator may commit
        # the cutover on its own majority view (harmless — parents
        # hold full supersets and generations are monotone), but the
        # cut-off peers MUST keep serving the old topology bit-equal,
        # and the DESTRUCTIVE phase (retire: parents purge) must never
        # advance while any reachable peer still lags the cutover
        # generation.
        ctrl = servers["sp-a"].split_controller
        chaos.partition("sp-a")
        chaos.release_split("sp-a", "cutover")
        t_end = time.time() + 3.0
        while time.time() < t_end:
            for q in (RATE_Q, COUNT_Q, SUM_Q):
                code, body, headers = _query(ports["sp-c"], q)
                assert code == 200 and body["status"] == "success"
                assert headers.get("X-FiloDB-Partial-Data") is None
                assert _canon(body) == oracles[q], \
                    f"mid-partition result diverged for {q}"
            time.sleep(0.1)
        phase = ctrl.status("sp")["phase"]
        assert phase in ("catchup", "serving"), \
            f"destructive phase {phase} advanced during the partition"
        # the cut-off peers cannot have adopted the cutover generation
        assert servers["sp-c"].manager.mapper("sp").num_shards \
            == NUM_SHARDS, "partitioned peer adopted the cutover"

        # ---- heal: the split resumes and runs to completion
        chaos.heal("sp-a")
        deadline = time.time() + 90
        while time.time() < deadline:
            if ctrl.status("sp")["phase"] == "complete":
                break
            time.sleep(0.2)
        assert ctrl.status("sp")["phase"] == "complete", \
            ctrl.status("sp")
        assert chaos.wait_split_phase("sp", "serving", 5)
        assert chaos.wait_split_phase("sp", "retire", 5)

    def test_3_children_serve_bit_equal_everywhere(self, cluster):
        servers, ports = cluster["servers"], cluster["ports"]
        oracles = cluster["oracles"]
        cluster["stop_produce"].set()
        cluster["producer"].join(timeout=5)

        # every node converged on the doubled topology
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(servers[n].manager.mapper("sp").num_shards == 8
                   and servers[n].manager.mapper("sp").topology
                   .split_phase is None for n in NODES):
                break
            time.sleep(0.1)
        for n in NODES:
            m = servers[n].manager.mapper("sp")
            assert m.num_shards == 8, f"{n} still at {m.num_shards}"
            assert m.topology.split_phase is None

        # zero dropped, zero double-counted: duplicate-sensitive legs
        # BIT-equal from every node's query surface, served by the
        # post-split topology (children + filtered/purged parents)
        for n in NODES:
            deadline = time.time() + 30
            ok = False
            while time.time() < deadline and not ok:
                ok = True
                for q in (COUNT_Q, SUM_Q, RATE_Q):
                    code, body, _h = _query(ports[n], q)
                    if code != 200 or \
                            not _equalish(q, _canon(body), oracles[q]):
                        ok = False
                        time.sleep(0.2)
                        break
            assert ok, f"node {n} diverged from the unsplit oracle"

        # the children actually hold and serve the migrated half
        child_rows = sum(
            sh.stats.rows_ingested + sh.stats.rows_split_filtered
            for n in NODES
            for sh in servers[n].memstore.shards("sp")
            if sh.shard_num >= NUM_SHARDS)
        assert child_rows > 0, "children ingested nothing"

        # retired parents physically dropped the migrated half: no
        # parent partition rehashes to a child shard anymore
        from filodb_tpu.parallel.shardmap import shard_of_tags
        for n in NODES:
            for sh in servers[n].memstore.shards("sp"):
                if sh.shard_num >= NUM_SHARDS:
                    continue
                for part in sh.partitions.values():
                    assert shard_of_tags(part.tags, 8, 1) == sh.shard_num, \
                        (n, sh.shard_num, part.tags)

    def test_4_admin_surfaces_report_the_split(self, cluster):
        ports = cluster["ports"]
        code, body, _h = _get(ports["sp-a"], "/admin/split/sp", timeout=10)
        assert code == 200
        st = body["data"]
        assert st["phase"] == "complete"
        assert st["total_shards"] == 8
        assert st["cutover_seconds"] is not None
        code, body, _h = _get(ports["sp-a"], "/admin/shards", timeout=10)
        assert code == 200
        ds = body["data"]["datasets"]["sp"]
        assert ds["topology"]["num_shards"] == 8
        # the ledger shows the LOCALLY-held shards; children this node
        # holds appear alongside their parents
        held = {r["shard"] for r in ds["shards"]}
        assert any(s >= NUM_SHARDS for s in held), held
        # CLI status against the live server
        from filodb_tpu.cli import main as cli_main
        rc = cli_main(["split-status", "--server",
                       f"http://127.0.0.1:{ports['sp-a']}",
                       "--dataset", "sp", "--json"])
        assert rc == 0
