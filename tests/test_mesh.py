"""Mesh engine (parallel/mesh.py) vs single-device path on the virtual
8-device CPU mesh — the stand-in for the reference's sbt-multi-jvm cluster
tests (SURVEY.md §4)."""

import numpy as np
import pytest

from filodb_tpu.core.chunk import build_batch
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
from filodb_tpu.query.logical import AggregationOperator as Agg
from filodb_tpu.query.logical import RangeFunctionId as F
from filodb_tpu.query.rangefns import apply_range_function

WINDOW = 300_000
SRANGE = StepRange(1_000_000, 1_450_000, 30_000)


def _mk_shards(num_shards=6, series_per_shard=5, rows=120, seed=0):
    rng = np.random.default_rng(seed)
    batches, gids = [], []
    for k in range(num_shards):
        ts, vs = [], []
        n = series_per_shard - (k % 2)  # uneven shards
        for s in range(n):
            r = rows - rng.integers(0, 30)
            t = np.sort(rng.integers(700_000, 1_460_000, size=r)).astype(np.int64)
            t = np.unique(t)
            v = np.cumsum(rng.random(len(t)) * 10).astype(np.float64)
            ts.append(t)
            vs.append(v)
        batches.append(build_batch(ts, vs))
        gids.append(np.array([s % 3 for s in range(n)], dtype=np.int32))
    return batches, gids


def _oracle(batches, gids, num_groups, func, agg):
    """Single-device kernels + numpy group aggregation."""
    per_shard = []
    for b, g in zip(batches, gids):
        stepped = np.asarray(apply_range_function(b, SRANGE, WINDOW, func))
        per_shard.append((stepped[: len(g)], g))
    T = SRANGE.num_steps
    all_vals = np.concatenate([s for s, _ in per_shard], axis=0)
    all_ids = np.concatenate([g for _, g in per_shard], axis=0)
    out = np.full((num_groups, T), np.nan)
    for g in range(num_groups):
        rows = all_vals[all_ids == g]
        fin = np.isfinite(rows)
        any_fin = fin.any(axis=0)
        if agg == Agg.SUM:
            v = np.where(fin, rows, 0.0).sum(axis=0)
        elif agg == Agg.COUNT:
            v = fin.sum(axis=0).astype(float)
        elif agg == Agg.AVG:
            v = np.where(fin, rows, 0.0).sum(axis=0) / np.maximum(fin.sum(axis=0), 1)
        elif agg == Agg.MIN:
            v = np.where(fin, rows, np.inf).min(axis=0)
        elif agg == Agg.MAX:
            v = np.where(fin, rows, -np.inf).max(axis=0)
        elif agg == Agg.STDDEV:
            n = np.maximum(fin.sum(axis=0), 1)
            m = np.where(fin, rows, 0.0).sum(axis=0) / n
            v = np.sqrt(np.maximum(
                np.where(fin, rows**2, 0.0).sum(axis=0) / n - m * m, 0.0))
        out[g] = np.where(any_fin, v, np.nan)
    return out


@pytest.fixture(scope="module")
def engine():
    return MeshEngine(make_mesh(shape=(4, 2)))


@pytest.mark.parametrize("agg", [Agg.SUM, Agg.COUNT, Agg.AVG, Agg.MIN,
                                 Agg.MAX, Agg.STDDEV])
def test_rate_agg_matches_single_device(engine, agg):
    batches, gids = _mk_shards()
    got = engine.window_aggregate(batches, gids, 3, SRANGE, WINDOW,
                                  range_fn=F.RATE, agg_op=agg)
    want = _oracle(batches, gids, 3, F.RATE, agg)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_gather_kernel_on_mesh(engine):
    batches, gids = _mk_shards(seed=7)
    got = engine.window_aggregate(batches, gids, 3, SRANGE, WINDOW,
                                  range_fn=F.MAX_OVER_TIME, agg_op=Agg.MAX)
    want = _oracle(batches, gids, 3, F.MAX_OVER_TIME, Agg.MAX)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_last_sample_selector_on_mesh(engine):
    batches, gids = _mk_shards(seed=3)
    got = engine.window_aggregate(batches, gids, 3, SRANGE, WINDOW,
                                  range_fn=None, agg_op=Agg.SUM)
    want = _oracle(batches, gids, 3, None, Agg.SUM)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_shard_axis_only_mesh():
    eng = MeshEngine(make_mesh(shape=(8, 1)))
    batches, gids = _mk_shards(num_shards=3, seed=11)
    got = eng.window_aggregate(batches, gids, 3, SRANGE, WINDOW,
                               range_fn=F.INCREASE, agg_op=Agg.SUM)
    want = _oracle(batches, gids, 3, F.INCREASE, Agg.SUM)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_init_multihost_single_process():
    """init_multihost joins a (1-process) distributed runtime and builds
    the global mesh engine — run in a subprocess because
    jax.distributed.initialize binds a coordination service for the
    process's lifetime."""
    import os
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:      # pick a free port, avoid collisions
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = textwrap.dedent(f"""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from filodb_tpu.parallel import mesh as m
        eng = m.init_multihost(coordinator_address="127.0.0.1:{port}",
                               num_processes=1, process_id=0)
        assert len(jax.devices()) == 8
        assert eng.mesh.devices.size == 8
        print("OK")
    """)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=180, cwd=repo_root)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
