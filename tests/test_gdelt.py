"""Multi-column event scans + topK — the GDELT use case.

The reference's dormant spark/ DataSource existed for wide event tables
(GDELT notebook, reference: doc/FiloDB_GDELT.snb; SURVEY §2.6 maps the
capability onto the multi-schema columnar core).  These tests prove the
core serves it natively: a wide event schema (several numeric columns +
a string column), per-column selected scans, group-by aggregation over
a chosen column, and topK ranking — all through the same ExecPlan
machinery the Prometheus path uses.
"""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DatasetOptions, Schemas
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec import (ExecContext, MultiSchemaPartitionsExec,
                                   ReduceAggregateExec)
from filodb_tpu.query.logical import (AggregationOperator, RangeFunctionId)
from filodb_tpu.query.model import QueryContext
from filodb_tpu.query.transformers import (AggregateMapReduce,
                                           AggregatePresenter,
                                           PeriodicSamplesMapper)

GDELT_SCHEMAS = Schemas.from_config({
    "gdelt-event": {
        "columns": ["timestamp:ts", "avg_tone:double", "num_mentions:double",
                    "num_articles:double", "event_code:string"],
        "value-column": "avg_tone",
        "downsamplers": [],
    },
})

T0 = 1_600_000_000_000
DAY = 86_400_000
N_DAYS = 30
ACTORS = ["USA", "CHN", "RUS", "DEU", "FRA", "GBR", "IND", "BRA"]


def _mk_store(seed=0):
    ms = TimeSeriesMemStore()
    shard = ms.setup("gdelt", GDELT_SCHEMAS, 0, StoreConfig())
    rng = np.random.default_rng(seed)
    b = RecordBuilder(GDELT_SCHEMAS["gdelt-event"], DatasetOptions())
    truth = {}
    for ai, actor in enumerate(ACTORS):
        tags = {"_metric_": "events", "actor": actor, "_ws_": "g",
                "_ns_": "news"}
        ts = T0 + np.arange(N_DAYS, dtype=np.int64) * DAY
        tone = rng.normal(0, 3, N_DAYS)
        mentions = rng.integers(1, 50, N_DAYS).astype(float) * (ai + 1)
        articles = rng.integers(1, 20, N_DAYS).astype(float)
        codes = [f"{rng.integers(10, 20):03d}" for _ in range(N_DAYS)]
        truth[actor] = (ts, tone, mentions, articles, codes)
        for i in range(N_DAYS):
            b.add(int(ts[i]), [tone[i], mentions[i], articles[i], codes[i]],
                  tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, GDELT_SCHEMAS), off)
    shard.flush_all()
    return ms, shard, truth


WINDOW = N_DAYS * DAY   # one window covering the whole month
STEPS0 = T0 + (N_DAYS - 1) * DAY + 1


def _leaf(column, fn=RangeFunctionId.SUM_OVER_TIME):
    leaf = MultiSchemaPartitionsExec(
        "gdelt", 0, [ColumnFilter("_metric_", Equals("events"))],
        T0, STEPS0, column=column)
    leaf.add_transformer(PeriodicSamplesMapper(
        start_ms=STEPS0, step_ms=DAY, end_ms=STEPS0,
        window_ms=WINDOW, function=fn))
    return leaf


class TestGdeltScans:
    def test_column_selected_scan(self):
        """Selecting a non-default column scans that column's chunks."""
        ms, shard, truth = _mk_store()
        leaf = _leaf("num_mentions")
        res = leaf.execute(ExecContext(ms, QueryContext()))
        got = {b_tags["actor"]: float(vals[0])
               for b in res.batches
               for b_tags, _ts, vals in b.to_series()}
        want = {a: truth[a][2].sum() for a in ACTORS}
        assert set(got) == set(ACTORS)
        for a in ACTORS:
            np.testing.assert_allclose(got[a], want[a], rtol=1e-9)

    def test_group_sum_over_column(self):
        """sum by ()(sum_over_time(num_articles[30d])) — full-table
        aggregate over a selected column."""
        ms, shard, truth = _mk_store()
        leaf = _leaf("num_articles")
        leaf.add_transformer(AggregateMapReduce(AggregationOperator.SUM))
        root = ReduceAggregateExec([leaf], AggregationOperator.SUM)
        root.add_transformer(AggregatePresenter(AggregationOperator.SUM))
        res = root.execute(ExecContext(ms, QueryContext()))
        got = float(res.batches[0].np_values()[0][0])
        want = sum(truth[a][3].sum() for a in ACTORS)
        np.testing.assert_allclose(got, want, rtol=1e-9)

    def test_topk_actors_by_mentions(self):
        """topk(3, sum_over_time(num_mentions[30d])) — the GDELT
        notebook's 'top actors' analysis (reference: doc/FiloDB_GDELT.snb)."""
        ms, shard, truth = _mk_store()
        leaf = _leaf("num_mentions")
        leaf.add_transformer(AggregateMapReduce(
            AggregationOperator.TOPK, params=(3,)))
        root = ReduceAggregateExec([leaf], AggregationOperator.TOPK, (3,))
        root.add_transformer(AggregatePresenter(
            AggregationOperator.TOPK, (3,)))
        res = root.execute(ExecContext(ms, QueryContext()))
        got = {}
        for b in res.batches:
            for tags, _ts, vals in b.to_series():
                v = np.asarray(vals)
                if np.isfinite(v).any():
                    got[tags["actor"]] = float(v[np.isfinite(v)][0])
        totals = {a: truth[a][2].sum() for a in ACTORS}
        want_top = sorted(totals, key=totals.get, reverse=True)[:3]
        assert set(got) == set(want_top)
        for a in want_top:
            np.testing.assert_allclose(got[a], totals[a], rtol=1e-9)

    def test_string_column_roundtrip(self):
        """The string column (dict-encoded) survives freeze + scan."""
        ms, shard, truth = _mk_store()
        res = shard.lookup_partitions(
            [ColumnFilter("actor", Equals("USA"))], 0, 2**62)
        assert len(res.part_ids) == 1
        part = shard.partitions[int(res.part_ids[0])]
        cid = part.schema.data.column("event_code").id
        ts, codes = part.read_range(0, 2**62, cid)
        # strings read back as UTF-8 bytes (ZeroCopyUTF8String contract)
        decoded = [c.decode() if isinstance(c, bytes) else c for c in codes]
        assert decoded == truth["USA"][4]
        assert len(ts) == N_DAYS

    def test_value_column_default_is_avg_tone(self):
        ms, shard, truth = _mk_store()
        leaf = _leaf(None)
        res = leaf.execute(ExecContext(ms, QueryContext()))
        got = {t["actor"]: float(v[0]) for b in res.batches
               for t, _ts, v in b.to_series()}
        for a in ACTORS:
            np.testing.assert_allclose(got[a], truth[a][1].sum(), rtol=1e-9)
