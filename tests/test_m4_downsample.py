"""M4 visualization downsampling (ISSUE 16, doc/coldstore.md).

Oracle strategy: the device kernel is SELECTION-only — per pixel bin
it picks min/max/first/last values and their indices, never computing
new values — so the interpret-mode kernel, the portable jnp reference
and a pure-NumPy loop oracle must all be BIT-equal (float32), across
NaN gaps, constant runs (ties break to the FIRST occurrence), all-NaN
bins and partial tiles.  The DownsampleMapper keeps <= 4*pixels points
per series and only ever re-emits original samples; the HTTP
``?downsample=`` edge wires it in and carries the points-in/out stats.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import ShardManager
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.ops.grid import (M4_PLANES, m4_grid, m4_grid_auto,
                                 m4_grid_ref)
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.query.model import PeriodicBatch, StepRange
from filodb_tpu.query.transformers import DownsampleMapper

BASE = 1_700_000_000_000


# ---------------------------------------------------------------------------
# NumPy loop oracle
# ---------------------------------------------------------------------------


def _m4_oracle(vals: np.ndarray, pixels: int) -> np.ndarray:
    """Per (bin, series): [vmin vmax vfirst vlast imin imax ifirst
    ilast], indices LOCAL to the bin, -1 / NaN for empty bins — the
    M4_PLANES contract, written as the obvious double loop."""
    vals = np.asarray(vals, np.float32)
    t, s = vals.shape
    w = -(-t // pixels)
    pad = np.full((pixels * w - t, s), np.nan, np.float32)
    v = np.concatenate([vals, pad], axis=0).reshape(pixels, w, s)
    out = np.empty((pixels, 8, s), np.float32)
    for p in range(pixels):
        for j in range(s):
            col = v[p, :, j]
            idxs = np.flatnonzero(np.isfinite(col))
            if len(idxs) == 0:
                out[p, :4, j] = np.nan
                out[p, 4:, j] = -1.0
                continue
            imin = idxs[np.argmin(col[idxs])]   # first occurrence wins
            imax = idxs[np.argmax(col[idxs])]
            ifirst, ilast = idxs[0], idxs[-1]
            out[p, :, j] = (col[imin], col[imax], col[ifirst],
                            col[ilast], imin, imax, ifirst, ilast)
    return out


def _bit_equal(a, b) -> bool:
    return np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)


def _cases():
    rng = np.random.default_rng(5)
    t, s = 103, 8
    gappy = rng.normal(0, 10, (t, s)).astype(np.float32)
    gappy[rng.random((t, s)) < 0.3] = np.nan     # NaN gaps
    const = np.ones((t, s), np.float32) * 7.5    # constant runs (ties)
    allnan = gappy.copy()
    allnan[:, 3] = np.nan                        # one all-NaN series
    allnan[40:80, :] = np.nan                    # empty bins mid-range
    exact = rng.normal(0, 1, (100, s)).astype(np.float32)  # t % P == 0
    return [("gappy", gappy, 10), ("const", const, 10),
            ("allnan", allnan, 10), ("exact", exact, 10),
            ("partial-tail", gappy, 9),          # w*P > T: padded tile
            ("one-per-bin", exact, 100)]         # w == 1


class TestM4Kernel:
    @pytest.mark.parametrize("name,vals,pixels",
                             _cases(), ids=[c[0] for c in _cases()])
    def test_interpret_kernel_bitequal_to_oracle(self, name, vals,
                                                 pixels):
        """CPU CI drives the real pallas kernel body in interpret mode:
        it must match the NumPy loop oracle BIT for bit."""
        want = _m4_oracle(vals, pixels)
        got = np.asarray(m4_grid(vals, pixels, lanes=8, interpret=True))
        assert got.shape == (pixels, 8, vals.shape[1])
        for k, plane in enumerate(M4_PLANES):
            assert _bit_equal(got[:, k, :], want[:, k, :]), (name, plane)

    @pytest.mark.parametrize("name,vals,pixels",
                             _cases(), ids=[c[0] for c in _cases()])
    def test_portable_ref_bitequal_to_oracle(self, name, vals, pixels):
        assert _bit_equal(m4_grid_ref(vals, pixels),
                          _m4_oracle(vals, pixels)), name

    def test_ties_break_to_first_occurrence(self):
        # [5, 1, 1, 5, 5] in one bin: min at LOCAL index 1, max at 0
        vals = np.array([[5], [1], [1], [5], [5]], np.float32)
        got = np.asarray(m4_grid_ref(vals, 1))[0, :, 0]
        assert got[4] == 1.0 and got[5] == 0.0    # imin, imax
        assert got[6] == 0.0 and got[7] == 4.0    # ifirst, ilast

    def test_auto_dispatch_matches_ref(self):
        rng = np.random.default_rng(9)
        vals = rng.normal(0, 1, (77, 16)).astype(np.float32)
        assert _bit_equal(m4_grid_auto(vals, 7), m4_grid_ref(vals, 7))


# ---------------------------------------------------------------------------
# DownsampleMapper
# ---------------------------------------------------------------------------


def _batch(vals: np.ndarray, step=30_000) -> PeriodicBatch:
    s, t = vals.shape
    return PeriodicBatch([{"inst": f"i{i}"} for i in range(s)],
                         StepRange(BASE, BASE + (t - 1) * step, step), vals)


class TestDownsampleMapper:
    def test_keeps_at_most_4x_pixels_only_original_samples(self):
        rng = np.random.default_rng(3)
        t, s, px = 10_000, 3, 100
        vals = rng.normal(0, 5, (s, t))
        vals[rng.random((s, t)) < 0.1] = np.nan
        [out] = DownsampleMapper(pixels=px).apply([_batch(vals)], None)
        thinned = out.np_values()
        f32 = vals.astype(np.float32)
        for i in range(s):
            kept = np.isfinite(thinned[i])
            assert kept.sum() <= 4 * px
            # every kept point is the original sample at that step
            assert np.array_equal(thinned[i][kept], f32[i][kept])
        # pixel-exactness: per bin, min and max survive the thinning
        w = -(-t // px)
        for i in range(s):
            for p in range(0, px, 17):
                seg, out_seg = f32[i, p * w:(p + 1) * w], \
                    thinned[i, p * w:(p + 1) * w]
                if np.isfinite(seg).any():
                    assert np.nanmin(seg) in out_seg[np.isfinite(out_seg)]
                    assert np.nanmax(seg) in out_seg[np.isfinite(out_seg)]

    def test_passthrough_when_already_small(self):
        vals = np.arange(12, dtype=np.float64).reshape(2, 6)
        b = _batch(vals)
        [out] = DownsampleMapper(pixels=6).apply([b], None)
        assert out is b    # num_steps <= pixels: untouched
        [out2] = DownsampleMapper(pixels=1000).apply([b], None)
        assert out2 is b

    def test_stats_count_points(self):
        from filodb_tpu.query.exec import ExecContext
        from filodb_tpu.query.model import QueryStats
        rng = np.random.default_rng(4)
        vals = rng.normal(0, 1, (2, 5_000))
        ctx = ExecContext(None)
        DownsampleMapper(pixels=50).apply([_batch(vals)], ctx)
        qs = QueryStats()
        ctx.fold_into(qs)
        assert qs.downsample_points_in == 10_000
        assert 0 < qs.downsample_points_out <= 2 * 4 * 50


# ---------------------------------------------------------------------------
# HTTP ?downsample=
# ---------------------------------------------------------------------------


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="class")
def server():
    mapper = ShardMapper(1)
    mapper.register_node(range(1), "local")
    mapper.update_status(0, ShardStatus.ACTIVE)
    ms = TimeSeriesMemStore()
    ms.setup("prom", DEFAULT_SCHEMAS, 0)
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    ts = BASE + np.arange(4_000, dtype=np.int64) * 15_000
    for i in range(2):
        b.add_series(ts, [rng.normal(3, 1, len(ts))],
                     {"_metric_": "g", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"})
    for off, c in enumerate(b.containers()):
        ms.get_shard("prom", 0).ingest_container(c, off)
    mgr = ShardManager()
    mgr.setup_dataset("prom", 1, min_num_nodes=1)
    mgr.add_node("local")
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=0)
    srv = FiloHttpServer(shard_manager=mgr)
    srv.bind_dataset(DatasetBinding("prom", ms, planner))
    port = srv.start()
    yield port
    srv.shutdown()


class TestHttpDownsample:
    Q = 'g{_ws_="w",_ns_="n"}'
    END = BASE + 4_000 * 15_000

    def _points(self, body):
        return {r["metric"]["inst"]: r["values"]
                for r in body["data"]["result"]}

    def test_egress_reduction_and_stats(self, server):
        code, full = _get(server, "/promql/prom/api/v1/query_range",
                          query=self.Q, start=BASE / 1000,
                          end=self.END / 1000, step="15s", stats="true")
        assert code == 200
        code, thin = _get(server, "/promql/prom/api/v1/query_range",
                          query=self.Q, start=BASE / 1000,
                          end=self.END / 1000, step="15s", stats="true",
                          downsample="64")
        assert code == 200
        fullp, thinp = self._points(full), self._points(thin)
        assert set(fullp) == set(thinp)
        for inst in fullp:
            n_full, n_thin = len(fullp[inst]), len(thinp[inst])
            assert n_thin <= 4 * 64
            assert n_full / n_thin >= 10   # real egress reduction
            # pixel-exact: every served point is an original sample
            orig = {t: np.float32(float(v)) for t, v in fullp[inst]}
            for t, v in thinp[inst]:
                assert t in orig and np.float32(float(v)) == orig[t]
        st = thin["data"]["stats"]["downsample"]
        assert st["pointsIn"] >= 2 * 4_000
        assert 0 < st["pointsOut"] <= 2 * 4 * 64
        assert full["data"]["stats"]["downsample"]["pointsOut"] == 0

    def test_invalid_downsample_is_client_error(self, server):
        for bad in ("abc", "-4", "0", "2000000"):
            code, body = _get(server, "/promql/prom/api/v1/query_range",
                              query=self.Q, start=BASE / 1000,
                              end=(BASE + 600_000) / 1000, step="15s",
                              downsample=bad)
            assert code == 400, bad
            assert body["errorType"] == "bad_data"
