"""REAL multi-process distribution tests: actual OS processes, actual
sockets — the analog of the reference's forked-JVM multi-node specs
(reference: coordinator/src/multi-jvm/.../ClusterRecoverySpec.scala,
standalone/src/multi-jvm/.../ClusterSingletonFailoverSpec.scala).

Two planes are proven across process boundaries:
- the DATA plane: the SPMD mesh serving program with its psum riding
  cross-process collectives (jax.distributed + Gloo on CPU; ICI/DCN on
  a real TPU pod), each process contributing only its own shard;
- the CONTROL plane: two FiloServer nodes converging shard ownership
  via status gossip, then one PromQL query scatter-gathering over the
  HTTP wire dispatch and merging both processes' data.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as sk:
        sk.bind(("127.0.0.1", 0))
        return sk.getsockname()[1]


def _spawn(script: str, *args: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, script), *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=os.path.dirname(HERE))


class TestCrossProcessCollective:
    def test_mesh_psum_across_two_processes(self):
        """Each process feeds ONE shard; the psum'd [G, T] must equal
        the host oracle over BOTH shards — on both processes."""
        addr = f"127.0.0.1:{_free_port()}"
        procs = [_spawn("mp_collective_worker.py", str(pid), addr)
                 for pid in (0, 1)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rc, out, err in outs:
            assert rc == 0, f"worker failed:\n{out}\n{err[-2000:]}"
            assert "RESULT OK" in out, out
            # the HBM-RESIDENT grid x mesh path ran end-to-end on this
            # worker (round-5 item 3): per-process staged pieces under
            # the global mesh, serve + memoized repeat asserted in-worker
            assert "RESIDENT OK" in out, out
            assert "serves=2" in out, out
        # both processes computed the identical replicated result
        sums = [line.split()[2] for rc, out, _ in outs
                for line in out.splitlines() if line.startswith("RESULT")]
        assert len(sums) == 2 and sums[0] == sums[1] and \
            sums[0] != "OK", sums
        rsums = [line.split()[2] for rc, out, _ in outs
                 for line in out.splitlines()
                 if line.startswith("RESIDENT")]
        assert len(rsums) == 2 and rsums[0] == rsums[1] and \
            rsums[0] != "OK", rsums


class TestCrossProcessCluster:
    def test_query_scatter_gathers_across_two_server_processes(self):
        """Two FiloServer processes split 4 shards; a query to node A
        must count EVERY series, including those owned by node B's
        process (HTTP wire dispatch + partial merge)."""
        port_a, port_b = _free_port(), _free_port()
        pa = _spawn("mp_node_worker.py", "node-a", str(port_a),
                    "node-b", str(port_b))
        pb = _spawn("mp_node_worker.py", "node-b", str(port_b),
                    "node-a", str(port_a))
        procs = [pa, pb]
        owned = {}
        try:
            deadline = time.time() + 120
            ready = set()
            while time.time() < deadline and len(ready) < 2:
                for name, p in (("node-a", pa), ("node-b", pb)):
                    if name in ready:
                        continue
                    assert p.poll() is None, \
                        (name, p.communicate()[0],
                         p.communicate()[1][-2000:])
                    line = p.stdout.readline()
                    if line.startswith("READY"):
                        owned[name] = [int(s) for s in
                                       line.split()[1].split(",")]
                        ready.add(name)
                    elif line.startswith("NEVER_CONVERGED"):
                        pytest.fail(f"{name} never converged: {line}")
            assert len(ready) == 2, f"workers not ready: {ready}"
            assert owned["node-a"] and owned["node-b"]
            assert sorted(owned["node-a"] + owned["node-b"]) == [0, 1, 2, 3]

            qs = urllib.parse.urlencode({
                "query": 'count(mpm{_ws_="w",_ns_="n"})',
                "start": 1_700_000_000, "end": 1_700_000_400,
                "step": "30s"})
            url = (f"http://127.0.0.1:{port_a}/promql/prom/api/v1/"
                   f"query_range?{qs}")
            body = json.loads(urllib.request.urlopen(
                url, timeout=60).read())
            assert body["status"] == "success", body
            result = body["data"]["result"]
            assert result, "empty result across processes"
            count = max(int(float(v)) for _t, v in result[0]["values"])
            assert count == 16, \
                f"query saw {count}/16 series (owned={owned})"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
