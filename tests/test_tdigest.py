"""t-digest sketch quantiles: accuracy, mergeability, bounded memory,
and the QuantileAggregator exact/sketch switchover.

Reference: exec/aggregator/RowAggregator.scala QuantileRowAggregator
(TDigest partials bounding memory at high cardinality).
"""

import numpy as np
import pytest

from filodb_tpu.query import tdigest
from filodb_tpu.query.aggregators import QuantileAggregator, aggregator_for
from filodb_tpu.query.model import PeriodicBatch, StepRange

BASE = 1_700_000_000_000


def _batch(vals, keys=None):
    S, T = vals.shape
    keys = keys or [{"inst": f"i{s}", "g": f"g{s % 2}"} for s in range(S)]
    return PeriodicBatch(keys, StepRange(BASE, 60_000, T), vals)


class TestTDigestCore:
    @pytest.mark.parametrize("q", [0.01, 0.25, 0.5, 0.9, 0.99])
    @pytest.mark.parametrize("dist", ["uniform", "normal", "lognormal"])
    def test_accuracy_vs_exact(self, q, dist):
        rng = np.random.default_rng(42)
        n = 20_000
        if dist == "uniform":
            data = rng.uniform(0, 100, n)
        elif dist == "normal":
            data = rng.normal(50, 10, n)
        else:
            data = rng.lognormal(1.0, 1.0, n)
        vals = data.reshape(n, 1)                  # n series, 1 step
        d = tdigest.from_values(vals, np.zeros(n, dtype=np.int64), 1,
                                compression=128)
        got = float(tdigest.quantile(d, q)[0, 0])
        want = float(np.quantile(data, q))
        spread = np.quantile(data, 0.95) - np.quantile(data, 0.05)
        assert abs(got - want) <= 0.05 * spread + 1e-9, \
            f"{dist} q={q}: got {got}, want {want}"

    def test_merge_matches_single_build(self):
        rng = np.random.default_rng(0)
        a = rng.normal(0, 1, (500, 3))
        b = rng.normal(0, 1, (500, 3))
        ids_a = np.zeros(500, dtype=np.int64)
        d_all = tdigest.from_values(np.concatenate([a, b]),
                                    np.zeros(1000, dtype=np.int64), 1)
        d_m = tdigest.merge(tdigest.from_values(a, ids_a, 1),
                            tdigest.from_values(b, ids_a, 1))
        for q in (0.1, 0.5, 0.9):
            np.testing.assert_allclose(tdigest.quantile(d_m, q),
                                       tdigest.quantile(d_all, q),
                                       atol=0.15)

    def test_memory_bounded(self):
        S = 50_000
        rng = np.random.default_rng(1)
        vals = rng.random((S, 4))
        ids = rng.integers(0, 10, S)
        d = tdigest.from_values(vals, ids, 10, compression=128)
        # O(G*T*C): 10*4*64 floats *2 arrays = 40KB, NOT O(S*T)
        assert d.nbytes < 100_000
        q = tdigest.quantile(d, 0.5)
        assert q.shape == (10, 4)
        assert np.isfinite(q).all()
        assert np.all((q > 0.4) & (q < 0.6))       # median of U(0,1)

    def test_nan_and_empty_cells(self):
        vals = np.array([[1.0, np.nan], [3.0, np.nan]])
        d = tdigest.from_values(vals, np.zeros(2, dtype=np.int64), 2)
        q = tdigest.quantile(d, 0.5)
        assert np.isfinite(q[0, 0])
        assert np.isnan(q[0, 1])                   # no samples at step 1
        assert np.isnan(q[1]).all()                # group 1 empty

    def test_exact_small_inputs(self):
        """With few values, digest quantiles hit exact order statistics."""
        vals = np.array([[10.0], [20.0], [30.0]])
        d = tdigest.from_values(vals, np.zeros(3, dtype=np.int64), 1)
        assert float(tdigest.quantile(d, 0.0)[0, 0]) == 10.0
        assert float(tdigest.quantile(d, 1.0)[0, 0]) == 30.0
        assert abs(float(tdigest.quantile(d, 0.5)[0, 0]) - 20.0) < 1e-9

    def test_from_members_roundtrip(self):
        members = np.array([[[1.0, 2.0], [3.0, np.nan]]])  # [1, 2, 2]
        d = tdigest.from_members(members)
        q = tdigest.quantile(d, 0.5)
        assert abs(q[0, 0] - 2.0) < 1.1             # median of {1,3}
        assert abs(q[0, 1] - 2.0) < 1e-9            # single value 2.0


class TestQuantileAggregatorSwitch:
    def test_small_stays_exact(self):
        agg = aggregator_for(QuantileAggregator.op)
        vals = np.arange(12.0).reshape(4, 3)
        p = agg.map(_batch(vals), ("g",), (), (0.5,), 1000)
        assert "members" in p.state
        out = agg.present(agg.reduce([p]))
        assert out.values.shape == (2, 3)
        # exact median of {0,6} rows etc.
        np.testing.assert_allclose(out.values[0], [3.0, 4.0, 5.0])

    def test_large_switches_to_sketch(self):
        agg = QuantileAggregator()
        rng = np.random.default_rng(2)
        S = 2_000
        vals = rng.random((S, 2))
        keys = [{"inst": f"i{s}"} for s in range(S)]
        p = agg.map(_batch(vals, keys), (), (), (0.9,), 10_000)
        assert "td_means" in p.state
        assert p.state["td_means"].nbytes < 10_000  # 1 group * 2 steps * 64
        out = agg.present(agg.reduce([p]))
        np.testing.assert_allclose(out.values, 0.9, atol=0.03)

    def test_mixed_exact_and_sketch_reduce(self):
        agg = QuantileAggregator()
        rng = np.random.default_rng(3)
        small = rng.random((10, 2))
        big = rng.random((2_000, 2))
        p1 = agg.map(_batch(small, [{"inst": f"a{s}"} for s in range(10)]),
                     (), (), (0.5,), 10_000)
        p2 = agg.map(_batch(big, [{"inst": f"b{s}"} for s in range(2_000)]),
                     (), (), (0.5,), 10_000)
        assert "members" in p1.state and "td_means" in p2.state
        out = agg.present(agg.reduce([p1, p2]))
        np.testing.assert_allclose(out.values, 0.5, atol=0.03)

    def test_sketch_accuracy_through_full_pipeline(self):
        """Exact vs sketch on the same data: within t-digest tolerance."""
        agg = QuantileAggregator()
        rng = np.random.default_rng(4)
        S = 1_000
        vals = rng.normal(100, 15, (S, 3))
        keys = [{"inst": f"i{s}"} for s in range(S)]
        p = agg.map(_batch(vals, keys), (), (), (0.95,), 10_000)
        out = agg.present(agg.reduce([p]))
        want = np.quantile(vals, 0.95, axis=0)
        np.testing.assert_allclose(out.values[0], want, rtol=0.02)