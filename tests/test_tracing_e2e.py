"""End-to-end query tracing across a 2-node (in-process) cluster.

ISSUE 2 acceptance: a query_range over HTTP with stats=true returns
per-stage timings, and /admin/traces/<trace_id> on the coordinator
shows ONE stitched span tree including the remote shard's spans
(propagated via the X-FiloDB-Trace-Id header + execplan-wire field)."""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.dispatch import (PARENT_SPAN_HEADER,
                                             TRACE_HEADER,
                                             dispatcher_factory)
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.query.scheduler import QueryScheduler
from filodb_tpu.utils.forensics import TRACE_STORE

BASE = 1_700_000_000_000
STEP = 10_000


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture(scope="module")
def cluster():
    """Two memstores, half the shards each; BOTH nodes serve HTTP and
    node-a (the coordinator) dispatches node-b's shards over the wire.
    node-a runs a query scheduler, node-b a leaf scheduler, so trace
    context must survive both thread-pool handoffs."""
    num_shards = 4
    mapper = ShardMapper(num_shards)
    rng = np.random.default_rng(5)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(8):
        tags = {"__name__": "trace_total", "instance": f"i{i}",
                "_ws_": "demo", "_ns_": "App-0"}
        ts = BASE + np.arange(300) * STEP
        vals = np.cumsum(rng.random(300))
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    by_shard = {}
    for off, c in enumerate(b.containers()):
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 1) \
                % num_shards
            by_shard.setdefault(shard, []).append((off, rec))
    used = sorted(by_shard)
    assert len(used) == 2
    shards_a = [used[0]] + [s for s in range(num_shards) if s not in used]
    shards_b = [used[1]]
    mapper.register_node(shards_a, "node-a")
    mapper.register_node(shards_b, "node-b")
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)

    stores = {"node-a": TimeSeriesMemStore(), "node-b": TimeSeriesMemStore()}
    for ms in stores.values():
        for s in range(num_shards):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
    for shard, recs in by_shard.items():
        node = mapper.coord_for_shard(shard)
        for off, rec in recs:
            stores[node].get_shard("prom", shard).ingest([rec], off)

    srv_b = FiloHttpServer()
    planner_b = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1)
    leaf_sched = QueryScheduler(num_workers=2, name="e2e-leaf")
    srv_b.bind_dataset(DatasetBinding("prom", stores["node-b"], planner_b,
                                      leaf_scheduler=leaf_sched))
    port_b = srv_b.start()

    endpoints = {"node-b": f"http://127.0.0.1:{port_b}"}
    disp = dispatcher_factory(mapper, endpoints, local_node="node-a")
    planner_a = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1,
                                     dispatcher_for_shard=disp)
    srv_a = FiloHttpServer()
    qsched = QueryScheduler(num_workers=2, name="e2e-query")
    srv_a.bind_dataset(DatasetBinding("prom", stores["node-a"], planner_a,
                                      scheduler=qsched))

    # ISSUE 15 satellite: a LOCAL-only dataset whose planner stack is
    # result-cache BELOW a (tier-less) rollup router — the standalone
    # composition — so the query.execute span must carry the router's
    # resolution decision (raw => "0") and the cache's hit/miss/partial
    # outcome.  All shards local (remote plans bypass the cache) and
    # chunks flushed (open segments are never memoized).
    from filodb_tpu.query.resultcache import (ResultCache,
                                              ResultCachingPlanner)
    from filodb_tpu.rollup.planner import RollupRouterPlanner
    ms_local = TimeSeriesMemStore()
    mapper_l = ShardMapper(1)
    mapper_l.register_node([0], "node-a")
    mapper_l.update_status(0, ShardStatus.ACTIVE)
    shard_l = ms_local.setup("proml", DEFAULT_SCHEMAS, 0)
    bl = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(4):
        tags = {"__name__": "local_total", "instance": f"i{i}",
                "_ws_": "demo", "_ns_": "App-0"}
        vals = np.cumsum(rng.random(300))
        for t, v in zip(BASE + np.arange(300) * STEP, vals):
            bl.add(int(t), [float(v)], tags)
    for off, c in enumerate(bl.containers()):
        shard_l.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    shard_l.flush_all()
    cache_l = ResultCache("proml", enabled=True, max_bytes=32 << 20)
    planner_l = ResultCachingPlanner(
        "proml",
        SingleClusterPlanner("proml", mapper_l, DatasetOptions(),
                             spread_default=0),
        ms_local, cache_l, segment_ms=120_000,
        routing_token_fn=mapper_l.routing_token)
    planner_l = RollupRouterPlanner("proml", planner_l, {},
                                    rolled_through_fn=lambda r: 0)
    srv_a.bind_dataset(DatasetBinding("proml", ms_local, planner_l,
                                      resultcache=cache_l))
    port_a = srv_a.start()
    yield {"port_a": port_a, "port_b": port_b,
           "remote_shard": shards_b[0], "endpoints": endpoints}
    srv_a.shutdown()
    srv_b.shutdown()
    qsched.shutdown()
    leaf_sched.shutdown()


def _query_range(cluster, **extra):
    params = dict(
        query='sum(rate(trace_total{_ws_="demo",_ns_="App-0"}[2m]))',
        start=(BASE + 600_000) / 1000, end=(BASE + 1_200_000) / 1000,
        step="30s", **extra)
    return _get(cluster["port_a"], "/promql/prom/api/v1/query_range",
                **params)


def _flatten(nodes, out=None):
    out = [] if out is None else out
    for n in nodes:
        out.append(n)
        _flatten(n["children"], out)
    return out


class TestStatsResponse:
    def test_stats_true_shape(self, cluster):
        code, body, headers = _query_range(cluster, stats="true")
        assert code == 200 and body["status"] == "success"
        assert len(body["data"]["result"]) == 1
        stats = body["data"]["stats"]
        timings = stats["timings"]
        for key in ("plan", "queue", "scan", "total"):
            assert key in timings, f"missing stage bucket {key}: {timings}"
        assert timings["total"] >= timings["plan"] >= 0.0
        samples = stats["samples"]
        # 8 series x 300 rows scanned somewhere across the two nodes
        assert samples["samplesScanned"] > 0
        assert samples["bytesScanned"] > 0
        assert stats["traceId"]
        assert headers.get("X-FiloDB-Trace-Id") == stats["traceId"]

    def test_no_stats_by_default(self, cluster):
        code, body, headers = _query_range(cluster)
        assert code == 200
        assert "stats" not in body["data"]
        assert "X-FiloDB-Trace-Id" not in headers

    def test_instant_query_stats(self, cluster):
        code, body, _ = _get(
            cluster["port_a"], "/promql/prom/api/v1/query",
            query='count(trace_total{_ws_="demo",_ns_="App-0"})',
            time=(BASE + 900_000) / 1000, stats="true")
        assert code == 200
        assert "timings" in body["data"]["stats"]


class TestStitchedTrace:
    def test_remote_spans_joined_into_one_tree(self, cluster):
        code, body, _ = _query_range(cluster, stats="true")
        assert code == 200
        tid = body["data"]["stats"]["traceId"]
        code, tbody, _ = _get(cluster["port_a"], f"/admin/traces/{tid}")
        assert code == 200
        roots = tbody["data"]["spans"]
        assert len(roots) == 1, \
            f"expected ONE stitched tree, got roots " \
            f"{[r['name'] for r in roots]}"
        assert roots[0]["name"] == "query"
        flat = _flatten(roots)
        names = [n["name"] for n in flat]
        assert "query.execute" in names
        assert "query.plan" in names
        assert "scheduler.queue_wait" in names  # node-a's scheduler
        # the remote dispatch span exists and the remote shard's
        # execplan span hangs UNDER it (correct parentage across the
        # process boundary), tagged with the remote shard id
        http_nodes = [n for n in flat if n["name"] == "dispatch.http"]
        assert http_nodes, names
        remote_kids = _flatten(http_nodes[0]["children"])
        remote_exec = [n for n in remote_kids
                       if n["name"] == "execplan.execute"]
        assert remote_exec, \
            "remote shard's spans were not stitched under dispatch.http"
        assert any(n["tags"].get("shard") == str(cluster["remote_shard"])
                   for n in remote_exec)
        # the DATA NODE's leaf-scheduler queue-wait/run split must join
        # the tree too (trace attached before submit on the remote side)
        remote_names = {n["name"] for n in remote_kids}
        assert "scheduler.run" in remote_names, remote_names
        assert "scheduler.queue_wait" in remote_names, remote_names

    def test_unknown_trace_404(self, cluster):
        code, body, _ = _get(cluster["port_a"], "/admin/traces/deadbeef00")
        assert code == 404

    def test_execplan_response_carries_spans(self, cluster):
        """The wire half of stitching: a data node returns its spans for
        the originating trace with the /execplan response."""
        from filodb_tpu.query.exec import MultiSchemaPartitionsExec
        from filodb_tpu.query import wire
        from filodb_tpu.core.filters import ColumnFilter, Equals
        plan = MultiSchemaPartitionsExec(
            "prom", cluster["remote_shard"],
            [ColumnFilter("_metric_", Equals("trace_total"))],
            BASE, BASE + 600_000)
        payload = wire.serialize_plan(plan)
        tid = "e2e0wire0trace00"
        req = urllib.request.Request(
            f"http://127.0.0.1:{cluster['port_b']}/execplan",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: tid, PARENT_SPAN_HEADER: "c0ffee"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        spans = out.get("spans")
        assert spans, "execplan response is missing its spans"
        assert all(s["trace_id"] == tid for s in spans)
        roots = [s for s in spans if s["parent_id"] == "c0ffee"]
        assert roots, "remote root span must parent onto the header span"
        # full stats travel on the wire too
        assert "timings" in out["stats"]
        assert out["stats"]["timings"].get("scan", 0) > 0


class TestSpanTagSatellites:
    """ISSUE 15 satellite: PRs 16-17 surfaced the rollup resolution and
    the result-cache outcome only under stats=true — the query.execute
    span (and therefore every /admin/slowlog entry) must carry them
    too."""

    def _local_query(self, cluster, query):
        return _get(cluster["port_a"], "/promql/proml/api/v1/query_range",
                    query=query, start=(BASE + 600_000) / 1000,
                    end=(BASE + 1_800_000) / 1000, step="30s",
                    stats="true")

    def _exec_tags(self, cluster, trace_id):
        code, tbody, _ = _get(cluster["port_a"],
                              f"/admin/traces/{trace_id}")
        assert code == 200
        flat = _flatten(tbody["data"]["spans"])
        ex = [n for n in flat if n["name"] == "query.execute"]
        assert ex, [n["name"] for n in flat]
        return ex[0]["tags"]

    def test_resolution_decision_tagged_even_for_raw(self, cluster):
        code, body, _ = self._local_query(
            cluster,
            'sum(rate(local_total{_ws_="demo",_ns_="App-0"}[2m]))')
        assert code == 200
        tags = self._exec_tags(cluster, body["data"]["stats"]["traceId"])
        # the router decided RAW: previously only stats=true could say
        # so; now the span names the decision (0 = raw)
        assert tags.get("resolution_ms") == "0", tags

    def test_resultcache_outcome_tagged(self, cluster):
        q = ('sum(rate(local_total{_ws_="demo",_ns_="App-0",'
             'instance!="zz"}[2m]))')
        # sight 1: doorkeeper only — the cache made no hit/miss
        # decision, so the span stays untagged
        code, body1, _ = self._local_query(cluster, q)
        assert code == 200
        tags1 = self._exec_tags(cluster,
                                body1["data"]["stats"]["traceId"])
        assert "resultcache" not in tags1, tags1
        # sight 2: split + store — everything recomputed => miss
        code, body2, _ = self._local_query(cluster, q)
        tags2 = self._exec_tags(cluster,
                                body2["data"]["stats"]["traceId"])
        assert tags2.get("resultcache") == "miss", tags2
        # sight 3: interior segments replay from the cache
        code, body3, _ = self._local_query(cluster, q)
        tags3 = self._exec_tags(cluster,
                                body3["data"]["stats"]["traceId"])
        assert tags3.get("resultcache") in ("hit", "partial"), tags3
        # the tag agrees with the stats=true split
        rc = body3["data"]["stats"]["resultCache"]
        assert rc["cachedSamples"] > 0
        if tags3["resultcache"] == "hit":
            assert rc["recomputedSamples"] == 0


class TestForensicsEndpoints:
    def test_slowlog_captures_query(self, cluster):
        old = TRACE_STORE.slow_threshold_s
        TRACE_STORE.slow_threshold_s = 0.0
        try:
            code, body, _ = _query_range(cluster, stats="true")
            tid = body["data"]["stats"]["traceId"]
            code, slog, _ = _get(cluster["port_a"], "/admin/slowlog")
            assert code == 200
            entries = slog["data"]["entries"]
            mine = [e for e in entries if e["trace_id"] == tid]
            assert mine, "completed query missing from the slow log"
            assert mine[0]["query"].startswith("sum(rate(trace_total")
            assert mine[0]["duration_s"] > 0
            assert mine[0]["tree"], "slow-log entry lost its span tree"
        finally:
            TRACE_STORE.slow_threshold_s = old

    def test_profilez(self, cluster):
        code, body, _ = _get(cluster["port_a"], "/debug/profilez",
                             seconds="0.05")
        assert code == 200
        assert body["data"]["samples"] >= 0
        assert "frames" in body["data"]

    def test_metrics_expose_query_families(self, cluster):
        _query_range(cluster)
        url = f"http://127.0.0.1:{cluster['port_a']}/metrics"
        text = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "filodb_query_request_seconds" in text
        assert 'endpoint="query_range"' in text
        assert "filodb_query_queue_depth" in text
        url_b = f"http://127.0.0.1:{cluster['port_b']}/metrics"
        text_b = urllib.request.urlopen(url_b, timeout=10).read().decode()
        assert "filodb_query_execplan_remote_seconds" in text_b
