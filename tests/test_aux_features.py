"""Chunk-metadata queries, per-key spread assignment, traced partitions.

Reference parity for three auxiliary surfaces: RawChunkMeta /
SelectChunkInfosExec (reference: LogicalPlan.scala RawChunkMeta,
exec/SelectChunkInfosExec.scala), config-driven spread-assignment
(filodb-defaults.conf spread-assignment + QueryActor.scala:70-85), and
TracingTimeSeriesPartition (TimeSeriesPartition.scala:451).
"""

import logging

import numpy as np

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.coordinator.planner import (SingleClusterPlanner,
                                            spread_provider_from_config)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext

T0 = 1_600_000_000_000
STEP = 10_000


def _mk(n_series=3, n_rows=120, cfg=None):
    ms = TimeSeriesMemStore()
    shard = ms.setup("ds", DEFAULT_SCHEMAS, 0, cfg or StoreConfig())
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    for i in range(n_series):
        tags = {"__name__": "m", "inst": f"i{i}", "_ws_": "w", "_ns_": "n"}
        for r in range(n_rows):
            b.add(T0 + r * STEP, [float(r + i)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    shard.flush_all()
    return ms, shard


class TestRawChunkMeta:
    def test_chunk_infos_served_via_planner(self):
        ms, shard = _mk()
        mapper = ShardMapper(1)
        mapper.register_node([0], "local")
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=0)
        plan = lp.RawChunkMeta(
            filters=(ColumnFilter("_metric_", Equals("m")),),
            start_ms=0, end_ms=2**62)
        ep = planner.materialize(plan, QueryContext())
        assert "SelectChunkInfosExec" in ep.print_tree()
        res = ep.execute(ExecContext(ms))
        rows = [r for b in res.batches for r in b]
        assert len(rows) == 3
        for row in rows:
            assert row["tags"]["inst"].startswith("i")
            assert row["chunks"], "flushed series must expose chunks"
            part = next(p for p in shard.partitions.values()
                        if p.tags == row["tags"])
            want = part.chunk_infos()
            got = row["chunks"]
            assert [c["chunk_id"] for c in got] == \
                [w.chunk_id for w in want]
            assert [c["num_rows"] for c in got] == \
                [w.num_rows for w in want]
            assert all(c["bytes"] > 0 for c in got)
            assert sum(c["num_rows"] for c in got) \
                + row["buffer_rows"] == 120

    def test_time_range_filters_chunks(self):
        ms, shard = _mk()
        mapper = ShardMapper(1)
        mapper.register_node([0], "local")
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=0)
        part = next(iter(shard.partitions.values()))
        first = part.chunk_infos()[0]
        plan = lp.RawChunkMeta(
            filters=(ColumnFilter("_metric_", Equals("m")),),
            start_ms=first.start_time, end_ms=first.end_time)
        res = planner.materialize(plan, QueryContext()).execute(
            ExecContext(ms))
        rows = [r for b in res.batches for r in b]
        for row in rows:
            assert len(row["chunks"]) == 1


class TestChunkMetaHttp:
    def test_admin_chunkmeta_route(self):
        import json
        import urllib.request

        from filodb_tpu.coordinator.cluster import ShardManager
        from filodb_tpu.http.server import DatasetBinding, FiloHttpServer

        ms, shard = _mk()
        mapper = ShardMapper(1)
        mapper.register_node([0], "local")
        mgr = ShardManager()
        mgr.setup_dataset("ds", 1, min_num_nodes=1)
        mgr.add_node("local")
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=0)
        srv = FiloHttpServer(shard_manager=mgr)
        srv.bind_dataset(DatasetBinding("ds", ms, planner))
        port = srv.start()
        try:
            url = (f"http://127.0.0.1:{port}/admin/chunkmeta/ds"
                   f"?match%5B%5D=m%7Binst%3D%22i0%22%7D")
            body = json.loads(urllib.request.urlopen(url, timeout=15).read())
            assert body["status"] == "success"
            assert len(body["data"]) == 1
            row = body["data"][0]
            assert row["tags"]["inst"] == "i0" and row["chunks"]
        finally:
            srv.shutdown()

    def test_chunkinfo_plan_wire_roundtrip(self):
        from filodb_tpu.query.exec import SelectChunkInfosExec
        from filodb_tpu.query.wire import deserialize_plan, serialize_plan

        plan = SelectChunkInfosExec(
            "ds", 0, [ColumnFilter("_metric_", Equals("m"))], 0, 10**15,
            QueryContext())
        d = serialize_plan(plan)
        back = deserialize_plan(d)
        assert isinstance(back, SelectChunkInfosExec)
        assert back.filters == plan.filters and back.shard == 0


class TestCliChunkMeta:
    def test_cli_chunkmeta_against_live_server(self, capsys):
        import json

        from filodb_tpu import cli
        from filodb_tpu.coordinator.cluster import ShardManager
        from filodb_tpu.http.server import DatasetBinding, FiloHttpServer

        ms, shard = _mk()
        mapper = ShardMapper(1)
        mapper.register_node([0], "local")
        mgr = ShardManager()
        mgr.setup_dataset("ds", 1, min_num_nodes=1)
        mgr.add_node("local")
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=0)
        srv = FiloHttpServer(shard_manager=mgr)
        srv.bind_dataset(DatasetBinding("ds", ms, planner))
        port = srv.start()
        try:
            rc = cli.main(["chunkmeta", "--server",
                           f"http://127.0.0.1:{port}", "--dataset", "ds",
                           'm{inst="i1"}'])
            assert rc == 0
            body = json.loads(capsys.readouterr().out)
            assert body["status"] == "success"
            assert len(body["data"]) == 1
            assert body["data"][0]["tags"]["inst"] == "i1"
        finally:
            srv.shutdown()


class TestSpreadAssignment:
    def test_provider_from_config(self):
        prov = spread_provider_from_config(
            [{"keys": {"_ws_": "demo", "_ns_": "App-0"}, "spread": 3},
             {"keys": {"_ws_": "demo"}, "spread": 2}], default=1)
        assert prov({"_ws_": "demo", "_ns_": "App-0"}) == 3
        assert prov({"_ws_": "demo", "_ns_": "other"}) == 2
        assert prov({"_ws_": "prod", "_ns_": "App-0"}) == 1
        assert prov({}) == 1

    def test_planner_uses_override_spread(self):
        mapper = ShardMapper(8)
        mapper.register_node(range(8), "local")
        prov = spread_provider_from_config(
            [{"keys": {"_ws_": "demo"}, "spread": 2}], default=0)
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=0,
                                       spread_provider=prov)
        filters = [ColumnFilter("_metric_", Equals("m")),
                   ColumnFilter("_ws_", Equals("demo")),
                   ColumnFilter("_ns_", Equals("n"))]
        shards = planner.shards_from_filters(filters, QueryContext())
        assert len(shards) == 4          # 2^2 of 8
        other = [ColumnFilter("_metric_", Equals("m")),
                 ColumnFilter("_ws_", Equals("prod")),
                 ColumnFilter("_ns_", Equals("n"))]
        assert len(planner.shards_from_filters(other, QueryContext())) == 1


class TestTracingPartition:
    def test_trace_filters_select_tracing_class(self, caplog):
        from filodb_tpu.memstore.partition import TracingTimeSeriesPartition
        cfg = StoreConfig(trace_filters={"inst": "i1"})
        with caplog.at_level(logging.INFO, logger="filodb.trace"):
            ms, shard = _mk(cfg=cfg)
        traced = [p for p in shard.partitions.values()
                  if isinstance(p, TracingTimeSeriesPartition)]
        assert len(traced) == 1 and traced[0].tags["inst"] == "i1"
        ingests = [r for r in caplog.records if "TRACE ingest" in r.message]
        freezes = [r for r in caplog.records if "TRACE freeze" in r.message]
        assert len(ingests) == 120
        assert freezes, "flush_all must log the traced freeze"

    def test_no_filters_no_tracing(self):
        from filodb_tpu.memstore.partition import TracingTimeSeriesPartition
        ms, shard = _mk()
        assert not any(isinstance(p, TracingTimeSeriesPartition)
                       for p in shard.partitions.values())
