"""Shared test data generators — the role of the reference's TestData /
MachineMetricsData / prom-schema producers (reference:
core/src/test/scala/filodb.core/TestData.scala, gateway
TestTimeseriesProducer.scala:25)."""

from __future__ import annotations

import numpy as np

from filodb_tpu.codecs import histcodec
from filodb_tpu.core.histogram import GeometricBuckets
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions

START_TS = 1_600_000_000_000  # fixed epoch millis base


def gauge_tags(i: int, metric: str = "heap_usage") -> dict[str, str]:
    return {"_metric_": metric, "_ws_": "demo", "_ns_": f"App-{i % 8}",
            "instance": str(i), "host": f"H{i % 4}"}


def gauge_containers(n_series: int = 10, n_samples: int = 100,
                     start: int = START_TS, step: int = 10_000,
                     metric: str = "heap_usage", seed: int = 42,
                     container_size: int = 256 * 1024) -> list[bytes]:
    """Deterministic gauge samples, one RecordContainer batch."""
    rng = np.random.default_rng(seed)
    builder = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                            container_size=container_size)
    vals = 50 + 15 * rng.standard_normal((n_series, n_samples))
    for t in range(n_samples):
        for s in range(n_series):
            builder.add(start + t * step, (float(vals[s, t]),), gauge_tags(s, metric))
    return builder.containers()


def counter_containers(n_series: int = 4, n_samples: int = 100,
                       start: int = START_TS, step: int = 10_000,
                       metric: str = "http_requests_total", seed: int = 3,
                       reset_every: int = 0) -> list[bytes]:
    rng = np.random.default_rng(seed)
    builder = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], DatasetOptions())
    for s in range(n_series):
        total = 0.0
        for t in range(n_samples):
            total += float(rng.integers(0, 10))
            if reset_every and t and t % reset_every == 0:
                total = 0.0
            builder.add(start + t * step, (total,), gauge_tags(s, metric))
    return builder.containers()


def histogram_containers(n_series: int = 2, n_samples: int = 50,
                         start: int = START_TS, step: int = 10_000,
                         metric: str = "req_latency", num_buckets: int = 8,
                         seed: int = 5) -> list[bytes]:
    rng = np.random.default_rng(seed)
    buckets = GeometricBuckets(2.0, 2.0, num_buckets)
    builder = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"], DatasetOptions())
    for s in range(n_series):
        cum = np.zeros(num_buckets, dtype=np.int64)
        for t in range(n_samples):
            cum += np.sort(rng.integers(0, 5, num_buckets))
            blob = histcodec.encode_hist_value(buckets, np.cumsum(cum))
            total = int(np.cumsum(cum)[-1])
            builder.add(start + t * step, (float(total), float(total), blob),
                        gauge_tags(s, metric))
    return builder.containers()


def hist_max_containers(n_series: int = 2, n_samples: int = 50,
                        start: int = START_TS, step: int = 10_000,
                        metric: str = "lat_hmax", num_buckets: int = 8,
                        seed: int = 9) -> list[bytes]:
    """prom-hist-max records: hist column + observed-max double column
    (reference: hist-max test schemas, SelectRawPartitionsExec.histMaxColumn).
    """
    rng = np.random.default_rng(seed)
    buckets = GeometricBuckets(2.0, 2.0, num_buckets)
    builder = RecordBuilder(DEFAULT_SCHEMAS["prom-hist-max"], DatasetOptions())
    for s in range(n_series):
        cum = np.zeros(num_buckets, dtype=np.int64)
        for t in range(n_samples):
            cum += np.sort(rng.integers(0, 5, num_buckets))
            blob = histcodec.encode_hist_value(buckets, np.cumsum(cum))
            total = int(np.cumsum(cum)[-1])
            mx = float(rng.uniform(1.0, 2.0 ** num_buckets))
            builder.add(start + t * step,
                        (float(total), float(total), mx, blob),
                        gauge_tags(s, metric))
    return builder.containers()
