"""Disk persistence, checkpointed recovery, and on-demand paging.

Mirrors the reference's persistence/recovery test strategy (reference:
cassandra ColumnStoreSpec, TimeSeriesMemStoreSpec recovery cases,
OnDemandPagingShard paging) against the sqlite-backed stores.
"""

import numpy as np
import pytest

from filodb_tpu.core.chunk import ChunkSet, ChunkSetInfo, encode_chunkset
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.odp import OnDemandPagingShard, QueryLimitExceeded
from filodb_tpu.store.columnstore import PartKeyRecord
from filodb_tpu.store.persistence import (DiskColumnStore, DiskMetaStore,
                                          pack_vectors, unpack_vectors)

BASE = 1_700_000_000_000


@pytest.fixture
def disk(tmp_path):
    return DiskColumnStore(str(tmp_path / "chunks.db"))


@pytest.fixture
def meta(tmp_path):
    return DiskMetaStore(str(tmp_path / "meta.db"))


def _mk_chunkset(pk=b"pk1", n=100, t0=BASE, seed=0):
    rng = np.random.default_rng(seed)
    ts = t0 + np.cumsum(rng.integers(9_000, 11_000, n))
    vals = np.cumsum(rng.random(n))
    schema = DEFAULT_SCHEMAS["gauge"]
    return encode_chunkset(schema, pk, ts.astype(np.int64), [vals]), ts, vals


def _builder_data(n_series=6, n_rows=300, metric="heap_usage",
                  container_size=1024 * 1024):
    schema = DEFAULT_SCHEMAS["gauge"]
    builder = RecordBuilder(schema, container_size=container_size)
    rng = np.random.default_rng(1)
    truth = {}
    for s in range(n_series):
        tags = {"__name__": metric, "job": "app", "instance": f"i{s}",
                "_ws_": "demo", "_ns_": "ns"}
        ts = BASE + np.cumsum(rng.integers(9_000, 11_000, n_rows))
        vals = np.cumsum(rng.random(n_rows))
        truth[f"i{s}"] = (ts.astype(np.int64), vals.copy())
        for t, v in zip(ts, vals):
            builder.add(int(t), [float(v)], tags)
    return builder.containers(), truth


def test_vector_blob_roundtrip():
    vs = [b"", b"abc", b"\x00" * 100, bytes(range(256))]
    assert unpack_vectors(pack_vectors(vs)) == vs


class TestDiskColumnStore:
    def test_chunk_roundtrip(self, disk):
        cs, ts, vals = _mk_chunkset()
        disk.write_chunks("ds", 0, [cs], ingestion_time=123)
        got = list(disk.read_raw_partitions("ds", 0, [b"pk1"], 0, 2**62))
        assert len(got) == 1
        pk, chunks = got[0]
        assert pk == b"pk1"
        assert chunks[0].info == cs.info
        assert chunks[0].vectors == cs.vectors  # byte-exact

    def test_time_range_filter(self, disk):
        cs1, ts1, _ = _mk_chunkset(n=50, t0=BASE)
        cs2, ts2, _ = _mk_chunkset(n=50, t0=BASE + 10**9, seed=1)
        disk.write_chunks("ds", 0, [cs1, cs2])
        got = list(disk.read_raw_partitions("ds", 0, [b"pk1"],
                                            BASE, BASE + 10**6))
        assert len(got[0][1]) == 1
        assert got[0][1][0].info.chunk_id == cs1.info.chunk_id

    def test_ingestion_time_scan(self, disk):
        cs1, *_ = _mk_chunkset(pk=b"a")
        cs2, *_ = _mk_chunkset(pk=b"b", seed=2)
        disk.write_chunks("ds", 0, [cs1], ingestion_time=100)
        disk.write_chunks("ds", 0, [cs2], ingestion_time=200)
        got = list(disk.chunksets_by_ingestion_time("ds", 0, 150, 250))
        assert [c.partkey for c in got] == [b"b"]

    def test_partkeys(self, disk):
        recs = [PartKeyRecord(f"pk{i}".encode(), BASE, BASE + i, 3)
                for i in range(5)]
        disk.write_part_keys("ds", 3, recs)
        got = sorted(disk.scan_part_keys("ds", 3), key=lambda r: r.partkey)
        assert [r.partkey for r in got] == [r.partkey for r in recs]
        assert got[2].end_time == BASE + 2
        # upsert updates end time
        disk.write_part_keys("ds", 3, [PartKeyRecord(b"pk0", BASE, BASE + 99, 3)])
        got = {r.partkey: r for r in disk.scan_part_keys("ds", 3)}
        assert got[b"pk0"].end_time == BASE + 99

    def test_shard_isolation(self, disk):
        cs, *_ = _mk_chunkset()
        disk.write_chunks("ds", 0, [cs])
        assert list(disk.read_raw_partitions("ds", 1, [b"pk1"], 0, 2**62)) == []
        assert disk.num_chunks("ds", 0) == 1

    def test_delete_part_keys(self, disk):
        cs, *_ = _mk_chunkset()
        disk.write_chunks("ds", 0, [cs])
        disk.write_part_keys("ds", 0, [PartKeyRecord(b"pk1", 0, 1, 0)])
        disk.delete_part_keys("ds", 0, [b"pk1"])
        assert list(disk.scan_part_keys("ds", 0)) == []
        assert disk.num_chunks("ds", 0) == 0

    def test_reopen_persists(self, tmp_path):
        path = str(tmp_path / "c.db")
        store = DiskColumnStore(path)
        cs, *_ = _mk_chunkset()
        store.write_chunks("ds", 0, [cs])
        store.shutdown()
        store2 = DiskColumnStore(path)
        got = list(store2.read_raw_partitions("ds", 0, [b"pk1"], 0, 2**62))
        assert got[0][1][0].vectors == cs.vectors


class TestDiskMetaStore:
    def test_checkpoints(self, meta):
        meta.write_checkpoint("ds", 1, 0, 100)
        meta.write_checkpoint("ds", 1, 1, 150)
        meta.write_checkpoint("ds", 1, 0, 200)  # upsert
        assert meta.read_checkpoints("ds", 1) == {0: 200, 1: 150}
        assert meta.read_earliest_checkpoint("ds", 1) == 150
        assert meta.read_highest_checkpoint("ds", 1) == 200
        assert meta.read_checkpoints("ds", 2) == {}

    def test_datasets(self, meta):
        meta.write_dataset("prom", '{"num_shards": 8}')
        assert meta.read_dataset("prom") == '{"num_shards": 8}'
        assert meta.list_datasets() == ["prom"]
        assert meta.read_dataset("nope") is None

    def test_memory_store_shared_across_threads(self):
        """Regression: a ':memory:' store must serve every thread from ONE
        database (plain :memory: sqlite is per-connection-private)."""
        import threading

        meta = DiskMetaStore(":memory:")
        meta.write_checkpoint("ds", 0, 1, 42)
        got: dict = {}

        def worker():
            try:
                got["cp"] = meta.read_checkpoints("ds", 0)
            except Exception as e:  # noqa: BLE001
                got["err"] = e

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert "err" not in got, got
        assert got["cp"] == {1: 42}


class TestRecovery:
    def test_restart_recovers_index_and_skips_persisted(self, tmp_path):
        """Full crash/restart cycle: flush → checkpoint → restart →
        recover_index + recover_stream with watermark skipping
        (reference: SURVEY.md §3.4)."""
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        containers, truth = _builder_data()
        cfg = StoreConfig(groups_per_shard=4)

        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all()
        n_persisted = disk.num_chunks("prom", 0)
        assert n_persisted > 0

        # --- restart ---
        store2 = TimeSeriesMemStore(disk, meta)
        shard2 = store2.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        assert store2.recover_index("prom", 0) == len(truth)
        replayed = store2.recover_stream(
            "prom", 0, [(off, c) for off, c in enumerate(containers)])
        # every record was already persisted+checkpointed: all skipped
        assert replayed == 0
        assert shard2.stats.rows_skipped > 0

        # queries work via ODP paging of the persisted chunks
        res = shard2.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        assert len(res.part_ids) == len(truth)
        tags_list, batch = shard2.scan_batch(res.part_ids, 0, 2**62)
        assert len(tags_list) == len(truth)
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, vals) in truth.items():
            i = by_inst[inst]
            n = len(ts)
            got_ts = np.asarray(batch.timestamps)[i][:n]
            got_vals = np.asarray(batch.values)[i][:n]
            np.testing.assert_array_equal(got_ts, ts)
            np.testing.assert_allclose(got_vals, vals)

    def test_partial_recovery_replays_tail(self, tmp_path):
        """Records after the checkpoint replay; records before skip."""
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        containers, truth = _builder_data(n_series=4, n_rows=200,
                                          container_size=8192)
        cfg = StoreConfig(groups_per_shard=2)

        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        # ingest+flush only the first half of the containers
        half = max(len(containers) // 2, 1)
        for off in range(half):
            store.ingest("prom", 0, containers[off], offset=off)
        store.get_shard("prom", 0).flush_all()

        store2 = TimeSeriesMemStore(disk, meta)
        shard2 = store2.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        store2.recover_index("prom", 0)
        replayed = store2.recover_stream(
            "prom", 0, [(off, c) for off, c in enumerate(containers)])
        assert replayed > 0  # the unflushed tail was re-ingested
        # no duplicates: per-series row count equals the source
        res = shard2.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        tags_list, batch = shard2.scan_batch(res.part_ids, 0, 2**62)
        counts = np.asarray(batch.row_counts)[:len(tags_list)]
        for i, t in enumerate(tags_list):
            assert counts[i] == len(truth[t["instance"]][0]), t


class TestOnDemandPaging:
    def _setup(self, tmp_path, **cfg_kw):
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        cfg = StoreConfig(groups_per_shard=2, **cfg_kw)
        shard = store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        assert isinstance(shard, OnDemandPagingShard)
        containers, truth = _builder_data(n_series=5, n_rows=250)
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        shard.flush_all()
        return disk, shard, truth

    def test_evict_then_query_pages_back(self, tmp_path):
        disk, shard, truth = self._setup(tmp_path)
        n_evicted = shard.evict_partitions(3)
        assert n_evicted == 3
        assert shard.num_partitions == len(truth) - 3
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        assert len(res.part_ids) == len(truth)  # index kept evicted entries
        tags_list, batch = shard.scan_batch(res.part_ids, 0, 2**62)
        assert len(tags_list) == len(truth)
        assert shard.stats.partitions_paged == 3
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, vals) in truth.items():
            i = by_inst[inst]
            np.testing.assert_array_equal(
                np.asarray(batch.timestamps)[i][:len(ts)], ts)

    def test_page_cache_reuse(self, tmp_path):
        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(2)
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        shard.scan_batch(res.part_ids, 0, 2**62)
        paged_once = shard.stats.partitions_paged
        shard.scan_batch(res.part_ids, 0, 2**62)
        assert shard.stats.partitions_paged == paged_once  # cache hit

    def test_deferred_publish_lands_in_page_cache(self, tmp_path):
        """The fused cold scan returns its batch BEFORE partition
        skeletons publish to the page cache (side thread); the very next
        query must join that publish and hit the cache — never re-page
        (reference: DemandPagedChunkStore pages via futures, but a
        paged-in chunk is immediately servable)."""
        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(len(truth))
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        ids = list(res.part_ids) + res.missing_partkeys
        tags_list, _ = shard.scan_batch(ids, 0, 2**62)
        assert len(tags_list) == len(truth)
        # stats count eagerly, with the triggering query
        assert shard.stats.partitions_paged == len(truth)
        shard.scan_batch(ids, 0, 2**62)
        assert shard.stats.partitions_paged == len(truth)  # cache hit
        assert len(shard.paged) == len(truth)              # published

    def test_pop_cancels_deferred_publish(self, tmp_path):
        """pop() and a gen-guarded put_many are safe in EITHER order: an
        evict's invalidation must never be overwritten by a deferred
        publish built from a pre-eviction disk read."""
        from filodb_tpu.memstore.odp import _PagedPartitions
        cache = _PagedPartitions(1 << 20)
        g = cache.gen
        cache.pop(1)                 # invalidation after guard capture
        cache.put_many([(1, "x", 10), (2, "z", 10)], gen_guard=g)
        assert cache.get(1) is None  # dropped: stale snapshot of 1 ...
        assert cache.get(2) == "z"   # ... but unrelated keys still land
        cache.put_many([(1, "y", 10)], gen_guard=cache.gen)
        assert cache.get(1) == "y"   # fresh guard: lands
        # pre-capture pops don't cancel
        cache.pop(3)
        g2 = cache.gen
        cache.put_many([(3, "w", 10)], gen_guard=g2)
        assert cache.get(3) == "w"

    def test_failed_publish_is_counted_not_silent(self, tmp_path,
                                                  monkeypatch):
        from filodb_tpu import native
        if native.batch_decoder() is None:
            pytest.skip("native disabled")   # publish exists only fused
        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(len(truth))
        monkeypatch.setattr(
            shard, "_materialize_paged",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        ids = list(res.part_ids) + res.missing_partkeys
        tags_list, _ = shard.scan_batch(ids, 0, 2**62)
        assert len(tags_list) == len(truth)   # the query itself succeeds
        shard._join_materialize()
        assert shard.stats.page_publish_errors == 1

    def test_page_cache_bytes_config(self, tmp_path):
        disk, shard, truth = self._setup(tmp_path,
                                         page_cache_bytes=7 << 20)
        assert shard.paged.max_bytes == 7 << 20

    def test_undersized_page_cache_still_scans(self, tmp_path):
        """A page cache too small for the working set must still serve
        scans correctly (the triggering query holds its own refs); only
        cache reuse is lost."""
        disk, shard, truth = self._setup(tmp_path, page_cache_bytes=1)
        shard.evict_partitions(len(truth))
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        ids = list(res.part_ids) + res.missing_partkeys
        tags_list, batch = shard.scan_batch(ids, 0, 2**62)
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, vals) in truth.items():
            i = by_inst[inst]
            np.testing.assert_array_equal(
                np.asarray(batch.timestamps)[i][:len(ts)], ts)

    def test_reingest_after_evict_reuses_part_id(self, tmp_path):
        disk, shard, truth = self._setup(tmp_path)
        before = {t: pid for pid, t in
                  ((pid, p.tags["instance"]) for pid, p in shard.partitions.items())}
        shard.evict_partitions(len(truth))
        schema = DEFAULT_SCHEMAS["gauge"]
        builder = RecordBuilder(schema)
        last_ts = int(max(ts[-1] for ts, _ in truth.values()))
        builder.add(last_ts + 60_000, [1.5],
                    {"__name__": "heap_usage", "job": "app", "instance": "i0",
                     "_ws_": "demo", "_ns_": "ns"})
        for c in builder.containers():
            shard.ingest_container(c, offset=10_000)
        assert shard.part_set[
            next(pk for pk, pid in shard.part_set.items()
                 if pid == before["i0"])] == before["i0"]

    def test_paged_partitions_serve_device_grid(self, tmp_path):
        """Once a dashboard pages evicted history in, repeat hits must
        serve from the DEVICE GRID (reference: DemandPagedChunkStore
        pages straight into block memory and serves identically)."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns
        from filodb_tpu.query.logical import RangeFunctionId as F

        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        shard = store.setup("prom", DEFAULT_SCHEMAS, 0,
                            StoreConfig(groups_per_shard=2))
        step = 10_000
        t0 = 1_700_000_000_000
        n_rows = 120
        schema = DEFAULT_SCHEMAS["gauge"]
        builder = RecordBuilder(schema)
        rng = np.random.default_rng(5)
        for s in range(6):
            tags = {"__name__": "pg", "job": "app", "instance": f"i{s}",
                    "_ws_": "demo", "_ns_": "ns"}
            ts = t0 + np.arange(n_rows, dtype=np.int64) * step
            vals = np.cumsum(rng.random(n_rows))
            for t, v in zip(ts, vals):
                builder.add(int(t), [float(v)], tags)
        for off, c in enumerate(builder.containers()):
            shard.ingest_container(c, off)
        shard.flush_all()
        shard.evict_partitions(6)
        assert shard.num_partitions == 0

        flt = [ColumnFilter("_metric_", Equals("pg"))]
        res = shard.lookup_partitions(flt, 0, 2**62)
        assert len(res.part_ids) == 6
        # first hit: pages chunks back from the column store
        tags_list, batch = shard.scan_batch(res.part_ids, 0, 2**62)
        assert shard.stats.partitions_paged == 6
        # repeat hit: the grid must serve the PAGED partitions
        steps0 = t0 + 120_000
        nsteps = 40
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps,
                              step, 120_000)
        assert got is not None, "grid did not serve paged partitions"
        gtags, vals, _tops = got
        sr = StepRange(steps0, steps0 + (nsteps - 1) * step, step)
        oracle = np.asarray(rangefns.apply_range_function(
            batch, sr, 120_000, F.RATE))
        order = {t["instance"]: i for i, t in enumerate(tags_list)}
        for i, t in enumerate(gtags):
            j = order[t["instance"]]
            np.testing.assert_allclose(vals[i], oracle[j], rtol=1e-9,
                                       equal_nan=True)

    def test_page_evict_invalidates_grid_plan(self, tmp_path):
        """LRU pressure dropping a paged partition must invalidate grid
        plans that referenced it — a repeat query falls back (and
        re-pages), never serves stale/empty lanes."""
        from filodb_tpu.query.logical import RangeFunctionId as F

        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        shard = store.setup("prom", DEFAULT_SCHEMAS, 0,
                            StoreConfig(groups_per_shard=2))
        step = 10_000
        t0 = 1_700_000_000_000
        builder = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for s in range(4):
            tags = {"__name__": "pe", "job": "app", "instance": f"i{s}",
                    "_ws_": "demo", "_ns_": "ns"}
            for r in range(100):
                builder.add(t0 + r * step, [float(s * 100 + r)], tags)
        for off, c in enumerate(builder.containers()):
            shard.ingest_container(c, off)
        shard.flush_all()
        shard.evict_partitions(4)
        flt = [ColumnFilter("_metric_", Equals("pe"))]
        res = shard.lookup_partitions(flt, 0, 2**62)
        shard.scan_batch(res.part_ids, 0, 2**62)     # page everything in
        epoch_before = shard.removal_epoch
        got = shard.scan_grid(res.part_ids, F.RATE, t0 + 120_000, 20,
                              step, 120_000)
        assert got is not None
        # simulate LRU pressure: shrink the cache and add an entry
        shard.paged.max_bytes = 1
        shard.paged.put(999_999, object(), 10)       # forces eviction
        assert shard.removal_epoch > epoch_before
        got2 = shard.scan_grid(res.part_ids, F.RATE, t0 + 120_000, 20,
                               step, 120_000)
        if got2 is not None:
            # re-validated and re-served (e.g. repaged): must be correct
            _t2, v2, _ = got2
            _t1, v1, _ = got
            np.testing.assert_allclose(v2, v1, rtol=1e-9, equal_nan=True)

    def test_evicted_lane_pruned_from_block_build(self, tmp_path):
        """Regression (round-4 ADVICE, medium): a grid block built while a
        laned partition is page-evicted must PRUNE that lane — never cache
        an all-NaN lane still mapped to the partition (it would serve
        'provably empty' for history that exists on disk once the
        partition pages back in; a re-paged partition instead gets a
        fresh lane, forcing a rebuild)."""
        from filodb_tpu.query.logical import RangeFunctionId as F

        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        shard = store.setup("prom", DEFAULT_SCHEMAS, 0,
                            StoreConfig(groups_per_shard=2))
        step = 10_000
        t0 = 1_700_000_000_000
        builder = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for s in range(4):
            tags = {"__name__": "nl", "job": "app", "instance": f"i{s}",
                    "_ws_": "demo", "_ns_": "ns"}
            for r in range(100):
                builder.add(t0 + r * step, [float(s * 100 + r)], tags)
        for off, c in enumerate(builder.containers()):
            shard.ingest_container(c, off)
        shard.flush_all()
        shard.evict_partitions(4)
        flt = [ColumnFilter("_metric_", Equals("nl"))]
        res = shard.lookup_partitions(flt, 0, 2**62)
        shard.scan_batch(res.part_ids, 0, 2**62)       # page everything in
        got = shard.scan_grid(res.part_ids, F.RATE, t0 + 120_000, 20,
                              step, 120_000)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        assert cache.blocks, "grid serve left no resident blocks"
        bi, blk = next(iter(cache.blocks.items()))
        victim = int(res.part_ids[-1])
        assert victim in cache.lane_of
        old_lane = cache.lane_of[victim]
        shard.paged.pop(victim)                        # LRU drop, mid-flight
        shard.bump_removal_epoch()
        # rebuilding with the lane unmaterializable must PRUNE it AND
        # fail THIS build (an in-flight pre-eviction prep must fall
        # back, never read a cached NaN lane) …
        assert cache._build(bi, blk.lanes) is None
        assert victim not in cache.lane_of
        # … while the NEXT build succeeds — a permanent eviction cannot
        # wedge future builds
        assert cache._build(bi, blk.lanes) is not None
        # … a re-appearing partition gets a FRESH lane, so the stale NaN
        # lane can never serve it, and end-to-end results stay correct
        cache.blocks.clear()
        cache._tails.clear()
        res2 = shard.lookup_partitions(flt, 0, 2**62)
        shard.scan_batch(res2.part_ids, 0, 2**62)      # re-page victim
        got2 = shard.scan_grid(res2.part_ids, F.RATE, t0 + 120_000, 20,
                               step, 120_000)
        if victim in cache.lane_of:        # re-laned: must be a new slot
            assert cache.lane_of[victim] > old_lane
        if got2 is not None:
            t1, v1, _ = got
            t2, v2, _ = got2
            o1 = {t["instance"]: v1[i] for i, t in enumerate(t1)}
            for i, t in enumerate(t2):
                np.testing.assert_allclose(v2[i], o1[t["instance"]],
                                           rtol=1e-9, equal_nan=True)

    def test_query_data_cap(self, tmp_path):
        disk, shard, truth = self._setup(tmp_path,
                                         max_data_per_shard_query=16)
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("heap_usage"))], 0, 2**62)
        with pytest.raises(QueryLimitExceeded):
            shard.scan_batch(res.part_ids, 0, 2**62)

    def test_concurrent_scans_thread_safe(self, tmp_path):
        """ODP shards are queried from concurrent HTTP handler threads:
        paging + the LRU must tolerate parallel scans (regression for the
        unlocked _PagedPartitions / in-place chunk-list mutation)."""
        import threading

        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(3)
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        res = shard.lookup_partitions(f, 0, 2**62)
        errs: list = []

        def worker():
            try:
                for _ in range(5):
                    tags_list, batch = shard.scan_batch(res.part_ids, 0, 2**62)
                    assert len(tags_list) == len(truth)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs

    def test_backfill_snapshots_leave_live_partition_untouched(self, tmp_path):
        """Older on-disk chunks of a recovery-tail resident are served via a
        read-only snapshot; the live partition (single-writer: the ingest
        thread) must never be mutated from the query path."""
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        containers, truth = _builder_data(n_series=4, n_rows=200,
                                          container_size=8192)
        cfg = StoreConfig(groups_per_shard=2)
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        half = max(len(containers) // 2, 1)
        for off in range(half):
            store.ingest("prom", 0, containers[off], offset=off)
        store.get_shard("prom", 0).flush_all()

        store2 = TimeSeriesMemStore(disk, meta)
        shard2 = store2.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        store2.recover_index("prom", 0)
        store2.recover_stream(
            "prom", 0, [(off, c) for off, c in enumerate(containers)])
        chunk_counts = {pid: len(p.chunks)
                        for pid, p in shard2.partitions.items()}
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        res = shard2.lookup_partitions(f, 0, 2**62)
        for _ in range(2):  # second scan exercises the cached backfill
            tags_list, batch = shard2.scan_batch(res.part_ids, 0, 2**62)
            counts = np.asarray(batch.row_counts)[:len(tags_list)]
            for i, t in enumerate(tags_list):
                assert counts[i] == len(truth[t["instance"]][0]), t
        for pid, p in shard2.partitions.items():
            assert len(p.chunks) == chunk_counts[pid]  # not mutated


    def test_narrow_then_wide_query_sees_full_history(self, tmp_path):
        """Regression: a narrow first query must not truncate what a later
        wide query sees (paged partitions hold full history)."""
        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(len(truth))
        some_ts = truth["i0"][0]
        narrow_end = int(some_ts[50])
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        res = shard.lookup_partitions(f, 0, narrow_end)
        shard.scan_batch(res.part_ids, 0, narrow_end)
        # now the wide query: every series must return all rows
        res = shard.lookup_partitions(f, 0, 2**62)
        tags_list, batch = shard.scan_batch(res.part_ids, 0, 2**62)
        counts = np.asarray(batch.row_counts)
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, _) in truth.items():
            assert counts[by_inst[inst]] == len(ts), inst

    def test_repeated_eviction_reclaims_memory(self, tmp_path):
        """Regression: ghost (already-evicted) index ids must not starve
        later evictions."""
        disk, shard, truth = self._setup(tmp_path)
        assert shard.evict_partitions(2) == 2
        assert shard.evict_partitions(2) == 2
        assert shard.num_partitions == len(truth) - 4


    def test_small_page_cache_does_not_drop_series(self, tmp_path):
        """Regression: partitions paged during one scan must all survive it
        even when their combined bytes exceed the page cache."""
        disk, shard, truth = self._setup(tmp_path)
        shard.evict_partitions(len(truth))
        shard.paged.max_bytes = 1  # pathological: cache holds ~one partition
        f = [ColumnFilter("_metric_", Equals("heap_usage"))]
        res = shard.lookup_partitions(f, 0, 2**62)
        tags_list, batch = shard.scan_batch(res.part_ids, 0, 2**62)
        assert len(tags_list) == len(truth)

    def test_evict_pending_data_feeds_downsampler_and_itime(self, tmp_path):
        """Regression: unflushed rows persisted during eviction must carry a
        real ingestion time and flow through the streaming downsampler."""
        from filodb_tpu.downsample import MemoryDownsamplePublisher
        disk, shard, truth = self._setup(tmp_path)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, (60_000,))
        # add fresh unflushed rows to one series
        schema = DEFAULT_SCHEMAS["gauge"]
        b = RecordBuilder(schema)
        last = int(max(ts[-1] for ts, _ in truth.values()))
        b.add(last + 60_000, [7.0],
              {"__name__": "heap_usage", "job": "app", "instance": "i0",
               "_ws_": "demo", "_ns_": "ns"})
        for c in b.containers():
            shard.ingest_container(c, offset=99)
        before = disk.num_chunks("prom", 0)
        shard.evict_partitions(len(truth))
        assert disk.num_chunks("prom", 0) > before
        assert sum(len(v) for v in pub.published.values()) > 0
        # the eviction-persisted chunk is visible to ingestion-time scans
        import time as _t
        now = int(_t.time() * 1000)
        got = list(disk.chunksets_by_ingestion_time(
            "prom", 0, now - 3_600_000, now + 3_600_000))
        assert len(got) >= 1


class TestBulkPageIn:
    """The vectorized ODP cold path (bulk sqlite read + native framed
    decode + fused batch assembly, VERDICT r4 missing #4) must be
    bit-identical to the per-partition generic path in every shape:
    pure-cold fused, range-trimmed, ragged, multi-chunk, and repeats."""

    def _fresh(self, tmp_path, n_series=24, rows_of=None, name="c"):
        """Ingest ragged per-series data, flush, and return a FRESH
        index-only store (pure cold) plus the ground truth."""
        disk = DiskColumnStore(str(tmp_path / f"{name}.db"))
        meta = DiskMetaStore(str(tmp_path / f"{name}m.db"))
        store = TimeSeriesMemStore(disk, meta)
        cfg = StoreConfig(max_chunks_size=120)   # multi-chunk partitions
        store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        schema = DEFAULT_SCHEMAS["gauge"]
        builder = RecordBuilder(schema, container_size=1 << 20)
        rng = np.random.default_rng(7)
        truth = {}
        for s in range(n_series):
            n = rows_of(s) if rows_of else 150 + 17 * (s % 9)
            tags = {"__name__": "bulk_metric", "job": "app",
                    "instance": f"i{s}", "_ws_": "demo", "_ns_": "ns"}
            ts = BASE + np.cumsum(rng.integers(9_000, 11_000, n))
            vals = np.cumsum(rng.random(n))
            truth[f"i{s}"] = (ts.astype(np.int64), vals.copy())
            for t, v in zip(ts, vals):
                builder.add(int(t), [float(v)], tags)
        sh = store.get_shard("prom", 0)
        for off, c in enumerate(builder.containers()):
            sh.ingest_container(c, off)
        sh.flush_all(ingestion_time=1000)
        cold = TimeSeriesMemStore(disk, meta)
        cold.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        assert cold.recover_index("prom", 0) == n_series
        return cold.get_shard("prom", 0), truth

    @staticmethod
    def _scan(shard, start=0, end=2**62):
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("bulk_metric"))], 0, 2**62)
        ids = list(res.part_ids) + res.missing_partkeys
        return shard.scan_batch(ids, start, end)

    @staticmethod
    def _rows_by_inst(tags, batch):
        out = {}
        for i, t in enumerate(tags):
            c = int(batch.row_counts[i])
            out[t["instance"]] = (
                np.asarray(batch.timestamps[i][:c]),
                np.asarray(batch.values[i][:c]))
        return out

    def _compare(self, tmp_path, start=0, end=2**62, rows_of=None):
        from filodb_tpu import native
        shard, truth = self._fresh(tmp_path, rows_of=rows_of, name="a")
        tags, batch = self._scan(shard, start, end)
        got = self._rows_by_inst(tags, batch)
        # generic oracle: same data, native batch decoder disabled
        shard2, _ = self._fresh(tmp_path, rows_of=rows_of, name="b")
        saved = native._batch_dec
        native._batch_dec = None
        try:
            tags2, batch2 = self._scan(shard2, start, end)
        finally:
            native._batch_dec = saved
        want = self._rows_by_inst(tags2, batch2)
        assert set(got) == set(want) == set(truth)
        for inst in want:
            np.testing.assert_array_equal(got[inst][0], want[inst][0])
            np.testing.assert_array_equal(got[inst][1], want[inst][1])
        return shard, got, truth

    def test_pure_cold_fused_matches_generic(self, tmp_path):
        shard, got, truth = self._compare(tmp_path)
        assert shard.stats.partitions_paged == len(truth)
        for inst, (ts, vals) in truth.items():
            np.testing.assert_array_equal(got[inst][0], ts)
            np.testing.assert_allclose(got[inst][1], vals)

    def test_range_trimmed_cold_matches_generic(self, tmp_path):
        # a window strictly inside the data defeats the fused path and
        # exercises the vectorized global-mask trim
        start = BASE + 400_000
        end = BASE + 1_300_000
        shard, got, truth = self._compare(tmp_path, start, end)
        for inst, (ts, vals) in truth.items():
            m = (ts >= start) & (ts <= end)
            np.testing.assert_array_equal(got[inst][0], ts[m])
            np.testing.assert_allclose(got[inst][1], vals[m])

    def test_uniform_rows_fused(self, tmp_path):
        # equal row counts take the reshape/no-mask branch
        shard, got, truth = self._compare(tmp_path, rows_of=lambda s: 200)
        for inst, (ts, vals) in truth.items():
            np.testing.assert_array_equal(got[inst][0], ts)

    def test_warm_repeat_serves_from_cache(self, tmp_path):
        shard, truth = self._fresh(tmp_path)
        t1, b1 = self._scan(shard)
        paged = shard.stats.partitions_paged
        t2, b2 = self._scan(shard)
        assert shard.stats.partitions_paged == paged   # no re-page
        r1, r2 = self._rows_by_inst(t1, b1), self._rows_by_inst(t2, b2)
        for inst in r1:
            np.testing.assert_array_equal(r1[inst][0], r2[inst][0])
            np.testing.assert_array_equal(r1[inst][1], r2[inst][1])

    def test_duplicate_ids_fall_back_consistently(self, tmp_path):
        shard, truth = self._fresh(tmp_path)
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("bulk_metric"))], 0, 2**62)
        ids = list(res.part_ids)
        dup = ids + ids[:3]
        tags, batch = shard.scan_batch(dup, 0, 2**62)
        assert len(tags) == len(dup)
        # the duplicated series' rows must appear twice, identically
        first = {t["instance"]: i for i, t in enumerate(tags[:len(ids)])}
        for k, t in enumerate(tags[len(ids):]):
            i = first[t["instance"]]
            np.testing.assert_array_equal(
                np.asarray(batch.timestamps[len(ids) + k]),
                np.asarray(batch.timestamps[i]))

    def test_page_decode_matches_unpack(self, tmp_path):
        """Native framed-row decode == Python unpack + per-chunk decode."""
        from filodb_tpu import native
        from filodb_tpu.core.chunk import decode_chunkset
        nb = native.batch_decoder()
        if nb is None:
            pytest.skip("native disabled")
        schema = DEFAULT_SCHEMAS["gauge"]
        rng = np.random.default_rng(3)
        blobs, counts, want_ts, want_v = [], [], [], []
        for s in range(17):
            n = 30 + 11 * s
            ts = BASE + np.cumsum(rng.integers(1_000, 2_000, n))
            vals = np.cumsum(rng.random(n))
            cs = encode_chunkset(schema, b"pk%d" % s,
                                 ts.astype(np.int64), [vals])
            blobs.append(pack_vectors(cs.vectors))
            counts.append(n)
            dts, dcols = decode_chunkset(schema, cs)
            want_ts.append(dts)
            want_v.append(dcols[0])
        flats = nb.page_decode(blobs, counts, [(0, False), (1, True)])
        assert flats is not None
        np.testing.assert_array_equal(flats[0], np.concatenate(want_ts))
        np.testing.assert_array_equal(flats[1], np.concatenate(want_v))
        # placed decode into a padded [S, R] matrix
        R = max(counts) + 5
        ts2d = np.empty((len(blobs), R), dtype=np.int64)
        v2d = np.empty((len(blobs), R), dtype=np.float64)
        starts = np.arange(len(blobs), dtype=np.int64) * R
        ok = nb.page_decode_into(blobs, counts,
                                 [(0, False, ts2d), (1, True, v2d)], starts)
        assert ok
        for i, n in enumerate(counts):
            np.testing.assert_array_equal(ts2d[i, :n], want_ts[i])
            np.testing.assert_array_equal(v2d[i, :n], want_v[i])

    def test_corrupt_framing_falls_back(self, tmp_path):
        from filodb_tpu import native
        nb = native.batch_decoder()
        if nb is None:
            pytest.skip("native disabled")
        assert nb.page_decode([b"\x01"], [10], [(0, False)]) is None
        out = np.empty((1, 16), dtype=np.int64)
        assert not nb.page_decode_into(
            [b"\xff\xff"], [10], [(0, False, out)],
            np.zeros(1, dtype=np.int64))

    def test_full_scan_ignores_unselected_schema_rows(self, tmp_path):
        """The full-shard range scan over-returns rows of partitions the
        query never asked for; a foreign-schema row that sorts FIRST
        must not disable the bulk path (its schema hash is not the
        reference hash — regression for h0-from-rows[0])."""
        disk = DiskColumnStore(str(tmp_path / "f.db"))
        meta = DiskMetaStore(str(tmp_path / "fm.db"))
        store = TimeSeriesMemStore(disk, meta)
        cfg = StoreConfig()
        store.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        schema = DEFAULT_SCHEMAS["gauge"]
        builder = RecordBuilder(schema, container_size=1 << 20)
        rng = np.random.default_rng(11)
        n_series, n = 300, 40    # >256 so the full-scan heuristic fires
        for s in range(n_series):
            ts = BASE + np.cumsum(rng.integers(9_000, 11_000, n))
            for t, v in zip(ts, np.cumsum(rng.random(n))):
                builder.add(int(t), [float(v)],
                            {"__name__": "fs_metric", "job": "app",
                             "instance": f"i{s}", "_ws_": "demo",
                             "_ns_": "ns"})
        sh = store.get_shard("prom", 0)
        for off, c in enumerate(builder.containers()):
            sh.ingest_container(c, off)
        sh.flush_all(ingestion_time=1000)
        # foreign-schema chunk whose partkey sorts before every real one
        cs, _, _ = _mk_chunkset(pk=b"\x00\x00early", n=10)
        cs.schema_hash = 0xBEEF
        disk.write_chunks("prom", 0, [cs], ingestion_time=1000)
        cold = TimeSeriesMemStore(disk, meta)
        cold.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
        assert cold.recover_index("prom", 0) == n_series
        shard = cold.get_shard("prom", 0)
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("fs_metric"))], 0, 2**62)
        tags, batch = shard.scan_batch(list(res.part_ids), 0, 2**62)
        assert len(tags) == n_series
        # the bulk path served it (not the per-partition fallback)
        assert shard.stats.partitions_paged == n_series
        assert not np.isnan(
            np.asarray(batch.values[0][:int(batch.row_counts[0])])).any()
