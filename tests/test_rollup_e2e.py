"""Tiered-resolution serving e2e (ISSUE 11 acceptance criteria).

1. A standalone server with rollup enabled serves a long-range query
   FROM the rolled tier: the chosen resolution is visible under
   ``stats=true``, the scan volume is >=10x below the raw-pinned path,
   and the stitched answer is exactly continuous with the raw answer
   (integer count equality at every step — no gap, no double-counted
   boundary step).

2. A 2-node rf=2 queue-transport cluster: each node rolls the shards
   it owns as primary, the rolled containers ride the PR 12
   ReplicaFanout dual-write, and the REPLICA serves them bit-equal.
"""

import json
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.parallel.shardmap import ShardStatus
from filodb_tpu.standalone import FiloServer

BASE = 1_700_000_000_000


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, timeout=30, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait(predicate, timeout_s, what, interval=0.05):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {what}")


def _series_map(body):
    out = {}
    for r in body["data"]["result"]:
        vals = {int(float(t) * 1000): v for t, v in r["values"]}
        out[r["metric"].get("inst", "")] = vals
    return out


class TestStandaloneRollupServing:
    def test_long_range_query_serves_rolled_tier(self):
        port = _free_port()
        config = {
            "node": "ro-0", "http-port": port,
            "dataplane": {"watermark-sample-interval-s": 3600},
            "datasets": [{
                "name": "prom", "num-shards": 2, "min-num-nodes": 1,
                "schema": "gauge", "spread": 1,
                "store": {"flush-interval": "1h",
                          "groups-per-shard": 2},
                # huge tick interval: the test drives run_once itself
                "rollup": {"resolutions": ["1m", "15m"],
                           "tick-interval-s": 3600},
            }],
        }
        srv = FiloServer(config)
        try:
            srv.start()
            assert "prom_ds_60000" in srv.manager.datasets()
            assert "prom_ds_900000" in srv.manager.datasets()
            pub = srv.write_publishers["prom"]
            rng = np.random.default_rng(21)
            n_series, span_min = 6, 120
            for i in range(n_series):
                ts = BASE + np.arange(0, span_min * 60_000, 10_000) + 1
                vals = rng.normal(50, 5, len(ts))
                for t, v in zip(ts, vals):
                    pub.add_sample("m", {"inst": f"i{i}", "_ws_": "w",
                                         "_ns_": "n"}, int(t), float(v))
            pub.flush()
            need = n_series * span_min * 6
            _wait(lambda: sum(sh.stats.rows_ingested
                              for sh in srv.memstore.shards("prom"))
                  >= need, 30, "raw ingest")
            srv.flush_all()
            srv.rollup_engine.run_once("prom")
            assert srv.rollup_engine.rolled_through(
                "prom", 60_000) > BASE
            _wait(lambda: sum(sh.stats.rows_ingested for sh in
                              srv.memstore.shards("prom_ds_60000"))
                  >= n_series * (span_min - 2), 30, "tier ingest")

            q = 'count_over_time(m{_ws_="w",_ns_="n"}[5m])'
            # windows align to ABSOLUTE period boundaries (periods tile
            # wall-clock multiples of the resolution, not the data start)
            start_s = ((BASE // 300_000) + 1) * 300
            end_s = ((BASE + (span_min - 10) * 60_000) // 300_000) * 300
            args = {"query": q, "start": start_s, "end": end_s,
                    "step": "5m", "stats": "true"}
            code, rolled = _get(port,
                                "/promql/prom/api/v1/query_range",
                                **args)
            assert code == 200
            st = rolled["data"]["stats"]
            # the chosen resolution is visible in stats=true
            assert st["resolutionMs"] == 60_000
            code, raw = _get(port, "/promql/prom/api/v1/query_range",
                             resolution="raw", **args)
            assert code == 200
            st_raw = raw["data"]["stats"]
            assert st_raw["resolutionMs"] == 0
            # >=10x fewer samples scanned than the raw-only path
            assert st_raw["samples"]["samplesScanned"] >= \
                10 * st["samples"]["samplesScanned"]
            # stitching continuity: integer counts equal at EVERY step
            got, want = _series_map(rolled), _series_map(raw)
            assert set(got) == set(want) and len(got) == n_series
            for inst in want:
                assert got[inst] == want[inst], inst

            # /admin/rollup + /metrics surfaces
            code, adm = _get(port, "/admin/rollup")
            assert code == 200
            ds = adm["data"]["datasets"][0]
            assert ds["dataset"] == "prom" and ds["passes"] >= 1
            assert int(ds["samples_written"]["60000"]) > 0
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "filodb_rollup_samples_written_total{" in text
            assert "filodb_rollup_lag_seconds{" in text
            assert 'filodb_rollup_queries_routed_total{' in text
        finally:
            srv.shutdown()


class TestReplicatedRollup:
    def test_rolled_chunks_ride_fanout_and_replica_serves_bitequal(self):
        ports = {"ro-a": _free_port(), "ro-b": _free_port()}
        peers = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
        servers = {}
        try:
            for n in ("ro-a", "ro-b"):
                servers[n] = FiloServer({
                    "node": n, "http-port": ports[n], "peers": peers,
                    "status-poll-interval-s": 0.2,
                    "dataplane": {"watermark-sample-interval-s": 3600},
                    "datasets": [{
                        "name": "prom", "num-shards": 2,
                        "min-num-nodes": 2, "replication-factor": 2,
                        "schema": "gauge", "spread": 1,
                        "rollup": {"resolutions": ["1m"],
                                   "tick-interval-s": 3600},
                    }],
                })
                servers[n].start()
            m = servers["ro-a"].manager.mapper("prom")
            _wait(lambda: all(
                len(m.live_replicas(s)) == 2
                and all(r.status is ShardStatus.ACTIVE
                        for r in m.live_replicas(s))
                for s in range(2)), 30, "rf=2 assignment (raw)")
            mt = servers["ro-a"].manager.mapper("prom_ds_60000")
            _wait(lambda: all(
                len(mt.live_replicas(s)) == 2 for s in range(2)),
                30, "rf=2 assignment (tier)")

            pub = servers["ro-a"].write_publishers["prom"]
            rng = np.random.default_rng(17)
            n_series, minutes = 4, 40
            for i in range(n_series):
                ts = BASE + np.arange(0, minutes * 60_000, 15_000) + 1
                vals = rng.normal(10, 2, len(ts))
                for t, v in zip(ts, vals):
                    pub.add_sample("m", {"inst": f"i{i}", "_ws_": "w",
                                         "_ns_": "n"}, int(t), float(v))
            pub.flush()
            need = n_series * minutes * 4
            _wait(lambda: all(
                sum(sh.stats.rows_ingested
                    for sh in servers[n].memstore.shards("prom"))
                >= need for n in servers), 30, "dual-write raw ingest")
            # both nodes flush + roll the shards they own as primary;
            # the emitted tier containers dual-write through the fanout
            for n in servers:
                servers[n].flush_all()
                servers[n].rollup_engine.run_once("prom")
            expect_tier = n_series * (minutes - 1)
            _wait(lambda: all(
                sum(sh.stats.rows_ingested for sh in
                    servers[n].memstore.shards("prom_ds_60000"))
                >= expect_tier for n in servers),
                30, "rolled rows on BOTH replicas")

            # every shard has exactly ONE rolling owner (the engine's
            # primary guard) yet BOTH nodes hold its rolled rows — the
            # non-owner's copies can only have arrived via the fanout
            owners = {s: m.coord_for_shard(s) for s in range(2)}
            assert all(o in servers for o in owners.values())
            for n, srv in servers.items():
                non_owned = [s for s, o in owners.items() if o != n]
                rows_here = sum(
                    sh.stats.rows_ingested
                    for sh in srv.memstore.shards("prom_ds_60000")
                    if sh.shard_num in non_owned)
                if non_owned:
                    assert rows_here > 0, (n, non_owned)

            args = {"query": 'sum_over_time(m{_ws_="w",_ns_="n"}[1m])',
                    "start": ((BASE // 60_000) + 1) * 60,
                    "end": ((BASE + (minutes - 2) * 60_000)
                            // 60_000) * 60,
                    "step": "1m"}
            answers = {}
            for n in servers:
                code, body = _get(
                    ports[n], "/promql/prom_ds_60000/api/v1/query_range",
                    **args)
                assert code == 200, (n, body)
                answers[n] = _series_map(body)
            assert set(answers["ro-a"]) == set(answers["ro-b"])
            assert len(answers["ro-a"]) == n_series
            for inst, steps in answers["ro-a"].items():
                other = answers["ro-b"][inst]
                assert steps.keys() == other.keys()
                for t, v in steps.items():
                    assert np.float64(float(v)).tobytes() == \
                        np.float64(float(other[t])).tobytes(), (inst, t)
        finally:
            for srv in servers.values():
                srv.shutdown()
