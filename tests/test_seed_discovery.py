"""DNS-SRV and Consul seed discovery against protocol-faithful fakes
(reference: akka-bootstrapper DnsSrvClusterSeedDiscovery.scala:12,
ConsulClusterSeedDiscovery + ConsulClient.scala)."""

import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from filodb_tpu.coordinator.bootstrap import (ConsulSeedDiscovery,
                                              DnsSrvSeedDiscovery,
                                              ExplicitListSeedDiscovery,
                                              seed_discovery_from_config)


def _name(n: str) -> bytes:
    out = bytearray()
    for label in n.rstrip(".").split("."):
        out += bytes([len(label)]) + label.encode()
    return bytes(out) + b"\x00"


class FakeDnsServer:
    """One-shot UDP DNS server answering SRV queries for a fixed zone."""

    def __init__(self, records):
        # records: list of (priority, weight, port, target)
        self.records = records
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.addr = self.sock.getsockname()
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        try:
            query, client = self.sock.recvfrom(4096)
        except OSError:
            return
        qid = query[:2]
        # parse question name to echo it
        pos = 12
        while query[pos] != 0:
            pos += 1 + query[pos]
        qname = query[12:pos + 1]
        qtail = query[pos + 1:pos + 5]
        resp = bytearray()
        resp += qid + (0x8180).to_bytes(2, "big")      # QR=1 RD RA
        resp += (1).to_bytes(2, "big")                  # QD
        resp += len(self.records).to_bytes(2, "big")    # AN
        resp += (0).to_bytes(4, "big")
        resp += qname + qtail
        for prio, weight, port, target in self.records:
            resp += b"\xc0\x0c"                         # ptr to question
            resp += (33).to_bytes(2, "big")             # SRV
            resp += (1).to_bytes(2, "big")              # IN
            resp += (60).to_bytes(4, "big")             # TTL
            rdata = struct.pack(">HHH", prio, weight, port) + _name(target)
            resp += len(rdata).to_bytes(2, "big") + rdata
        self.sock.sendto(bytes(resp), client)

    def close(self):
        self.sock.close()


class TestDnsSrv:
    def test_srv_discovery(self):
        dns = FakeDnsServer([(10, 50, 8080, "localhost"),
                             (20, 10, 9090, "localhost")])
        try:
            d = DnsSrvSeedDiscovery("_filodb._tcp.test.local",
                                    resolver=dns.addr, timeout_s=3)
            seeds = d.discover()
        finally:
            dns.close()
        # priority order, targets resolved to A records
        assert seeds == ["http://127.0.0.1:8080", "http://127.0.0.1:9090"]

    def test_priority_weight_ordering(self):
        dns = FakeDnsServer([(20, 1, 9002, "localhost"),
                             (10, 1, 9001, "localhost"),
                             (10, 99, 9000, "localhost")])
        try:
            d = DnsSrvSeedDiscovery("_f._tcp.x", resolver=dns.addr)
            seeds = d.discover()
        finally:
            dns.close()
        ports = [int(s.rsplit(":", 1)[1]) for s in seeds]
        assert ports == [9000, 9001, 9002]  # prio asc, weight desc

    def test_no_resolver_returns_empty(self):
        d = DnsSrvSeedDiscovery("_f._tcp.x", resolver=("127.0.0.1", 1),
                                timeout_s=0.3)
        assert d.discover() == []

    def test_name_compression_roundtrip(self):
        buf = b"\x03foo\x03bar\x00" + b"\xc0\x00"
        name, nxt = DnsSrvSeedDiscovery._read_name(buf, 9)
        assert name == "foo.bar" and nxt == 11


class _ConsulHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path.startswith("/v1/health/service/filodb"):
            assert "passing=1" in self.path
            body = json.dumps([
                {"Node": {"Address": "10.0.0.1"},
                 "Service": {"Address": "10.0.0.1", "Port": 8080}},
                {"Node": {"Address": "10.0.0.2"},
                 "Service": {"Address": "", "Port": 8081}},
            ]).encode()
            self.send_response(200)
        else:
            body = b"[]"
            self.send_response(404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestConsul:
    def test_consul_discovery(self):
        srv = ThreadingHTTPServer(("127.0.0.1", 0), _ConsulHandler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            d = ConsulSeedDiscovery(
                "filodb", f"http://127.0.0.1:{srv.server_address[1]}")
            seeds = d.discover()
        finally:
            srv.shutdown()
            srv.server_close()
        # service address preferred; node address as fallback
        assert seeds == ["http://10.0.0.1:8080", "http://10.0.0.2:8081"]

    def test_consul_down_returns_empty(self):
        d = ConsulSeedDiscovery("filodb", "http://127.0.0.1:1",
                                timeout_s=0.3)
        assert d.discover() == []


class TestConfigFactory:
    def test_explicit(self):
        d = seed_discovery_from_config({"mechanism": "explicit",
                                        "seeds": ["http://a:1"]})
        assert isinstance(d, ExplicitListSeedDiscovery)
        assert d.discover() == ["http://a:1"]

    def test_dns_srv(self):
        d = seed_discovery_from_config({"mechanism": "dns-srv",
                                        "srv-name": "_f._tcp.x",
                                        "resolver": "127.0.0.1:5353"})
        assert isinstance(d, DnsSrvSeedDiscovery)
        assert d.resolver == ("127.0.0.1", 5353)

    def test_consul(self):
        d = seed_discovery_from_config({"mechanism": "consul",
                                        "service": "filodb"})
        assert isinstance(d, ConsulSeedDiscovery)

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            seed_discovery_from_config({"mechanism": "zk"})
