"""Chaos e2e (ISSUE 7 acceptance criteria): a 3-node rf=2 cluster under
active ingest + queries survives a hard node kill with ZERO
``ShardUnavailable`` surfaced to clients and results bit-equal to a
no-fault oracle run; the killed node rejoins, replays from its own
checkpoint, is held in Recovery, and is promoted to Active only after
its watermark reaches the replica group's head — without double-counting
a single sample.  A partition (proxy blackhole) scenario rides along.

Marked slow-ish but kept in tier-1: this is THE acceptance test for the
replica-group layer.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.ingest.broker import BrokerClient, BrokerServer
from filodb_tpu.integrity.faultinject import (FlakyTcpProxy,
                                              NodeChaosController)
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.standalone import FiloServer

BASE = 1_700_000_000_000
NUM_SHARDS = 4
NODES = ("ha-a", "ha-b", "ha-c")
# the frozen query window: fully ingested BEFORE any fault, so every
# query against it — oracle, mid-kill, post-rejoin — must be bit-equal
N_INSTANCES = 12
N_SAMPLES = 240            # 1s apart -> [BASE, BASE+240s)
WINDOW = (BASE + 60_000, BASE + 180_000)

# no shard-key matcher: the planner fans out to EVERY active shard, so
# the scatter-gather always crosses the replica group that excludes the
# coordinator — the kill is guaranteed to exercise failover routing
RATE_Q = 'sum(rate(ha_total[2m]))'
# duplicate-SENSITIVE shapes: a double-ingested sample changes these
COUNT_Q = 'sum(count_over_time(ha_total[1m]))'
SUM_Q = 'sum(sum_over_time(ha_total[1m]))'


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, timeout=30, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read()), dict(e.headers)
        except Exception:
            return e.code, {"error": str(e)}, {}


def _query(port, promql):
    return _get(port, "/promql/ha/api/v1/query_range", timeout=25,
                query=promql, start=WINDOW[0] / 1000, end=WINDOW[1] / 1000,
                step="15s")


def _node_config(node, http_port, broker_port, data_dir, peer_endpoints):
    return {
        "node": node,
        "http-port": http_port,
        "data-dir": str(data_dir),
        "peers": dict(peer_endpoints),
        "status-poll-interval-s": 0.25,
        "failure-detector-timeout-ms": 1_500,
        "dataplane": {"watermark-sample-interval-s": 3600},
        "datasets": [{
            "name": "ha", "num-shards": NUM_SHARDS, "min-num-nodes": 3,
            "replication-factor": 2, "schema": "gauge", "spread": 1,
            "source": {"factory": "broker", "port": broker_port,
                       "topic": "ha"},
            "store": {"flush-interval": "1h", "groups-per-shard": 4},
            "workload": {"dispatch": {"retries": 1, "backoff-s": 0.01,
                                      "timeout-cap-s": 10}},
        }],
    }


def _produce_frozen(client, route_mapper):
    """The oracle dataset: N_INSTANCES series x N_SAMPLES, routed by the
    same bit-splice the cluster uses, one container per (shard, batch)."""
    by_shard = {s: RecordBuilder(DEFAULT_SCHEMAS["gauge"],
                                 container_size=1 << 16)
                for s in range(NUM_SHARDS)}
    from filodb_tpu.core.record import partition_hash, shard_key_hash
    from filodb_tpu.core.schemas import DatasetOptions
    opts = DatasetOptions()
    rng = np.random.default_rng(7)
    n = 0
    for i in range(N_INSTANCES):
        tags = {"_metric_": "ha_total", "instance": f"i{i}",
                "_ws_": "w", "_ns_": "n"}
        shard = route_mapper.ingestion_shard(
            shard_key_hash(tags, opts), partition_hash(tags, opts),
            1) % NUM_SHARDS
        vals = np.cumsum(rng.random(N_SAMPLES))
        for k in range(N_SAMPLES):
            by_shard[shard].add(BASE + k * 1000, [float(vals[k])], tags)
            n += 1
    for s, b in by_shard.items():
        for c in b.containers():
            client.produce("ha", s, c)
    return n


def _bg_container(i):
    """Background-ingest traffic: timestamps BEYOND the frozen window so
    live ingest never perturbs the oracle comparison."""
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 13)
    b.add(BASE + 400_000 + i * 250, [float(i)],
          {"__name__": "ha_bg", "instance": f"bg{i % 11}", "_ws_": "w",
           "_ns_": "n"})
    (out,) = b.containers()
    return out


def _broker_rows(client, shard, from_offset):
    """Exact sample rows held by the broker log at/above an offset."""
    rows = 0
    off = from_offset
    while True:
        batch = client.fetch("ha", shard, off, wait_ms=0)
        if not batch:
            return rows
        for o, msg in batch:
            rows += sum(1 for _ in decode_container(msg, DEFAULT_SCHEMAS))
            off = o + 1


def _canon(body):
    """Canonical form of a query_range result for bit-equality."""
    series = body["data"]["result"]
    return sorted((tuple(sorted(s["metric"].items())),
                   tuple((t, v) for t, v in s["values"]))
                  for s in series)


def _lag_zero(port, expect_rows):
    code, body, _ = _get(port, "/admin/shards", timeout=10)
    if code != 200:
        return False
    ds = body["data"]["datasets"].get("ha")
    if ds is None:
        return False
    total = sum(r["rows_ingested"] for r in ds["shards"])
    return total >= expect_rows


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    broker = BrokerServer(port=0)
    broker.start()
    client = BrokerClient(port=broker.port)
    client.create_topic("ha", NUM_SHARDS)

    route_mapper = ShardMapper(NUM_SHARDS)
    n_frozen = _produce_frozen(client, route_mapper)

    ports = {n: _free_port() for n in NODES}
    proxies = {n: FlakyTcpProxy(backend_port=ports[n]) for n in NODES}
    for p in proxies.values():
        p.start()
    # every node's view of its peers goes THROUGH the chaos proxies, so
    # partitions/stalls hit gossip and dispatch alike
    peer_eps = {n: f"http://127.0.0.1:{proxies[n].port}" for n in NODES}

    dirs = {n: tmp_path_factory.mktemp(n) for n in NODES}
    servers = {}
    chaos = NodeChaosController()
    for n in NODES:
        servers[n] = FiloServer(_node_config(n, ports[n], broker.port,
                                             dirs[n], peer_eps))
        servers[n].start()
        chaos.register(
            n,
            kill_fn=(lambda _s=servers[n]: (_s.http.shutdown(),
                                            _s.shutdown())),
            proxy=proxies[n])

    # convergence: every node ingested the frozen dataset on every shard
    # replica it holds, and the leader sees rf=2 live groups
    deadline = time.time() + 60
    converged = False
    while time.time() < deadline:
        leader = servers[NODES[0]]
        m = leader.manager.mapper("ha")
        groups_ok = all(len(m.live_replicas(s)) == 2
                        for s in range(NUM_SHARDS))
        rows_ok = all(
            sum(sh.stats.rows_ingested
                for sh in servers[n].memstore.shards("ha"))
            >= sum(N_SAMPLES for i in range(N_INSTANCES)
                   if _shard_of(route_mapper, i) in
                   set(m.shards_for_node(n)))
            for n in NODES)
        statuses_ok = all(
            r.status.value == "Active"
            for s in range(NUM_SHARDS) for r in m.live_replicas(s))
        if groups_ok and rows_ok and statuses_ok:
            converged = True
            break
        time.sleep(0.1)
    assert converged, "3-node rf=2 cluster never converged"

    yield {"servers": servers, "ports": ports, "proxies": proxies,
           "chaos": chaos, "client": client, "broker": broker,
           "dirs": dirs, "peer_eps": peer_eps, "n_frozen": n_frozen}

    for n, srv in servers.items():
        if not chaos.killed(n):
            try:
                srv.shutdown()
            except Exception:
                pass
    for p in proxies.values():
        p.shutdown()
    client.close()
    broker.shutdown()


def _shard_of(route_mapper, i):
    from filodb_tpu.core.record import partition_hash, shard_key_hash
    from filodb_tpu.core.schemas import DatasetOptions
    opts = DatasetOptions()
    tags = {"_metric_": "ha_total", "instance": f"i{i}",
            "_ws_": "w", "_ns_": "n"}
    return route_mapper.ingestion_shard(
        shard_key_hash(tags, opts), partition_hash(tags, opts),
        1) % NUM_SHARDS


class TestChaosKillFailoverRejoin:
    """One ordered scenario (method order matters: pytest runs them in
    definition order within the module-scoped cluster)."""

    def test_1_oracle_and_kill_failover(self, cluster):
        from filodb_tpu.utils.observability import REGISTRY
        ports = cluster["ports"]
        chaos = cluster["chaos"]
        client = cluster["client"]

        # ---- no-fault oracle run on the coordinator we will query
        oracles = {}
        for q in (RATE_Q, COUNT_Q, SUM_Q):
            code, body, headers = _query(ports["ha-a"], q)
            assert code == 200 and body["status"] == "success", body
            assert body["data"]["result"], f"oracle empty for {q}"
            assert headers.get("X-FiloDB-Partial-Data") is None
            oracles[q] = _canon(body)
        cluster["oracles"] = oracles

        # checkpoint everything so the killed node can later replay
        # from its own checkpoint (the rejoin acceptance criterion)
        for n in NODES:
            cluster["servers"][n].flush_all()

        # ---- background ingest: the cluster is live while we kill
        stop_produce = threading.Event()

        def produce_loop():
            i = 0
            while not stop_produce.is_set():
                shard = i % NUM_SHARDS
                try:
                    client.produce("ha", shard, _bg_container(i))
                except Exception:
                    pass
                i += 1
                time.sleep(0.002)

        producer = threading.Thread(target=produce_loop, daemon=True)
        producer.start()
        cluster["stop_produce"] = stop_produce
        cluster["producer"] = producer

        failover = REGISTRY.counter("filodb_dispatch_failover_total")
        failover_before = failover.total()

        # ---- queries in flight while the node dies
        results = []

        def query_loop(seconds):
            t_end = time.time() + seconds
            while time.time() < t_end:
                q = (RATE_Q, COUNT_Q, SUM_Q)[len(results) % 3]
                code, body, headers = _query(ports["ha-a"], q)
                results.append((q, code, body, headers))
                time.sleep(0.05)

        qt = threading.Thread(target=query_loop, args=(6.0,), daemon=True)
        qt.start()
        time.sleep(0.8)            # mid-query, mid-ingest ...
        chaos.kill("ha-b")         # ... hard node kill
        qt.join(timeout=30)

        assert len(results) > 20
        bad = [(q, code) for q, code, body, _h in results if code != 200
               or body.get("status") != "success"]
        assert not bad, f"client-visible failures across the kill: {bad}"
        partial = [h for _q, _c, _b, h in results
                   if h.get("X-FiloDB-Partial-Data")]
        assert not partial, "partial results surfaced despite a live replica"
        # bit-equality of every mid-kill answer with the no-fault oracle
        for q, _code, body, _h in results:
            assert _canon(body) == oracles[q], \
                f"mid-kill result diverged from oracle for {q}"
        # and the kill actually exercised replica failover
        assert failover.total() > failover_before, \
            "no failover happened — the kill never hit a routed replica"

    def test_2_survivors_demote_dead_replicas(self, cluster):
        servers = cluster["servers"]
        deadline = time.time() + 20
        demoted = False
        while time.time() < deadline:
            m = servers["ha-a"].manager.mapper("ha")
            dead = [s for s in range(NUM_SHARDS)
                    if any(r.node == "ha-b" and r.status.value == "Down"
                           for r in m.replicas(s))]
            held = [s for s in range(NUM_SHARDS)
                    if any(r.node == "ha-b" for r in m.replicas(s))]
            if held and len(dead) == len(held):
                demoted = True
                break
            time.sleep(0.1)
        assert demoted, "leader never demoted the killed node's replicas"
        # every shard still queryable from the surviving replica
        m = servers["ha-a"].manager.mapper("ha")
        for s in range(NUM_SHARDS):
            assert m.best_status(s).queryable
        # queries remain clean AFTER detection settled, too
        code, body, headers = _query(cluster["ports"]["ha-a"], COUNT_Q)
        assert code == 200
        assert headers.get("X-FiloDB-Partial-Data") is None
        assert _canon(body) == cluster["oracles"][COUNT_Q]

    def test_3_rejoin_recovers_and_promotes_at_group_head(self, cluster):
        ports = cluster["ports"]
        chaos = cluster["chaos"]
        servers = cluster["servers"]
        # freeze background ingest so the group head is stationary and
        # the promotion gate is exact
        cluster["stop_produce"].set()
        cluster["producer"].join(timeout=5)
        from filodb_tpu.utils.devicewatch import FLIGHT
        n_flight_before = len(FLIGHT.events(kind="shard.replica"))

        def start_b():
            srv = FiloServer(_node_config(
                "ha-b", ports["ha-b"], cluster["broker"].port,
                cluster["dirs"]["ha-b"], cluster["peer_eps"]))
            srv.start()
            servers["ha-b"] = srv
            chaos.register("ha-b",
                           kill_fn=(lambda _s=srv: (_s.http.shutdown(),
                                                    _s.shutdown())),
                           proxy=cluster["proxies"]["ha-b"])
            return srv

        srv_b = chaos.restart("ha-b", start_b)

        # the rejoined node replays from ITS OWN checkpoint: recovery
        # starts from persisted offsets, not zero
        deadline = time.time() + 45
        promoted = False
        saw_recovery = False
        while time.time() < deadline:
            evs = FLIGHT.events(kind="shard.replica")[n_flight_before:]
            b_evs = [e for e in evs if e.get("node") == "ha-b"
                     and e.get("dataset") == "ha"]
            saw_recovery = saw_recovery or any(
                e["status"] == "Recovery" for e in b_evs)
            m = servers["ha-a"].manager.mapper("ha")
            b_shards = [s for s in range(NUM_SHARDS)
                        if any(r.node == "ha-b" for r in m.replicas(s))]
            if b_shards and all(
                    m.state(s).replica("ha-b") is not None
                    and m.state(s).replica("ha-b").status.value == "Active"
                    for s in b_shards):
                promoted = True
                break
            time.sleep(0.1)
        assert promoted, "rejoined node never promoted back to Active"
        assert saw_recovery, \
            "rejoined node skipped the Recovery state entirely"

        # promotion only at the group head: b's ingested offsets reached
        # the max across the group on every shard it holds
        m = servers["ha-a"].manager.mapper("ha")
        for s in range(NUM_SHARDS):
            rep = m.state(s).replica("ha-b")
            if rep is None:
                continue
            sh = srv_b.memstore.get_shard("ha", s)
            assert sh.latest_offset >= m.group_head(s) - 1, \
                (s, sh.latest_offset, m.group_head(s))

        # replay came from the CHECKPOINT, not offset zero: for every
        # shard the rejoined node holds, its fresh ingest counter equals
        # exactly the broker rows AT AND ABOVE its resume offset
        # (min checkpoint + 1), and is strictly less than a from-zero
        # replay wherever the checkpoint covered data
        client = cluster["client"]
        m = servers["ha-a"].manager.mapper("ha")
        b_shards = [s for s in range(NUM_SHARDS)
                    if m.state(s).replica("ha-b") is not None]
        assert b_shards
        checked = 0
        for s in b_shards:
            cps = srv_b.metastore.read_checkpoints("ha", s)
            if not cps or min(cps.values()) <= 0:
                continue
            resume = min(cps.values()) + 1
            expected = _broker_rows(client, s, resume)
            from_zero = _broker_rows(client, s, 0)
            sh = srv_b.memstore.get_shard("ha", s)
            got = sh.stats.rows_ingested + sh.stats.rows_skipped
            assert got == expected, \
                (s, resume, got, expected, "replayed a different range")
            assert expected < from_zero, \
                (s, "checkpoint covered nothing — test setup broken")
            checked += 1
        assert checked > 0, "no checkpointed shard verified"

        # no double-counting: duplicate-sensitive queries served by the
        # REJOINED node are bit-equal to the no-fault oracle
        deadline = time.time() + 20
        ok = False
        while time.time() < deadline:
            code, body, _ = _query(ports["ha-b"], COUNT_Q)
            if code == 200 and body.get("status") == "success" \
                    and body["data"]["result"]:
                ok = _canon(body) == cluster["oracles"][COUNT_Q]
                if ok:
                    break
            time.sleep(0.2)
        assert ok, "rejoined node's answers diverge (double-counting?)"
        for q in (RATE_Q, SUM_Q):
            code, body, _ = _query(ports["ha-b"], q)
            assert code == 200
            assert _canon(body) == cluster["oracles"][q]

    def test_4_partition_mid_query_then_heal(self, cluster):
        """A partitioned (not killed) node: its proxy blackholes, peers
        fail over, clients stay clean; healing restores it."""
        ports = cluster["ports"]
        chaos = cluster["chaos"]
        chaos.stall("ha-c", n=2, stall_s=0.3)   # wedge a couple of
        code, body, _ = _query(ports["ha-a"], COUNT_Q)  # connections
        assert code == 200
        chaos.partition("ha-c")
        try:
            t_end = time.time() + 3.0
            while time.time() < t_end:
                for q in (RATE_Q, COUNT_Q, SUM_Q):
                    code, body, headers = _query(ports["ha-a"], q)
                    assert code == 200 and body["status"] == "success"
                    assert headers.get("X-FiloDB-Partial-Data") is None
                    assert _canon(body) == cluster["oracles"][q]
                time.sleep(0.1)
        finally:
            chaos.heal("ha-c")
        # after healing, ha-c's replicas return to service
        deadline = time.time() + 20
        back = False
        while time.time() < deadline:
            m = cluster["servers"]["ha-a"].manager.mapper("ha")
            c_reps = [r for s in range(NUM_SHARDS)
                      for r in m.replicas(s) if r.node == "ha-c"]
            if c_reps and all(r.status.value in ("Active", "Recovery")
                              for r in c_reps):
                back = True
                break
            time.sleep(0.1)
        assert back, "healed node never returned to service"
