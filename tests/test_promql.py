"""PromQL parser + planner tests (reference: prometheus ParserSpec,
coordinator SingleClusterPlannerSpec — SURVEY.md §4)."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex, NotEquals
from filodb_tpu.core.record import partition_hash, shard_key_hash
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore import TimeSeriesMemStore
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.promql import parse_query, query_range_to_logical_plan
from filodb_tpu.promql.parser import ParseError, duration_ms, tokenize
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext
from tests import oracle
from tests.data import START_TS, counter_containers, gauge_containers

S, T, E = 1_000_000, 10_000, 2_000_000  # parse grid


def parse(q):
    return parse_query(q, S, T, E)


class TestLexer:
    def test_durations(self):
        assert duration_ms("5m") == 300_000
        assert duration_ms("1h30m") == 5_400_000
        assert duration_ms("90s") == 90_000
        assert duration_ms("1d") == 86_400_000

    def test_tokens(self):
        toks = tokenize('sum(rate(foo{a="b"}[5m]))')
        assert [t.text for t in toks[:3]] == ["sum", "(", "rate"]


class TestSelectors:
    def test_plain_metric(self):
        p = parse("http_requests_total")
        assert isinstance(p, lp.PeriodicSeries)
        rs = p.raw_series
        assert ColumnFilter("_metric_", Equals("http_requests_total")) in rs.filters
        # 5m staleness lookback
        assert rs.range_selector.from_ms == S - 300_000

    def test_matchers(self):
        p = parse('foo{job="api", instance!="0", path=~"/v./.*", env!~"dev.*"}')
        f = p.raw_series.filters
        assert ColumnFilter("job", Equals("api")) in f
        assert ColumnFilter("instance", NotEquals("0")) in f
        assert any(isinstance(x.filter, EqualsRegex) and x.column == "path"
                   for x in f)

    def test_name_matcher_only(self):
        p = parse('{__name__="foo"}')
        assert ColumnFilter("_metric_", Equals("foo")) in p.raw_series.filters

    def test_offset(self):
        p = parse("foo offset 10m")
        assert p.offset_ms == 600_000
        assert p.raw_series.range_selector.to_ms == E - 600_000

    def test_range_needs_function(self):
        with pytest.raises(ParseError):
            parse("foo[5m]")


class TestFunctions:
    def test_rate(self):
        p = parse("rate(foo[5m])")
        assert isinstance(p, lp.PeriodicSeriesWithWindowing)
        assert p.function == lp.RangeFunctionId.RATE
        assert p.window_ms == 300_000
        assert p.series.range_selector.from_ms == S - 300_000

    def test_quantile_over_time(self):
        p = parse("quantile_over_time(0.95, foo[10m])")
        assert p.function == lp.RangeFunctionId.QUANTILE_OVER_TIME
        assert p.function_args == (0.95,)

    def test_holt_winters_and_predict(self):
        p = parse("holt_winters(foo[20m], 0.5, 0.1)")
        assert p.function_args == (0.5, 0.1)
        p2 = parse("predict_linear(foo[20m], 3600)")
        assert p2.function_args == (3600.0,)

    def test_instant_functions(self):
        p = parse("abs(foo)")
        assert isinstance(p, lp.ApplyInstantFunction)
        assert p.function == lp.InstantFunctionId.ABS
        p2 = parse("clamp_max(foo, 10)")
        assert p2.function_args == (10.0,)
        p3 = parse("histogram_quantile(0.9, foo)")
        assert p3.function == lp.InstantFunctionId.HISTOGRAM_QUANTILE
        assert p3.function_args == (0.9,)

    def test_label_replace(self):
        p = parse('label_replace(foo, "dst", "$1", "src", "(.*)")')
        assert isinstance(p, lp.ApplyMiscellaneousFunction)
        assert p.string_args == ("dst", "$1", "src", "(.*)")

    def test_sort_absent_scalar_vector_time(self):
        assert isinstance(parse("sort(foo)"), lp.ApplySortFunction)
        a = parse("absent(foo)")
        assert isinstance(a, lp.ApplyAbsentFunction)
        assert ColumnFilter("_metric_", Equals("foo")) in a.filters
        assert isinstance(parse("scalar(foo)"), lp.ScalarVaryingDoublePlan)
        v = parse("vector(1)")
        assert isinstance(v, lp.VectorPlan)
        t = parse("time()")
        assert isinstance(t, lp.ScalarTimeBasedPlan)

    def test_last_over_time(self):
        p = parse("last_over_time(foo[10m])")
        assert isinstance(p, lp.PeriodicSeries)
        assert p.raw_series.lookback_ms == 600_000


class TestAggregates:
    def test_sum_by(self):
        for q in ("sum by (job) (foo)", "sum(foo) by (job)"):
            p = parse(q)
            assert isinstance(p, lp.Aggregate)
            assert p.operator == lp.AggregationOperator.SUM
            assert p.by == ("job",)

    def test_without(self):
        p = parse("avg without (instance, host) (foo)")
        assert p.without == ("instance", "host")

    def test_topk_quantile_count_values(self):
        p = parse("topk(5, foo)")
        assert p.operator == lp.AggregationOperator.TOPK
        assert p.params == (5.0,)
        p2 = parse("quantile(0.9, foo)")
        assert p2.params == (0.9,)
        p3 = parse('count_values("version", foo)')
        assert p3.params == ("version",)

    def test_nested(self):
        p = parse("sum(rate(foo[1m])) by (job)")
        assert isinstance(p, lp.Aggregate)
        assert isinstance(p.vectors, lp.PeriodicSeriesWithWindowing)


class TestBinaryOps:
    def test_precedence(self):
        p = parse("foo + bar * 2")
        assert isinstance(p, lp.BinaryJoin)
        assert p.operator == lp.BinaryOperator.ADD
        assert isinstance(p.rhs, lp.ScalarVectorBinaryOperation)

    def test_scalar_scalar(self):
        p = parse("1 + 2 * 3")
        assert isinstance(p, lp.ScalarBinaryOperation)

    def test_pow_right_assoc(self):
        p = parse("2 ^ 3 ^ 2")
        assert isinstance(p, lp.ScalarBinaryOperation)
        assert isinstance(p.rhs, lp.ScalarBinaryOperation)

    def test_comparison_bool(self):
        p = parse("foo > bool 5")
        assert isinstance(p, lp.ScalarVectorBinaryOperation)
        assert p.bool_mode

    def test_set_ops_and_matching(self):
        p = parse("foo and on (job) bar")
        assert p.operator == lp.BinaryOperator.LAND
        assert p.on == ("job",)
        p2 = parse("foo / ignoring (instance) group_left bar")
        assert p2.ignoring == ("instance",)
        assert p2.cardinality == lp.Cardinality.MANY_TO_ONE

    def test_unary_minus(self):
        p = parse("-foo")
        assert isinstance(p, lp.ScalarVectorBinaryOperation)
        assert p.scalar_is_lhs

    def test_parse_errors(self):
        for q in ("foo bar", "sum(", "rate(foo)", "foo{a=}", "and foo"):
            with pytest.raises(ParseError):
                parse(q)


class TestShardMapper:
    def test_bit_splice(self):
        m = ShardMapper(32)
        spread = 3
        sk, pk = 0b10110_101, 0b001
        shard = m.ingestion_shard(sk, pk, spread)
        assert shard & m.part_hash_mask(spread) == pk & 0b111
        assert shard & m.shard_hash_mask(spread) == sk & m.shard_hash_mask(spread)

    def test_query_shards_cover_ingestion(self):
        m = ShardMapper(32)
        for spread in (0, 2, 5):
            sk = 0xDEADBEEF
            shards = m.query_shards(sk, spread)
            assert len(shards) == 1 << spread
            for ph in (0, 7, 123, 99999):
                assert m.ingestion_shard(sk, ph, spread) % 32 in \
                    [s % 32 for s in shards]

    def test_status_lifecycle(self):
        m = ShardMapper(4)
        m.register_node([0, 1], "node-a")
        m.update_status(0, ShardStatus.ACTIVE)
        assert m.coord_for_shard(0) == "node-a"
        assert m.active_shards() == [0]
        m.unassign(0)
        assert m.active_shards() == []


class TestPlanner:
    @pytest.fixture(scope="class")
    def setup(self):
        ms = TimeSeriesMemStore()
        cfg = StoreConfig(groups_per_shard=4, max_chunks_size=64,
                          batch_row_pad=32, batch_series_pad=4)
        num_shards = 4
        mapper = ShardMapper(num_shards)
        mapper.register_node(range(num_shards), "local")
        for s in range(num_shards):
            mapper.update_status(s, ShardStatus.ACTIVE)
            ms.setup("ds", DEFAULT_SCHEMAS, s, cfg)
        # route records to shards exactly like the gateway would
        opts = DatasetOptions()
        spread = 1
        from filodb_tpu.core.record import decode_container
        for off, c in enumerate(gauge_containers(n_series=8, n_samples=100) +
                                counter_containers(n_series=4, n_samples=100)):
            per_shard = {}
            for rec in decode_container(c, DEFAULT_SCHEMAS):
                shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                               spread) % num_shards
                per_shard.setdefault(shard, []).append(rec)
            for shard, recs in per_shard.items():
                ms.get_shard("ds", shard).ingest(recs, off)
        planner = SingleClusterPlanner("ds", mapper, opts,
                                       spread_default=spread)
        return ms, planner

    def q(self, query, start=START_TS + 300_000, end=START_TS + 800_000):
        return query_range_to_logical_plan(query, start, 10_000, end)

    def test_shard_pruning(self, setup):
        ms, planner = setup
        plan = self.q('sum(rate(http_requests_total{_ws_="demo",_ns_="App-0"}[5m]))')
        ep = planner.materialize(plan)
        tree = ep.print_tree()
        # spread=1 -> exactly 2 shard leaves
        assert tree.count("MultiSchemaPartitionsExec") == 2

    def test_no_pruning_without_shard_key(self, setup):
        ms, planner = setup
        ep = planner.materialize(self.q('sum(foo{instance="1"})'))
        assert ep.print_tree().count("MultiSchemaPartitionsExec") == 4

    def test_end_to_end_sum_rate(self, setup):
        ms, planner = setup
        query = 'sum(rate(http_requests_total{_ws_="demo",_ns_="App-0"}[2m]))'
        start, end = START_TS + 300_000, START_TS + 800_000
        ep = planner.materialize(self.q(query, start, end))
        res = ep.execute(ExecContext(ms))
        assert len(res.batches) == 1
        got = res.batches[0].np_values()[0]
        # oracle: all matching series across all shards
        rows = []
        for s in range(4):
            shard = ms.get_shard("ds", s)
            look = shard.lookup_partitions(
                [ColumnFilter("_metric_", Equals("http_requests_total")),
                 ColumnFilter("_ns_", Equals("App-0"))], 0,
                np.iinfo(np.int64).max)
            for pid in look.part_ids:
                part = shard.partitions[int(pid)]
                ts, vals = part.read_range(0, np.iinfo(np.int64).max)
                rows.append(oracle.range_fn("rate", ts, vals, start, end,
                                            10_000, 120_000))
        expect = np.nansum(np.stack(rows), axis=0)
        np.testing.assert_allclose(got, expect, rtol=1e-9)

    def test_end_to_end_binary_join(self, setup):
        ms, planner = setup
        query = 'heap_usage{_ws_="demo"} - heap_usage{_ws_="demo"}'
        ep = planner.materialize(self.q(query))
        res = ep.execute(ExecContext(ms))
        b = res.batches[0]
        assert b.num_series == 8
        v = b.np_values()
        assert np.nanmax(np.abs(v[np.isfinite(v)])) == 0.0

    def test_end_to_end_scalar_ops(self, setup):
        ms, planner = setup
        ep = planner.materialize(self.q('heap_usage * 0 + 3'))
        res = ep.execute(ExecContext(ms))
        v = res.batches[0].np_values()
        assert (v[np.isfinite(v)] == 3.0).all()

    def test_end_to_end_absent(self, setup):
        ms, planner = setup
        ep = planner.materialize(self.q('absent(nonexistent_metric)'))
        res = ep.execute(ExecContext(ms))
        assert (res.batches[0].np_values() == 1.0).all()

    def test_end_to_end_topk(self, setup):
        ms, planner = setup
        ep = planner.materialize(self.q('topk(3, heap_usage{_ws_="demo"})'))
        res = ep.execute(ExecContext(ms))
        b = res.batches[0]
        v = b.np_values()
        assert 3 <= b.num_series <= 8
        finite_per_step = np.isfinite(v).sum(axis=0)
        assert (finite_per_step <= 3).all()

    def test_metadata_plans(self, setup):
        ms, planner = setup
        mdplan = lp.SeriesKeysByFilters(
            (ColumnFilter("_metric_", Equals("heap_usage")),), 0,
            np.iinfo(np.int64).max)
        res = planner.materialize(mdplan).execute(ExecContext(ms))
        assert len(res.batches[0]) == 8
        lv = lp.LabelValues(("_ns_",), (), 0, np.iinfo(np.int64).max)
        res2 = planner.materialize(lv).execute(ExecContext(ms))
        assert len(res2.batches[0]["_ns_"]) == 8

    def test_hierarchical_reduce_shape(self, setup):
        ms, planner0 = setup
        mapper = ShardMapper(64)
        planner = SingleClusterPlanner("ds", mapper, DatasetOptions(),
                                       spread_default=1)
        ep = planner.materialize(self.q("sum(foo)"))
        tree = ep.print_tree()
        # 64 leaves -> 8 intermediate reduces under the root
        assert tree.count("ReduceAggregateExec") == 9


class TestParserRegressions:
    """Fixes from code review: lexer prefixes, zero-arg time fns, bool
    modifier, strict durations, string escapes, unary-vs-pow precedence."""

    def test_metric_name_starting_with_inf(self):
        p = parse_query("influxdb_up", S, T, E)
        leaves = lp.leaf_raw_series(p)
        assert any(f.column == "_metric_" and f.filter.value == "influxdb_up"
                   for f in leaves[0].filters)
        p2 = parse_query("rate(inflight_requests[5m])", S, T, E)
        assert lp.leaf_raw_series(p2)

    def test_inf_nan_literals_still_parse(self):
        p = parse_query("foo > Inf", S, T, E)
        assert isinstance(p, lp.ScalarVectorBinaryOperation)
        p = parse_query("NaN", S, T, E)
        assert isinstance(p, lp.ScalarFixedDoublePlan)

    def test_zero_arg_time_functions(self):
        for fn in ("hour", "minute", "month", "year", "day_of_week",
                   "day_of_month", "days_in_month"):
            p = parse_query(f"{fn}()", S, T, E)
            assert isinstance(p, lp.ScalarTimeBasedPlan), fn
        # one-arg instant form still works
        p = parse_query("hour(foo)", S, T, E)
        assert isinstance(p, lp.ApplyInstantFunction)

    def test_bool_modifier_on_vector_vector(self):
        p = parse_query("foo > bool bar", S, T, E)
        assert isinstance(p, lp.BinaryJoin)
        assert p.bool_mode is True
        p2 = parse_query("foo > bar", S, T, E)
        assert p2.bool_mode is False

    def test_unitless_duration_rejected(self):
        with pytest.raises(ParseError):
            parse_query("rate(foo[30])", S, T, E)
        with pytest.raises(ParseError):
            parse_query("foo offset 5", S, T, E)

    def test_non_ascii_string_values(self):
        p = parse_query('foo{a="café", b="x\\ny", c="\\u00e9"}',
                        S, T, E)
        filters = {f.column: f.filter.value for f in
                   lp.leaf_raw_series(p)[0].filters}
        assert filters["a"] == "café"
        assert filters["b"] == "x\ny"
        assert filters["c"] == "é"

    def test_unary_minus_pow_precedence(self):
        p = parse_query("-2^2", S, T, E)
        import filodb_tpu.query.exec as qe
        from filodb_tpu.query.model import QueryContext
        ex = qe.ScalarBinaryOperationExec(p.operator, p.lhs, p.rhs,
                                          S, T, E)
        vals = ex.do_execute(ExecContext(None, "ds"))[0].values
        assert float(np.asarray(vals).ravel()[0]) == -4.0

    def test_unary_minus_mul_precedence(self):
        p = parse_query("-2*3", S, T, E)
        import filodb_tpu.query.exec as qe
        ex = qe.ScalarBinaryOperationExec(p.operator, p.lhs, p.rhs,
                                          S, T, E)
        vals = ex.do_execute(ExecContext(None, "ds"))[0].values
        assert float(np.asarray(vals).ravel()[0]) == -6.0
