"""Cluster coordination: assignment strategy, ShardManager state machine,
failure detection + reassignment, ingestion coordinator lifecycle.

Mirrors the reference's coordinator unit-test strategy (reference:
coordinator/src/test/.../ShardManagerSpec.scala,
ShardAssignmentStrategySpec, IngestionStreamSpec — single-process specs
with deterministic sources instead of a real cluster).
"""

import time

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import (DefaultShardAssignmentStrategy,
                                            FailureDetector,
                                            IngestionStarted,
                                            RecoveryInProgress, ShardDown,
                                            ShardManager,
                                            ShardAssignmentStarted)
from filodb_tpu.coordinator.node import IngestionCoordinator, NodeCoordinator
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.ingest.stream import (ListStreamFactory, QueueStreamFactory,
                                      source_factory)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardStatus

BASE = 1_700_000_000_000


class TestAssignmentStrategy:
    def test_even_spread(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 8, min_num_nodes=2)
        a = mgr.add_node("node-a")
        b = mgr.add_node("node-b")
        assert len(a["ds"]) == 4 and len(b["ds"]) == 4
        assert set(a["ds"]) | set(b["ds"]) == set(range(8))

    def test_idempotent(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 8, min_num_nodes=2)
        first = mgr.add_node("node-a")["ds"]
        again = mgr.add_node("node-a")["ds"]
        assert first == again

    def test_nodes_beyond_min_take_leftovers_only(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 8, min_num_nodes=2)
        mgr.add_node("a")
        mgr.add_node("b")
        c = mgr.add_node("c")
        assert c["ds"] == []  # all shards taken

    def test_dataset_after_nodes(self):
        mgr = ShardManager()
        mgr.add_node("a")
        mgr.add_node("b")
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        m = mgr.mapper("ds")
        assert m.num_assigned == 4
        assert len(m.shards_for_node("a")) == 2


class TestShardManagerEvents:
    def test_status_lifecycle(self):
        events = []
        mgr = ShardManager()
        mgr.subscribe(events.append)
        mgr.setup_dataset("ds", 4, min_num_nodes=1)
        mgr.add_node("a")
        m = mgr.mapper("ds")
        assert m.status(0) == ShardStatus.ASSIGNED
        mgr.publish_event(RecoveryInProgress("ds", 0, "a", 42))
        assert m.status(0) == ShardStatus.RECOVERY
        assert m._states[0].recovery_progress == 42
        mgr.publish_event(IngestionStarted("ds", 0, "a"))
        assert m.status(0) == ShardStatus.ACTIVE
        assert any(isinstance(e, ShardAssignmentStarted) for e in events)

    def test_remove_node_reassigns(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        mgr.add_node("a")
        mgr.add_node("b")
        freed = mgr.remove_node("a")
        assert len(freed["ds"]) == 2
        m = mgr.mapper("ds")
        # survivors picked up the freed shards
        assert len(m.shards_for_node("b")) == 4
        assert m.num_assigned == 4

    def test_reassignment_rate_limit(self):
        clock = [0.0]
        mgr = ShardManager(reassignment_min_interval_ms=60_000,
                           clock=lambda: clock[0])
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        mgr.add_node("a")
        mgr.add_node("b")
        mgr.remove_node("a")          # reassigns a's shard to b (first move)
        m = mgr.mapper("ds")
        assert m.num_assigned == 2
        mgr.add_node("a2")
        # kill b immediately: its original shard moves (never moved before),
        # but the shard already moved once stays Down under the rate limit
        mgr.remove_node("b")
        down = [s for s in range(2) if m.status(s) == ShardStatus.DOWN]
        assert len(down) == 1
        clock[0] += 120.0             # advance past the interval
        mgr.remove_node("a2")         # membership event triggers reassign
        # (a2's shards freed; still one node? none left -> stays down)
        # bring a node back and confirm the rate limit has expired
        mgr.add_node("c")
        assert len(m.shards_for_node("c")) >= 1

    def test_stop_start_shards(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 4, min_num_nodes=1)
        mgr.add_node("a")
        assert mgr.stop_shards("ds", [1]) == [1]
        assert mgr.mapper("ds").status(1) == ShardStatus.STOPPED


class TestFailureDetector:
    def test_timeout_declares_down_and_reassigns(self):
        clock = [100.0]
        mgr = ShardManager(clock=lambda: clock[0])
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        fd = FailureDetector(mgr, timeout_ms=5_000, clock=lambda: clock[0])
        fd.heartbeat("a")
        fd.heartbeat("b")
        assert mgr.mapper("ds").num_assigned == 4
        clock[0] += 3.0
        fd.heartbeat("b")  # a goes silent
        clock[0] += 3.0
        dead = fd.check()
        assert dead == ["a"]
        assert fd.alive() == ["b"]
        m = mgr.mapper("ds")
        assert len(m.shards_for_node("b")) == 4  # took over a's shards


def _containers(metric="up", n_series=3, n_rows=120, shards=(0,)):
    """Builds per-shard container lists."""
    rng = np.random.default_rng(0)
    out = {s: [] for s in shards}
    for s in shards:
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=4096)
        for i in range(n_series):
            tags = {"__name__": metric, "instance": f"s{s}i{i}",
                    "_ws_": "w", "_ns_": "n"}
            ts = BASE + np.cumsum(rng.integers(5_000, 15_000, n_rows))
            for t, v in zip(ts, rng.random(n_rows)):
                b.add(int(t), [float(v)], tags)
        out[s] = list(enumerate(b.containers()))
    return out


class TestIngestionCoordinator:
    def test_start_ingests_finite_stream(self):
        data = _containers(shards=(0, 1))
        store = TimeSeriesMemStore()
        events = []
        ic = IngestionCoordinator("node-a", "prom", DEFAULT_SCHEMAS, store,
                                  ListStreamFactory(data),
                                  event_sink=events.append)
        ic.start_ingestion(0, blocking=True)
        ic.start_ingestion(1, blocking=True)
        for s in (0, 1):
            sh = store.get_shard("prom", s)
            assert sh.stats.rows_ingested == 3 * 120
        assert any(isinstance(e, IngestionStarted) for e in events)

    def test_recovery_reports_progress_and_skips(self):
        data = _containers(n_rows=200)
        store = TimeSeriesMemStore()
        # phase 1: ingest + flush + checkpoint
        ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store,
                                  ListStreamFactory(data))
        ic.start_ingestion(0, blocking=True)
        store.get_shard("prom", 0).flush_all()
        rows_before = store.get_shard("prom", 0).stats.rows_ingested

        # stagger one flush group's checkpoint to an earlier offset so the
        # source resumes early and per-group watermarks do the fine skipping
        # pick a group that actually holds a series (flush checkpoints
        # every group, including empty ones)
        g0 = next(iter(store.get_shard("prom", 0).partitions.values())).group
        store.meta.write_checkpoint("prom", 0, g0, 0)

        # phase 2: simulate restart (fresh memstore sharing meta+colstore)
        store2 = TimeSeriesMemStore(store.store, store.meta)
        events = []
        ic2 = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store2,
                                   ListStreamFactory(data),
                                   event_sink=events.append,
                                   recovery_report_interval=1)
        ic2.start_ingestion(0, blocking=True)
        sh = store2.get_shard("prom", 0)
        # group g0 replays (its checkpoint was early); the other groups'
        # records in the replayed range skip via their watermarks
        assert sh.stats.rows_ingested > 0
        assert sh.stats.rows_skipped > 0
        assert any(isinstance(e, IngestionStarted) for e in events)

    def test_resync_starts_and_stops(self):
        factory = QueueStreamFactory()
        store = TimeSeriesMemStore()
        ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store,
                                  factory)
        ic.resync([0, 1])
        time.sleep(0.05)
        assert ic.running_shards() == [0, 1]
        # push live data through the queue edge
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        b.add(BASE + 1000, [1.0], {"__name__": "up", "instance": "x",
                                   "_ws_": "w", "_ns_": "n"})
        for c in b.containers():
            factory.stream_for("prom", 0).push(c)
        deadline = time.time() + 5
        while time.time() < deadline:
            if store.get_shard("prom", 0).stats.rows_ingested == 1:
                break
            time.sleep(0.01)
        assert store.get_shard("prom", 0).stats.rows_ingested == 1
        ic.resync([1])  # shard 0 unassigned
        assert ic.running_shards() == [1]
        ic.stop_all()
        assert ic.running_shards() == []

    def test_node_coordinator_wiring(self):
        data = _containers()
        store = TimeSeriesMemStore()
        nc = NodeCoordinator("n", store)
        nc.setup_dataset("prom", DEFAULT_SCHEMAS, ListStreamFactory(data))
        nc.resync("prom", [0])
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                if store.get_shard("prom", 0).stats.rows_ingested == 3 * 120:
                    break
            except Exception:
                pass
            time.sleep(0.01)
        assert store.get_shard("prom", 0).stats.rows_ingested == 3 * 120
        nc.shutdown()


def test_source_factory_registry():
    f = source_factory("queue")
    assert isinstance(f, QueueStreamFactory)
    with pytest.raises(ValueError):
        source_factory("nope")


def test_drained_finite_stream_stays_queryable():
    """Regression: a CSV-style load that drains must leave the shard
    ACTIVE (queryable), not STOPPED."""
    mgr = ShardManager()
    mgr.setup_dataset("prom", 1, min_num_nodes=1)
    mgr.add_node("n")
    data = _containers()
    store = TimeSeriesMemStore()
    ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store,
                              ListStreamFactory(data),
                              event_sink=mgr.publish_event)
    ic.start_ingestion(0, blocking=True)
    assert mgr.mapper("prom").status(0) == ShardStatus.ACTIVE
    assert mgr.mapper("prom").active_shards() == [0]

def test_queue_offsets_resume_above_checkpoints():
    """Regression: after a restart the live queue's offsets must start
    above the recovery checkpoints or watermarks drop new records."""
    factory = QueueStreamFactory()
    store = TimeSeriesMemStore()
    store.setup("prom", DEFAULT_SCHEMAS, 0)
    # simulate prior checkpoints at offset 57
    for g in range(store.get_shard("prom", 0).num_groups):
        store.meta.write_checkpoint("prom", 0, g, 57)
    ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store, factory)
    ic.resync([0])
    time.sleep(0.05)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    b.add(BASE + 5, [2.0], {"__name__": "up", "instance": "z",
                            "_ws_": "w", "_ns_": "n"})
    off = factory.stream_for("prom", 0).push(b.containers()[0])
    assert off >= 58  # numbering fast-forwarded past the checkpoint
    deadline = time.time() + 5
    while time.time() < deadline:
        if store.get_shard("prom", 0).stats.rows_ingested == 1:
            break
        time.sleep(0.01)
    assert store.get_shard("prom", 0).stats.rows_ingested == 1
    ic.stop_all()


def test_restart_after_stop_with_pending_items():
    """Regression: stop with queued items leaves no stale sentinel; a
    restarted consumer ingests the backlog and keeps running."""
    factory = QueueStreamFactory()
    store = TimeSeriesMemStore()
    ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, store, factory)
    ic.resync([0])
    time.sleep(0.05)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    b.add(BASE + 1000, [1.0], {"__name__": "up", "instance": "x",
                               "_ws_": "w", "_ns_": "n"})
    cont = b.containers()[0]
    factory.stream_for("prom", 0).push(cont)
    ic.stop_ingestion(0)
    assert ic.running_shards() == []
    # backlog arrives while stopped
    b2 = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    b2.add(BASE + 2000, [2.0], {"__name__": "up", "instance": "x",
                                "_ws_": "w", "_ns_": "n"})
    factory.stream_for("prom", 0).push(b2.containers()[0])
    ic.start_ingestion(0)
    deadline = time.time() + 5
    while time.time() < deadline:
        if store.get_shard("prom", 0).stats.rows_ingested >= 2:
            break
        time.sleep(0.01)
    assert store.get_shard("prom", 0).stats.rows_ingested == 2
    assert ic.running_shards() == [0]  # still alive, not killed by sentinel
    ic.stop_all()
