"""Downsampling subsystem: period markers, chunk downsamplers, flush-time
emission, downsample store serving, and the batch job.

Oracle strategy mirrors the reference's downsample specs (reference:
core/src/test/.../downsample/ShardDownsamplerSpec.scala,
spark-jobs DownsamplerMainSpec): brute-force per-period aggregates over
the raw samples must match what the subsystem emits.
"""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.downsample import (BatchDownsampler,
                                   DownsampledTimeSeriesStore,
                                   MemoryDownsamplePublisher,
                                   ShardDownsampler, ds_dataset_name,
                                   parse_downsampler, parse_period_marker)
from filodb_tpu.downsample.chunkdown import (CounterPeriodMarker, DMin,
                                             TimePeriodMarker)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore

BASE = 1_700_000_000_000
RES = 60_000


def _oracle_periods(ts, res=RES):
    """period id for each sample: period p covers ((p)*res, (p+1)*res]."""
    return (np.asarray(ts) - 1) // res


class TestParsing:
    def test_specs(self):
        assert isinstance(parse_downsampler("dMin(1)"), DMin)
        assert parse_downsampler("tTime(0)").is_time
        assert parse_downsampler("dAvgSc(3,4)").count_col == 4
        with pytest.raises(ValueError):
            parse_downsampler("dBogus(1)")
        with pytest.raises(ValueError):
            parse_downsampler("dMin")

    def test_period_marker_specs(self):
        assert isinstance(parse_period_marker("time(0)"), TimePeriodMarker)
        assert isinstance(parse_period_marker("counter(1)"), CounterPeriodMarker)
        with pytest.raises(ValueError):
            parse_period_marker("weird(0)")


class TestPeriodMarkers:
    def test_time_bounds_match_oracle(self):
        rng = np.random.default_rng(0)
        ts = BASE + np.sort(rng.integers(1, 10 * RES, 300))
        bounds, ends = TimePeriodMarker(0).periods(ts, [], RES)
        pids = _oracle_periods(ts)
        # every period's rows share one period id, and the stamp is its end
        for i in range(len(ends)):
            seg = pids[bounds[i]:bounds[i + 1]]
            assert (seg == seg[0]).all()
            assert ends[i] == (seg[0] + 1) * RES
        assert bounds[0] == 0 and bounds[-1] == len(ts)

    def test_boundary_sample_belongs_to_earlier_period(self):
        # a sample exactly at p*res closes period p-1 (range is (start, end])
        ts = np.array([RES, RES + 1], dtype=np.int64)
        bounds, ends = TimePeriodMarker(0).periods(ts, [], RES)
        assert len(ends) == 2
        assert ends[0] == RES and ends[1] == 2 * RES

    def test_counter_marker_splits_at_reset(self):
        ts = BASE + 1 + np.arange(10) * 1000  # +1: stay off period boundary
        vals = np.array([1, 2, 3, 4, 1, 2, 3, 4, 5, 6], dtype=np.float64)
        bounds, ends = CounterPeriodMarker(1).periods(ts, [vals], 10**9)
        # one time period, split once at the reset (row 4)
        assert list(bounds) == [0, 4, 10]
        assert ends[0] == ts[3]  # truncated period stamped with last sample

    def test_counter_marker_no_reset_is_time_marker(self):
        ts = BASE + np.arange(100) * 7000
        vals = np.cumsum(np.ones(100))
        b1, e1 = CounterPeriodMarker(1).periods(ts, [vals], RES)
        b2, e2 = TimePeriodMarker(0).periods(ts, [vals], RES)
        np.testing.assert_array_equal(b1, b2)
        np.testing.assert_array_equal(e1, e2)


class TestDownsamplers:
    def _data(self):
        rng = np.random.default_rng(1)
        ts = BASE + np.sort(rng.integers(1, 5 * RES, 200))
        vals = rng.normal(10, 3, 200)
        return ts, vals

    def test_agg_values_match_oracle(self):
        ts, vals = self._data()
        bounds, ends = TimePeriodMarker(0).periods(ts, [vals], RES)
        pids = _oracle_periods(ts)
        got = {
            "min": parse_downsampler("dMin(1)").downsample(ts, [vals], bounds, ends),
            "max": parse_downsampler("dMax(1)").downsample(ts, [vals], bounds, ends),
            "sum": parse_downsampler("dSum(1)").downsample(ts, [vals], bounds, ends),
            "count": parse_downsampler("dCount(1)").downsample(ts, [vals], bounds, ends),
            "avg": parse_downsampler("dAvg(1)").downsample(ts, [vals], bounds, ends),
            "last": parse_downsampler("dLast(1)").downsample(ts, [vals], bounds, ends),
        }
        for i, p in enumerate(np.unique(pids)):
            seg = vals[pids == p]
            assert got["min"][i] == seg.min()
            assert got["max"][i] == seg.max()
            np.testing.assert_allclose(got["sum"][i], seg.sum())
            assert got["count"][i] == len(seg)
            np.testing.assert_allclose(got["avg"][i], seg.mean())
            assert got["last"][i] == seg[-1]

    def test_nan_aware(self):
        ts = BASE + np.arange(4) * 1000 + 1
        vals = np.array([1.0, np.nan, 3.0, np.nan])
        bounds, ends = TimePeriodMarker(0).periods(ts, [vals], 10**9)
        assert parse_downsampler("dSum(1)").downsample(ts, [vals], bounds, ends)[0] == 4.0
        assert parse_downsampler("dCount(1)").downsample(ts, [vals], bounds, ends)[0] == 2
        assert parse_downsampler("dLast(1)").downsample(ts, [vals], bounds, ends)[0] == 3.0

    def test_avg_sc(self):
        # re-downsampling: avg = sum(sums)/sum(counts)
        ts = BASE + np.arange(4) * 1000 + 1
        sums = np.array([10.0, 20.0, 30.0, 40.0])
        counts = np.array([1.0, 2.0, 3.0, 4.0])
        bounds, ends = TimePeriodMarker(0).periods(ts, [sums, counts], 10**9)
        d = parse_downsampler("dAvgSc(1,2)")
        np.testing.assert_allclose(
            d.downsample(ts, [sums, counts], bounds, ends), [100.0 / 10.0])


def _ingest_gauge(n_series=4, n_rows=500, res_span=20):
    schemas = DEFAULT_SCHEMAS
    builder = RecordBuilder(schemas["gauge"])
    rng = np.random.default_rng(7)
    truth = {}
    for s in range(n_series):
        tags = {"__name__": "disk_io", "job": "app", "instance": f"i{s}",
                "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.sort(rng.integers(1, res_span * RES, n_rows))
        ts = np.unique(ts)
        vals = rng.normal(50, 10, len(ts))
        truth[f"i{s}"] = (ts.astype(np.int64), vals.copy())
        for t, v in zip(ts, vals):
            builder.add(int(t), [float(v)], tags)
    return schemas, builder.containers(), truth


class TestFlushTimeDownsampling:
    def test_flush_emits_and_store_serves(self):
        schemas, containers, truth = _ingest_gauge()
        store = TimeSeriesMemStore()
        shard = store.setup("prom", schemas, 0)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, (RES,))
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        shard.flush_all()

        ds = DownsampledTimeSeriesStore("prom", resolutions_ms=(RES,))
        ds.setup(schemas, 0)
        n = ds.ingest_from_publisher(pub)
        assert n > 0

        ds_shard = ds.shard(RES, 0)
        res = ds_shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("disk_io"))], 0, 2**62)
        tags_list, batch = ds_shard.scan_batch(res.part_ids, 0, 2**62)
        assert len(tags_list) == len(truth)
        # ds-gauge value column is avg (value-column of ds-gauge);
        # check per-period averages match a brute-force oracle
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, vals) in truth.items():
            i = by_inst[inst]
            n_rows = int(np.asarray(batch.row_counts)[i])
            got_ts = np.asarray(batch.timestamps)[i][:n_rows]
            got_avg = np.asarray(batch.values)[i][:n_rows]
            pids = _oracle_periods(ts)
            uniq = np.unique(pids)
            assert n_rows == len(uniq)
            for j, p in enumerate(uniq):
                assert got_ts[j] == (p + 1) * RES
                np.testing.assert_allclose(got_avg[j], vals[pids == p].mean())

    def test_counter_downsample_preserves_increase(self):
        schemas = DEFAULT_SCHEMAS
        builder = RecordBuilder(schemas["prom-counter"])
        rng = np.random.default_rng(3)
        tags = {"__name__": "reqs_total", "job": "api", "instance": "i0",
                "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.sort(rng.integers(1, 10 * RES, 300))
        ts = np.unique(ts)
        vals = np.cumsum(rng.random(len(ts)))
        for t, v in zip(ts, vals):
            builder.add(int(t), [float(v)], tags)

        store = TimeSeriesMemStore()
        shard = store.setup("prom", schemas, 0)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, (RES,))
        for off, c in enumerate(builder.containers()):
            store.ingest("prom", 0, c, offset=off)
        shard.flush_all()

        ds = DownsampledTimeSeriesStore("prom", resolutions_ms=(RES,))
        ds.setup(schemas, 0)
        ds.ingest_from_publisher(pub)
        ds_shard = ds.shard(RES, 0)
        res = ds_shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("reqs_total"))], 0, 2**62)
        _, batch = ds_shard.scan_batch(res.part_ids, 0, 2**62)
        n_rows = int(np.asarray(batch.row_counts)[0])
        lasts = np.asarray(batch.values)[0][:n_rows]
        # monotone counter: increase computable from consecutive lasts
        assert lasts[-1] == vals[-1]
        np.testing.assert_allclose(lasts[-1] - lasts[0],
                                   vals[-1] - vals[_oracle_periods(ts).searchsorted(
                                       _oracle_periods(ts)[0], side="right") - 1])


class TestBatchDownsampler:
    def test_batch_job_writes_downsample_datasets(self, tmp_path):
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        schemas, containers, truth = _ingest_gauge(n_series=3, n_rows=400)
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", schemas, 0)
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all(ingestion_time=1000)

        job = BatchDownsampler("prom", schemas, disk, resolutions_ms=(RES,))
        written = job.run_shard(0, 0, 2**62)
        assert written[RES] > 0

        # serve the downsample dataset from a fresh store via recovery
        ds_mem = TimeSeriesMemStore(disk, meta)
        name = ds_dataset_name("prom", RES)
        ds_shard = ds_mem.setup(name, schemas, 0)
        assert ds_mem.recover_index(name, 0) == len(truth)
        res = ds_shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("disk_io"))], 0, 2**62)
        tags_list, batch = ds_shard.scan_batch(res.part_ids, 0, 2**62)
        assert len(tags_list) == len(truth)
        by_inst = {t["instance"]: i for i, t in enumerate(tags_list)}
        for inst, (ts, vals) in truth.items():
            i = by_inst[inst]
            n_rows = int(np.asarray(batch.row_counts)[i])
            pids = _oracle_periods(ts)
            assert n_rows == len(np.unique(pids))
            got_avg = np.asarray(batch.values)[i][:n_rows]
            for j, p in enumerate(np.unique(pids)):
                np.testing.assert_allclose(got_avg[j], vals[pids == p].mean())


    def test_planar_path_taken_and_equivalent(self, tmp_path):
        """Aligned full-live data must take the COLUMNAR write path
        (downsample_planes) and produce byte-equal aggregates to the
        per-series downsample_arrays fallback for every resolution in
        the ladder."""
        from filodb_tpu.core.record import (RecordBuilder, parse_partkey)
        from filodb_tpu.core.schemas import DatasetOptions

        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", DEFAULT_SCHEMAS, 0,
                    StoreConfig(max_chunks_size=720))
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
        rng = np.random.default_rng(9)
        n_rows, step = 720, 5_000
        ts = BASE + np.arange(n_rows, dtype=np.int64) * step
        for i in range(7):
            tags = {"_metric_": "pl", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            b.add_series(ts, [rng.random(n_rows) * 10], tags)
        for off, c in enumerate(b.containers()):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all(ingestion_time=1000)

        pairs = [(parse_partkey(cs.partkey), cs) for cs in
                 disk.chunksets_by_ingestion_time("prom", 0, 0, 2**62)]
        samp = ShardDownsampler("prom", 0, DEFAULT_SCHEMAS["gauge"],
                                None, (RES, 900_000))
        prepared = samp.prepare_arrays(pairs)
        for res in (RES, 900_000):
            planar = samp.downsample_planes(prepared, res)
            assert planar is not None, res
            tags_list, pe, planes, leftovers = planar
            assert len(tags_list) == 7 and not leftovers, res
            per = samp.downsample_arrays(prepared, res)
            by_inst = {t["instance"]: (t2, cols)
                       for t, t2, cols in per}
            for i, tags in enumerate(tags_list):
                t_ref, cols_ref = by_inst[tags["instance"]]
                np.testing.assert_array_equal(pe, t_ref)
                for ci, plane in enumerate(planes):
                    np.testing.assert_array_equal(plane[:, i],
                                                  cols_ref[ci], err_msg=str((res, ci)))

    def test_successive_windows_widen_partkey_lifetime(self, tmp_path):
        """Two batch runs over DIFFERENT ingestion windows: the second
        must widen the downsample partkey's time range, never narrow it
        (merge_part_keys vs the replacing write_part_keys)."""
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        schemas, containers, truth = _ingest_gauge(n_series=2, n_rows=400)
        store = TimeSeriesMemStore(disk, meta)
        store.setup("prom", schemas, 0)
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        store.get_shard("prom", 0).flush_all(ingestion_time=1000)
        # second batch of later data for the same series, later itime
        ts0 = max(int(ts[-1]) for ts, _ in truth.values()) + RES
        from filodb_tpu.core.record import RecordBuilder
        from filodb_tpu.core.schemas import DatasetOptions
        b = RecordBuilder(schemas["gauge"], DatasetOptions())
        rng = np.random.default_rng(3)
        for inst in truth:
            tags = {"__name__": "disk_io", "job": "app", "instance": inst,
                    "_ws_": "w", "_ns_": "n"}
            later = ts0 + np.arange(200, dtype=np.int64) * 10_000
            b.add_series(later, [rng.random(200)], tags)
        for off, c in enumerate(b.containers()):
            store.ingest("prom", 0, c, offset=100 + off)
        store.get_shard("prom", 0).flush_all(ingestion_time=2000)

        job = BatchDownsampler("prom", schemas, disk,
                               resolutions_ms=(RES,))
        job.run_shard(0, 0, 1500)          # first window only
        name = ds_dataset_name("prom", RES)
        first = {r.partkey: (r.start_time, r.end_time)
                 for r in disk.scan_part_keys(name, 0)}
        assert first
        job.run_shard(0, 1500, 2**62)      # second window
        merged = {r.partkey: (r.start_time, r.end_time)
                  for r in disk.scan_part_keys(name, 0)}
        for pk, (s0, e0) in first.items():
            s1, e1 = merged[pk]
            assert s1 <= s0, "later window narrowed partkey start"
            assert e1 > e0, "later window did not extend partkey end"


def _count_mismatch_blobs():
    """The blobs both halves of the count-mismatch check share: each
    encodes exactly 5 rows but is handed to a decoder expecting 8."""
    from filodb_tpu.codecs import deltadelta, doublecodec
    short_ll = deltadelta.encode(np.arange(5, dtype=np.int64))
    dbl_blobs = (
        doublecodec.encode(np.random.default_rng(0).normal(0, 1, 5)),
        doublecodec.encode(np.full(5, 3.5)),
        doublecodec.encode(np.arange(5, dtype=np.float64)))
    return short_ll, dbl_blobs


def test_batch_decode_count_semantics_pure():
    """Pure-Python half of the count-mismatch contract, running in
    tier-1 unconditionally (no native skip): the reference decoders
    establish the ground truth the native batch decoder must enforce —
    these blobs really do carry 5 rows, not the 8 the mismatching
    caller claims, and round-trip losslessly."""
    from filodb_tpu.codecs import deltadelta, doublecodec
    short_ll, dbl_blobs = _count_mismatch_blobs()
    ll = deltadelta.decode(short_ll)
    assert len(ll) == 5
    np.testing.assert_array_equal(ll, np.arange(5, dtype=np.int64))
    for blob in dbl_blobs:
        assert len(doublecodec.decode(blob)) == 5


def test_batch_decode_rejects_count_mismatch_native():
    """Native half: a blob whose header count disagrees with the
    expected row count must error, never serve uninitialized memory.
    Only THIS assertion needs the native library — the pure-Python
    semantics above run everywhere."""
    from filodb_tpu import native

    if not native.enable():
        pytest.skip("native library unavailable")
    nb = native.batch_decoder()
    short_ll, dbl_blobs = _count_mismatch_blobs()
    with pytest.raises(ValueError):
        nb.ll_decode_batch([short_ll], [8])
    for blob in dbl_blobs:
        with pytest.raises(ValueError):
            nb.dbl_decode_batch([blob], [8])


def test_best_resolution():
    ds = DownsampledTimeSeriesStore("prom", resolutions_ms=(60_000, 3_600_000))
    assert ds.best_resolution(30_000) == 60_000
    assert ds.best_resolution(60_000) == 60_000
    assert ds.best_resolution(3_600_000) == 3_600_000
    assert ds.best_resolution(10**9) == 3_600_000


class TestDownsampleQueryRewrites:
    """Query-side downsample-schema rewrites (reference:
    MultiSchemaPartitionsExec.scala:41-85, RangeFunction.scala:238-267):
    min/max/sum/count/avg_over_time over a ds-gauge dataset must read the
    matching aggregate COLUMN, not the avg column, and therefore match a
    brute-force oracle over the RAW samples exactly (windows aligned to
    period boundaries)."""

    W = 5  # window periods

    @pytest.fixture(scope="class")
    def served_store(self):
        schemas, containers, truth = _ingest_gauge(n_series=3, n_rows=600,
                                                   res_span=30)
        store = TimeSeriesMemStore()
        shard = store.setup("prom", schemas, 0)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, (RES,))
        for off, c in enumerate(containers):
            store.ingest("prom", 0, c, offset=off)
        shard.flush_all()
        ds = DownsampledTimeSeriesStore("prom", resolutions_ms=(RES,))
        ds.setup(schemas, 0)
        ds.ingest_from_publisher(pub)
        return ds, truth

    def _run(self, ds, promql, start, step, end):
        from filodb_tpu.coordinator.planner import SingleClusterPlanner
        from filodb_tpu.core.schemas import DatasetOptions
        from filodb_tpu.parallel.shardmap import ShardMapper
        from filodb_tpu.promql.parser import query_range_to_logical_plan
        from filodb_tpu.query.exec import ExecContext
        from filodb_tpu.query.model import QueryContext
        name = ds_dataset_name("prom", RES)
        planner = SingleClusterPlanner(name, ShardMapper(1), DatasetOptions(),
                                       spread_default=0)
        plan = query_range_to_logical_plan(promql, start, step, end)
        ep = planner.materialize(plan, QueryContext(sample_limit=10**9))
        res = ep.execute(ExecContext(ds.memstore))
        out = {}
        for b in res.batches:
            vals = np.asarray(b.values)
            for i, tags in enumerate(b.keys):
                out[tags["instance"]] = (np.asarray(b.steps.timestamps()),
                                         vals[i])
        return out

    def _oracle(self, ts, vals, step_ts, fn):
        w = self.W * RES
        out = np.full(len(step_ts), np.nan)
        for j, t in enumerate(step_ts):
            m = (ts > t - w) & (ts <= t)
            if m.any():
                out[j] = fn(vals[m])
        return out

    @pytest.mark.parametrize("func,orc", [
        ("min_over_time", np.min), ("max_over_time", np.max),
        ("sum_over_time", np.sum), ("count_over_time", len),
        ("avg_over_time", np.mean)])
    def test_matches_raw_oracle(self, func, orc, served_store):
        ds, truth = served_store
        # steps on period boundaries so ds periods tile the windows
        start = ((BASE // RES) + self.W + 1) * RES
        end = ((BASE // RES) + 25) * RES
        out = self._run(
            ds, f'{func}(disk_io{{_ws_="w",_ns_="n"}}[{self.W}m])',
            start, RES, end)
        assert set(out) == set(truth)
        for inst, (ts, vals) in truth.items():
            got_ts, got = out[inst]
            want = self._oracle(ts, vals, got_ts, orc)
            both = np.isfinite(got) & np.isfinite(want)
            assert (np.isfinite(got) == np.isfinite(want)).all()
            np.testing.assert_allclose(got[both], want[both], rtol=1e-10)

    def test_instant_selector_serves_avg(self, served_store):
        ds, truth = served_store
        start = ((BASE // RES) + self.W + 1) * RES
        end = ((BASE // RES) + 25) * RES
        out = self._run(ds, 'disk_io{_ws_="w",_ns_="n"}', start, RES, end)
        # last sample within lookback = the latest period's AVG
        for inst, (ts, vals) in truth.items():
            got_ts, got = out[inst]
            for j, t in enumerate(got_ts):
                pids = _oracle_periods(ts)
                elig = pids[(ts <= t) & (ts > t - 300_000)]
                if len(elig) == 0:
                    assert np.isnan(got[j])
                    continue
                p = elig[-1]
                np.testing.assert_allclose(got[j], vals[pids == p].mean(),
                                           rtol=1e-10)


class TestGridDownsamplePath:
    """The vectorized grid downsampler (downsample/griddown.py) must be
    byte-identical to the per-series host path on regular-cadence data,
    and must hand reset/irregular series back to the host path."""

    STEP = 5_000
    RESOLUTIONS = (60_000, 900_000)

    def _mk(self, schema_name, make_vals, n_series=6, n_rows=360,
            irregular=(), gaps=True):
        schemas = DEFAULT_SCHEMAS
        builder = RecordBuilder(schemas[schema_name])
        rng = np.random.default_rng(5)
        t0 = 1_700_000_000_000
        for s in range(n_series):
            tags = {"__name__": "m", "inst": f"i{s}", "_ws_": "w",
                    "_ns_": "n"}
            ts = t0 + np.arange(n_rows, dtype=np.int64) * self.STEP \
                + (s * 13) % self.STEP + 1
            if s in irregular:
                # two samples in one bucket: grid must refuse this lane
                ts = np.sort(np.concatenate([ts, ts[:5] + 1]))
            keep = np.ones(len(ts), bool)
            if gaps and s % 2 == 0:
                keep[rng.random(len(ts)) < 0.1] = False   # missed scrapes
            vals = make_vals(rng, len(ts), s)
            for t, v in zip(ts[keep], vals[keep]):
                builder.add(int(t), [float(v)], tags)
        return schemas, builder.containers()

    def _run(self, schemas, containers, schema_name, force_host):
        from filodb_tpu.core.record import decode_container
        from filodb_tpu.downsample import griddown
        import unittest.mock as mock
        store = TimeSeriesMemStore()
        shard = store.setup("prom", schemas, 0)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, self.RESOLUTIONS)
        ctx = mock.patch.object(griddown, "grid_supported",
                                lambda d: False) if force_host \
            else mock.patch.object(griddown, "detect_gstep",
                                   griddown.detect_gstep)
        with ctx:
            for off, c in enumerate(containers):
                store.ingest("prom", 0, c, offset=off)
            shard.flush_all()
        out = {}
        for res in self.RESOLUTIONS:
            recs = []
            for sh, cont in pub.drain(res):
                for r in decode_container(cont, schemas):
                    key = (res, r.tags.get("inst"))
                    recs.append((key, r.timestamp,
                                 tuple(np.round(np.asarray(
                                     r.values, np.float64), 9))))
            recs.sort()
            out[res] = recs
        return out

    def test_gauge_grid_matches_host(self):
        schemas, containers = self._mk(
            "gauge", lambda rng, n, s: rng.normal(50, 10, n),
            irregular=(3,))
        grid = self._run(schemas, containers, "gauge", force_host=False)
        host = self._run(schemas, containers, "gauge", force_host=True)
        assert grid == host
        assert any(len(v) for v in grid.values())

    def test_counter_grid_matches_host_with_resets(self):
        def mk(rng, n, s):
            v = np.cumsum(rng.random(n) * 3)
            if s in (1, 4):                   # resets -> host fallback
                v[n // 2:] -= v[n // 2] * 0.95
            return v
        schemas, containers = self._mk("prom-counter", mk)
        grid = self._run(schemas, containers, "prom-counter",
                         force_host=False)
        host = self._run(schemas, containers, "prom-counter",
                         force_host=True)
        assert grid == host


def test_grid_downsample_nan_samples_match_host():
    """NaN-valued samples (staleness markers) must produce identical
    downsample records on the grid and host paths, at full precision
    even when jax x64 is off (the numpy f64 twin)."""
    import math
    import unittest.mock as mock

    from filodb_tpu.core.record import decode_container
    from filodb_tpu.downsample import griddown

    def run(force_host):
        store = TimeSeriesMemStore()
        shard = store.setup("prom", DEFAULT_SCHEMAS, 0)
        pub = MemoryDownsamplePublisher()
        shard.enable_downsampling(pub, (60_000,))
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        t0 = 1_700_000_000_000
        tags = {"__name__": "m", "_ws_": "w", "_ns_": "n"}
        vals = [5.0, float("nan"), 7.0] + [float("nan")] * 3
        for i, v in enumerate(vals * 20):
            b.add(t0 + i * 5_000 + 1, [v], tags)
        ctx = mock.patch.object(griddown, "grid_supported",
                                lambda d: False) if force_host \
            else mock.patch.object(griddown, "detect_gstep",
                                   griddown.detect_gstep)
        with ctx:
            for off, c in enumerate(b.containers()):
                store.ingest("prom", 0, c, offset=off)
            shard.flush_all()
        out = []
        for sh, cont in pub.drain(60_000):
            for r in decode_container(cont, DEFAULT_SCHEMAS):
                out.append((r.timestamp,
                            tuple(np.asarray(r.values, np.float64))))
        out.sort()
        return out

    g, h = run(False), run(True)
    assert len(g) == len(h) and len(g) > 0
    for (tg, vg), (th, vh) in zip(g, h):
        assert tg == th
        for x, y in zip(vg, vh):
            assert (math.isnan(x) and math.isnan(y)) or x == y, (vg, vh)
